"""Profile YCSB point ops.

Default mode (legacy): engine-level single vs batched point reads with
a cProfile dump.

`--json` mode: the RPC-path YCSB through a real MiniCluster with the
request scheduler on — prints ONE JSON object with ops/s next to the
scheduler's own accounting (per-lane depth/wait histograms, batch-size
distribution, group-commit fan-in), so batching policy is tunable from
data instead of guesswork; plus a grouped-scan stage split
(dict-merge / build / kernel / combine wall, slot occupancy, compile
counts for the dict-key GROUP BY kernel).  Env knobs: PROFILE_OPS
(default 4000), PROFILE_CLIENTS (default 16), PROFILE_ROWS (default
20000).
"""
import os
import sys
import tempfile
import time

os.environ.setdefault("YBTPU_PLATFORM", "cpu")
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def legacy_profile():
    import cProfile
    import pstats
    from yugabyte_db_tpu.models.ycsb import YcsbTabletWorkload, \
        usertable_info
    from yugabyte_db_tpu.tablet import Tablet

    t = Tablet("ycsb", usertable_info(), tempfile.mkdtemp(prefix="ycsb-"))
    w = YcsbTabletWorkload(t, n_rows=100_000)
    w.load()
    w.run("c", ops=2000)
    for tag, kw in (("single", {}), ("batch16", {"clients": 16})):
        best = 0
        for _ in range(3):
            r = w.run("c", ops=30000, **kw)
            best = max(best, r.ops_per_sec)
        print(f"{tag}: {best:.0f} ops/s")

    pr = cProfile.Profile()
    pr.enable()
    w.run("c", ops=30000)
    pr.disable()
    pstats.Stats(pr).sort_stats("cumulative").print_stats(22)


async def rpc_profile() -> dict:
    """RPC-path YCSB-C (+ a write phase) against one tserver; returns
    ops/s + live scheduler stats."""
    import asyncio

    from yugabyte_db_tpu.docdb.operations import ReadRequest, RowOp
    from yugabyte_db_tpu.models.ycsb import usertable_info
    from yugabyte_db_tpu.ops.scan import AggSpec
    from yugabyte_db_tpu.tools.mini_cluster import MiniCluster

    ops = int(os.environ.get("PROFILE_OPS", "4000"))
    clients = int(os.environ.get("PROFILE_CLIENTS", "16"))
    n_rows = int(os.environ.get("PROFILE_ROWS", "20000"))

    mc = await MiniCluster(tempfile.mkdtemp(prefix="ycsb-rpc-"),
                           num_tservers=1).start()
    try:
        c = mc.client()
        await c.create_table(usertable_info(), num_tablets=1,
                             replication_factor=1)
        await mc.wait_for_leaders("usertable")
        rows = [{"ycsb_key": i,
                 **{f"field{j}": "x" * 100 for j in range(10)}}
                for i in range(n_rows)]
        for i in range(0, n_rows, 2000):
            await c.insert("usertable", rows[i:i + 2000])

        import numpy as np
        rng = np.random.default_rng(1)
        keys = rng.integers(0, n_rows, ops)

        async def read_worker(sl):
            for k in sl:
                await c.get("usertable", {"ycsb_key": int(k)})

        t0 = time.perf_counter()
        await asyncio.gather(*[
            read_worker(keys[i::clients]) for i in range(clients)])
        read_s = time.perf_counter() - t0

        wkeys = rng.integers(0, n_rows, ops // 2)

        async def write_worker(sl):
            for k in sl:
                await c.write("usertable", [RowOp("upsert", {
                    "ycsb_key": int(k),
                    **{f"field{j}": "u" * 100 for j in range(10)}})])

        from yugabyte_db_tpu.tablet.tablet import FLUSH_APPLY_STATS
        from yugabyte_db_tpu.tablet.tablet_peer import (
            WRITE_PATH_STATS, reset_write_path_stats)
        reset_write_path_stats()
        flush0 = dict(FLUSH_APPLY_STATS)
        t0 = time.perf_counter()
        await asyncio.gather(*[
            write_worker(wkeys[i::clients]) for i in range(clients)])
        write_s = time.perf_counter() - t0
        # write-path stage split: admission wait lives in the
        # scheduler stats below (point_write lane wait_us); the rest
        # of the path — group merge / replicate (append+fsync+commit)
        # / apply / flush handoff — accumulates here.  entries/batches
        # is the group-commit fanin: batches == WAL 'write' entries,
        # so ops/batches >> 1 proves coalesced groups rode ONE
        # LogEntry batch each
        write_path = {
            "ops": ops // 2,
            "group_merge_s": round(WRITE_PATH_STATS["group_merge_s"], 4),
            "replicate_s": round(WRITE_PATH_STATS["replicate_s"], 4),
            "apply_s": round(WRITE_PATH_STATS["apply_s"], 4),
            "wal_entries": WRITE_PATH_STATS["batches"],
            "queued_writes_per_entry": round(
                WRITE_PATH_STATS["entries"]
                / max(WRITE_PATH_STATS["batches"], 1), 2),
            "flush_handoff_s": round(
                FLUSH_APPLY_STATS["handoff_s"] - flush0["handoff_s"], 4),
            "flush_inline_s": round(
                FLUSH_APPLY_STATS["inline_s"] - flush0["inline_s"], 4),
            "background_flushes": (FLUSH_APPLY_STATS["background_flushes"]
                                   - flush0["background_flushes"]),
        }

        # a burst of identical aggregate scans: exercises coalescing
        t0 = time.perf_counter()
        await asyncio.gather(*[
            c.scan("usertable", ReadRequest(
                "", aggregates=(AggSpec("count"),)))
            for _ in range(32)])
        scan_s = time.perf_counter() - t0

        stats = await c.messenger.call(
            mc.tservers[0].messenger.addr, "tserver",
            "scheduler_stats", {})

        # --- trace_overhead: paired sampled-on/off read rounds -------
        # the ISSUE 14 overhead gate in profile form: point reads at
        # default sampling vs sampling off, interleaved, best-of; the
        # ASH sampler thread runs on both sides (a real server always
        # has it).  WARN at >2% cost.
        from yugabyte_db_tpu.utils import flags as _flags
        from yugabyte_db_tpu.utils.trace import ASH
        ASH.start()
        t_ops = max(500, ops // 4)
        t_keys = rng.integers(0, n_rows, t_ops)

        async def trace_round():
            async def w(sl):
                for k in sl:
                    await c.get("usertable", {"ycsb_key": int(k)})
            t0 = time.perf_counter()
            await asyncio.gather(*[
                w(t_keys[i::clients]) for i in range(clients)])
            return t_ops / (time.perf_counter() - t0)

        default_rate = _flags.REGISTRY._flags[
            "trace_sampling_rate"].default
        rates = {"off": 0.0, "on": default_rate}
        t_res = {"off": [], "on": []}
        try:
            for _ in range(2):
                for side, rate in rates.items():
                    _flags.set_flag("trace_sampling_rate", rate)
                    t_res[side].append(await trace_round())
        finally:
            _flags.set_flag("trace_sampling_rate", default_rate)
        trace_overhead = {
            "ops_per_round": t_ops,
            "default_sampling_rate": default_rate,
            "read_ops_per_s_off": round(max(t_res["off"]), 1),
            "read_ops_per_s_on": round(max(t_res["on"]), 1),
            "on_vs_off": round(max(t_res["on"]) / max(t_res["off"]), 3),
        }
        if trace_overhead["on_vs_off"] < 0.98:
            print(f"WARN: trace_overhead on_vs_off="
                  f"{trace_overhead['on_vs_off']} — tracing at default "
                  "sampling costs >2% of the read hot path",
                  file=sys.stderr)

        return {
            "metric": "ycsb_rpc_profile",
            "clients": clients,
            "read_ops_per_s": round(ops / read_s, 1),
            "write_ops_per_s": round((ops // 2) / write_s, 1),
            "agg_scans_per_s": round(32 / scan_s, 1),
            "write_path": write_path,
            "scheduler": stats,
            "trace_overhead": trace_overhead,
            "bulk_load": bulk_load_profile(),
            "grouped_scan": grouped_scan_profile(),
        }
    finally:
        await mc.shutdown()


def bulk_load_profile(n_rows: int = 200_000) -> dict:
    """Engine-level bulk-load stage split: the fused gather/encode
    feeder vs the streaming SST write stage (tablet.LAST_BULK_LOAD_STATS
    from one usertable-shaped load).  gather ~= wall - write overlap
    means the pipeline is producer-bound; write_stage_s ~= wall means
    the disk is the wall."""
    import numpy as np
    from yugabyte_db_tpu.models.ycsb import usertable_info
    from yugabyte_db_tpu.tablet import Tablet
    from yugabyte_db_tpu.tablet.tablet import LAST_BULK_LOAD_STATS

    t = Tablet("ycsb-bulk", usertable_info(),
               tempfile.mkdtemp(prefix="ycsb-bulk-"))
    payload = np.asarray(["x" * 100], object).repeat(n_rows)
    cols = {"ycsb_key": np.arange(n_rows, dtype=np.int64),
            **{f"field{j}": payload for j in range(10)}}
    t0 = time.perf_counter()
    loaded = t.bulk_load(cols)
    wall = time.perf_counter() - t0
    return {"rows": loaded, "rows_per_s": round(loaded / wall, 1),
            **LAST_BULK_LOAD_STATS}


def grouped_scan_profile(n_rows: int = 200_000, rounds: int = 3) -> dict:
    """Engine-level dict-key GROUP BY stage split: Q1 over the
    string-keyed lineitem through the streamed grouped kernel
    (tablet.read, grouped_pushdown_enabled on), reporting dict-merge /
    batch-build / kernel / per-chunk combine wall, slot occupancy, and
    the shared kernel's launch+compile counters — the same stage keys
    profile_bypass.py reports for the bypass route, so the two paths
    compare cell-for-cell."""
    import numpy as np
    from yugabyte_db_tpu.docdb.operations import (_SHARED_KERNEL,
                                                  ReadRequest)
    from yugabyte_db_tpu.models.tpch import (ROWS_PER_SF,
                                             generate_lineitem,
                                             lineitem_str_data,
                                             lineitem_str_info,
                                             numpy_reference,
                                             tpch_q1_str)
    from yugabyte_db_tpu.ops.grouped_scan import (GROUPED_STATS,
                                                  LAST_GROUPED_STATS)
    from yugabyte_db_tpu.ops.stream_scan import LAST_STREAM_STATS
    from yugabyte_db_tpu.tablet import Tablet
    from yugabyte_db_tpu.utils import flags

    data = generate_lineitem(n_rows / ROWS_PER_SF)
    n = len(data["rowid"])
    t = Tablet("li-grp-prof", lineitem_str_info(),
               tempfile.mkdtemp(prefix="grp-prof-"))
    t.bulk_load(lineitem_str_data(data), block_rows=32768)
    q = tpch_q1_str()

    def req():
        return ReadRequest("lineitem_s", where=q.where,
                           aggregates=q.aggs, group_by=q.group)

    flags.set_flag("streaming_chunk_rows", 32768)
    try:
        c0 = _SHARED_KERNEL.compiles
        l0 = GROUPED_STATS["launches"]
        resp = t.read(req())            # compile + warm
        assert resp.backend == "tpu", "grouped pushdown fell back"
        compile_launches = GROUPED_STATS["launches"] - l0
        best = None
        for _ in range(rounds):
            t0 = time.perf_counter()
            resp = t.read(req())
            wall = time.perf_counter() - t0
            if best is None or wall < best[0]:
                best = (wall, dict(LAST_GROUPED_STATS),
                        dict(LAST_STREAM_STATS))
        wall, grouped, stream = best
        ref = numpy_reference(q, data)
        counts = np.asarray(resp.group_counts)
        for g in np.nonzero(counts)[0]:
            key = tuple(str(v[g]) for v in resp.group_values)
            assert int(counts[g]) == ref[key][2], f"grouped {key}"
        return {
            "rows": n,
            "wall_s": round(wall, 4),
            "rows_per_s": round(n / wall, 1),
            "path": grouped.get("path"),
            "dict_merge_s": grouped.get("dict_merge_s"),
            "build_s": stream.get("build_s"),
            "kernel_s": grouped.get("kernel_s"),
            "combine_s": grouped.get("combine_s"),
            "num_slots": grouped.get("num_slots"),
            "slots_occupied": grouped.get("slots_occupied"),
            "spilled_rows": grouped.get("spilled_rows"),
            "chunks": stream.get("chunks"),
            "launches_per_scan": compile_launches,
            "kernel_compiles": _SHARED_KERNEL.compiles - c0,
        }
    finally:
        flags.REGISTRY.reset("streaming_chunk_rows")


def main():
    if "--json" in sys.argv:
        import asyncio
        import json
        print(json.dumps(asyncio.run(rpc_profile())))
    else:
        legacy_profile()


if __name__ == "__main__":
    main()
