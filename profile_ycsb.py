"""Profile YCSB-C single vs batched point reads (throwaway)."""
import os, tempfile, time, cProfile, pstats
os.environ.setdefault("YBTPU_PLATFORM", "cpu")
from yugabyte_db_tpu.models.ycsb import YcsbTabletWorkload, usertable_info
from yugabyte_db_tpu.tablet import Tablet

t = Tablet("ycsb", usertable_info(), tempfile.mkdtemp(prefix="ycsb-"))
w = YcsbTabletWorkload(t, n_rows=100_000)
w.load()
w.run("c", ops=2000)
for tag, kw in (("single", {}), ("batch16", {"clients": 16})):
    best = 0
    for _ in range(3):
        r = w.run("c", ops=30000, **kw)
        best = max(best, r.ops_per_sec)
    print(f"{tag}: {best:.0f} ops/s")

pr = cProfile.Profile()
pr.enable()
w.run("c", ops=30000)
pr.disable()
pstats.Stats(pr).sort_stats("cumulative").print_stats(22)
