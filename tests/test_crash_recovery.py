"""Crash-point recovery + colocation/txn interplay + TTL-extended
randomized checking."""
import asyncio
import random

import pytest

from yugabyte_db_tpu.docdb import ReadRequest, RowOp, WriteRequest
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
from yugabyte_db_tpu.utils import fault_injection as fi
from yugabyte_db_tpu.utils.hybrid_time import HybridClock, MockPhysicalClock
from tests.test_tablet import make_info


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def _clean():
    yield
    fi.clear_crash_points()


class TestCrashRecovery:
    def test_flush_crash_recovers_via_wal_replay(self, tmp_path):
        """A crash between SST write and manifest update must lose
        nothing: the data re-applies from the Raft log on reopen
        (reference: tablet_bootstrap replay + frontier dedup)."""
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                from tests.test_load_balancer import kv_info
                await c.create_table(kv_info(), num_tablets=1)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": i, "v": float(i)}
                                      for i in range(30)])
                peer = next(p for ts in mc.tservers
                            for p in ts.peers.values())
                fi.arm_crash_point("flush:before_manifest")
                with pytest.raises(fi.CrashPointHit):
                    peer.tablet.flush()
                fi.clear_crash_points()
                # "process restart"
                await mc.restart_tserver(0)
                await mc.wait_for_leaders("kv")
                c2 = mc.client()
                for i in (0, 15, 29):
                    row = await c2.get("kv", {"k": i})
                    assert row is not None and row["v"] == float(i)
            finally:
                await mc.shutdown()
        run(go())


class TestColocatedTxns:
    def test_txn_across_colocated_tables(self, tmp_path):
        async def go():
            from tests.test_colocation import small_table
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_tablegroup("g")
                await c.create_table(small_table("ta"), tablegroup="g")
                await c.create_table(small_table("tb"), tablegroup="g")
                await mc.wait_for_leaders("ta")
                await c.insert("ta", [{"k": 1, "v": 10.0}])
                await c.insert("tb", [{"k": 1, "v": 20.0}])
                await c._master_call("get_status_tablet", {})
                await mc.wait_for_leaders("system.transactions")
                txn = await c.transaction().begin()
                await txn.insert("ta", [{"k": 1, "v": 5.0}])
                await txn.insert("tb", [{"k": 1, "v": 25.0}])
                # invisible before commit
                assert (await c.get("ta", {"k": 1}))["v"] == 10.0
                await txn.commit()
                await asyncio.sleep(0.4)
                assert (await c.get("ta", {"k": 1}))["v"] == 5.0
                assert (await c.get("tb", {"k": 1}))["v"] == 25.0
            finally:
                await mc.shutdown()
        run(go())


class TestRandomizedWithTtl:
    @pytest.mark.parametrize("seed", [13, 77])
    def test_ttl_interleaved_ops(self, tmp_path, seed):
        from yugabyte_db_tpu.tablet import Tablet
        rng = random.Random(seed)
        clock = HybridClock(MockPhysicalClock(1_000_000))
        t = Tablet(f"rttl-{seed}", make_info(), str(tmp_path), clock=clock)
        alive = {}          # k -> (expire_ht or None, v)
        for step in range(200):
            clock._physical.advance_micros(rng.randint(1, 2000))
            k = rng.randint(0, 15)
            r = rng.random()
            if r < 0.5:
                ttl = rng.choice([None, 5, 50])   # ms
                v = float(step)
                t.apply_write(WriteRequest("t1", [
                    RowOp("upsert", {"k": k, "v": v, "s": "x"},
                          ttl_ms=ttl)]))
                now = clock.now().value
                expire = None if ttl is None else \
                    now + ((ttl * 1000) << 12)
                alive[k] = (expire, v)
            elif r < 0.6:
                t.apply_write(WriteRequest("t1",
                                           [RowOp("delete", {"k": k})]))
                alive.pop(k, None)
            elif r < 0.7:
                t.flush()
        now = clock.now().value
        for k in range(16):
            got = t.read(ReadRequest("t1", pk_eq={"k": k}, read_ht=now))
            ent = alive.get(k)
            expect_alive = ent is not None and (
                ent[0] is None or ent[0] > now)
            if expect_alive:
                assert got.rows and got.rows[0]["v"] == ent[1], f"k={k}"
            else:
                assert not got.rows, f"k={k} should be gone"


class TestTruncateRecovery:
    def test_truncate_replays_after_sigkill(self, tmp_path):
        """The Raft-replicated truncate survives a crash: replay must
        not resurrect pre-truncate rows (the manifest persists the
        empty SST set atomically and the flushed frontier advances to
        the truncate op)."""
        async def go():
            from yugabyte_db_tpu.docdb import ReadRequest
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            from tests.test_load_balancer import kv_info
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=2)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": i, "v": float(i)}
                                      for i in range(100)])
                # flush some of it so SST deletion is exercised too
                for p in mc.tservers[0].peers.values():
                    p.tablet.flush()
                await c.insert("kv", [{"k": 1000 + i, "v": 0.0}
                                      for i in range(20)])
                await c.truncate_table("kv")
                rows = (await c.scan("kv", ReadRequest(""))).rows
                assert rows == []
                await c.insert("kv", [{"k": 7, "v": 7.0}])
                await mc.restart_tserver(0)
                await mc.wait_for_leaders("kv")
                rows = (await c.scan("kv", ReadRequest(""))).rows
                assert [(r["k"], r["v"]) for r in rows] == [(7, 7.0)]
            finally:
                await mc.shutdown()
        asyncio.run(go())

    def test_truncate_discards_inflight_compaction_output(self, tmp_path):
        """A compaction that snapshotted its inputs before TRUNCATE
        must not install its merged output afterward (it would
        resurrect every truncated row); a flush whose frozen memtable
        was truncated away likewise discards its SST."""
        import os
        from yugabyte_db_tpu.storage.lsm import LsmStore, WriteBatch
        from yugabyte_db_tpu.storage.sst import SstWriter
        st = LsmStore(str(tmp_path / "s"), name="regular")
        for i in range(3):
            b = WriteBatch()
            for j in range(50):
                b.put(b"k%02d%02d" % (i, j), b"v")
            st.apply(b)
            st.flush()
        _, ssts = st.read_snapshot()
        inputs = list(ssts)
        st.truncate()
        path = st._new_sst_path()
        w = SstWriter(path)
        w.add(b"resurrected", b"x")
        w.finish()
        st.replace_ssts(inputs, path)
        _, ssts = st.read_snapshot()
        assert len(ssts) == 0
        assert not os.path.exists(path)

    def test_concurrent_on_conflict_increments_lose_nothing(self,
                                                            tmp_path):
        """ON CONFLICT DO UPDATE locks the conflicting row: concurrent
        `SET v = v + 1` statements serialize (PG semantics), no lost
        updates."""
        async def go():
            from yugabyte_db_tpu.ql.executor import SqlSession
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                s0 = SqlSession(c)
                await s0.execute("CREATE TABLE ci (k bigint PRIMARY "
                                 "KEY, v bigint) WITH tablets = 1")
                await s0.execute("INSERT INTO ci (k, v) VALUES (1, 0)")
                await c.messenger.call(mc.master.messenger.addr,
                                       "master", "get_status_tablet", {})
                await mc.wait_for_leaders("system.transactions")

                async def incr():
                    s = SqlSession(c)
                    await s.execute(
                        "INSERT INTO ci (k, v) VALUES (1, 1) "
                        "ON CONFLICT (k) DO UPDATE SET v = v + 1")
                await asyncio.gather(*[incr() for _ in range(8)])
                r = await s0.execute("SELECT v FROM ci WHERE k = 1")
                assert r.rows[0]["v"] == 8
            finally:
                await mc.shutdown()
        asyncio.run(go())

    def test_snapshot_restore_recovers_pre_truncate_data(self, tmp_path):
        """A snapshot taken BEFORE a truncate restores the
        pre-truncate rows into a clone — truncate must not damage
        snapshot hard-links (the store swaps files wholesale)."""
        async def go():
            from yugabyte_db_tpu.docdb import ReadRequest
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            from tests.test_load_balancer import kv_info
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=2)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": i, "v": float(i)}
                                      for i in range(30)])
                snap = await c._master_call("create_snapshot",
                                            {"table": "kv"})
                await c.truncate_table("kv")
                assert (await c.scan("kv", ReadRequest(""))).rows == []
                await c._master_call(
                    "restore_snapshot",
                    {"snapshot_id": snap["snapshot_id"],
                     "new_name": "kv_before"})
                await mc.wait_for_leaders("kv_before")
                rows = (await c.scan("kv_before", ReadRequest(""))).rows
                assert sorted(r["k"] for r in rows) == list(range(30))
            finally:
                await mc.shutdown()
        run(go())
