"""PostgreSQL v3 wire protocol tests with a minimal raw-socket client
implementing the same framing a real driver uses."""
import asyncio
import struct

import pytest

from yugabyte_db_tpu.ql.pg_server import PgServer
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster


def run(coro):
    return asyncio.run(coro)


class MiniPgClient:
    def __init__(self, reader, writer):
        self.reader, self.writer = reader, writer

    async def startup(self, ssl_probe=False):
        if ssl_probe:
            self.writer.write(struct.pack(">II", 8, 80877103))
            await self.writer.drain()
            assert await self.reader.readexactly(1) == b"N"
        params = b"user\x00yb\x00database\x00yb\x00\x00"
        body = struct.pack(">I", 196608) + params
        self.writer.write(struct.pack(">I", len(body) + 4) + body)
        await self.writer.drain()
        msgs = await self.read_until(b"Z")
        assert any(t == b"R" for t, _ in msgs)      # AuthenticationOk
        assert any(t == b"S" for t, _ in msgs)      # ParameterStatus
        return msgs

    async def read_msg(self):
        hdr = await self.reader.readexactly(5)
        (ln,) = struct.unpack(">I", hdr[1:5])
        body = await self.reader.readexactly(ln - 4) if ln > 4 else b""
        return hdr[:1], body

    async def read_until(self, tag):
        out = []
        while True:
            t, b = await self.read_msg()
            out.append((t, b))
            if t == tag:
                return out

    async def query(self, sql):
        body = sql.encode() + b"\x00"
        self.writer.write(b"Q" + struct.pack(">I", len(body) + 4) + body)
        await self.writer.drain()
        return await self.read_until(b"Z")

    @staticmethod
    def rows(msgs):
        out = []
        for t, b in msgs:
            if t != b"D":
                continue
            (n,) = struct.unpack_from(">H", b)
            pos = 2
            vals = []
            for _ in range(n):
                (ln,) = struct.unpack_from(">i", b, pos)
                pos += 4
                if ln < 0:
                    vals.append(None)
                else:
                    vals.append(b[pos:pos + ln].decode())
                    pos += ln
            out.append(vals)
        return out


class TestPgWire:
    def test_psql_style_session(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            srv = PgServer(mc.client())
            addr = await srv.start()
            try:
                reader, writer = await asyncio.open_connection(*addr)
                c = MiniPgClient(reader, writer)
                await c.startup(ssl_probe=True)   # psql always probes SSL
                msgs = await c.query(
                    "CREATE TABLE pgt (k bigint, v double, s text, "
                    "PRIMARY KEY (k))")
                assert any(t == b"C" for t, _ in msgs)
                await mc.wait_for_leaders("pgt")
                await c.query("INSERT INTO pgt (k, v, s) VALUES "
                              "(1, 1.5, 'one'), (2, 2.5, 'two')")
                msgs = await c.query("SELECT k, v, s FROM pgt ORDER BY k")
                assert any(t == b"T" for t, _ in msgs)   # RowDescription
                rows = c.rows(msgs)
                assert rows == [["1", "1.5", "one"], ["2", "2.5", "two"]]
                # multi-statement + aggregate
                msgs = await c.query(
                    "INSERT INTO pgt (k, v, s) VALUES (3, 3.5, 'x'); "
                    "SELECT count(*) FROM pgt")
                assert c.rows(msgs)[-1] == ["3"]
                # error surfaces as ErrorResponse then ReadyForQuery
                msgs = await c.query("SELECT * FROM missing_table")
                assert msgs[0][0] == b"E"
                assert b"42601" in msgs[0][1] or b"missing" in msgs[0][1]
                assert msgs[-1][0] == b"Z"
                # session still usable after the error
                msgs = await c.query("SELECT s FROM pgt WHERE k = 1")
                assert c.rows(msgs) == [["one"]]
                writer.close()
            finally:
                await srv.shutdown()
                await mc.shutdown()
        run(go())

    def test_extended_protocol_parse_bind_execute(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            srv = PgServer(mc.client())
            addr = await srv.start()
            try:
                reader, writer = await asyncio.open_connection(*addr)
                c = MiniPgClient(reader, writer)
                await c.startup()
                await c.query("CREATE TABLE ep (k bigint, v text, "
                              "PRIMARY KEY (k))")
                await mc.wait_for_leaders("ep")

                def parse(name, sql):
                    body = name.encode() + b"\x00" + sql.encode() + \
                        b"\x00" + struct.pack(">H", 0)
                    return b"P" + struct.pack(">I", len(body) + 4) + body

                def bind(portal, stmt, params):
                    body = portal.encode() + b"\x00" + stmt.encode() + b"\x00"
                    body += struct.pack(">H", 0)           # fmt codes
                    body += struct.pack(">H", len(params))
                    for p in params:
                        raw = p.encode()
                        body += struct.pack(">i", len(raw)) + raw
                    body += struct.pack(">H", 0)           # result fmts
                    return b"B" + struct.pack(">I", len(body) + 4) + body

                def execute(portal):
                    body = portal.encode() + b"\x00" + struct.pack(">i", 0)
                    return b"E" + struct.pack(">I", len(body) + 4) + body

                sync = b"S" + struct.pack(">I", 4)
                # INSERT via extended protocol with $1/$2
                writer.write(parse("s1", "INSERT INTO ep (k, v) VALUES "
                                         "($1, $2)"))
                writer.write(bind("", "s1", ["7", "it's bound"]))
                writer.write(execute(""))
                writer.write(sync)
                await writer.drain()
                msgs = await c.read_until(b"Z")
                tags = [t for t, _ in msgs]
                assert b"1" in tags and b"2" in tags and b"C" in tags
                # SELECT it back the same way
                writer.write(parse("s2", "SELECT v FROM ep WHERE k = $1"))
                writer.write(bind("", "s2", ["7"]))
                writer.write(execute(""))
                writer.write(sync)
                await writer.drain()
                msgs = await c.read_until(b"Z")
                assert c.rows(msgs) == [["it's bound"]]
                writer.close()
            finally:
                await srv.shutdown()
                await mc.shutdown()
        run(go())

    def test_binary_params_and_results(self, tmp_path):
        """Extended protocol with BINARY parameter and result formats
        (format code 1), the psycopg3-default mode: int8/float8/text
        params arrive big-endian, results return binary when Bind's
        result-format codes ask for it."""
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            srv = PgServer(mc.client())
            addr = await srv.start()
            try:
                reader, writer = await asyncio.open_connection(*addr)
                c = MiniPgClient(reader, writer)
                await c.startup()
                await c.query("CREATE TABLE bp (k bigint, v double, "
                              "s text, PRIMARY KEY (k))")
                await mc.wait_for_leaders("bp")

                def parse(name, sql, ptypes=()):
                    body = name.encode() + b"\x00" + sql.encode() + b"\x00"
                    body += struct.pack(">H", len(ptypes))
                    for t in ptypes:
                        body += struct.pack(">I", t)
                    return b"P" + struct.pack(">I", len(body) + 4) + body

                def bind(portal, stmt, raws, pfmts, rfmts):
                    body = portal.encode() + b"\x00" + stmt.encode() + b"\x00"
                    body += struct.pack(f">H{len(pfmts)}H", len(pfmts),
                                        *pfmts)
                    body += struct.pack(">H", len(raws))
                    for raw in raws:
                        body += struct.pack(">i", len(raw)) + raw
                    body += struct.pack(f">H{len(rfmts)}H", len(rfmts),
                                        *rfmts)
                    return b"B" + struct.pack(">I", len(body) + 4) + body

                def execute(portal):
                    body = portal.encode() + b"\x00" + struct.pack(">i", 0)
                    return b"E" + struct.pack(">I", len(body) + 4) + body

                sync = b"S" + struct.pack(">I", 4)
                # binary int8 + float8 + text params (OIDs declared)
                writer.write(parse("b1", "INSERT INTO bp (k, v, s) "
                                         "VALUES ($1, $2, $3)",
                                   (20, 701, 25)))
                writer.write(bind("", "b1",
                                  [struct.pack(">q", 42),
                                   struct.pack(">d", 2.75),
                                   b"bin"],
                                  (1, 1, 1), ()))
                writer.write(execute(""))
                writer.write(sync)
                await writer.drain()
                msgs = await c.read_until(b"Z")
                assert not any(t == b"E" for t, _ in msgs), msgs
                # read back with BINARY results (one code applies to all)
                writer.write(parse("b2", "SELECT k, v, s FROM bp "
                                         "WHERE k = $1", (20,)))
                writer.write(bind("", "b2", [struct.pack(">q", 42)],
                                  (1,), (1,)))
                writer.write(execute(""))
                writer.write(sync)
                await writer.drain()
                msgs = await c.read_until(b"Z")
                drow = next(b for t, b in msgs if t == b"D")
                (n,) = struct.unpack_from(">H", drow)
                assert n == 3
                pos = 2
                vals = []
                for _ in range(n):
                    (ln,) = struct.unpack_from(">i", drow, pos)
                    pos += 4
                    vals.append(drow[pos:pos + ln])
                    pos += ln
                assert struct.unpack(">q", vals[0])[0] == 42
                assert struct.unpack(">d", vals[1])[0] == 2.75
                assert vals[2] == b"bin"
                # RowDescription carries format code 1
                trow = next(b for t, b in msgs if t == b"T")
                assert trow[-2:] == struct.pack(">h", 1)
                writer.close()
            finally:
                await srv.shutdown()
                await mc.shutdown()
        run(go())
