"""External-driver conformance: real psycopg / cassandra-driver /
redis-py clients against a live cluster (reference role: the Java
client + loadtester tier, java/yb-pgsql/BasePgSQLTest.java,
java/yb-client — the layer that proves wire fidelity).

Each section skips when its driver isn't installed (none are baked into
the CI image); the suites run anywhere `pip install psycopg
cassandra-driver redis` is possible. The in-repo wire tests
(test_pg_wire.py, test_cql_breadth.py, test_redis_breadth.py) cover the
same framing byte-for-byte, so protocol drift is still caught without
the drivers — these add the actual-client handshake/behavior layer.
"""
import time

import pytest

from tests.driver_cluster import ClusterThread

psycopg = pytest.importorskip("psycopg", reason="psycopg not installed")


def _pg_cluster(tmp_path):
    from yugabyte_db_tpu.ql.pg_server import PgServer
    return ClusterThread(tmp_path, PgServer)


def test_psycopg_crud_and_prepared(tmp_path):
    with _pg_cluster(tmp_path) as ct:
        host, port = ct.addr
        with psycopg.connect(host=host, port=port, dbname="yb",
                             user="yb", autocommit=True) as conn:
            cur = conn.cursor()
            cur.execute("CREATE TABLE drv (k bigint, v double, s text, "
                        "PRIMARY KEY (k))")
            time.sleep(0.5)
            # extended protocol with parameters (binary in psycopg3)
            cur.execute("INSERT INTO drv (k, v, s) VALUES (%s, %s, %s)",
                        (1, 2.5, "one"))
            cur.execute("INSERT INTO drv (k, v, s) VALUES (%s, %s, %s)",
                        (2, 3.5, "two"))
            cur.execute("SELECT k, v, s FROM drv ORDER BY k")
            assert cur.fetchall() == [(1, 2.5, "one"), (2, 3.5, "two")]
            cur.execute("SELECT sum(v) FROM drv WHERE k >= %s", (1,))
            assert float(cur.fetchone()[0]) == 6.0
            # introspection through information_schema
            cur.execute("SELECT column_name FROM "
                        "information_schema.columns WHERE "
                        "table_name = 'drv' ORDER BY ordinal_position")
            assert [r[0] for r in cur.fetchall()] == ["k", "v", "s"]
            cur.execute("UPDATE drv SET v = 0 WHERE k = %s", (1,))
            cur.execute("DELETE FROM drv WHERE k = %s", (2,))
            cur.execute("SELECT count(*) FROM drv")
            assert int(cur.fetchone()[0]) == 1


def test_psycopg_txn(tmp_path):
    with _pg_cluster(tmp_path) as ct:
        host, port = ct.addr
        with psycopg.connect(host=host, port=port, dbname="yb",
                             user="yb", autocommit=True) as conn:
            cur = conn.cursor()
            cur.execute("CREATE TABLE drvt (k bigint, v double, "
                        "PRIMARY KEY (k))")
            time.sleep(0.5)
            cur.execute("INSERT INTO drvt (k, v) VALUES (1, 10)")
            cur.execute("BEGIN")
            cur.execute("UPDATE drvt SET v = 99 WHERE k = 1")
            cur.execute("ROLLBACK")
            time.sleep(0.3)
            cur.execute("SELECT v FROM drvt WHERE k = 1")
            assert float(cur.fetchone()[0]) == 10.0
