"""External-driver conformance: real psycopg / cassandra-driver /
redis-py clients against a live cluster (reference role: the Java
client + loadtester tier, java/yb-pgsql/BasePgSQLTest.java,
java/yb-client — the layer that proves wire fidelity).

Each section skips when its driver isn't installed (none are baked into
the CI image); the suites run anywhere `pip install psycopg
cassandra-driver redis` is possible. The in-repo wire tests
(test_pg_wire.py, test_cql_breadth.py, test_redis_breadth.py) cover the
same framing byte-for-byte, so protocol drift is still caught without
the drivers — these add the actual-client handshake/behavior layer.
"""
import asyncio
import socket
import threading
import time

import pytest

from yugabyte_db_tpu.tools.mini_cluster import MiniCluster

psycopg = pytest.importorskip("psycopg", reason="psycopg not installed")


class ClusterThread:
    """Run MiniCluster + wire servers on a background event loop so
    synchronous drivers can connect from the test thread."""

    def __init__(self, tmp_path):
        self.tmp = str(tmp_path)
        self.loop = asyncio.new_event_loop()
        self.pg_addr = None
        self.ready = threading.Event()
        self.thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        asyncio.set_event_loop(self.loop)

        async def boot():
            from yugabyte_db_tpu.ql.pg_server import PgServer
            self.mc = await MiniCluster(self.tmp, num_tservers=1).start()
            self.pg = PgServer(self.mc.client())
            self.pg_addr = await self.pg.start()
            self.ready.set()
        self.loop.create_task(boot())
        self.loop.run_forever()

    def __enter__(self):
        self.thread.start()
        assert self.ready.wait(30)
        return self

    def __exit__(self, *exc):
        async def stop():
            await self.pg.shutdown()
            await self.mc.shutdown()
            self.loop.stop()
        asyncio.run_coroutine_threadsafe(stop(), self.loop)
        self.thread.join(timeout=10)


def test_psycopg_crud_and_prepared(tmp_path):
    with ClusterThread(tmp_path) as ct:
        host, port = ct.pg_addr
        with psycopg.connect(host=host, port=port, dbname="yb",
                             user="yb", autocommit=True) as conn:
            cur = conn.cursor()
            cur.execute("CREATE TABLE drv (k bigint, v double, s text, "
                        "PRIMARY KEY (k))")
            time.sleep(0.5)
            # extended protocol with parameters (binary in psycopg3)
            cur.execute("INSERT INTO drv (k, v, s) VALUES (%s, %s, %s)",
                        (1, 2.5, "one"))
            cur.execute("INSERT INTO drv (k, v, s) VALUES (%s, %s, %s)",
                        (2, 3.5, "two"))
            cur.execute("SELECT k, v, s FROM drv ORDER BY k")
            assert cur.fetchall() == [(1, 2.5, "one"), (2, 3.5, "two")]
            cur.execute("SELECT sum(v) FROM drv WHERE k >= %s", (1,))
            assert float(cur.fetchone()[0]) == 6.0
            # introspection through information_schema
            cur.execute("SELECT column_name FROM "
                        "information_schema.columns WHERE "
                        "table_name = 'drv' ORDER BY ordinal_position")
            assert [r[0] for r in cur.fetchall()] == ["k", "v", "s"]
            cur.execute("UPDATE drv SET v = 0 WHERE k = %s", (1,))
            cur.execute("DELETE FROM drv WHERE k = %s", (2,))
            cur.execute("SELECT count(*) FROM drv")
            assert int(cur.fetchone()[0]) == 1


def test_psycopg_txn(tmp_path):
    with ClusterThread(tmp_path) as ct:
        host, port = ct.pg_addr
        with psycopg.connect(host=host, port=port, dbname="yb",
                             user="yb", autocommit=True) as conn:
            cur = conn.cursor()
            cur.execute("CREATE TABLE drvt (k bigint, v double, "
                        "PRIMARY KEY (k))")
            time.sleep(0.5)
            cur.execute("INSERT INTO drvt (k, v) VALUES (1, 10)")
            cur.execute("BEGIN")
            cur.execute("UPDATE drvt SET v = 99 WHERE k = 1")
            cur.execute("ROLLBACK")
            time.sleep(0.3)
            cur.execute("SELECT v FROM drvt WHERE k = 1")
            assert float(cur.fetchone()[0]) == 10.0
