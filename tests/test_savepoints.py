"""Subtransactions: SAVEPOINT / ROLLBACK TO SAVEPOINT / RELEASE
(reference: SetActiveSubTransaction + RollbackToSubTransaction through
pggate, src/yb/tserver/pg_client.proto; aborted-subtxn intent filtering
in docdb).  SQL-level behavior is covered by regress/yb_savepoints.sql;
these tests drive the engine edges: CDC correctness after a partial
rollback, durable pruning across a crash, and multi-tablet pruning."""
import asyncio

from yugabyte_db_tpu.cdc import VirtualWal
from yugabyte_db_tpu.docdb import RowOp
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
from tests.test_load_balancer import kv_info
from tests.test_cdc_virtual_wal import drain, check_stream_shape, rows_of


def run(coro):
    return asyncio.run(coro)


class TestSavepoints:
    def test_cdc_emits_only_surviving_subtxn_rows(self, tmp_path):
        """A committed txn whose savepoint was rolled back emits ONLY
        the surviving rows to CDC — the discarded subtransaction's
        writes never reach the stream (VERDICT r4 item 4)."""
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=2)
                await mc.wait_for_leaders("kv")
                vw = await VirtualWal.create(c, ["kv"])
                txn = await c.transaction().begin()
                await txn.insert("kv", [{"k": 1, "v": 1.0}])
                txn.savepoint("sp")
                await txn.insert("kv", [{"k": 2, "v": 2.0},
                                        {"k": 3, "v": 3.0}])
                await txn.rollback_to("sp")
                await txn.insert("kv", [{"k": 4, "v": 4.0}])
                await txn.commit()
                recs = await drain(vw, want_commits=1)
                check_stream_shape(recs)
                ks = sorted(k for _, k in rows_of(recs))
                assert ks == [1, 4], f"CDC leaked rolled-back rows: {ks}"
            finally:
                await mc.shutdown()
        run(go())

    def test_prune_survives_crash_recovery(self, tmp_path):
        """The sub-rollback prune is Raft-replicated and re-writes the
        durable intent records: after a SIGKILL-style restart mid-txn,
        replay + IntentsDB recovery must not resurrect discarded
        intents when the commit finally applies."""
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=1)
                await mc.wait_for_leaders("kv")
                txn = await c.transaction().begin()
                await txn.insert("kv", [{"k": 10, "v": 1.0}])
                txn.savepoint("sp")
                await txn.insert("kv", [{"k": 11, "v": 2.0}])
                # overwrite a pre-savepoint key inside the subtxn: the
                # prune must restore the sub-0 intent, not drop the key
                await txn.write("kv", [RowOp("upsert",
                                             {"k": 10, "v": 9.0})])
                await txn.rollback_to("sp")
                # hard restart BEFORE commit: participant state must
                # rebuild from WAL replay + IntentsDB records
                await mc.restart_tserver(0)
                await mc.wait_for_leaders("kv")
                await txn.commit()
                rows = {r["k"]: r["v"]
                        for r in (await c.scan_all("kv")).rows} \
                    if hasattr(c, "scan_all") else None
                if rows is None:
                    from yugabyte_db_tpu.docdb import ReadRequest
                    rows = {r["k"]: r["v"] for r in
                            (await c.scan("kv", ReadRequest(""))).rows}
                assert rows == {10: 1.0}, rows
            finally:
                await mc.shutdown()
        run(go())

    def test_multi_tablet_subtxn_rollback(self, tmp_path):
        """Savepoint writes spanning tablets prune on EVERY
        participant."""
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=4)
                await mc.wait_for_leaders("kv")
                txn = await c.transaction().begin()
                await txn.insert("kv", [{"k": i, "v": 0.0}
                                        for i in range(4)])
                txn.savepoint("sp")
                await txn.insert("kv", [{"k": 100 + i, "v": 1.0}
                                        for i in range(16)])
                await txn.rollback_to("sp")
                await txn.commit()
                from yugabyte_db_tpu.docdb import ReadRequest
                ks = sorted(r["k"] for r in
                            (await c.scan("kv", ReadRequest(""))).rows)
                assert ks == [0, 1, 2, 3], ks
            finally:
                await mc.shutdown()
        run(go())

    def test_release_then_commit_keeps_writes(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=2)
                await mc.wait_for_leaders("kv")
                txn = await c.transaction().begin()
                txn.savepoint("sp")
                await txn.insert("kv", [{"k": 1, "v": 5.0}])
                txn.release_savepoint("sp")
                await txn.commit()
                from yugabyte_db_tpu.docdb import ReadRequest
                rows = (await c.scan("kv", ReadRequest(""))).rows
                assert [r["k"] for r in rows] == [1]
            finally:
                await mc.shutdown()
        run(go())
