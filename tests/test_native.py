"""Native library cross-checks: C++ paths must be byte-exact with the
Python fallbacks (both stay live; reference keeps everything in C++ —
src/yb/rocksdb block builder, util/bloom, table/merger.cc)."""
import numpy as np
import pytest

from yugabyte_db_tpu.storage import native_lib as nl
from yugabyte_db_tpu.storage.columnar import fnv64_bytes
from yugabyte_db_tpu.storage.sst import BloomFilter

pytestmark = pytest.mark.skipif(not nl.available(),
                                reason="native lib not built")


class TestNative:
    def test_fnv_matches_python(self):
        keys = [b"", b"a", b"hello world", bytes(range(256)) * 3]
        out = nl.fnv64_batch(keys)
        for k, h in zip(keys, out):
            assert int(h) == fnv64_bytes(k)

    def test_block_roundtrip_prefix_compression(self):
        import random
        rng = random.Random(4)
        entries = sorted(
            (bytes([0x24]) + rng.randbytes(8), rng.randbytes(rng.randint(0, 40)))
            for _ in range(500))
        enc = nl.block_encode(entries)
        assert nl.block_decode(enc) == entries

    def test_bloom_matches_python_build(self):
        rng = np.random.default_rng(0)
        hashes = rng.integers(0, 2**63, 1000).astype(np.uint64)
        py = BloomFilter.build(hashes, bits_per_key=10)
        nat_bits = nl.bloom_build(hashes, len(py.bits) * 8, py.k)
        np.testing.assert_array_equal(nat_bits, py.bits)

    def test_kway_merge_dedup(self):
        runs = [[b"a", b"c", b"x"], [b"b", b"c"], [b"c", b"d"]]
        idx, dup = nl.kway_merge(runs)
        flat = [k for r in runs for k in r]
        merged = [flat[i] for i, d in zip(idx, dup) if not d]
        assert merged == [b"a", b"b", b"c", b"d", b"x"]
        # the surviving c comes from the newest run (run 0)
        c_pos = merged.index(b"c")
        surviving = [flat[i] for i, d in zip(idx, dup) if not d]
        assert idx[list(dup).index(True) - 1] == 1  # run0's 'c' kept first
