"""Secondary index tests: create+backfill, maintenance on writes,
index-accelerated SQL lookups (reference analog: index scans via
yb_lsm.c + online backfill)."""
import asyncio

import pytest

from yugabyte_db_tpu.ql import SqlSession
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster


def run(coro):
    return asyncio.run(coro)


class TestSecondaryIndex:
    def test_backfill_lookup_and_maintenance(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute(
                    "CREATE TABLE users (id bigint, email text, age int, "
                    "PRIMARY KEY (id)) WITH tablets = 2")
                await mc.wait_for_leaders("users")
                await s.execute(
                    "INSERT INTO users (id, email, age) VALUES "
                    "(1, 'a@x.com', 30), (2, 'b@x.com', 40), "
                    "(3, 'c@x.com', 30)")
                # create + backfill
                r = await s.execute(
                    "CREATE INDEX users_by_email ON users (email)")
                assert "3 rows" in r.status
                await mc.wait_for_leaders("users_by_email")
                # fresh session so the meta cache sees the index
                s2 = SqlSession(mc.client())
                r = await s2.execute(
                    "SELECT id, age FROM users WHERE email = 'b@x.com'")
                assert len(r.rows) == 1 and r.rows[0]["id"] == 2
                # maintenance: new row becomes findable via the index
                await s2.execute("INSERT INTO users (id, email, age) VALUES "
                                 "(4, 'd@x.com', 50)")
                r = await s2.execute(
                    "SELECT id FROM users WHERE email = 'd@x.com'")
                assert [row["id"] for row in r.rows] == [4]
                # update moves the index entry
                await s2.execute(
                    "UPDATE users SET email = 'z@x.com' WHERE id = 1")
                r = await s2.execute(
                    "SELECT id FROM users WHERE email = 'a@x.com'")
                assert r.rows == []
                r = await s2.execute(
                    "SELECT id FROM users WHERE email = 'z@x.com'")
                assert [row["id"] for row in r.rows] == [1]
                # delete removes the entry
                await s2.execute("DELETE FROM users WHERE id = 2")
                r = await s2.execute(
                    "SELECT id FROM users WHERE email = 'b@x.com'")
                assert r.rows == []
                # residual predicate on top of the index
                r = await s2.execute(
                    "SELECT id FROM users WHERE email = 'z@x.com' "
                    "AND age > 100")
                assert r.rows == []
            finally:
                await mc.shutdown()
        run(go())

    def test_index_lookup_api(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                s = SqlSession(c)
                await s.execute(
                    "CREATE TABLE ev (id bigint, kind text, "
                    "PRIMARY KEY (id))")
                await mc.wait_for_leaders("ev")
                await s.execute(
                    "INSERT INTO ev (id, kind) VALUES (1, 'click'), "
                    "(2, 'view'), (3, 'click')")
                await c.create_secondary_index("ev", "ev_by_kind", "kind")
                await mc.wait_for_leaders("ev_by_kind")
                c2 = mc.client()
                pks = await c2.index_lookup("ev", "ev_by_kind", "click")
                assert sorted(p["id"] for p in pks) == [1, 3]
            finally:
                await mc.shutdown()
        run(go())
