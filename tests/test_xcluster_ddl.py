"""xCluster DDL replication: source schema changes mirror onto the
target before the affected row images apply (reference: xCluster
automatic-mode DDL replication,
master/xcluster/xcluster_ddl_queue_handler.cc)."""
import asyncio

from yugabyte_db_tpu.cdc import XClusterReplicator
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
from tests.test_load_balancer import kv_info


def run(coro):
    return asyncio.run(coro)


async def _drain(repl, want, rounds=40):
    n = 0
    for _ in range(rounds):
        n += await repl.step()
        if n >= want:
            return n
        await asyncio.sleep(0.05)
    return n


class TestXClusterDdl:
    def test_add_column_replicates_with_data(self, tmp_path):
        async def go():
            src = await MiniCluster(str(tmp_path / "src"),
                                    num_tservers=1).start()
            dst = await MiniCluster(str(tmp_path / "dst"),
                                    num_tservers=1).start()
            try:
                cs, cd = src.client(), dst.client()
                await cs.create_table(kv_info(), num_tablets=2)
                await src.wait_for_leaders("kv")
                repl = XClusterReplicator(cs, cd, "kv",
                                          poll_interval=0.05)
                await repl.ensure_target_table()
                await dst.wait_for_leaders("kv")
                await cs.insert("kv", [{"k": 1, "v": 1.0}])
                assert await _drain(repl, 1) >= 1
                # DDL on the source, then rows that USE the new column
                await cs.alter_table_add_columns("kv",
                                                 [("tag", "string")])
                await cs.insert("kv", [{"k": 2, "v": 2.0,
                                        "tag": "fresh"}])
                assert await _drain(repl, 1) >= 1
                row = await cd.get("kv", {"k": 2})
                assert row is not None and row["tag"] == "fresh", row
                # pre-DDL row reads as NULL in the new column
                row1 = await cd.get("kv", {"k": 1})
                assert row1 is not None and row1.get("tag") is None
            finally:
                await src.shutdown()
                await dst.shutdown()
        run(go())

    def test_drop_column_replicates(self, tmp_path):
        async def go():
            src = await MiniCluster(str(tmp_path / "src"),
                                    num_tservers=1).start()
            dst = await MiniCluster(str(tmp_path / "dst"),
                                    num_tservers=1).start()
            try:
                cs, cd = src.client(), dst.client()
                await cs.create_table(kv_info(), num_tablets=2)
                await src.wait_for_leaders("kv")
                repl = XClusterReplicator(cs, cd, "kv",
                                          poll_interval=0.05)
                await repl.ensure_target_table()
                await dst.wait_for_leaders("kv")
                await cs.insert("kv", [{"k": 1, "v": 1.0}])
                assert await _drain(repl, 1) >= 1
                await cs.alter_table_drop_columns("kv", ["v"])
                await cs.insert("kv", [{"k": 2}])
                assert await _drain(repl, 1) >= 1
                tgt = await cd._table("kv", refresh=True)
                names = [c.name for c in tgt.info.schema.columns]
                assert "v" not in names, names
                assert await cd.get("kv", {"k": 2}) is not None
            finally:
                await src.shutdown()
                await dst.shutdown()
        run(go())
