"""UNIQUE constraints and FK-lite (reference: unique-index enforcement
through yb_access/yb_lsm.c:233-366 — the index doc key IS the indexed
value so duplicates collide — and FK checks through the PG executor).

The headline property (VERDICT r4 item 5): two CONCURRENT inserts of
the same unique key cannot both commit."""
import asyncio

import pytest

from yugabyte_db_tpu.docdb import ReadRequest, RowOp
from yugabyte_db_tpu.docdb.table_codec import TableInfo
from yugabyte_db_tpu.dockv.packed_row import (
    ColumnSchema, ColumnType, TableSchema,
)
from yugabyte_db_tpu.dockv.partition import PartitionSchema
from yugabyte_db_tpu.rpc import RpcError
from yugabyte_db_tpu.ql.executor import SqlSession
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster


def users_info():
    schema = TableSchema(columns=(
        ColumnSchema(0, "id", ColumnType.INT64, is_hash_key=True),
        ColumnSchema(1, "email", ColumnType.STRING),
    ), version=1)
    return TableInfo("", "users", schema, PartitionSchema("hash", 1))


def run(coro):
    return asyncio.run(coro)


async def make_cluster(root):
    mc = await MiniCluster(root, num_tservers=1).start()
    c = mc.client()
    await c.create_table(users_info(), num_tablets=2)
    await mc.wait_for_leaders("users")
    await c.create_secondary_index("users", "users_email_key", "email",
                                   unique=True)
    await mc.wait_for_leaders("users_email_key")
    return mc, c


class TestUniqueConstraint:
    def test_concurrent_duplicate_inserts_exactly_one_commits(
            self, tmp_path):
        async def go():
            mc, c = await make_cluster(str(tmp_path))
            try:
                async def ins(i):
                    try:
                        await c.insert("users", [
                            {"id": i, "email": "a@x"}])
                        return True
                    except RpcError as e:
                        assert e.code == "DUPLICATE_KEY", e
                        return False
                oks = await asyncio.gather(*[ins(i) for i in range(8)])
                assert sum(oks) == 1, oks
                rows = (await c.scan("users", ReadRequest(""))).rows
                assert len(rows) == 1
            finally:
                await mc.shutdown()
        run(go())

    def test_concurrent_txn_duplicate_exactly_one_commits(self, tmp_path):
        async def go():
            mc, c = await make_cluster(str(tmp_path))
            try:
                # status tablet up front
                await c.messenger.call(mc.master.messenger.addr,
                                       "master", "get_status_tablet", {})
                await mc.wait_for_leaders("system.transactions")

                async def ins(i):
                    txn = await c.transaction().begin()
                    try:
                        await txn.insert("users", [
                            {"id": 100 + i, "email": "txn@x"}])
                        await txn.commit()
                        return True
                    except RpcError:
                        try:
                            await txn.abort()
                        except Exception:   # noqa: BLE001
                            pass
                        return False
                oks = await asyncio.gather(*[ins(i) for i in range(4)])
                assert sum(oks) == 1, oks
                rows = [r for r in
                        (await c.scan("users", ReadRequest(""))).rows
                        if r["email"] == "txn@x"]
                assert len(rows) == 1
            finally:
                await mc.shutdown()
        run(go())

    def test_sequential_duplicate_rejected_and_freed_by_delete(
            self, tmp_path):
        async def go():
            mc, c = await make_cluster(str(tmp_path))
            try:
                await c.insert("users", [{"id": 1, "email": "b@x"}])
                with pytest.raises(RpcError) as ei:
                    await c.insert("users", [{"id": 2, "email": "b@x"}])
                assert ei.value.code == "DUPLICATE_KEY"
                # same row upsert with the same value is NOT a duplicate
                await c.insert("users", [{"id": 1, "email": "b@x"}])
                # delete frees the value for reuse
                await c.delete("users", [{"id": 1}])
                await c.insert("users", [{"id": 3, "email": "b@x"}])
                # changing the value frees the old one
                await c.insert("users", [{"id": 3, "email": "c@x"}])
                await c.insert("users", [{"id": 4, "email": "b@x"}])
            finally:
                await mc.shutdown()
        run(go())

    def test_unique_backfill_rejects_existing_duplicates(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(users_info(), num_tablets=1)
                await mc.wait_for_leaders("users")
                await c.insert("users", [{"id": 1, "email": "d@x"},
                                         {"id": 2, "email": "d@x"}])
                with pytest.raises(RpcError):
                    await c.create_secondary_index(
                        "users", "users_email_key", "email",
                        unique=True)
            finally:
                await mc.shutdown()
        run(go())


class TestSqlConstraints:
    def test_unique_column_and_fk(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute(
                    "CREATE TABLE dept (id bigint PRIMARY KEY, "
                    "name text UNIQUE) WITH tablets = 1")
                await s.execute(
                    "CREATE TABLE emp (id bigint PRIMARY KEY, "
                    "dept_id bigint REFERENCES dept (id)) "
                    "WITH tablets = 1")
                await s.execute(
                    "INSERT INTO dept (id, name) VALUES (1, 'eng')")
                with pytest.raises(RpcError) as ei:
                    await s.execute("INSERT INTO dept (id, name) "
                                    "VALUES (2, 'eng')")
                assert ei.value.code == "DUPLICATE_KEY"
                await s.execute(
                    "INSERT INTO emp (id, dept_id) VALUES (10, 1)")
                with pytest.raises(ValueError,
                                   match="foreign key"):
                    await s.execute("INSERT INTO emp (id, dept_id) "
                                    "VALUES (11, 99)")
                # NULL FK is valid
                await s.execute("INSERT INTO emp (id, dept_id) "
                                "VALUES (12, NULL)")
                with pytest.raises(ValueError, match="foreign key"):
                    await s.execute(
                        "UPDATE emp SET dept_id = 42 WHERE id = 10")
                r = await s.execute(
                    "SELECT count(*) FROM emp")
                assert r.rows[0]["count"] == 2
            finally:
                await mc.shutdown()
        run(go())

    def test_create_unique_index_sql(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute(
                    "CREATE TABLE t (k bigint PRIMARY KEY, v text) "
                    "WITH tablets = 1")
                await s.execute("INSERT INTO t (k, v) VALUES (1, 'a')")
                await s.execute(
                    "CREATE UNIQUE INDEX t_v_key ON t (v)")
                with pytest.raises(RpcError):
                    await s.execute(
                        "INSERT INTO t (k, v) VALUES (2, 'a')")
                await s.execute("INSERT INTO t (k, v) VALUES (2, 'b')")
            finally:
                await mc.shutdown()
        run(go())

    def test_unique_inside_txn_savepoint(self, tmp_path):
        """Unique enforcement composes with subtransactions: a
        duplicate in a rolled-back savepoint does not poison the txn's
        later legitimate insert."""
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute(
                    "CREATE TABLE u (k bigint PRIMARY KEY, "
                    "v text UNIQUE) WITH tablets = 1")
                await s.execute("INSERT INTO u (k, v) VALUES (1, 'x')")
                await s.execute("BEGIN")
                await s.execute("SAVEPOINT sp")
                with pytest.raises(RpcError):
                    await s.execute(
                        "INSERT INTO u (k, v) VALUES (2, 'x')")
                await s.execute("ROLLBACK TO SAVEPOINT sp")
                await s.execute("INSERT INTO u (k, v) VALUES (3, 'y')")
                await s.execute("COMMIT")
                r = await s.execute("SELECT count(*) FROM u")
                assert r.rows[0]["count"] == 2
            finally:
                await mc.shutdown()
        run(go())

    def test_multirow_insert_duplicate_in_one_statement(self, tmp_path):
        """Two rows with the same unique value in ONE statement must
        fail (within-batch insert-if-absent), txn and non-txn."""
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute(
                    "CREATE TABLE m (k bigint PRIMARY KEY, "
                    "v text UNIQUE) WITH tablets = 1")
                with pytest.raises(RpcError):
                    await s.execute("INSERT INTO m (k, v) "
                                    "VALUES (1, 'z'), (2, 'z')")
                await s.execute("BEGIN")
                with pytest.raises(RpcError):
                    await s.execute("INSERT INTO m (k, v) "
                                    "VALUES (3, 'w'), (4, 'w')")
                await s.execute("ROLLBACK")
                r = await s.execute("SELECT count(*) FROM m")
                assert r.rows[0]["count"] == 0
            finally:
                await mc.shutdown()
        run(go())

    def test_unique_violation_leaves_no_ghost_index_intent(self,
                                                           tmp_path):
        """With TWO indexes, a unique violation on the second must roll
        back the first index's intent (implicit per-statement subtxn) —
        a later COMMIT must not publish a ghost entry."""
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            try:
                c = mc.client()
                s = SqlSession(c)
                await s.execute(
                    "CREATE TABLE g (k bigint PRIMARY KEY, a text, "
                    "b text UNIQUE) WITH tablets = 1")
                await s.execute("CREATE INDEX g_a ON g (a)")
                await s.execute(
                    "INSERT INTO g (k, a, b) VALUES (1, 'p', 'q')")
                await s.execute("BEGIN")
                with pytest.raises(RpcError):
                    await s.execute("INSERT INTO g (k, a, b) "
                                    "VALUES (2, 'pp', 'q')")
                await s.execute("INSERT INTO g (k, a, b) "
                                "VALUES (3, 'r', 's')")
                await s.execute("COMMIT")
                # the non-unique index must NOT contain the rolled-back
                # row's entry ('pp' -> k=2)
                pks = await c.index_lookup("g", "g_a", "pp")
                assert pks == [], pks
                pks = await c.index_lookup("g", "g_a", "r")
                assert [p["k"] for p in pks] == [3]
            finally:
                await mc.shutdown()
        run(go())

    def test_self_referential_fk(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute(
                    "CREATE TABLE emp2 (id bigint PRIMARY KEY, "
                    "mgr bigint REFERENCES emp2 (id)) WITH tablets = 1")
                await s.execute(
                    "INSERT INTO emp2 (id, mgr) VALUES (1, NULL)")
                await s.execute(
                    "INSERT INTO emp2 (id, mgr) VALUES (2, 1)")
                with pytest.raises(ValueError, match="foreign key"):
                    await s.execute(
                        "INSERT INTO emp2 (id, mgr) VALUES (3, 99)")
            finally:
                await mc.shutdown()
        run(go())

    def test_unique_update_move_failure_keeps_old_entry(self, tmp_path):
        """UPDATE moving a unique value onto a taken one must fail
        WITHOUT un-indexing the old value (inserts run before deletes,
        in separate batches)."""
        async def go():
            mc, c = await make_cluster(str(tmp_path))
            try:
                await c.insert("users", [{"id": 1, "email": "x@x"},
                                         {"id": 2, "email": "y@x"}])
                with pytest.raises(RpcError):
                    await c.insert("users", [{"id": 1, "email": "y@x"}])
                # x@x must still be indexed to row 1
                pks = await c.index_lookup("users", "users_email_key",
                                           "x@x")
                assert [p["id"] for p in pks] == [1]
                row = await c.get("users", {"id": 1})
                assert row["email"] == "x@x"
            finally:
                await mc.shutdown()
        run(go())

    def test_multi_index_partial_failure_undoes_earlier(self, tmp_path):
        """Non-txn insert: when a later unique index rejects, entries
        already written to earlier indexes are compensated away."""
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            try:
                c = mc.client()
                s = SqlSession(c)
                await s.execute(
                    "CREATE TABLE mi (k bigint PRIMARY KEY, a text, "
                    "b text) WITH tablets = 1")
                await s.execute("CREATE INDEX mi_a ON mi (a)")
                await s.execute("CREATE UNIQUE INDEX mi_b ON mi (b)")
                await s.execute(
                    "INSERT INTO mi (k, a, b) VALUES (1, 'p', 'u')")
                with pytest.raises(RpcError):
                    await s.execute("INSERT INTO mi (k, a, b) "
                                    "VALUES (2, 'q', 'u')")
                pks = await c.index_lookup("mi", "mi_a", "q")
                assert pks == [], pks     # no orphan in mi_a
            finally:
                await mc.shutdown()
        run(go())

    def test_failed_unique_backfill_deregisters_index(self, tmp_path):
        """A CREATE UNIQUE INDEX that fails on pre-existing duplicates
        must leave NO registered index behind: later inserts are not
        gated, and the index can be recreated after the fix."""
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(users_info(), num_tablets=1)
                await mc.wait_for_leaders("users")
                await c.insert("users", [{"id": 1, "email": "d@x"},
                                         {"id": 2, "email": "d@x"}])
                with pytest.raises(RpcError):
                    await c.create_secondary_index(
                        "users", "u_email", "email", unique=True)
                # no half-registered gate: same value inserts twice
                await c.insert("users", [{"id": 3, "email": "e@x"}])
                await c.insert("users", [{"id": 4, "email": "e@x"}])
                # fix the duplicates, recreate cleanly
                await c.delete("users", [{"id": 2}, {"id": 4}])
                n = await c.create_secondary_index(
                    "users", "u_email", "email", unique=True)
                assert n == 2
                with pytest.raises(RpcError):
                    await c.insert("users", [{"id": 5, "email": "d@x"}])
            finally:
                await mc.shutdown()
        run(go())

    def test_self_ref_fk_same_statement(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute(
                    "CREATE TABLE emp3 (id bigint PRIMARY KEY, "
                    "mgr bigint REFERENCES emp3 (id)) WITH tablets = 1")
                # later row references an earlier row of the SAME
                # statement, and a row references itself (PG-legal)
                await s.execute("INSERT INTO emp3 (id, mgr) "
                                "VALUES (5, 5), (6, 5)")
                r = await s.execute("SELECT count(*) FROM emp3")
                assert r.rows[0]["count"] == 2
            finally:
                await mc.shutdown()
        run(go())

    def test_fk_restrict_parent_delete(self, tmp_path):
        """Parent-delete RESTRICT: committed children block, txn-view
        children count (deleted don't, added do), self-referential
        statements pass (PG NO ACTION shape)."""
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute("CREATE TABLE pp (id bigint PRIMARY "
                                "KEY, n text) WITH tablets = 1")
                await s.execute(
                    "CREATE TABLE cc (id bigint PRIMARY KEY, p_id "
                    "bigint REFERENCES pp (id)) WITH tablets = 1")
                await s.execute(
                    "INSERT INTO pp (id, n) VALUES (1, 'a'), (2, 'b')")
                await s.execute("INSERT INTO cc (id, p_id) VALUES "
                                "(10, 1)")
                with pytest.raises(ValueError, match="still referenced"):
                    await s.execute("DELETE FROM pp WHERE id = 1")
                await s.execute("DELETE FROM pp WHERE id = 2")
                # txn: child-then-parent in one txn is legal
                await s.execute("BEGIN")
                await s.execute("DELETE FROM cc WHERE id = 10")
                await s.execute("DELETE FROM pp WHERE id = 1")
                await s.execute("COMMIT")
                # txn-added child blocks the parent delete
                await s.execute("INSERT INTO pp (id, n) VALUES (5, 'e')")
                await s.execute("BEGIN")
                await s.execute("INSERT INTO cc (id, p_id) VALUES "
                                "(50, 5)")
                with pytest.raises(ValueError, match="still referenced"):
                    await s.execute("DELETE FROM pp WHERE id = 5")
                await s.execute("ROLLBACK")
                # self-referential row deletes cleanly
                await s.execute(
                    "CREATE TABLE se (id bigint PRIMARY KEY, mgr "
                    "bigint REFERENCES se (id)) WITH tablets = 1")
                await s.execute("INSERT INTO se (id, mgr) VALUES (1, 1)")
                await s.execute("DELETE FROM se WHERE id = 1")
            finally:
                await mc.shutdown()
        run(go())

    def test_for_share_readers_coexist_writers_wait(self, tmp_path):
        """FOR SHARE: shared row locks under any isolation — readers
        never block readers, a writer conflicts with live holders
        (reference: FOR SHARE row marks as kStrongRead intents)."""
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            try:
                c = mc.client()
                s1, s2 = SqlSession(c), SqlSession(c)
                await s1.execute("CREATE TABLE fs (k bigint PRIMARY "
                                 "KEY, v bigint) WITH tablets = 1")
                await s1.execute("INSERT INTO fs (k, v) VALUES (1, 10)")
                await c.messenger.call(mc.master.messenger.addr,
                                       "master", "get_status_tablet", {})
                await mc.wait_for_leaders("system.transactions")
                await s1.execute("BEGIN")
                await s2.execute("BEGIN")
                r1 = await s1.execute(
                    "SELECT v FROM fs WHERE k = 1 FOR SHARE")
                r2 = await s2.execute(
                    "SELECT v FROM fs WHERE k = 1 FOR SHARE")
                assert r1.rows == r2.rows == [{"v": 10}]
                # a writer in a THIRD session conflicts while the
                # share locks are live (this is the teeth of the test:
                # it fails if lock_rows(force=True) stops locking)
                s3 = SqlSession(c)
                await s3.execute("BEGIN")
                with pytest.raises(RpcError):
                    await s3.execute(
                        "UPDATE fs SET v = 77 WHERE k = 1")
                try:
                    await s3.execute("ROLLBACK")
                except Exception:   # noqa: BLE001 — already aborted
                    pass
                # s2 releases; s1 (a holder itself) can then write
                await s2.execute("COMMIT")
                await s1.execute("UPDATE fs SET v = 99 WHERE k = 1")
                await s1.execute("COMMIT")
                r = await s1.execute("SELECT v FROM fs WHERE k = 1")
                assert r.rows == [{"v": 99}]
            finally:
                await mc.shutdown()
        run(go())

    def test_composite_unique_and_index(self, tmp_path):
        """Multi-column UNIQUE + composite secondary indexes: the
        index doc key is the full value tuple (first column hashed,
        rest range), so duplicates collide on the whole tuple while
        partial matches insert freely; prefix lookups narrow by every
        provided column."""
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            try:
                c = mc.client()
                s = SqlSession(c)
                await s.execute(
                    "CREATE TABLE co (id bigint PRIMARY KEY, a bigint, "
                    "b text, q bigint, UNIQUE (a, b)) WITH tablets = 2")
                await s.execute("INSERT INTO co (id, a, b, q) VALUES "
                                "(1, 7, 'x', 2), (2, 7, 'y', 3), "
                                "(3, 8, 'x', 1)")
                with pytest.raises(RpcError):
                    await s.execute("INSERT INTO co (id, a, b, q) "
                                    "VALUES (4, 7, 'x', 9)")
                # same first column, different second: fine
                await s.execute("INSERT INTO co (id, a, b, q) VALUES "
                                "(5, 7, 'z', 1)")
                # tuple freed by moving one component
                await s.execute("UPDATE co SET b = 'w' WHERE id = 1")
                await s.execute("INSERT INTO co (id, a, b, q) VALUES "
                                "(6, 7, 'x', 4)")
                # ON CONFLICT arbitrates on the composite unique
                await s.execute(
                    "INSERT INTO co (id, a, b, q) VALUES (9, 7, 'z', 5)"
                    " ON CONFLICT (a) DO UPDATE SET q = q + excluded.q")
                r = await s.execute("SELECT q FROM co WHERE id = 5")
                assert r.rows == [{"q": 6}]
                # composite non-unique index: full and prefix lookups
                await s.execute("CREATE INDEX co_aq ON co (a, q)")
                pks = await c.index_lookup("co", "co_aq", [7, 6])
                assert [p["id"] for p in pks] == [5]
                pks = sorted(p["id"] for p in
                             await c.index_lookup("co", "co_aq", [7]))
                assert pks == [1, 2, 5, 6]
                # string components end with terminators: 'c' is not a
                # prefix-match of 'cd'
                await s.execute("INSERT INTO co (id, a, b, q) VALUES "
                                "(11, 9, 'cd', 1), (12, 9, 'c', 1)")
            finally:
                await mc.shutdown()
        run(go())


class TestDropIndex:
    def test_drop_index_sql(self, tmp_path):
        """DROP INDEX deregisters the index (writes stop maintaining
        it, the planner stops using it), drops its backing table, and
        frees the name for re-creation; IF EXISTS forgives absence
        (reference: DROP INDEX -> master DeleteTable on the index
        relation, src/yb/master/catalog_manager.cc)."""
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            try:
                c = mc.client()
                s = SqlSession(c)
                await s.execute(
                    "CREATE TABLE di (k bigint PRIMARY KEY, a text) "
                    "WITH tablets = 1")
                await s.execute("CREATE INDEX di_a ON di (a)")
                await s.execute(
                    "INSERT INTO di (k, a) VALUES (1, 'x'), (2, 'y')")
                r = await s.execute("EXPLAIN SELECT k FROM di "
                                    "WHERE a = 'x'")
                assert "Index Lookup" in r.rows[0]["QUERY PLAN"]
                await s.execute("DROP INDEX di_a")
                # planner reverts to seq scan; queries still answer
                r = await s.execute("EXPLAIN SELECT k FROM di "
                                    "WHERE a = 'x'")
                assert "Seq Scan" in r.rows[0]["QUERY PLAN"]
                r = await s.execute("SELECT k FROM di WHERE a = 'x'")
                assert [x["k"] for x in r.rows] == [1]
                # the backing table is gone from the catalog
                names = {t["name"] for t in await c.list_tables()}
                assert "di_a" not in names
                # writes no longer maintain the dropped index; the
                # name is free for a fresh index that backfills anew
                await s.execute(
                    "INSERT INTO di (k, a) VALUES (3, 'z')")
                await s.execute("CREATE INDEX di_a ON di (a)")
                pks = await c.index_lookup("di", "di_a", "z")
                assert [p["k"] for p in pks] == [3]
                await s.execute("DROP INDEX di_a")
                with pytest.raises(Exception):
                    await s.execute("DROP INDEX di_a")
                await s.execute("DROP INDEX IF EXISTS di_a")
            finally:
                await mc.shutdown()
        asyncio.run(go())

    def test_concurrent_drop_heals_other_clients_cache(self, tmp_path):
        """A client that cached the index list before another session
        ran DROP INDEX must not fail its base-table writes forever:
        the NOT_FOUND from the dead index table triggers a catalog
        refresh and the write proceeds (both txn and non-txn paths)."""
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            try:
                a, b = mc.client(), mc.client()
                sa = SqlSession(a)
                await sa.execute(
                    "CREATE TABLE cd (k bigint PRIMARY KEY, a text) "
                    "WITH tablets = 1")
                await sa.execute("CREATE INDEX cd_a ON cd (a)")
                # B populates its cache with the index registered
                await b.write("cd", [RowOp("upsert",
                                           {"k": 1, "a": "x"})])
                assert (await b._table("cd")).indexes
                await sa.execute("DROP INDEX cd_a")
                # non-txn write through B's stale cache must succeed
                await b.write("cd", [RowOp("upsert",
                                           {"k": 2, "a": "y"})])
                assert not (await b._table("cd")).indexes
                # and a txn write from a third stale client too
                c = mc.client()
                await sa.execute("CREATE INDEX cd_a ON cd (a)")
                await c.write("cd", [RowOp("upsert",
                                           {"k": 3, "a": "z"})])
                await sa.execute("DROP INDEX cd_a")
                sc = SqlSession(c)
                await sc.execute("BEGIN")
                await sc.execute(
                    "INSERT INTO cd (k, a) VALUES (4, 'w')")
                await sc.execute("COMMIT")
                r = await sc.execute("SELECT count(*) FROM cd")
                assert r.rows[0]["count"] == 4
            finally:
                await mc.shutdown()
        asyncio.run(go())


class TestFkActions:
    """ON DELETE CASCADE / SET NULL referential actions (reference:
    PG referential action triggers; ours run statement-inline through
    the executor's FK machinery)."""

    async def _setup(self, tmp_path):
        mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
        s = SqlSession(mc.client())
        await s.execute("CREATE TABLE p (id bigint PRIMARY KEY, nm "
                        "text) WITH tablets = 1")
        await s.execute(
            "CREATE TABLE c1 (id bigint PRIMARY KEY, pid bigint "
            "REFERENCES p (id) ON DELETE CASCADE) WITH tablets = 1")
        await s.execute(
            "CREATE TABLE g (id bigint PRIMARY KEY, cid bigint "
            "REFERENCES c1 (id) ON DELETE CASCADE) WITH tablets = 1")
        await s.execute(
            "CREATE TABLE c2 (id bigint PRIMARY KEY, pid bigint "
            "REFERENCES p (id) ON DELETE SET NULL) WITH tablets = 1")
        await s.execute("INSERT INTO p (id, nm) VALUES (1,'a'),(2,'b')")
        await s.execute(
            "INSERT INTO c1 (id, pid) VALUES (10,1),(11,1),(12,2)")
        await s.execute("INSERT INTO g (id, cid) VALUES (100,10)")
        await s.execute("INSERT INTO c2 (id, pid) VALUES (20,1),(21,2)")
        return mc, s

    def test_cascade_chain_and_set_null(self, tmp_path):
        async def go():
            mc, s = await self._setup(tmp_path)
            try:
                await s.execute("DELETE FROM p WHERE id = 1")
                r = await s.execute("SELECT id FROM c1 ORDER BY id")
                assert [x["id"] for x in r.rows] == [12]
                r = await s.execute("SELECT id FROM g")
                assert r.rows == []          # grandchild cascaded
                r = await s.execute("SELECT id, pid FROM c2 "
                                    "ORDER BY id")
                assert [(x["id"], x["pid"]) for x in r.rows] == \
                    [(20, None), (21, 2)]
            finally:
                await mc.shutdown()
        asyncio.run(go())

    def test_restrict_grandchild_vetoes_cascade(self, tmp_path):
        async def go():
            mc, s = await self._setup(tmp_path)
            try:
                await s.execute(
                    "CREATE TABLE gr (id bigint PRIMARY KEY, cid "
                    "bigint REFERENCES c1 (id)) WITH tablets = 1")
                await s.execute("INSERT INTO gr (id, cid) "
                                "VALUES (200, 11)")
                with pytest.raises(Exception, match="still referenced"):
                    await s.execute("DELETE FROM p WHERE id = 1")
                # nothing was half-deleted outside a txn? the veto runs
                # BEFORE any delete of that child's rows, and the
                # parent row survives
                r = await s.execute("SELECT count(*) FROM p")
                assert r.rows[0]["count"] == 2
                r = await s.execute("SELECT count(*) FROM c1")
                assert r.rows[0]["count"] == 3
            finally:
                await mc.shutdown()
        asyncio.run(go())

    def test_cascade_inside_txn_rolls_back(self, tmp_path):
        async def go():
            mc, s = await self._setup(tmp_path)
            try:
                await s.execute("BEGIN")
                await s.execute("DELETE FROM p WHERE id = 1")
                r = await s.execute("SELECT count(*) FROM c1")
                assert r.rows[0]["count"] == 1
                await s.execute("ROLLBACK")
                r = await s.execute("SELECT count(*) FROM c1")
                assert r.rows[0]["count"] == 3
                r = await s.execute("SELECT count(*) FROM g")
                assert r.rows[0]["count"] == 1
            finally:
                await mc.shutdown()
        asyncio.run(go())

    def test_self_referential_cascade_cycle(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            s = SqlSession(mc.client())
            try:
                await s.execute(
                    "CREATE TABLE emp (id bigint PRIMARY KEY, mgr "
                    "bigint REFERENCES emp (id) ON DELETE CASCADE) "
                    "WITH tablets = 1")
                await s.execute("INSERT INTO emp (id, mgr) VALUES "
                                "(1, NULL)")
                await s.execute("INSERT INTO emp (id, mgr) VALUES "
                                "(2, 1), (3, 2)")
                # mutual cycle: 4 <-> 5
                await s.execute("INSERT INTO emp (id, mgr) VALUES "
                                "(4, 1)")
                await s.execute("INSERT INTO emp (id, mgr) VALUES "
                                "(5, 4)")
                await s.execute("UPDATE emp SET mgr = 5 WHERE id = 4")
                await s.execute("DELETE FROM emp WHERE id = 1")
                # the 1->2->3 chain cascades; the detached 4<->5 cycle
                # references no deleted row and survives (PG semantics)
                r = await s.execute("SELECT id FROM emp ORDER BY id")
                assert [x["id"] for x in r.rows] == [4, 5]
                # deleting INTO the cycle takes both without looping
                await s.execute("DELETE FROM emp WHERE id = 4")
                r = await s.execute("SELECT count(*) FROM emp")
                assert r.rows[0]["count"] == 0
            finally:
                await mc.shutdown()
        asyncio.run(go())

    def test_sibling_restrict_vetoes_before_any_cascade_write(
            self, tmp_path):
        """A RESTRICT child of the SAME parent must veto before the
        cascade/set-null SIBLINGS write anything — even outside a
        transaction (the plan/check/execute split)."""
        async def go():
            mc, s = await self._setup(tmp_path)
            try:
                await s.execute(
                    "CREATE TABLE hold (id bigint PRIMARY KEY, pid "
                    "bigint REFERENCES p (id) ON DELETE RESTRICT) "
                    "WITH tablets = 1")
                await s.execute("INSERT INTO hold (id, pid) "
                                "VALUES (300, 1)")
                with pytest.raises(Exception, match="still referenced"):
                    await s.execute("DELETE FROM p WHERE id = 1")
                # cascade siblings untouched, set-null sibling intact
                r = await s.execute("SELECT count(*) FROM c1")
                assert r.rows[0]["count"] == 3
                r = await s.execute("SELECT count(*) FROM g")
                assert r.rows[0]["count"] == 1
                r = await s.execute("SELECT pid FROM c2 WHERE id = 20")
                assert r.rows[0]["pid"] == 1
            finally:
                await mc.shutdown()
        asyncio.run(go())

    def test_set_null_on_not_null_column_vetoes(self, tmp_path):
        """ON DELETE SET NULL against a NOT NULL FK column must error
        (PG 23502) before any write, not store a NULL."""
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            s = SqlSession(mc.client())
            try:
                await s.execute("CREATE TABLE p2 (id bigint PRIMARY "
                                "KEY) WITH tablets = 1")
                await s.execute(
                    "CREATE TABLE c3 (id bigint PRIMARY KEY, pid "
                    "bigint NOT NULL REFERENCES p2 (id) ON DELETE "
                    "SET NULL) WITH tablets = 1")
                await s.execute("INSERT INTO p2 (id) VALUES (1)")
                await s.execute("INSERT INTO c3 (id, pid) "
                                "VALUES (10, 1)")
                with pytest.raises(ValueError, match="not-null"):
                    await s.execute("DELETE FROM p2 WHERE id = 1")
                r = await s.execute("SELECT pid FROM c3 WHERE id = 10")
                assert r.rows[0]["pid"] == 1
                r = await s.execute("SELECT count(*) FROM p2")
                assert r.rows[0]["count"] == 1
            finally:
                await mc.shutdown()
        asyncio.run(go())

    def test_deep_cascade_chain(self, tmp_path):
        """Cascade depth is a worklist, not recursion: a 600-link
        self-referential chain deletes in one statement."""
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            s = SqlSession(mc.client())
            try:
                await s.execute(
                    "CREATE TABLE ln (id bigint PRIMARY KEY, prev "
                    "bigint REFERENCES ln (id) ON DELETE CASCADE) "
                    "WITH tablets = 1")
                c = mc.client()
                await c.write("ln", [RowOp("upsert",
                                           {"id": 0, "prev": None})])
                await c.write("ln", [RowOp("upsert",
                                           {"id": i, "prev": i - 1})
                                     for i in range(1, 600)])
                await s.execute("DELETE FROM ln WHERE id = 0")
                r = await s.execute("SELECT count(*) FROM ln")
                assert r.rows[0]["count"] == 0
            finally:
                await mc.shutdown()
        asyncio.run(go())

    def test_set_null_preserves_other_columns(self, tmp_path):
        """SET NULL rewrites only the FK column — sibling payload
        columns must survive (upserts are full-row packed writes, so
        the plan must carry the whole row)."""
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            s = SqlSession(mc.client())
            try:
                await s.execute("CREATE TABLE p3 (id bigint PRIMARY "
                                "KEY) WITH tablets = 1")
                await s.execute(
                    "CREATE TABLE c4 (id bigint PRIMARY KEY, pid "
                    "bigint REFERENCES p3 (id) ON DELETE SET NULL, "
                    "payload text) WITH tablets = 1")
                await s.execute("INSERT INTO p3 (id) VALUES (1)")
                await s.execute("INSERT INTO c4 (id, pid, payload) "
                                "VALUES (10, 1, 'important')")
                await s.execute("DELETE FROM p3 WHERE id = 1")
                r = await s.execute("SELECT pid, payload FROM c4 "
                                    "WHERE id = 10")
                assert r.rows[0]["pid"] is None
                assert r.rows[0]["payload"] == "important"
            finally:
                await mc.shutdown()
        asyncio.run(go())

    def test_cascade_wins_over_set_null_on_same_row(self, tmp_path):
        """A child row with BOTH actions toward one parent deletes
        (PG: the cascade trigger removes it; the set-null update then
        matches nothing) — regardless of FK declaration order."""
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            s = SqlSession(mc.client())
            try:
                await s.execute("CREATE TABLE p4 (id bigint PRIMARY "
                                "KEY) WITH tablets = 1")
                await s.execute(
                    "CREATE TABLE c5 (id bigint PRIMARY KEY, "
                    "a bigint REFERENCES p4 (id) ON DELETE SET NULL, "
                    "b bigint REFERENCES p4 (id) ON DELETE CASCADE) "
                    "WITH tablets = 1")
                await s.execute("INSERT INTO p4 (id) VALUES (1)")
                await s.execute("INSERT INTO c5 (id, a, b) "
                                "VALUES (10, 1, 1)")
                await s.execute("DELETE FROM p4 WHERE id = 1")
                r = await s.execute("SELECT count(*) FROM c5")
                assert r.rows[0]["count"] == 0
            finally:
                await mc.shutdown()
        asyncio.run(go())

    def test_on_update_no_action_parses(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            s = SqlSession(mc.client())
            try:
                await s.execute("CREATE TABLE p5 (id bigint PRIMARY "
                                "KEY) WITH tablets = 1")
                await s.execute(
                    "CREATE TABLE c6 (id bigint PRIMARY KEY, pid "
                    "bigint REFERENCES p5 (id) ON DELETE CASCADE "
                    "ON UPDATE NO ACTION) WITH tablets = 1")
                with pytest.raises(ValueError, match="ON UPDATE"):
                    await s.execute(
                        "CREATE TABLE c7 (id bigint PRIMARY KEY, pid "
                        "bigint REFERENCES p5 (id) ON UPDATE CASCADE) "
                        "WITH tablets = 1")
                # NO ACTION keeps its name in the catalog
                r = await s.execute(
                    "SELECT delete_rule FROM information_schema."
                    "referential_constraints")
                assert r.rows[0]["delete_rule"] == "CASCADE"
            finally:
                await mc.shutdown()
        asyncio.run(go())

    def test_two_set_null_fks_both_null(self, tmp_path):
        """A child with TWO SET NULL FKs toward one parent nulls both
        columns (merged row image, not two restoring upserts)."""
        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            s = SqlSession(mc.client())
            try:
                await s.execute("CREATE TABLE p6 (id bigint PRIMARY "
                                "KEY) WITH tablets = 1")
                await s.execute(
                    "CREATE TABLE c8 (id bigint PRIMARY KEY, "
                    "a bigint REFERENCES p6 (id) ON DELETE SET NULL, "
                    "b bigint REFERENCES p6 (id) ON DELETE SET NULL) "
                    "WITH tablets = 1")
                await s.execute("INSERT INTO p6 (id) VALUES (1)")
                await s.execute("INSERT INTO c8 (id, a, b) "
                                "VALUES (10, 1, 1)")
                await s.execute("DELETE FROM p6 WHERE id = 1")
                r = await s.execute("SELECT a, b FROM c8 "
                                    "WHERE id = 10")
                assert r.rows[0]["a"] is None
                assert r.rows[0]["b"] is None
            finally:
                await mc.shutdown()
        asyncio.run(go())
