"""Shared helper for external-driver conformance tests: run a
MiniCluster + one wire server on a background event loop so synchronous
drivers (psycopg, cassandra-driver, redis-py) can connect from the test
thread."""
import asyncio
import threading

from yugabyte_db_tpu.tools.mini_cluster import MiniCluster


class ClusterThread:
    """`server_factory(client) -> server` where server has async
    start() -> addr and shutdown()."""

    def __init__(self, tmp_path, server_factory):
        self.tmp = str(tmp_path)
        self.server_factory = server_factory
        self.loop = asyncio.new_event_loop()
        self.addr = None
        self.ready = threading.Event()
        self.thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        asyncio.set_event_loop(self.loop)

        async def boot():
            self.mc = await MiniCluster(self.tmp, num_tservers=1).start()
            self.srv = self.server_factory(self.mc.client())
            self.addr = await self.srv.start()
            self.ready.set()
        self.loop.create_task(boot())
        self.loop.run_forever()

    def __enter__(self):
        self.thread.start()
        assert self.ready.wait(30)
        return self

    def __exit__(self, *exc):
        async def stop():
            await self.srv.shutdown()
            await self.mc.shutdown()
            self.loop.stop()
        asyncio.run_coroutine_threadsafe(stop(), self.loop)
        self.thread.join(timeout=10)
