"""Device compaction (merge + MVCC GC) and vector kernel tests, verified
against scalar reference implementations."""
import jax.numpy as jnp
import numpy as np
import pytest

from yugabyte_db_tpu.dockv import DocKey, KeyEntryValue, SubDocKey
from yugabyte_db_tpu.dockv import bulk
from yugabyte_db_tpu.ops.compaction import (
    compact_entry_arrays, compact_runs, keys_to_words, split_ht_suffix,
)
from yugabyte_db_tpu.ops.vector import (
    IvfFlatIndex, exact_search, kmeans, l2_distance2,
)
from yugabyte_db_tpu.utils.hybrid_time import DocHybridTime, HybridTime

K = KeyEntryValue


def build_keys(specs):
    """specs: list of (pk:int, ht_micros:int, wid:int) -> [N, L] matrix."""
    mats = []
    for pk, ht, wid in specs:
        sdk = SubDocKey(DocKey.make(range=(K.int64(pk),)), (),
                        DocHybridTime(HybridTime.from_micros(ht), wid))
        mats.append(np.frombuffer(sdk.encode(), np.uint8))
    return np.stack(mats)


class TestKeyWords:
    def test_words_preserve_order(self):
        rng = np.random.default_rng(3)
        pks = rng.integers(-10**6, 10**6, 200)
        keys = build_keys([(int(p), 100, 0) for p in pks])
        words = keys_to_words(keys)
        order_bytes = sorted(range(200), key=lambda i: keys[i].tobytes())
        order_words = sorted(range(200), key=lambda i: tuple(words[i]))
        assert order_bytes == order_words

    def test_split_ht_suffix(self):
        keys = build_keys([(1, 500, 7)])
        dk, ht, wid = split_ht_suffix(keys)
        assert ht[0] == HybridTime.from_micros(500).value
        assert wid[0] == 7
        # dk is the doc key alone
        got, _ = DocKey.decode(dk[0].tobytes())
        assert got.range[0].value == 1


def ht_val(micros):
    return HybridTime.from_micros(micros).value


class TestMergeGc:
    def test_merge_sorts_and_dedups(self):
        # two runs with an exact duplicate entry (replay scenario)
        keys = build_keys([(2, 100, 0), (1, 100, 0), (1, 100, 0)])
        tomb = np.zeros(3, bool)
        order, keep = compact_entry_arrays(keys, tomb, history_cutoff=0)
        kept = [keys[i].tobytes() for i, k in zip(order, keep) if k]
        assert len(kept) == 2
        assert kept == sorted(kept)

    def test_gc_drops_overwritten_history(self):
        # key 5 written at t=100, 200, 300; cutoff=250
        keys = build_keys([(5, 100, 0), (5, 200, 0), (5, 300, 0)])
        tomb = np.zeros(3, bool)
        order, keep = compact_entry_arrays(keys, tomb,
                                           history_cutoff=ht_val(250))
        _, hts, _ = split_ht_suffix(keys)
        kept_hts = sorted(int(hts[i]) for i, k in zip(order, keep) if k)
        # keep: 300 (> cutoff) and 200 (latest <= cutoff); drop 100
        assert kept_hts == [ht_val(200), ht_val(300)]

    def test_gc_keeps_all_recent(self):
        keys = build_keys([(5, 100, 0), (5, 200, 0)])
        order, keep = compact_entry_arrays(keys, np.zeros(2, bool),
                                           history_cutoff=ht_val(50))
        assert keep.sum() == 2

    def test_tombstone_collapses_at_cutoff(self):
        # delete at 200 covers write at 100; cutoff 300 > both → both go
        keys = build_keys([(5, 100, 0), (5, 200, 0)])
        tomb = np.array([False, True])
        order, keep = compact_entry_arrays(keys, tomb,
                                           history_cutoff=ht_val(300))
        assert keep.sum() == 0

    def test_tombstone_above_cutoff_retained(self):
        keys = build_keys([(5, 100, 0), (5, 200, 0)])
        tomb = np.array([False, True])
        order, keep = compact_entry_arrays(keys, tomb,
                                           history_cutoff=ht_val(150))
        _, hts, _ = split_ht_suffix(keys)
        kept_hts = sorted(int(hts[i]) for i, k in zip(order, keep) if k)
        # tombstone (200) above cutoff kept; 100 is latest <= cutoff, kept
        assert kept_hts == [ht_val(100), ht_val(200)]

    def test_compact_runs_mixed_widths(self):
        run1 = build_keys([(1, 100, 0), (3, 100, 0)])
        # wider keys (two range components)
        mats = []
        for pk in (2, 4):
            sdk = SubDocKey(DocKey.make(range=(K.int64(pk), K.string("xx"))),
                            (), DocHybridTime(HybridTime.from_micros(100), 0))
            mats.append(np.frombuffer(sdk.encode(), np.uint8))
        run2 = np.stack(mats)
        order, keep = compact_runs(
            [(run1, np.zeros(2, bool)), (run2, np.zeros(2, bool))],
            history_cutoff=0)
        assert keep.sum() == 4
        # check global sort: reconstruct pk order
        all_keys = [run1[0], run1[1], run2[0], run2[1]]
        kept = [all_keys[i] for i, k in zip(order, keep) if k]
        pks = [DocKey.decode(bytes(m.tobytes()))[0].range[0].value
               for m in kept]
        assert pks == [1, 2, 3, 4]

    def test_fuzz_against_scalar_gc(self):
        rng = np.random.default_rng(11)
        specs = []
        for _ in range(300):
            specs.append((int(rng.integers(0, 40)),
                          int(rng.integers(1, 50)) * 10, 0))
        # dedup exact duplicates in specs for simpler scalar model
        specs = list(dict.fromkeys(specs))
        keys = build_keys(specs)
        tomb = rng.random(len(specs)) < 0.2
        cutoff = ht_val(250)
        order, keep = compact_entry_arrays(keys, tomb, history_cutoff=cutoff)

        # scalar reference
        by_pk = {}
        for i, (pk, ht, wid) in enumerate(specs):
            by_pk.setdefault(pk, []).append((ht_val(ht), tomb[i], i))
        expect = set()
        for pk, versions in by_pk.items():
            versions.sort(reverse=True)
            latest_leq_done = False
            for htv, tb, i in versions:
                if htv > cutoff:
                    expect.add(i)
                elif not latest_leq_done:
                    latest_leq_done = True
                    if not tb:
                        expect.add(i)
        got = {int(order[j]) for j in range(len(keep)) if keep[j]}
        assert got == expect


class TestVector:
    def test_l2_matches_numpy(self):
        rng = np.random.default_rng(0)
        q = rng.normal(size=(4, 32)).astype(np.float32)
        b = rng.normal(size=(50, 32)).astype(np.float32)
        d = np.asarray(l2_distance2(q, b))
        ref = ((q[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        np.testing.assert_allclose(d, ref, rtol=2e-2, atol=2e-2)

    def test_exact_search_topk(self):
        rng = np.random.default_rng(1)
        b = rng.normal(size=(200, 16)).astype(np.float32)
        q = b[[5, 17]] + 0.001
        d, idx = exact_search(q, b, k=3)
        assert idx[0, 0] == 5 and idx[1, 0] == 17

    def test_kmeans_clusters(self):
        rng = np.random.default_rng(2)
        a = rng.normal(0, 0.1, (100, 8)) + 5
        b = rng.normal(0, 0.1, (100, 8)) - 5
        cents = kmeans(np.vstack([a, b]).astype(np.float32), 2, iters=8)
        means = sorted(cents.mean(axis=1))
        assert means[0] < -4 and means[1] > 4

    def test_ivfflat_recall(self):
        rng = np.random.default_rng(3)
        base = rng.normal(size=(2000, 32)).astype(np.float32)
        idx = IvfFlatIndex.build(base, nlists=16, iters=5)
        q = base[:20] + 0.001
        d, ids = idx.search(q, k=1, nprobe=4)
        recall = (ids[:, 0] == np.arange(20)).mean()
        assert recall >= 0.9

    def test_device_full_scan_kernel(self):
        """The accelerator full-scan path (pre-chunked layout, traced
        operands) must match exact_search — on CPU it is routed away
        (the list-major twin runs instead), so drive it directly."""
        from yugabyte_db_tpu.ops.vector import _full_scan_search
        rng = np.random.default_rng(4)
        base = rng.normal(size=(1000, 24)).astype(np.float32)
        idx = IvfFlatIndex.build(base, nlists=8, iters=4)
        q = jnp.asarray(base[:16] + 0.001)
        d, i = _full_scan_search(q, idx._vec, idx._nrm, 5)
        d_ref, i_ref = exact_search(q, jnp.asarray(base), 5)
        assert np.array_equal(np.asarray(i), np.asarray(i_ref))
        np.testing.assert_allclose(np.asarray(d), np.asarray(d_ref),
                                   rtol=1e-4, atol=1e-3)

    def test_device_full_scan_padded_tail(self):
        """Padded tail rows (inf norms) can never win a top-k slot and
        returned indices stay < n even when n % chunk != 0."""
        from yugabyte_db_tpu.ops.vector import _full_scan_search
        rng = np.random.default_rng(5)
        base = rng.normal(size=(777, 8)).astype(np.float32)
        idx = IvfFlatIndex.build(base, nlists=4, iters=3)
        old_chunk = IvfFlatIndex.CHUNK
        try:
            IvfFlatIndex.CHUNK = 100           # forces pad = 23
            idx2 = IvfFlatIndex(np.asarray(idx.centroids),
                                np.asarray(idx.lists),
                                np.asarray(idx.list_lens), base)
        finally:
            IvfFlatIndex.CHUNK = old_chunk
        q = jnp.asarray(base[:8])
        d, i = _full_scan_search(q, idx2._vec, idx2._nrm, 7)
        assert np.asarray(i).max() < 777
        d_ref, i_ref = exact_search(q, jnp.asarray(base), 7)
        assert np.array_equal(np.asarray(i), np.asarray(i_ref))

    def test_device_ivf_probe_kernel_matches_cpu_twin(self):
        """The accelerator gather path and the CPU list-major twin
        implement the SAME IVF semantics: identical probed lists must
        yield identical neighbor sets."""
        from yugabyte_db_tpu.ops.vector import _ivf_probe_search
        rng = np.random.default_rng(6)
        base = rng.normal(size=(3000, 16)).astype(np.float32)
        idx = IvfFlatIndex.build(base, nlists=32, iters=5)
        q = base[:5] + 0.001
        # small batch on CPU routes to the list-major twin
        d_cpu, i_cpu = idx.search(q, k=4, nprobe=6)
        d_dev, i_dev = _ivf_probe_search(
            jnp.asarray(q), idx.centroids, idx.lists, idx.list_lens,
            idx._vec.reshape(-1, idx.dim), idx._nrm.reshape(-1), 4, 6)
        assert np.array_equal(np.sort(i_cpu, 1), np.sort(np.asarray(i_dev), 1))
        np.testing.assert_allclose(np.sort(d_cpu, 1),
                                   np.sort(np.asarray(d_dev), 1),
                                   rtol=1e-4, atol=1e-3)
