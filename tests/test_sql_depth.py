"""Regress-style SQL depth suite: CTEs, window functions, RIGHT/FULL
joins, multi-statement scripts, timestamp/interval/decimal arithmetic,
scalar functions (reference: ported slices of
src/postgres/src/test/regress — with.sql, window.sql, join.sql,
timestamp.sql, numeric.sql shapes)."""
import asyncio
import tempfile
from decimal import Decimal

import pytest

from yugabyte_db_tpu.ql import SqlSession
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster


@pytest.fixture(scope="module")
def sess():
    loop = asyncio.new_event_loop()

    async def setup():
        mc = await MiniCluster(tempfile.mkdtemp(prefix="depth-"),
                               num_tservers=1).start()
        s = SqlSession(mc.client())
        await s.execute_script("""
          CREATE TABLE emp (id bigint, dept bigint, salary double,
                            name text, hired timestamp, bonus numeric,
                            PRIMARY KEY (id));
          INSERT INTO emp (id, dept, salary, name, hired, bonus) VALUES
            (1, 1, 100.0, 'ann', 1000000, '10.50'),
            (2, 1, 200.0, 'bob', 2000000, '20.25'),
            (3, 1, 200.0, 'cat', 3000000, '0.125'),
            (4, 2, 150.0, 'dan', 4000000, '99.99'),
            (5, 2, 50.0, 'eve', 5000000, NULL),
            (6, 3, 300.0, 'fay', 6000000, '1.00');
          CREATE TABLE dept (d bigint, dname text, PRIMARY KEY (d));
          INSERT INTO dept (d, dname) VALUES (1, 'eng'), (2, 'ops'),
            (9, 'empty')
        """)
        return mc, s

    mc, s = loop.run_until_complete(setup())

    def run(sql):
        return loop.run_until_complete(s.execute(sql))

    yield run
    loop.run_until_complete(mc.shutdown())
    loop.close()


class TestMultiStatement:
    def test_script_returns_per_statement(self, sess):
        # exercised in the fixture; also via execute_script directly
        pass


class TestCtes:
    def test_basic_cte(self, sess):
        r = sess("WITH t AS (SELECT dept, sum(salary) AS tot FROM emp "
                 "GROUP BY dept) SELECT dept, tot FROM t "
                 "WHERE tot > 150 ORDER BY tot DESC")
        assert [row["dept"] for row in r.rows] == [1, 3, 2]

    def test_chained_ctes(self, sess):
        r = sess("WITH a AS (SELECT id, salary FROM emp WHERE dept = 1),"
                 " b AS (SELECT id FROM a WHERE salary >= 200) "
                 "SELECT count(*) FROM b")
        assert r.rows[0]["count"] == 2

    def test_cte_with_where_order_limit(self, sess):
        r = sess("WITH t AS (SELECT * FROM emp) SELECT name FROM t "
                 "WHERE salary > 90 ORDER BY salary DESC, name LIMIT 3")
        assert [x["name"] for x in r.rows] == ["fay", "bob", "cat"]

    def test_cte_aggregate_no_group(self, sess):
        r = sess("WITH t AS (SELECT * FROM emp WHERE dept = 2) "
                 "SELECT min(salary), max(salary), avg(salary), "
                 "count(*) FROM t")
        row = r.rows[0]
        assert row["min_salary"] == 50.0 and row["max_salary"] == 150.0
        assert row["count"] == 2

    def test_cte_group_having(self, sess):
        r = sess("WITH t AS (SELECT * FROM emp) SELECT dept, count(*) "
                 "AS n FROM t GROUP BY dept HAVING count(*) > 1 "
                 "ORDER BY dept")
        assert [(x["dept"], x["n"]) for x in r.rows] == [(1, 3), (2, 2)]

    def test_cte_in_join(self, sess):
        r = sess("WITH big AS (SELECT id, dept, name FROM emp WHERE "
                 "salary >= 200) SELECT name, dname FROM big "
                 "JOIN dept ON dept = d ORDER BY name")
        assert [(x["name"], x["dname"]) for x in r.rows] == [
            ("bob", "eng"), ("cat", "eng")]

    def test_cte_window(self, sess):
        r = sess("WITH t AS (SELECT * FROM emp) SELECT name, "
                 "row_number() OVER (ORDER BY salary DESC, name) AS rn "
                 "FROM t ORDER BY rn LIMIT 2")
        assert [x["name"] for x in r.rows] == ["fay", "bob"]

    def test_explain_cte(self, sess):
        r = sess("EXPLAIN WITH t AS (SELECT * FROM emp) "
                 "SELECT * FROM t")
        assert "CTE Scan" in r.rows[0]["QUERY PLAN"]


class TestWindowFunctions:
    def test_row_number_partitioned(self, sess):
        r = sess("SELECT name, row_number() OVER (PARTITION BY dept "
                 "ORDER BY salary DESC, name) AS rn FROM emp "
                 "ORDER BY name")
        got = {x["name"]: x["rn"] for x in r.rows}
        assert got == {"ann": 3, "bob": 1, "cat": 2, "dan": 1,
                       "eve": 2, "fay": 1}

    def test_rank_and_dense_rank(self, sess):
        r = sess("SELECT name, rank() OVER (ORDER BY salary) AS rk, "
                 "dense_rank() OVER (ORDER BY salary) AS dk FROM emp "
                 "ORDER BY name")
        got = {x["name"]: (x["rk"], x["dk"]) for x in r.rows}
        assert got["eve"] == (1, 1)
        assert got["ann"] == (2, 2)
        assert got["dan"] == (3, 3)
        assert got["bob"] == (4, 4) and got["cat"] == (4, 4)
        assert got["fay"] == (6, 5)

    def test_sum_over_partition(self, sess):
        r = sess("SELECT name, sum(salary) OVER (PARTITION BY dept) "
                 "AS t FROM emp ORDER BY name")
        got = {x["name"]: x["t"] for x in r.rows}
        assert got["ann"] == 500.0 and got["dan"] == 200.0 \
            and got["fay"] == 300.0

    def test_cumulative_sum_with_peers(self, sess):
        """PG default frame: peers (order ties) share the cumulative."""
        r = sess("SELECT name, sum(salary) OVER (PARTITION BY dept "
                 "ORDER BY salary) AS c FROM emp WHERE dept = 1 "
                 "ORDER BY name")
        got = {x["name"]: x["c"] for x in r.rows}
        assert got["ann"] == 100.0
        assert got["bob"] == 500.0 and got["cat"] == 500.0   # peers

    def test_lag_lead(self, sess):
        r = sess("SELECT id, lag(salary) OVER (ORDER BY id) AS p, "
                 "lead(salary) OVER (ORDER BY id) AS n FROM emp "
                 "ORDER BY id")
        assert r.rows[0]["p"] is None and r.rows[0]["n"] == 200.0
        assert r.rows[-1]["p"] == 50.0 and r.rows[-1]["n"] is None

    def test_lag_with_offset(self, sess):
        r = sess("SELECT id, lag(salary, 2) OVER (ORDER BY id) AS p "
                 "FROM emp ORDER BY id")
        assert [x["p"] for x in r.rows] == [None, None, 100.0, 200.0,
                                            200.0, 150.0]

    def test_count_avg_windows(self, sess):
        r = sess("SELECT name, count(*) OVER (PARTITION BY dept) AS n, "
                 "avg(salary) OVER (PARTITION BY dept) AS a FROM emp "
                 "WHERE dept = 2 ORDER BY name")
        assert all(x["n"] == 2 and x["a"] == 100.0 for x in r.rows)

    def test_window_requires_over(self, sess):
        with pytest.raises(Exception):
            sess("SELECT row_number() FROM emp")


class TestOuterJoins:
    def test_right_join(self, sess):
        r = sess("SELECT name, dname FROM emp RIGHT JOIN dept "
                 "ON dept = d ORDER BY dname, name")
        assert {(x["name"], x["dname"]) for x in r.rows} == {
            (None, "empty"), ("ann", "eng"), ("bob", "eng"),
            ("cat", "eng"), ("dan", "ops"), ("eve", "ops")}

    def test_full_join(self, sess):
        r = sess("SELECT name, dname FROM emp FULL JOIN dept "
                 "ON dept = d")
        pairs = {(x["name"], x["dname"]) for x in r.rows}
        assert (None, "empty") in pairs          # right-unmatched
        assert ("fay", None) in pairs            # left-unmatched
        assert len(r.rows) == 7

    def test_right_outer_keyword(self, sess):
        r = sess("SELECT dname FROM emp RIGHT OUTER JOIN dept "
                 "ON dept = d WHERE name IS NULL")
        assert [x["dname"] for x in r.rows] == ["empty"]


class TestTimestampArithmetic:
    def test_literal_and_interval(self, sess):
        r = sess("SELECT id FROM emp WHERE hired < timestamp "
                 "'1970-01-01 00:00:04' ORDER BY id")
        assert [x["id"] for x in r.rows] == [1, 2, 3]

    def test_interval_add(self, sess):
        r = sess("SELECT id FROM emp WHERE hired + interval '2 seconds'"
                 " <= 5000000 ORDER BY id")
        assert [x["id"] for x in r.rows] == [1, 2, 3]

    def test_interval_units(self, sess):
        r = sess("SELECT interval '1 day' AS d, "
                 "interval '1 hour 30 minutes' AS hm, "
                 "interval '2 weeks' AS w FROM emp WHERE id = 1")
        row = r.rows[0]
        assert row["d"] == 86_400_000_000
        assert row["hm"] == 5_400_000_000
        assert row["w"] == 14 * 86_400_000_000

    def test_now_is_recent(self, sess):
        import time
        r = sess("SELECT now() AS t FROM emp WHERE id = 1")
        assert abs(r.rows[0]["t"] / 1e6 - time.time()) < 60


class TestDecimalArithmetic:
    def test_decimal_compare_is_numeric(self, sess):
        # lexicographic would put '0.125' > '10.50' FALSE etc; numeric
        # compare must find exactly the rows > 5
        r = sess("SELECT id FROM emp WHERE bonus > 5 ORDER BY id")
        assert [x["id"] for x in r.rows] == [1, 2, 4]

    def test_decimal_sum_exact(self, sess):
        r = sess("SELECT sum(bonus) AS s FROM emp")
        assert r.rows[0]["s"] == Decimal("131.865")

    def test_decimal_arith(self, sess):
        r = sess("SELECT id FROM emp WHERE bonus * 2 = 40.5")
        assert [x["id"] for x in r.rows] == [2]

    def test_decimal_min_max(self, sess):
        r = sess("SELECT min(bonus) AS lo, max(bonus) AS hi FROM emp")
        assert r.rows[0]["lo"] == Decimal("0.125")
        assert r.rows[0]["hi"] == Decimal("99.99")


class TestScalarFunctions:
    def test_string_fns(self, sess):
        r = sess("SELECT upper(name) AS u, lower(upper(name)) AS l, "
                 "length(name) AS n FROM emp WHERE id = 4")
        assert r.rows[0] == {"u": "DAN", "l": "dan", "n": 3}

    def test_coalesce(self, sess):
        r = sess("SELECT coalesce(bonus, '0') AS b FROM emp "
                 "WHERE id = 5")
        assert r.rows[0]["b"] == "0"

    def test_numeric_fns(self, sess):
        r = sess("SELECT abs(50.0 - salary) AS a, round(salary / 7) "
                 "AS r, floor(salary / 7) AS f, ceil(salary / 7) AS c "
                 "FROM emp WHERE id = 1")
        row = r.rows[0]
        assert row["a"] == 50.0 and row["f"] == 14 and row["c"] == 15

    def test_cast(self, sess):
        r = sess("SELECT cast(salary AS bigint) AS i, "
                 "cast(id AS text) AS t FROM emp WHERE id = 2")
        assert r.rows[0] == {"i": 200, "t": "2"}

    def test_fn_in_where(self, sess):
        r = sess("SELECT id FROM emp WHERE upper(name) = 'EVE'")
        assert [x["id"] for x in r.rows] == [5]


class TestStrictNullFunctions:
    def test_null_in_any_argument_yields_null(self, tmp_path):
        """PG scalar functions are strict: a NULL in ANY argument
        returns NULL (previously later-position NULLs crashed int()
        or stringified to 'None')."""
        import asyncio
        from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
        from yugabyte_db_tpu.ql.executor import SqlSession

        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute("CREATE TABLE sn (k bigint, n text, "
                                "m bigint, PRIMARY KEY (k)) "
                                "WITH tablets = 1")
                await mc.wait_for_leaders("sn")
                await s.execute("INSERT INTO sn (k, n, m) VALUES "
                                "(1, 'hello', NULL)")
                for q in [
                    "SELECT substr(n, m) AS x FROM sn",
                    "SELECT lpad(n, 8, NULL) AS x FROM sn",
                    "SELECT replace(n, NULL, 'y') AS x FROM sn",
                    "SELECT mod(k, m) AS x FROM sn",
                ]:
                    r = await s.execute(q)
                    assert r.rows[0]["x"] is None, (q, r.rows)
                # NULL-tolerant fns keep their special semantics
                r = await s.execute(
                    "SELECT concat(n, NULL, '!') AS c, "
                    "greatest(k, m) AS g FROM sn")
                assert r.rows[0]["c"] == "hello!"
                assert r.rows[0]["g"] == 1
            finally:
                await mc.shutdown()
        asyncio.run(go())


class TestJoinOrderBySemantics:
    """Join ORDER BY reference rules (review-found regressions):
    qualified refs always mean the table column (never an alias), and
    PG's DISTINCT/ORDER BY select-list rule."""

    def test_qualified_order_col_not_shadowed_by_alias(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute("CREATE TABLE a (id bigint PRIMARY KEY,"
                                " name text) WITH tablets = 1")
                await s.execute(
                    "CREATE TABLE b (id bigint PRIMARY KEY, "
                    "a_id bigint, amt bigint) WITH tablets = 1")
                await s.execute(
                    "INSERT INTO a (id, name) VALUES (1, 'x'), (2, 'y')")
                await s.execute("INSERT INTO b (id, a_id, amt) VALUES "
                                "(10, 1, 5), (11, 1, 7), (12, 2, 3)")
                # alias 'name' shadows a.name's bare name; ORDER BY
                # a.name must still sort by the TABLE column
                r = await s.execute(
                    "SELECT b.amt AS name FROM a "
                    "JOIN b ON a.id = b.a_id ORDER BY a.name, b.amt")
                assert [row["name"] for row in r.rows] == [5, 7, 3]
                # sort-only qualified column, plain case
                r = await s.execute(
                    "SELECT a.name FROM a JOIN b ON a.id = b.a_id "
                    "ORDER BY b.amt")
                assert [row["name"] for row in r.rows] == ["y", "x", "x"]
            finally:
                await mc.shutdown()
        asyncio.run(go())

    def test_distinct_order_by_must_be_projected(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            import pytest as _pt
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute("CREATE TABLE a (id bigint PRIMARY KEY,"
                                " name text) WITH tablets = 1")
                await s.execute(
                    "CREATE TABLE b (id bigint PRIMARY KEY, "
                    "a_id bigint, amt bigint) WITH tablets = 1")
                await s.execute(
                    "INSERT INTO a (id, name) VALUES (1, 'x'), (2, 'y')")
                await s.execute("INSERT INTO b (id, a_id, amt) VALUES "
                                "(10, 1, 5), (11, 1, 7), (12, 2, 3)")
                with _pt.raises(ValueError, match="select list"):
                    await s.execute(
                        "SELECT DISTINCT name FROM a "
                        "JOIN b ON a.id = b.a_id ORDER BY b.amt")
                r = await s.execute(
                    "SELECT DISTINCT name FROM a "
                    "JOIN b ON a.id = b.a_id ORDER BY name")
                assert r.rows == [{"name": "x"}, {"name": "y"}]
            finally:
                await mc.shutdown()
        asyncio.run(go())


class TestExplainAnalyze:
    def test_explain_analyze_runs_and_reports_actuals(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute("CREATE TABLE ea (k bigint PRIMARY "
                                "KEY, v bigint) WITH tablets = 1")
                await s.execute(
                    "INSERT INTO ea (k, v) VALUES (1, 1), (2, 2)")
                r = await s.execute(
                    "EXPLAIN ANALYZE SELECT k FROM ea WHERE v > 1")
                plan = [x["QUERY PLAN"] for x in r.rows]
                assert any("Actual rows: 1" in ln for ln in plan), plan
                assert any(ln.startswith("Execution Time:")
                           for ln in plan), plan
                # DML side effects apply, as in PG
                await s.execute(
                    "EXPLAIN ANALYZE UPDATE ea SET v = 9 WHERE k = 1")
                r = await s.execute("SELECT v FROM ea WHERE k = 1")
                assert r.rows == [{"v": 9}]
            finally:
                await mc.shutdown()
        asyncio.run(go())


class TestPkMovingUpdate:
    """UPDATE that SETs a primary-key column re-keys like PG: old key
    deleted, new key strict-inserted (collision errors), children
    referencing the moved key veto (ON UPDATE NO ACTION scope)."""

    def test_rekey_overlap_collision_and_fk_veto(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            s = SqlSession(mc.client())
            try:
                await s.execute("CREATE TABLE t (k bigint PRIMARY "
                                "KEY, v bigint) WITH tablets = 2")
                await s.execute("INSERT INTO t (k, v) VALUES "
                                "(1, 10), (2, 20), (3, 30)")
                await s.execute("UPDATE t SET k = 100 WHERE k = 1")
                r = await s.execute("SELECT k FROM t ORDER BY k")
                assert [x["k"] for x in r.rows] == [2, 3, 100]
                # overlapping moves re-key cleanly
                await s.execute("UPDATE t SET k = k + 1 WHERE k < 10")
                r = await s.execute("SELECT k, v FROM t ORDER BY k")
                assert [(x["k"], x["v"]) for x in r.rows] == \
                    [(3, 20), (4, 30), (100, 10)]
                # collision with an existing key errors
                with pytest.raises(Exception, match="duplicate"):
                    await s.execute("UPDATE t SET k = 100 WHERE k = 3")
                # txn rollback restores the original keys
                await s.execute("BEGIN")
                await s.execute("UPDATE t SET k = 200 WHERE k = 4")
                r = await s.execute("SELECT k FROM t ORDER BY k")
                assert [x["k"] for x in r.rows] == [3, 100, 200]
                await s.execute("ROLLBACK")
                r = await s.execute("SELECT k FROM t ORDER BY k")
                assert [x["k"] for x in r.rows] == [3, 4, 100]
                # a referenced key cannot move away from its children
                await s.execute(
                    "CREATE TABLE ch (id bigint PRIMARY KEY, tk "
                    "bigint REFERENCES t (k)) WITH tablets = 1")
                await s.execute("INSERT INTO ch (id, tk) "
                                "VALUES (1, 3)")
                with pytest.raises(Exception,
                                   match="still referenced"):
                    await s.execute("UPDATE t SET k = 5 WHERE k = 3")
                r = await s.execute("SELECT k FROM t ORDER BY k")
                assert [x["k"] for x in r.rows] == [3, 4, 100]
            finally:
                await mc.shutdown()
        asyncio.run(go())

    def test_txn_rekey_collision_keeps_row(self, tmp_path):
        """Inside an explicit txn a re-key whose strict insert
        collides must roll the WHOLE statement back — committing must
        not make the old row vanish."""
        async def go():
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            s = SqlSession(mc.client())
            try:
                await s.execute("CREATE TABLE tx2 (k bigint PRIMARY "
                                "KEY, v bigint) WITH tablets = 1")
                await s.execute("INSERT INTO tx2 (k, v) VALUES "
                                "(1, 10), (2, 20)")
                await s.execute("BEGIN")
                with pytest.raises(Exception, match="duplicate"):
                    await s.execute("UPDATE tx2 SET k = 2 WHERE k = 1")
                await s.execute("COMMIT")
                r = await s.execute("SELECT k FROM tx2 ORDER BY k")
                assert [x["k"] for x in r.rows] == [1, 2]
            finally:
                await mc.shutdown()
        asyncio.run(go())

    def test_overlapping_shift_of_referenced_keys_allowed(
            self, tmp_path):
        """Moving k=k+1 over a referenced key that the SAME statement
        re-creates passes (end-of-statement NO ACTION), while moving
        a referenced key away with no replacement still vetoes —
        including when the child declared ON DELETE CASCADE (delete
        actions don't fire for updates)."""
        async def go():
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            s = SqlSession(mc.client())
            try:
                await s.execute("CREATE TABLE par2 (k bigint PRIMARY "
                                "KEY) WITH tablets = 1")
                await s.execute(
                    "CREATE TABLE ch2 (id bigint PRIMARY KEY, pk2 "
                    "bigint REFERENCES par2 (k) ON DELETE CASCADE) "
                    "WITH tablets = 1")
                await s.execute("INSERT INTO par2 (k) VALUES (2), (3)")
                await s.execute("INSERT INTO ch2 (id, pk2) "
                                "VALUES (1, 3)")
                # 2->3, 3->4: key 3 re-created by the same statement
                await s.execute("UPDATE par2 SET k = k + 1")
                r = await s.execute("SELECT k FROM par2 ORDER BY k")
                assert [x["k"] for x in r.rows] == [3, 4]
                # moving 3 away entirely: child still references it,
                # and ON DELETE CASCADE must NOT delete the child
                with pytest.raises(Exception,
                                   match="still referenced"):
                    await s.execute(
                        "UPDATE par2 SET k = 9 WHERE k = 3")
                r = await s.execute("SELECT count(*) FROM ch2")
                assert r.rows[0]["count"] == 1
            finally:
                await mc.shutdown()
        asyncio.run(go())
