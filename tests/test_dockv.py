"""dockv encoding tests — the memcmp-order invariant and round-trips.

Modeled on the reference's doc key tests (reference:
src/yb/dockv/doc_key-test.cc, randomized comparison strategy like
src/yb/docdb/randomized_docdb-test.cc).
"""
import random

import pytest

from yugabyte_db_tpu.dockv import (
    DocKey, KeyEntryValue, SubDocKey, decode_key_entry, encode_key_entry,
    PartitionSchema, Partition, hash_key_for,
    ColumnSchema, ColumnType, TableSchema, SchemaPacking, RowPacker,
    unpack_row, SchemaPackingStorage,
)
from yugabyte_db_tpu.dockv.partition import split_partition
from yugabyte_db_tpu.utils.hybrid_time import DocHybridTime, HybridTime


K = KeyEntryValue


def rand_entry(rng, desc=False):
    kind = rng.choice(["null", "int32", "int64", "double", "string"])
    if kind == "null":
        return K.null(desc)
    if kind == "int32":
        return K.int32(rng.randint(-2**31, 2**31 - 1), desc)
    if kind == "int64":
        return K.int64(rng.randint(-2**63, 2**63 - 1), desc)
    if kind == "double":
        return K.double(rng.uniform(-1e12, 1e12), desc)
    s = "".join(rng.choice("ab\x01z") for _ in range(rng.randint(0, 6)))
    return K.string(s, desc)


def entry_sort_key(e):
    order = {"null": 0, "bool": 1, "int32": 2, "int64": 3,
             "double": 4, "string": 5}
    return (order[e.kind], e.value if e.value is not None else 0)


class TestKeyEntryEncoding:
    @pytest.mark.parametrize("e", [
        K.null(), K.bool_(True), K.bool_(False),
        K.int32(0), K.int32(-1), K.int32(2**31 - 1), K.int32(-2**31),
        K.int64(123456789012345), K.int64(-99),
        K.double(0.0), K.double(-1.5), K.double(3.25e300),
        K.string(""), K.string("hello"), K.string("a\x00b\x00\x01c"),
        K.raw_bytes(b"\x00\xff\x00"),
        K.timestamp(1700000000_000000),
        K.int32(42, desc=True), K.int64(-7, desc=True),
        K.double(2.5, desc=True), K.string("zz\x00q", desc=True),
        K.column_id(300),
    ])
    def test_roundtrip(self, e):
        enc = encode_key_entry(e)
        dec, pos = decode_key_entry(enc, 0)
        assert pos == len(enc)
        assert dec == e

    def test_int_order(self):
        vals = sorted(random.Random(7).sample(range(-10**9, 10**9), 200))
        encs = [encode_key_entry(K.int64(v)) for v in vals]
        assert encs == sorted(encs)

    def test_int_desc_order(self):
        vals = sorted(random.Random(8).sample(range(-10**6, 10**6), 200))
        encs = [encode_key_entry(K.int64(v, desc=True)) for v in vals]
        assert encs == sorted(encs, reverse=True)

    def test_double_order(self):
        rng = random.Random(9)
        vals = sorted(rng.uniform(-1e9, 1e9) for _ in range(200))
        encs = [encode_key_entry(K.double(v)) for v in vals]
        assert encs == sorted(encs)

    def test_string_order_with_zeros(self):
        vals = sorted(["", "a", "a\x00", "a\x00\x00", "a\x00\x01", "a\x01",
                       "ab", "b"])
        encs = [encode_key_entry(K.string(v)) for v in vals]
        assert encs == sorted(encs)

    def test_string_prefix_freedom(self):
        # "ab" < "ab\x00..." must hold in encoded space
        a = encode_key_entry(K.string("ab"))
        b = encode_key_entry(K.string("ab\x00"))
        c = encode_key_entry(K.string("abc"))
        assert a < b < c


class TestDocKey:
    def test_roundtrip_hash(self):
        dk = DocKey.make(hash=0xBEEF, hashed=(K.int64(5), K.string("x")),
                         range=(K.int32(9), K.null()))
        enc = dk.encode()
        dec, pos = DocKey.decode(enc)
        assert pos == len(enc)
        assert dec == dk

    def test_roundtrip_range_only(self):
        dk = DocKey.make(range=(K.string("k1"), K.int64(2)))
        dec, _ = DocKey.decode(dk.encode())
        assert dec == dk

    def test_subdockey_ht_ordering(self):
        """Newer hybrid times must sort FIRST for the same doc key."""
        dk = DocKey.make(range=(K.string("row"),))
        older = SubDocKey(dk, (), DocHybridTime(HybridTime.from_micros(100), 0))
        newer = SubDocKey(dk, (), DocHybridTime(HybridTime.from_micros(200), 0))
        assert newer.encode() < older.encode()
        # same HT, higher write_id sorts first
        w0 = SubDocKey(dk, (), DocHybridTime(HybridTime.from_micros(100), 0))
        w1 = SubDocKey(dk, (), DocHybridTime(HybridTime.from_micros(100), 1))
        assert w1.encode() < w0.encode()

    def test_subdockey_roundtrip(self):
        dk = DocKey.make(hash=7, hashed=(K.int32(1),), range=(K.string("r"),))
        sdk = SubDocKey(dk, (K.column_id(12),),
                        DocHybridTime(HybridTime.from_micros(55), 3))
        dec = SubDocKey.decode(sdk.encode())
        assert dec == sdk

    def test_fuzz_tuple_order_matches_bytes_order(self):
        rng = random.Random(42)
        keys = []
        for _ in range(300):
            n = rng.randint(1, 3)
            entries = tuple(
                K.int64(rng.randint(-1000, 1000)) for _ in range(n))
            keys.append(entries)
        encoded = [DocKey.make(range=e).encode() for e in keys]
        py_sorted = sorted(range(len(keys)),
                           key=lambda i: tuple(e.value for e in keys[i]))
        enc_sorted = sorted(range(len(keys)), key=lambda i: encoded[i])
        # tuples of equal prefix but different length: shorter sorts first in
        # both systems (GroupEnd 0x21 is larger than kLowest, smaller than
        # any value type >= 0x30? ensure it's smaller than all value types)
        assert [keys[i] for i in py_sorted] == [keys[i] for i in enc_sorted]


class TestPartition:
    def test_hash_deterministic(self):
        h1 = hash_key_for((K.int64(42),))
        h2 = hash_key_for((K.int64(42),))
        assert h1 == h2
        assert 0 <= h1 < 0x10000

    def test_partition_routing(self):
        ps = PartitionSchema("hash", 1)
        parts = ps.create_partitions(8)
        assert len(parts) == 8
        for trial in range(100):
            pk = ps.partition_key_for_row((K.int64(trial),))
            owners = [p for p in parts if p.contains(pk)]
            assert len(owners) == 1

    def test_even_split_bounds(self):
        ps = PartitionSchema("hash", 1)
        parts = ps.create_partitions(4)
        assert parts[0].start == b""
        assert parts[-1].end == b""
        for a, b in zip(parts, parts[1:]):
            assert a.end == b.start

    def test_split_partition(self):
        p = Partition(b"\x40\x00", b"\x80\x00")
        lo, hi = split_partition(p)
        assert lo.start == p.start and hi.end == p.end
        assert lo.end == hi.start
        assert lo.contains(b"\x40\x01") and not hi.contains(b"\x40\x01")

    def test_range_partitioning(self):
        ps = PartitionSchema("range")
        sp = [DocKey.make(range=(K.int64(100),)).encode()]
        parts = ps.create_partitions(2, split_points=sp)
        k_lo = ps.partition_key_for_row((K.int64(5),))
        k_hi = ps.partition_key_for_row((K.int64(200),))
        assert parts[0].contains(k_lo) and parts[1].contains(k_hi)


def sample_schema():
    return TableSchema(columns=(
        ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
        ColumnSchema(1, "qty", ColumnType.FLOAT64),
        ColumnSchema(2, "price", ColumnType.FLOAT64),
        ColumnSchema(3, "flag", ColumnType.BOOL),
        ColumnSchema(4, "name", ColumnType.STRING),
        ColumnSchema(5, "blob", ColumnType.BINARY),
        ColumnSchema(6, "n", ColumnType.INT32),
    ), version=3)


class TestPackedRow:
    def test_roundtrip(self):
        schema = sample_schema()
        sp = SchemaPacking.from_schema(schema)
        packer = RowPacker(sp)
        vals = {1: 2.5, 2: 10.0, 3: True, 4: "héllo", 5: b"\x00\x01", 6: -7}
        data = packer.pack(vals)
        out = unpack_row(sp, data)
        assert out == vals

    def test_nulls(self):
        schema = sample_schema()
        sp = SchemaPacking.from_schema(schema)
        packer = RowPacker(sp)
        vals = {1: None, 2: 3.0, 3: None, 4: None, 5: b"", 6: 0}
        out = unpack_row(sp, packer.pack(vals))
        assert out == vals

    def test_missing_treated_as_null(self):
        schema = sample_schema()
        sp = SchemaPacking.from_schema(schema)
        out = unpack_row(sp, RowPacker(sp).pack({2: 1.0}))
        assert out[1] is None and out[4] is None and out[2] == 1.0

    def test_fixed_stride(self):
        """All-fixed-schema packed rows have identical length — the property
        the columnar block decode relies on."""
        schema = TableSchema(columns=(
            ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
            ColumnSchema(1, "a", ColumnType.FLOAT64),
            ColumnSchema(2, "b", ColumnType.INT32),
        ), version=1)
        sp = SchemaPacking.from_schema(schema)
        p = RowPacker(sp)
        lens = {len(p.pack({1: float(i), 2: i})) for i in range(50)}
        assert len(lens) == 1

    def test_storage_versioning(self):
        st = SchemaPackingStorage()
        s3 = sample_schema()
        st.add_schema(s3)
        packed = RowPacker(st.get(3)).pack({2: 9.0})
        assert st.version_of(packed) == 3
