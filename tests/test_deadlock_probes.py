"""Cross-tablet deadlock detection via coordinator probes + persisted
SERIALIZABLE read locks surviving leader failover (reference:
docdb/deadlock_detector.cc; kStrongRead intents in
docdb/conflict_resolution.cc)."""
import asyncio
import time

import pytest

from yugabyte_db_tpu.rpc import RpcError
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
from tests.test_transactions import kv_info, make_cluster, run


def _find_tablet_keys(c, mc, n_keys=3):
    """Keys routed to n_keys DIFFERENT tablets of 'acct'."""
    # partition routing is deterministic: probe keys until three land
    # on distinct tablets
    pass


class TestCrossTabletDeadlock:
    def test_three_tablet_cycle_resolves_via_probe(self, tmp_path):
        """T1->T2->T3->T1 across three different tablets: no single
        tablet sees a local cycle, so only the coordinator probes can
        break it — and well before the 5s wait timeout."""
        async def go():
            mc, c = await make_cluster(str(tmp_path), tablets=8)
            try:
                ct = await c._table("acct")
                # find three keys on three different tablets
                by_tablet = {}
                for k in range(200):
                    pkey = ct.info.partition_schema.partition_key_for_row(
                        ct.codec.pk_entries({"k": k, "bal": 0.0}))
                    for loc in ct.locations:
                        if loc.partition.contains(pkey):
                            by_tablet.setdefault(loc.tablet_id, k)
                            break
                    if len(by_tablet) >= 3:
                        break
                keys = list(by_tablet.values())[:3]
                assert len(keys) == 3
                k1, k2, k3 = keys

                txns = [await c.transaction().begin() for _ in range(3)]
                t1, t2, t3 = txns
                await t1.insert("acct", [{"k": k1, "bal": 1.0}])
                await t2.insert("acct", [{"k": k2, "bal": 2.0}])
                await t3.insert("acct", [{"k": k3, "bal": 3.0}])

                outcomes = {}

                async def step(txn, name, key):
                    try:
                        await txn.insert("acct", [{"k": key, "bal": 9.0}])
                        await txn.commit()
                        outcomes[name] = "committed"
                    except RpcError as e:
                        outcomes[name] = e.code

                t0 = time.monotonic()
                await asyncio.gather(
                    step(t1, "t1", k2), step(t2, "t2", k3),
                    step(t3, "t3", k1))
                elapsed = time.monotonic() - t0
                committed = [n for n, o in outcomes.items()
                             if o == "committed"]
                # the probe aborts exactly ONE victim (the youngest);
                # its successor in the cycle then commits, and the
                # remaining txn legitimately aborts via first-committer-
                # wins against that commit — so exactly one commits
                assert len(committed) == 1, outcomes
                assert elapsed < 4.5, (
                    f"cycle broke only at the wait timeout "
                    f"({elapsed:.1f}s) — probes did not fire")
            finally:
                await mc.shutdown()
        run(go())


class TestPersistedReadLocks:
    def test_read_locks_survive_leader_failover(self, tmp_path):
        """SERIALIZABLE read locks replicate through Raft: after the
        leader dies, the new leader still blocks conflicting writers
        until the reader commits."""
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=3).start()
            c = mc.client()
            await c.create_table(kv_info(), num_tablets=1,
                                 replication_factor=3)
            await mc.wait_for_leaders("acct")
            await c.insert("acct", [{"k": 1, "bal": 100.0}])
            await c.messenger.call(mc.master.messenger.addr, "master",
                                   "get_status_tablet", {})
            await mc.wait_for_leaders("system.transactions")

            reader = await c.transaction(
                isolation="serializable").begin()
            row = await reader.get("acct", {"k": 1})
            assert row["bal"] == 100.0

            # find + kill the acct tablet leader (not the status leader)
            ct = await c._table("acct")
            acct_tid = ct.locations[0].tablet_id
            leader_idx = None
            for i, ts in enumerate(mc.tservers):
                p = ts.peers.get(acct_tid)
                if p is not None and p.is_leader():
                    leader_idx = i
            assert leader_idx is not None
            victim_uuid = mc.tservers[leader_idx].uuid
            await mc.stop_tserver(leader_idx)
            # wait for a new acct leader among survivors
            deadline = asyncio.get_event_loop().time() + 20.0
            new_leader = None
            while asyncio.get_event_loop().time() < deadline:
                for i, ts in enumerate(mc.tservers):
                    if ts.uuid == victim_uuid or i == leader_idx:
                        continue
                    p = ts.peers.get(acct_tid)
                    if p is not None and p.is_leader():
                        new_leader = p
                        break
                if new_leader:
                    break
                await asyncio.sleep(0.1)
            assert new_leader is not None, "no new leader elected"
            # the new leader must still hold the read lock
            assert new_leader.participant._read_holders, \
                "read locks were lost in the failover"

            # a conflicting writer must block/abort, not slip through
            for ts in mc.tservers:
                for p in ts.peers.values():
                    p.participant.wait_timeout = 1.0
            writer = await c.transaction().begin()
            with pytest.raises(RpcError):
                await writer.insert("acct", [{"k": 1, "bal": 0.0}])
            await mc.shutdown()
        run(go())
