"""Fault injection tests: crash points, probabilistic faults, YCSB
workload smoke, TPU filter-pushdown row scans (reference analog: the
TEST_ flag / sync point / crash point machinery of SURVEY.md §4)."""
import numpy as np
import pytest

from yugabyte_db_tpu.docdb import ReadRequest, RowOp, WriteRequest
from yugabyte_db_tpu.models.ycsb import (
    YcsbTabletWorkload, generate_rows, usertable_info,
)
from yugabyte_db_tpu.storage.lsm import LsmStore, WriteBatch
from yugabyte_db_tpu.tablet import Tablet
from yugabyte_db_tpu.utils import fault_injection as fi, flags
from yugabyte_db_tpu.utils.status import StatusError


@pytest.fixture(autouse=True)
def _clean():
    yield
    fi.clear_crash_points()
    fi.clear_sync_points()
    flags.REGISTRY.reset("TEST_fault_crash_fraction")


class TestCrashPoints:
    def test_crash_during_flush_keeps_manifest_consistent(self, tmp_path):
        db = LsmStore(str(tmp_path))
        db.apply(WriteBatch([(b"a", b"1"), (b"b", b"2")]))
        fi.arm_crash_point("flush:before_manifest")
        with pytest.raises(fi.CrashPointHit):
            db.flush()
        # "process restart": reopen from disk — manifest never listed the
        # orphan SST, so the store opens clean (data would be recovered
        # from the WAL by the tablet peer)
        db2 = LsmStore(str(tmp_path))
        assert db2.ssts == []

    def test_wal_crash_point_fires(self, tmp_path):
        from yugabyte_db_tpu.consensus import Log, LogEntry
        log = Log(str(tmp_path))
        fi.arm_crash_point("wal:after_append")
        with pytest.raises(fi.CrashPointHit):
            log.append([LogEntry(1, 1, "write", b"x")])
        fi.clear_crash_points()
        log2 = Log(str(tmp_path))
        assert log2.last_index == 1   # entry was durably appended first

    def test_maybe_fault_probabilistic(self, tmp_path):
        flags.set_flag("TEST_fault_crash_fraction", 1.0)
        db = LsmStore(str(tmp_path))
        with pytest.raises(StatusError):
            db.apply(WriteBatch([(b"k", b"v")]))
        flags.set_flag("TEST_fault_crash_fraction", 0.0)
        db.apply(WriteBatch([(b"k", b"v")]))

    def test_sync_point_callback(self):
        hits = []
        fi.set_sync_point("test:point", lambda: hits.append(1))
        fi.TEST_SYNC_POINT("test:point")
        fi.TEST_SYNC_POINT("unarmed:point")
        assert hits == [1]


class TestYcsb:
    def test_workload_c_and_a(self, tmp_path):
        t = Tablet("u1", usertable_info(), str(tmp_path))
        w = YcsbTabletWorkload(t, n_rows=500)
        assert w.load() == 500
        rc = w.run("c", ops=50)
        assert rc.ops_per_sec > 0
        ra = w.run("a", ops=50)
        assert ra.ops == 50
        # updates took effect for workload a
        resp = t.read(ReadRequest("usertable", pk_eq={"ycsb_key": 0}))
        assert resp.rows


class TestTpuFilterScan:
    def test_filter_pushdown_rows_match_cpu(self, tmp_path):
        from yugabyte_db_tpu.ops import Expr
        C = Expr.col
        info = usertable_info()
        t = Tablet("u2", info, str(tmp_path))
        t.bulk_load(generate_rows(6000))
        req = ReadRequest("usertable", columns=("ycsb_key", "field0"),
                          where=(C(0) >= 5990).node)
        flags.set_flag("tpu_min_rows_for_pushdown", 100)
        try:
            tpu = t.read(req)
            flags.set_flag("tpu_pushdown_enabled", False)
            cpu = t.read(ReadRequest("usertable",
                                     columns=("ycsb_key", "field0"),
                                     where=(C(0) >= 5990).node))
        finally:
            flags.REGISTRY.reset("tpu_pushdown_enabled")
            flags.REGISTRY.reset("tpu_min_rows_for_pushdown")
        assert tpu.backend == "tpu" and cpu.backend == "cpu"
        key = lambda r: r["ycsb_key"]
        assert sorted(tpu.rows, key=key) == sorted(cpu.rows, key=key)
        assert len(tpu.rows) == 10
