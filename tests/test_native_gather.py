"""Parity suite for the fused native gather/scatter/encode layer
(native/ybtpu_native.cpp gather_multi/copy_multi/gather_heap/
fnv64_rows_fixed via storage/native_lib.py).

Every test builds the same output twice — once through the native fused
call, once through the pure-numpy fallback oracle — and asserts byte
identity.  Shapes cover what the hot paths actually send: mixed column
widths (1/2/4/8-byte lanes plus wide uint8 key matrices), null-mask
lanes, empty inputs, non-contiguous/duplicated/reversed permutations,
and (slow) a source large enough that a byte offset overflows int32 —
the >2 GiB safety check for the int64 offset arithmetic.
"""
import numpy as np
import pytest

from yugabyte_db_tpu.storage import native_lib


RNG = np.random.default_rng(1234)

#: the hot paths' lane shapes: (dtype, row-shape suffix)
LANES = [
    (np.uint8, ()),          # tombstone / null masks
    (np.int16, ()),
    (np.uint32, ()),         # write_id
    (np.uint64, ()),         # ht / key_hash
    (np.float64, ()),        # value columns
    (np.uint8, (25,)),       # doc-key matrix rows
    (np.uint8, (38,)),       # full SubDocKey matrix rows
    (np.int64, (3,)),        # multi-word rows
]


def _src(n, dtype, suffix):
    if dtype == np.float64:
        return RNG.normal(size=(n,) + suffix)
    info = np.iinfo(dtype)
    return RNG.integers(info.min, int(info.max) + 1, (n,) + suffix,
                        dtype=dtype)


def _jobs(n_src, idx, dst_idx, n_out):
    jobs, oracle = [], []
    for dtype, suffix in LANES:
        src = _src(n_src, dtype, suffix)
        dst_native = np.zeros((n_out,) + suffix, dtype)
        dst_oracle = np.zeros((n_out,) + suffix, dtype)
        jobs.append((src, dst_native, idx, dst_idx))
        oracle.append((src, dst_oracle, idx, dst_idx))
    return jobs, oracle


def _assert_parity(jobs, oracle):
    native_ok = native_lib.gather_multi(jobs)
    native_lib.gather_multi_fallback(oracle)
    if not native_ok:
        pytest.skip("native library unavailable — fallback is the "
                    "only implementation; nothing to compare")
    for (_, got, _, _), (_, want, _, _) in zip(jobs, oracle):
        assert got.dtype == want.dtype
        np.testing.assert_array_equal(got, want)


class TestFusedGatherParity:
    def test_gather_mixed_widths(self):
        idx = RNG.integers(0, 1000, 700).astype(np.int64)
        jobs, oracle = _jobs(1000, idx, None, 700)
        _assert_parity(jobs, oracle)

    def test_gather_scatter_mixed_widths(self):
        idx = RNG.integers(0, 500, 300).astype(np.int64)
        dst_idx = RNG.permutation(900)[:300].astype(np.int64)
        jobs, oracle = _jobs(500, idx, dst_idx, 900)
        _assert_parity(jobs, oracle)

    def test_pure_copy_and_scatter_only(self):
        src = _src(400, np.uint64, ())
        for didx in (None,
                     RNG.permutation(400).astype(np.int64)):
            got = np.zeros(400, np.uint64)
            want = np.zeros(400, np.uint64)
            jobs = [(src, got, None, didx)]
            ora = [(src, want, None, didx)]
            _assert_parity(jobs, ora)

    def test_non_contiguous_permutations(self):
        # strided / reversed / duplicated index shapes: callers must
        # pre-coerce to contiguous int64; the wrapper REFUSES the
        # non-contiguous form instead of silently misreading it
        base = np.arange(2000, dtype=np.int64)
        strided = base[::2]
        assert not strided.flags["C_CONTIGUOUS"] or strided.base is not None
        src = _src(2000, np.uint64, ())
        dst = np.zeros(1000, np.uint64)
        if native_lib.available():
            assert not native_lib.gather_multi(
                [(src, dst, base[::2], None)])
        # the coerced form gathers identically to numpy
        idx = np.ascontiguousarray(base[::2])
        rev = np.ascontiguousarray(base[::-1][:1000])
        dup = np.zeros(1000, np.int64) + 7
        for perm in (idx, rev, dup):
            jobs, oracle = _jobs(2000, perm, None, 1000)
            _assert_parity(jobs, oracle)

    def test_wrong_index_dtype_refused(self):
        if not native_lib.available():
            pytest.skip("native library unavailable")
        src = _src(100, np.uint64, ())
        dst = np.zeros(50, np.uint64)
        assert not native_lib.gather_multi(
            [(src, dst, np.arange(50, dtype=np.int32), None)])

    def test_row_width_mismatch_refused(self):
        if not native_lib.available():
            pytest.skip("native library unavailable")
        src = _src(100, np.uint8, (25,))
        dst = np.zeros((50, 38), np.uint8)
        assert not native_lib.gather_multi(
            [(src, dst, np.arange(50, dtype=np.int64), None)])

    def test_empty_inputs(self):
        idx = np.zeros(0, np.int64)
        jobs, oracle = _jobs(10, idx, None, 0)
        _assert_parity(jobs, oracle)
        # empty job list: False (nothing fused), fallback no-ops
        assert not native_lib.gather_multi([])
        native_lib.gather_multi_fallback([])

    def test_gather_columns_forced_fallback_parity(self, monkeypatch):
        # with the library forced away, gather_columns must produce the
        # same bytes through the numpy fallback (the no-toolchain path)
        monkeypatch.setattr(native_lib, "_LIB", None)
        monkeypatch.setattr(native_lib, "_TRIED", True)
        idx = RNG.integers(0, 300, 200).astype(np.int64)
        jobs, oracle = _jobs(300, idx, None, 200)
        assert not native_lib.available()
        native_lib.gather_columns(jobs)
        for (_, got, _, _), (src, want, i, d) in zip(jobs, oracle):
            np.testing.assert_array_equal(got, src[i])

    def test_gather_columns_entry_point(self):
        # the one entry hot paths call: must produce oracle output
        # whether or not the native library loaded
        idx = RNG.integers(0, 300, 200).astype(np.int64)
        jobs, oracle = _jobs(300, idx, None, 200)
        native_lib.gather_columns(jobs)
        native_lib.gather_multi_fallback(oracle)
        for (_, got, _, _), (_, want, _, _) in zip(jobs, oracle):
            np.testing.assert_array_equal(got, want)


class TestCopyMulti:
    def test_segmented_copy_parity(self):
        srcs = [_src(n, np.float64, ()) for n in (100, 1, 4096)]
        out_native = np.zeros(4197, np.float64)
        out_oracle = np.zeros(4197, np.float64)
        jobs, pos = [], 0
        for s in srcs:
            jobs.append((s, out_native[pos:pos + len(s)]))
            out_oracle[pos:pos + len(s)] = s
            pos += len(s)
        if not native_lib.copy_multi(jobs):
            pytest.skip("native library unavailable")
        np.testing.assert_array_equal(out_native, out_oracle)

    def test_nbytes_mismatch_refused(self):
        if not native_lib.available():
            pytest.skip("native library unavailable")
        assert not native_lib.copy_multi(
            [(np.zeros(4, np.int64), np.zeros(3, np.int64))])


class TestGatherHeap:
    def test_varlen_heap_parity(self):
        heap = RNG.integers(0, 256, 5000).astype(np.uint8)
        lens = RNG.integers(0, 40, 200).astype(np.int64)
        src_start = RNG.integers(0, 4900, 200).astype(np.int64)
        src_start = np.minimum(src_start, 5000 - lens)
        out_ends = np.cumsum(lens)
        dst_start = np.ascontiguousarray(out_ends - lens)
        out = np.zeros(int(out_ends[-1]), np.uint8)
        if not native_lib.gather_heap(heap, src_start, dst_start,
                                      lens, out):
            pytest.skip("native library unavailable")
        want = np.concatenate(
            [heap[s:s + l] for s, l in zip(src_start, lens)])
        np.testing.assert_array_equal(out, want)

    def test_zero_length_rows(self):
        heap = np.arange(16, dtype=np.uint8)
        lens = np.zeros(5, np.int64)
        zeros = np.zeros(5, np.int64)
        out = np.zeros(0, np.uint8)
        if not native_lib.gather_heap(heap, zeros, zeros, lens, out):
            pytest.skip("native library unavailable")


class TestFnvRows:
    def test_matches_numpy_and_scalar(self):
        from yugabyte_db_tpu.storage.columnar import (_HASH_MULT,
                                                      _HASH_OFF,
                                                      fnv64_bytes)
        mat = RNG.integers(0, 256, (500, 25)).astype(np.uint8)
        nat = native_lib.fnv64_rows_fixed(mat)
        if nat is None:
            pytest.skip("native library unavailable")
        ref = np.full(mat.shape[0], _HASH_OFF)
        for j in range(mat.shape[1]):
            ref = (ref ^ mat[:, j].astype(np.uint64)) * _HASH_MULT
        np.testing.assert_array_equal(nat, ref)
        assert int(nat[0]) == fnv64_bytes(mat[0].tobytes())


@pytest.mark.slow
class TestLargeOffsets:
    def test_gather_beyond_2gib_byte_offsets(self):
        """>2 GiB-index safety: row_bytes * idx must be computed in
        int64 — an int32 wrap would read ~2 GiB below the intended
        offset and corrupt the gather silently."""
        if not native_lib.available():
            pytest.skip("native library unavailable")
        row = 512
        n = (1 << 31) // row + 64           # ~2.03 GiB + a little
        src = np.zeros((n, row), np.uint8)
        marks = np.asarray([0, n // 2, n - 2, n - 1], np.int64)
        for m in marks:
            src[m, :8] = np.frombuffer(
                np.uint64(m).tobytes(), np.uint8)
        dst = np.zeros((len(marks), row), np.uint8)
        assert native_lib.gather_multi([(src, dst, marks, None)])
        for i, m in enumerate(marks):
            got = int(np.frombuffer(dst[i, :8].tobytes(), np.uint64)[0])
            assert got == int(m), f"row {m}: offset arithmetic wrapped"
