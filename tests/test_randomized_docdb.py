"""Randomized DocDB model checking.

Reference strategy: an in-memory model double-checks DocDB under
randomized operation sequences (src/yb/docdb/in_mem_docdb.cc,
randomized_docdb-test.cc). Here: random upserts/deletes at increasing
hybrid times with random flush/compaction interleavings; reads at random
historical timestamps must match a versioned dict model; TPU aggregate
results must match model-side aggregation too.
"""
import random

import numpy as np
import pytest

from yugabyte_db_tpu.docdb import ReadRequest, RowOp, WriteRequest
from yugabyte_db_tpu.ops import AggSpec, Expr
from yugabyte_db_tpu.tablet import Tablet
from yugabyte_db_tpu.utils import flags
from yugabyte_db_tpu.utils.hybrid_time import (
    HybridClock, HybridTime, MockPhysicalClock,
)
from tests.test_tablet import make_info

C = Expr.col


class VersionedModel:
    """The in-memory truth: key -> [(ht, row_or_None)] sorted by ht."""

    def __init__(self):
        self.hist = {}

    def put(self, k, row, ht):
        self.hist.setdefault(k, []).append((ht, row))

    def delete(self, k, ht):
        self.hist.setdefault(k, []).append((ht, None))

    def get(self, k, read_ht):
        best = None
        for ht, row in self.hist.get(k, []):
            if ht <= read_ht:
                if best is None or ht > best[0]:
                    best = (ht, row)
        return best[1] if best else None

    def visible_rows(self, read_ht):
        out = {}
        for k in self.hist:
            r = self.get(k, read_ht)
            if r is not None:
                out[k] = r
        return out


@pytest.mark.parametrize("seed", [7, 23, 91])
def test_randomized_ops_match_model(tmp_path, seed):
    rng = random.Random(seed)
    clock = HybridClock(MockPhysicalClock(1_000_000))
    tablet = Tablet(f"rand-{seed}", make_info(), str(tmp_path),
                    clock=clock)
    model = VersionedModel()
    checkpoints = []      # (read_ht, snapshot of model state at that point)

    for step in range(300):
        clock._physical.advance_micros(rng.randint(1, 50))
        op = rng.random()
        k = rng.randint(0, 30)
        if op < 0.6:
            row = {"k": k, "v": float(rng.randint(0, 1000)),
                   "s": f"s{step}"}
            resp = tablet.apply_write(WriteRequest(
                "t1", [RowOp("upsert", row)]))
            # the tablet assigned its own HT; read it back from the clock
            ht = clock.now().value
            model.put(k, row, ht - 1)   # write happened just before `now`
        elif op < 0.75:
            tablet.apply_write(WriteRequest("t1", [RowOp("delete",
                                                         {"k": k})]))
            ht = clock.now().value
            model.delete(k, ht - 1)
        elif op < 0.85:
            tablet.flush()
        elif op < 0.9 and tablet.num_sst_files() >= 2:
            tablet.compact()
        if rng.random() < 0.1:
            checkpoints.append(clock.now().value)

    # point reads at current time match the model
    now = clock.now().value
    for k in range(31):
        got = tablet.read(ReadRequest("t1", pk_eq={"k": k},
                                      read_ht=now))
        expect = model.get(k, now)
        if expect is None:
            assert not got.rows, f"key {k}: expected absent"
        else:
            assert got.rows and got.rows[0]["v"] == expect["v"], \
                f"key {k}: {got.rows} vs {expect}"

    # historical reads at random checkpoints match (MVCC time travel)
    for read_ht in checkpoints[:10]:
        visible = model.visible_rows(read_ht)
        resp = tablet.read(ReadRequest("t1", columns=("k", "v"),
                                       read_ht=read_ht))
        got = {r["k"]: r for r in resp.rows}
        assert set(got) == set(visible), \
            f"@{read_ht}: {sorted(got)} vs {sorted(visible)}"
        for k, r in visible.items():
            assert got[k]["v"] == r["v"]

    # aggregate pushdown agrees with the model at a historical point
    if checkpoints:
        read_ht = checkpoints[-1]
        visible = model.visible_rows(read_ht)
        flags.set_flag("tpu_min_rows_for_pushdown", 1)
        try:
            resp = tablet.read(ReadRequest(
                "t1", aggregates=(AggSpec("sum", C(1).node),
                                  AggSpec("count")),
                read_ht=read_ht))
        finally:
            flags.REGISTRY.reset("tpu_min_rows_for_pushdown")
        expect_sum = sum(r["v"] for r in visible.values())
        assert int(resp.agg_values[1]) == len(visible)
        np.testing.assert_allclose(float(resp.agg_values[0]), expect_sum,
                                   rtol=1e-5)
