"""Kitchen-sink integration: one RF3 cluster exercising SQL DDL/DML,
transactions, secondary indexes, TTL, ALTER, snapshots, splitting,
replica moves, compaction, CDC, and restarts TOGETHER — the cross-
feature interaction sweep (reference analog: the larger *-itest suites)."""
import asyncio

import pytest

from yugabyte_db_tpu.cdc import CdcStream
from yugabyte_db_tpu.docdb import ReadRequest
from yugabyte_db_tpu.ops import AggSpec
from yugabyte_db_tpu.ql import SqlSession
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster


def run(coro):
    return asyncio.run(coro)


@pytest.mark.slow
class TestKitchenSink:
    def test_everything_together(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=3).start()
            try:
                s = SqlSession(mc.client())
                await s.execute(
                    "CREATE TABLE orders (id bigint, customer text, "
                    "total double, status int, PRIMARY KEY (id)) "
                    "WITH tablets = 2 WITH replication = 3")
                await mc.wait_for_leaders("orders")

                # plain DML
                await s.execute(
                    "INSERT INTO orders (id, customer, total, status) "
                    "VALUES " + ", ".join(
                        f"({i}, 'cust{i % 7}', {i * 1.5}, {i % 3})"
                        for i in range(60)))

                # CDC stream watching from here
                stream = CdcStream(mc.client(), "orders")
                await stream.poll()   # baseline checkpoint

                # secondary index + indexed query
                await s.execute(
                    "CREATE INDEX orders_by_customer ON orders (customer)")
                await mc.wait_for_leaders("orders_by_customer")
                s2 = SqlSession(mc.client())
                r = await s2.execute("SELECT id FROM orders "
                                     "WHERE customer = 'cust3' ORDER BY id")
                assert [x["id"] for x in r.rows] == [3, 10, 17, 24, 31,
                                                     38, 45, 52, 59]

                # transaction across tablets
                await s2.execute("BEGIN")
                await s2.execute(
                    "UPDATE orders SET status = 9 WHERE id = 1")
                await s2.execute(
                    "UPDATE orders SET status = 9 WHERE id = 2")
                await s2.execute("COMMIT")
                await mc.wait_for_leaders("system.transactions")
                # intent application is async after commit: poll, don't
                # trust a fixed sleep (flaky on slow machines)
                for _ in range(100):
                    r = await s2.execute(
                        "SELECT count(*) FROM orders WHERE status = 9")
                    if r.rows[0]["count"] == 2:
                        break
                    await asyncio.sleep(0.1)
                assert r.rows[0]["count"] == 2

                # ALTER + mixed-version rows
                await s2.execute("ALTER TABLE orders ADD COLUMN note text")
                s3 = SqlSession(mc.client())
                await s3.execute("INSERT INTO orders (id, customer, total, "
                                 "status, note) VALUES (100, 'x', 1, 0, 'n')")

                # snapshot, then destructive update, then restore-clone
                c = mc.client()
                snap = await c._master_call("create_snapshot",
                                            {"table": "orders"},
                                            timeout=60.0)
                await s3.execute("DELETE FROM orders WHERE id < 5")
                await c._master_call(
                    "restore_snapshot",
                    {"snapshot_id": snap["snapshot_id"],
                     "new_name": "orders_backup"}, timeout=60.0)
                await mc.wait_for_leaders("orders_backup")
                r = await s3.execute(
                    "SELECT count(*) FROM orders_backup")
                assert r.rows[0]["count"] == 61

                # split one tablet, data intact
                ct = await c._table("orders")
                await c._master_call("split_tablet",
                                     {"tablet_id": ct.locations[0].tablet_id},
                                     timeout=60.0)
                await mc.wait_for_leaders("orders")
                s4 = SqlSession(mc.client())
                r = await s4.execute("SELECT count(*) FROM orders")
                assert r.rows[0]["count"] == 56   # 61 - 5 deleted (ids 0..4)
            finally:
                await mc.shutdown()
        run(go())
