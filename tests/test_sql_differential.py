"""Differential SQL testing: the device-kernel pushdown path and the
CPU row-interpreter path must return IDENTICAL results for the same
query (reference analog: the reference validates pushdown vs PG
evaluation through its regress matrix; ours runs the same randomized
query against both execution paths and diffs).

This is the equivalence harness for the TPU story: every aggregate /
filter / group shape the device kernels accelerate has a CPU twin, and
a divergence between them is a silent-wrong-results bug by definition.
"""
import asyncio
import random

from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
from yugabyte_db_tpu.ql.executor import SqlSession
from yugabyte_db_tpu.utils import flags


def run(coro):
    return asyncio.run(coro)


N_ROWS = 6000


def _gen_queries(rng):
    """Randomized filter/aggregate/group shapes over the fixed schema
    (k bigint pk, a bigint, b bigint, s text, f double)."""
    preds = [
        lambda: f"a > {rng.randint(0, 50)}",
        lambda: f"b BETWEEN {rng.randint(0, 20)} AND {rng.randint(30, 60)}",
        lambda: f"a IN ({rng.randint(0, 9)}, {rng.randint(10, 19)}, "
                f"{rng.randint(20, 29)})",
        lambda: f"s LIKE '{rng.choice('abc')}%'",
        lambda: f"f < {rng.uniform(0, 100):.3f}",
        lambda: f"a % {rng.randint(2, 7)} = 0",
        lambda: "b IS NOT NULL",
        lambda: f"NOT (a = {rng.randint(0, 50)})",
    ]
    aggs = ["count(*)", "sum(a)", "min(b)", "max(b)", "sum(f)",
            "avg(a)", "count(b)"]
    out = []
    for _ in range(18):
        where = " AND ".join(p() for p in rng.sample(preds,
                                                     rng.randint(1, 3)))
        agg = ", ".join(rng.sample(aggs, rng.randint(1, 3)))
        out.append(f"SELECT {agg} FROM dt WHERE {where}")
    for _ in range(6):
        where = preds[rng.randrange(len(preds))]()
        out.append(f"SELECT b, count(*), sum(a) FROM dt WHERE {where} "
                   f"GROUP BY b ORDER BY b")
    for _ in range(6):
        where = preds[rng.randrange(len(preds))]()
        lim = rng.randint(1, 50)
        out.append(f"SELECT k, a FROM dt WHERE {where} "
                   f"ORDER BY k LIMIT {lim}")
    return out


def _norm(rows):
    """Comparable form: floats rounded (the two paths may accumulate
    float sums in different orders — SUM itself is exact int64 fixed
    point, but avg division and f64 displays can differ in the last
    ulp)."""
    out = []
    for r in rows:
        nr = {}
        for k, v in r.items():
            if isinstance(v, float):
                nr[k] = round(v, 6)
            else:
                nr[k] = v
        out.append(nr)
    return out


class TestSqlDifferential:
    def test_pushdown_vs_interpreter_equivalence(self, tmp_path):
        async def go():
            rng = random.Random(20260730)
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            from yugabyte_db_tpu.ops import scan as _scan_mod
            orig_run = _scan_mod.ScanKernel.run
            try:
                s = SqlSession(mc.client())
                # ONE tablet + flushed SSTs + a lowered threshold so
                # the pushdown gate (per-tablet SST rows >=
                # tpu_min_rows_for_pushdown; memtable rows count as 0)
                # actually engages — and PROVE it below by counting
                # ScanKernel.run invocations, or the diff silently
                # compares the interpreter against itself
                await s.execute(
                    "CREATE TABLE dt (k bigint PRIMARY KEY, a bigint, "
                    "b bigint, s text, f double) WITH tablets = 1")
                rows = []
                for k in range(N_ROWS):
                    a = rng.randint(0, 99)
                    b = rng.choice([None] + list(range(8)))
                    sv = rng.choice(["apple", "banana", "cherry",
                                     "avocado", "blueberry"])
                    f = rng.uniform(0, 100)
                    rows.append(f"({k}, {a}, "
                                f"{'NULL' if b is None else b}, "
                                f"'{sv}', {f:.4f})")
                for lo in range(0, N_ROWS, 500):
                    await s.execute(
                        "INSERT INTO dt (k, a, b, s, f) VALUES "
                        + ", ".join(rows[lo:lo + 500]))
                for ts_ in mc.tservers:
                    for peer in ts_.peers.values():
                        peer.tablet.flush()
                flags.set_flag("tpu_min_rows_for_pushdown", 64)
                await s.execute("ANALYZE dt")
                kernel_runs = {"n": 0}

                def counting_run(self_, *a, **kw):
                    kernel_runs["n"] += 1
                    return orig_run(self_, *a, **kw)
                _scan_mod.ScanKernel.run = counting_run
                queries = _gen_queries(rng)
                diffs = []
                for q in queries:
                    flags.set_flag("tpu_pushdown_enabled", True)
                    r_dev = await s.execute(q)
                    flags.set_flag("tpu_pushdown_enabled", False)
                    r_cpu = await s.execute(q)
                    if _norm(r_dev.rows) != _norm(r_cpu.rows):
                        diffs.append(
                            (q, r_dev.rows[:3], r_cpu.rows[:3]))
                _scan_mod.ScanKernel.run = orig_run
                assert kernel_runs["n"] > 0, (
                    "the pushdown side never reached the scan kernel — "
                    "the differential is vacuous")
                assert not diffs, (
                    f"{len(diffs)} divergences between the pushdown "
                    f"and interpreter paths:\n" + "\n".join(
                        f"  {q}\n    dev: {d}\n    cpu: {c}"
                        for q, d, c in diffs))
            finally:
                _scan_mod.ScanKernel.run = orig_run
                flags.REGISTRY.reset("tpu_pushdown_enabled")
                flags.REGISTRY.reset("tpu_min_rows_for_pushdown")
                await mc.shutdown()
        run(go())
