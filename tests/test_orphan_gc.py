"""Orphan-replica GC: the master's catalog-driven sweep deletes
replicas a tserver keeps reporting that the catalog no longer maps to
it (reference analog: tablet-report reconciliation issuing DeleteTablet
from ProcessTabletReportBatch, master_heartbeat_service.cc:854)."""
import asyncio
import os

from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
from yugabyte_db_tpu.utils import flags


def run(coro):
    return asyncio.run(coro)


async def _wait(cond, timeout=15.0, interval=0.1):
    t0 = asyncio.get_event_loop().time()
    while asyncio.get_event_loop().time() - t0 < timeout:
        if cond():
            return True
        await asyncio.sleep(interval)
    return cond()


class TestOrphanReplicaGC:
    def test_stray_replica_deleted_after_grace(self, tmp_path):
        async def go():
            prior = flags.get("master_orphan_gc_grace_s")
            flags.set_flag("master_orphan_gc_grace_s", 1.0)
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                from tests.test_load_balancer import kv_info
                await c.create_table(kv_info("kv"), num_tablets=1)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": 1, "v": 1.0}])
                ts = mc.tservers[0]
                legit = set(ts.peers)
                # plant a stray replica the catalog knows nothing about
                # (e.g. a split child left behind by an interrupted
                # split, or a lost delete_tablet after a move)
                ent = mc.master.tablets[next(iter(legit))]
                await ts.rpc_create_tablet({
                    "tablet_id": "stray-tablet-001",
                    "table": dict(mc.master.tables[ent["table_id"]]
                                  ["info"]),
                    "partition": ent["partition"],
                    "raft_peers": [[ts.uuid,
                                    list(ts.messenger.addr)]],
                })
                assert "stray-tablet-001" in ts.peers
                ok = await _wait(
                    lambda: "stray-tablet-001" not in ts.peers)
                assert ok, "orphan replica was not GCed"
                assert not os.path.exists(
                    ts._tablet_dir("stray-tablet-001"))
                # catalog-mapped replicas survive the sweep
                assert legit <= set(ts.peers)
                rows = await c.get("kv", {"k": 1})
                assert rows["v"] == 1.0
            finally:
                flags.set_flag("master_orphan_gc_grace_s", prior)
                await mc.shutdown()
        run(go())

    def test_orphan_within_grace_survives(self, tmp_path):
        async def go():
            prior = flags.get("master_orphan_gc_grace_s")
            flags.set_flag("master_orphan_gc_grace_s", 3600.0)
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                from tests.test_load_balancer import kv_info
                await c.create_table(kv_info("kv"), num_tablets=1)
                await mc.wait_for_leaders("kv")
                ts = mc.tservers[0]
                ent = mc.master.tablets[next(iter(ts.peers))]
                await ts.rpc_create_tablet({
                    "tablet_id": "stray-tablet-002",
                    "table": dict(mc.master.tables[ent["table_id"]]
                                  ["info"]),
                    "partition": ent["partition"],
                    "raft_peers": [[ts.uuid,
                                    list(ts.messenger.addr)]],
                })
                # several heartbeat + sweep cycles inside the grace
                # window: the replica must NOT be condemned yet
                await asyncio.sleep(2.5)
                assert "stray-tablet-002" in ts.peers
            finally:
                flags.set_flag("master_orphan_gc_grace_s", prior)
                await mc.shutdown()
        run(go())
