"""Native host hot path (native/ybtpu_hot.c): byte-equivalence with the
Python encoders/decoders it replaces (reference analogs:
dockv/doc_key.cc encode, dockv/pg_row.cc row materialization)."""
import random
import tempfile

import pytest

from yugabyte_db_tpu.docdb.hotpath import available
from yugabyte_db_tpu.docdb.table_codec import TableCodec, TableInfo
from yugabyte_db_tpu.dockv.packed_row import (
    ColumnSchema, ColumnType, TableSchema,
)
from yugabyte_db_tpu.dockv.partition import PartitionSchema

pytestmark = pytest.mark.skipif(
    not available(), reason="native hot path unavailable (no toolchain)")


SHAPES = [
    ([("k", ColumnType.INT64, False)], "hash", 1),
    ([("k", ColumnType.INT32, False)], "hash", 1),
    ([("a", ColumnType.INT64, False), ("b", ColumnType.STRING, False)],
     "hash", 1),
    ([("a", ColumnType.STRING, False), ("b", ColumnType.INT64, True)],
     "hash", 1),
    ([("a", ColumnType.FLOAT64, False)], "hash", 1),
    ([("a", ColumnType.INT64, False), ("b", ColumnType.STRING, False)],
     "range", 0),
    ([("a", ColumnType.TIMESTAMP, False)], "hash", 1),
]


def _mkval(t, rng):
    if t == ColumnType.INT64:
        return rng.choice([0, -1, 1, -2**62, 2**62,
                           rng.randint(-10**12, 10**12)])
    if t == ColumnType.INT32:
        return rng.randint(-2**31, 2**31 - 1)
    if t == ColumnType.FLOAT64:
        return rng.choice([0.0, -1.5, 3.14, -1e300, 1e-300, rng.random()])
    if t == ColumnType.TIMESTAMP:
        return rng.randint(0, 2**48)
    if t == ColumnType.STRING:
        return rng.choice(["", "abc", "a\x00b", "héllo", "x" * 300,
                           chr(1) + chr(0)])
    raise AssertionError(t)


class TestDocKeyEncodeEquivalence:
    def test_fuzz_vs_python(self):
        rng = random.Random(7)
        for cols, kind, nh in SHAPES:
            schema = TableSchema(tuple(
                ColumnSchema(i, n, t,
                             is_hash_key=(kind == "hash" and i < nh),
                             is_range_key=not (kind == "hash" and i < nh),
                             sort_desc=desc)
                for i, (n, t, desc) in enumerate(cols)), 1)
            info = TableInfo("t", "t", schema, PartitionSchema(kind, nh))
            codec = TableCodec(info)
            assert codec._key_spec is not None
            for _ in range(200):
                row = {n: _mkval(t, rng) for n, t, _ in cols}
                assert codec.doc_key_prefix(row) == \
                    codec.doc_key(row).encode(), row

    def test_null_pk_components(self):
        """NULL RANGE components encode as kNull (PG indexes rows with
        NULL key parts — composite index entries need it); the C fast
        path declines them and the Python fallback produces the bytes,
        so both paths stay consistent.  NULL HASH components still
        error — they route the tablet."""
        schema = TableSchema((
            ColumnSchema(0, "a", ColumnType.INT64, is_hash_key=True),
            ColumnSchema(1, "b", ColumnType.STRING, is_range_key=True),
        ), 1)
        codec = TableCodec(TableInfo("t", "t", schema,
                                     PartitionSchema("hash", 1)))
        k_null = codec.doc_key_prefix({"a": 5, "b": None})
        k_val = codec.doc_key_prefix({"a": 5, "b": "x"})
        assert k_null != k_val
        # stable and distinct from any real value's encoding
        assert k_null == codec.doc_key_prefix({"a": 5, "b": None})
        with pytest.raises(Exception):
            codec.doc_key_prefix({"a": None, "b": "x"})


class TestExtractorEquivalence:
    def test_point_read_row_matches_python(self):
        """The native extractor and the Python decode produce identical
        rows for a table mixing fixed, string, and missing columns."""
        from yugabyte_db_tpu.docdb.operations import ReadRequest, RowOp, \
            WriteRequest
        from yugabyte_db_tpu.tablet import Tablet
        schema = TableSchema((
            ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
            ColumnSchema(1, "s", ColumnType.STRING),
            ColumnSchema(2, "f", ColumnType.FLOAT64),
            ColumnSchema(3, "i", ColumnType.INT32),
            ColumnSchema(4, "b", ColumnType.BOOL),
        ), 1)
        info = TableInfo("mix", "mix", schema, PartitionSchema("hash", 1))
        t = Tablet("mix", info, tempfile.mkdtemp(prefix="hot-"))
        rows = [{"k": i, "s": f"v\x00{i}" if i % 3 else None,
                 "f": i * 1.5, "i": i % 7, "b": bool(i % 2)}
                for i in range(200)]
        t.apply_write(WriteRequest("mix", [RowOp("upsert", r)
                                           for r in rows]))
        t.flush()
        for r in rows[::17]:
            got = t.read(ReadRequest("mix", pk_eq={"k": r["k"]})).rows[0]
            assert got == r, (got, r)


class TestNativeBlockFinder:
    """The fused native point lookup (BlockFinder) must agree with the
    Python MVCC walk across versions, deletes, flush boundaries and
    batched reads."""

    def _tablet(self, tmp_path):
        from yugabyte_db_tpu.docdb.operations import (
            ReadRequest, RowOp, WriteRequest,
        )
        from yugabyte_db_tpu.models.ycsb import usertable_info
        from yugabyte_db_tpu.tablet import Tablet
        t = Tablet("ht", usertable_info(), str(tmp_path / "ht"))
        return t, ReadRequest, RowOp, WriteRequest

    def test_versions_deletes_and_flush(self, tmp_path):
        t, ReadRequest, RowOp, WriteRequest = self._tablet(tmp_path)
        row = lambda k, tag: {"ycsb_key": k,
                              **{f"field{i}": tag for i in range(10)}}
        for k in range(200):
            t.apply_write(WriteRequest("usertable",
                                       [RowOp("upsert", row(k, "v1"))]))
        t.flush()
        for k in range(0, 200, 2):           # overwrite evens post-flush
            t.apply_write(WriteRequest("usertable",
                                       [RowOp("upsert", row(k, "v2"))]))
        for k in range(0, 200, 5):           # delete every 5th
            t.apply_write(WriteRequest(
                "usertable", [RowOp("delete", {"ycsb_key": k})]))
        t.flush()
        for k in (0, 1, 2, 5, 10, 55, 199):
            got = t.read(ReadRequest("usertable",
                                     pk_eq={"ycsb_key": k})).rows
            if k % 5 == 0:
                assert got == [], k
            else:
                want = "v2" if k % 2 == 0 else "v1"
                assert got[0]["field0"] == want, k

    def test_multi_read_matches_single(self, tmp_path):
        t, ReadRequest, RowOp, WriteRequest = self._tablet(tmp_path)
        import numpy as np
        from yugabyte_db_tpu.models.ycsb import generate_rows
        t.bulk_load(generate_rows(5000))
        t.apply_write(WriteRequest("usertable", [RowOp(
            "delete", {"ycsb_key": 17})]))
        keys = [17, 3, 4999, 999999, 0]
        batch = t.multi_read("usertable", [{"ycsb_key": k} for k in keys])
        for k, b in zip(keys, batch):
            single = t.read(ReadRequest("usertable",
                                        pk_eq={"ycsb_key": k})).rows
            assert (b is None and single == []) or single[0] == b, k


class TestNativePacker:
    def test_pack_matches_python(self):
        """Native Packer output must be byte-identical to the Python
        RowPacker for every supported type incl. NULLs."""
        from yugabyte_db_tpu.dockv.packed_row import (
            ColumnSchema, ColumnType, RowPacker, SchemaPacking,
            TableSchema)
        schema = TableSchema(columns=(
            ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
            ColumnSchema(1, "b", ColumnType.BOOL),
            ColumnSchema(2, "i", ColumnType.INT32),
            ColumnSchema(3, "d", ColumnType.FLOAT64),
            ColumnSchema(4, "f", ColumnType.FLOAT32),
            ColumnSchema(5, "ts", ColumnType.TIMESTAMP),
            ColumnSchema(6, "s", ColumnType.STRING),
            ColumnSchema(7, "y", ColumnType.BINARY),
        ), version=3)
        packing = SchemaPacking.from_schema(schema)
        packer = RowPacker(packing)
        import itertools
        rows = [
            {1: True, 2: -5, 3: 2.5, 4: 1.5, 5: 123456789,
             6: "héllo", 7: b"\x00\xff"},
            {1: None, 2: None, 3: None, 4: None, 5: None,
             6: None, 7: None},
            {1: False, 2: 2**31 - 1, 3: -0.0, 4: 0.0, 5: -1,
             6: "", 7: b""},
            {2: 7, 6: "only-some"},
            {6: "x", 7: memoryview(b"view-backed")},   # buffer protocol
        ]
        for row in rows:
            nat = packer._native_packer()
            assert nat is not None
            got = nat.pack(row)
            # bypass the native path for the reference encoding
            packer2 = RowPacker(packing)
            packer2._native = None
            want = packer2.pack(row)
            assert got == want, row

    def test_pack_type_errors_match(self):
        from yugabyte_db_tpu.dockv.packed_row import (
            ColumnSchema, ColumnType, RowPacker, SchemaPacking,
            TableSchema)
        import pytest
        schema = TableSchema(columns=(
            ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
            ColumnSchema(1, "i", ColumnType.INT32),
        ), version=1)
        packer = RowPacker(SchemaPacking.from_schema(schema))
        assert packer._native_packer() is not None
        py = RowPacker(SchemaPacking.from_schema(schema))
        py._native = None
        for bad in ({1: "not-an-int"}, {1: 2**40}):
            with pytest.raises(Exception):
                packer.pack(bad)
            with pytest.raises(Exception):   # python path fails too
                py.pack(bad)

    def test_exotic_types_fall_back_to_python(self):
        from yugabyte_db_tpu.dockv.packed_row import (
            ColumnSchema, ColumnType, RowPacker, SchemaPacking,
            TableSchema)
        schema = TableSchema(columns=(
            ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
            ColumnSchema(1, "j", ColumnType.JSON),
        ), version=1)
        packer = RowPacker(SchemaPacking.from_schema(schema))
        assert packer._native_packer() is None

    def test_float32_overflow_fails_loudly_both_paths(self):
        from yugabyte_db_tpu.dockv.packed_row import (
            ColumnSchema, ColumnType, RowPacker, SchemaPacking,
            TableSchema)
        import math
        import pytest
        schema = TableSchema(columns=(
            ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
            ColumnSchema(1, "f", ColumnType.FLOAT32),
        ), version=1)
        nat = RowPacker(SchemaPacking.from_schema(schema))
        assert nat._native_packer() is not None
        py = RowPacker(SchemaPacking.from_schema(schema))
        py._native = None
        for p in (nat, py):
            with pytest.raises(Exception):
                p.pack({1: 1e300})
        # infinities are representable (struct.pack('<f', inf) works)
        assert nat.pack({1: math.inf}) == py.pack({1: math.inf})


class TestFusedRangeRead:
    """ybtpu_hot.range_read (one C call: encode + per-SST point lookup
    + cross-SST merge + memtable-guard probe) must agree with the
    per-key Python path on every branch: SST-only, multi-SST version
    merge, memtable overlay, tombstones, and the fallback shapes."""

    def _tablet(self, tmp_path, name="fr"):
        from yugabyte_db_tpu.docdb.operations import (
            ReadRequest, RowOp, WriteRequest, _hot_mod,
        )
        from yugabyte_db_tpu.models.ycsb import usertable_info
        from yugabyte_db_tpu.tablet import Tablet
        # equality against the per-key path is vacuous unless the
        # native fused call is actually reachable
        assert hasattr(_hot_mod(), "range_read")
        t = Tablet(name, usertable_info(), str(tmp_path / name))
        return t, ReadRequest, RowOp, WriteRequest

    @staticmethod
    def _between(ReadRequest, lo, hi, columns=None):
        from yugabyte_db_tpu.models.ycsb import usertable_info
        kid = usertable_info().schema.key_columns[0].id
        return ReadRequest("usertable",
                           where=("between", ("col", kid),
                                  ("const", lo), ("const", hi)),
                           columns=columns)

    def _scan_both(self, t, req):
        """Run the scan through the fused path and the per-key path;
        both must return identical row sets."""
        from yugabyte_db_tpu.docdb import operations as ops
        fused = t.read(req).rows
        orig = ops.DocReadOperation._range_read_fused
        ops.DocReadOperation._range_read_fused = \
            ops.DocReadOperation._enumerated_multi_get
        try:
            plain = t.read(req).rows
        finally:
            ops.DocReadOperation._range_read_fused = orig
        key = lambda r: r["ycsb_key"]
        assert sorted(fused, key=key) == sorted(plain, key=key)
        return fused

    def test_sst_versions_tombstones_and_mem_overlay(self, tmp_path):
        t, ReadRequest, RowOp, WriteRequest = self._tablet(tmp_path)
        row = lambda k, tag: {"ycsb_key": k,
                              **{f"field{i}": tag for i in range(10)}}
        for k in range(300):
            t.apply_write(WriteRequest("usertable",
                                       [RowOp("upsert", row(k, "v1"))]))
        t.flush()
        for k in range(0, 300, 2):          # second SST: newer evens
            t.apply_write(WriteRequest("usertable",
                                       [RowOp("upsert", row(k, "v2"))]))
        for k in range(0, 300, 7):          # SST tombstones
            t.apply_write(WriteRequest(
                "usertable", [RowOp("delete", {"ycsb_key": k})]))
        t.flush()
        # memtable overlay: updates, deletes, and a resurrect
        t.apply_write(WriteRequest("usertable",
                                   [RowOp("upsert", row(10, "mem"))]))
        t.apply_write(WriteRequest(
            "usertable", [RowOp("delete", {"ycsb_key": 11})]))
        t.apply_write(WriteRequest("usertable",
                                   [RowOp("upsert", row(14, "back"))]))
        got = {r["ycsb_key"]: r["field0"] for r in self._scan_both(
            t, self._between(ReadRequest, 8, 20,
                             columns=["ycsb_key", "field0"]))}
        assert got == {8: "v2", 9: "v1", 10: "mem", 12: "v2", 13: "v1",
                       14: "back", 15: "v1", 16: "v2", 17: "v1",
                       18: "v2", 19: "v1", 20: "v2"}

    def test_range_past_table_edges_and_missing_keys(self, tmp_path):
        t, ReadRequest, RowOp, WriteRequest = self._tablet(tmp_path)
        from yugabyte_db_tpu.models.ycsb import generate_rows
        t.bulk_load(generate_rows(50))
        t.flush()
        rows = self._scan_both(t, self._between(ReadRequest, 45, 60))
        assert sorted(r["ycsb_key"] for r in rows) == list(range(45, 50))
        assert self._scan_both(
            t, self._between(ReadRequest, 1000, 1009)) == []

    def test_memtable_only_rows_visible(self, tmp_path):
        t, ReadRequest, RowOp, WriteRequest = self._tablet(tmp_path)
        row = lambda k: {"ycsb_key": k,
                         **{f"field{i}": "m" for i in range(10)}}
        for k in range(20):                  # never flushed
            t.apply_write(WriteRequest("usertable",
                                       [RowOp("upsert", row(k))]))
        rows = self._scan_both(t, self._between(ReadRequest, 5, 14))
        assert sorted(r["ycsb_key"] for r in rows) == list(range(5, 15))

    def test_empty_and_inverted_ranges_return_no_rows(self, tmp_path):
        t, ReadRequest, RowOp, WriteRequest = self._tablet(tmp_path)
        from yugabyte_db_tpu.models.ycsb import generate_rows
        t.bulk_load(generate_rows(100))
        t.flush()
        # BETWEEN 10 AND 5 is an empty range, not an error
        assert t.read(self._between(ReadRequest, 10, 5)).rows == []
        assert t.read(self._between(ReadRequest, -5, -1)).rows == []
