"""Sequences: catalog-persisted counters with client-side block caches
(reference: PG sequences + tserver/pg_client_session.cc
PgSequenceCache), serial column defaults, nextval/currval."""
import asyncio

from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
from yugabyte_db_tpu.ql.executor import SqlSession


def run(coro):
    return asyncio.run(coro)


class TestSequences:
    def test_two_clients_never_collide(self, tmp_path):
        """Blocks are Raft-committed past the allocation before any
        value is handed out: two independent clients (each with its own
        cache) must produce disjoint values."""
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c1, c2 = mc.client(), mc.client()
                await c1.create_sequence("s1")
                got = []
                for _ in range(120):     # crosses block boundaries
                    got.append(await c1.sequence_next("s1"))
                    got.append(await c2.sequence_next("s1"))
                assert len(set(got)) == len(got), "duplicate values"
            finally:
                await mc.shutdown()
        run(go())

    def test_restart_never_reuses_values(self, tmp_path):
        """A master restart may skip the unused remainder of a cached
        block but can never hand out an already-issued value."""
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            c = mc.client()
            await c.create_sequence("s2")
            before = [await c.sequence_next("s2") for _ in range(7)]
            await mc.shutdown()

            mc2 = await MiniCluster(str(tmp_path),
                                    num_tservers=1).start()
            try:
                c2 = mc2.client()
                after = [await c2.sequence_next("s2") for _ in range(7)]
                assert not (set(before) & set(after)), (before, after)
                assert min(after) > max(before)
            finally:
                await mc2.shutdown()
        run(go())

    def test_serial_column_and_sql_surface(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute("CREATE SEQUENCE sq START 50 "
                                "INCREMENT BY 2")
                r = await s.execute("SELECT nextval('sq') AS v")
                assert r.rows[0]["v"] == 50
                r = await s.execute("SELECT nextval('sq') AS v")
                assert r.rows[0]["v"] == 52
                r = await s.execute("SELECT currval('sq') AS v")
                assert r.rows[0]["v"] == 52
                await s.execute("CREATE TABLE su (k bigserial, n text, "
                                "PRIMARY KEY (k)) WITH tablets = 1")
                await mc.wait_for_leaders("su")
                await s.execute(
                    "INSERT INTO su (n) VALUES ('a'), ('b')")
                r = await s.execute("SELECT k, n FROM su ORDER BY k")
                assert [(x["k"], x["n"]) for x in r.rows] == \
                    [(1, "a"), (2, "b")]
                # explicit nextval in VALUES advances per row
                await s.execute("INSERT INTO su (k, n) VALUES "
                                "(nextval('sq'), 'x'), "
                                "(nextval('sq'), 'y')")
                r = await s.execute(
                    "SELECT k FROM su WHERE n = 'y'")
                assert r.rows[0]["k"] == 56
                await s.execute("DROP SEQUENCE sq")
                try:
                    await s.execute("SELECT nextval('sq') AS v")
                    raise AssertionError("dropped sequence served")
                except AssertionError:
                    raise
                except Exception:
                    pass
            finally:
                await mc.shutdown()
        run(go())

    def test_currval_before_nextval_errors(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute("CREATE SEQUENCE fresh")
                try:
                    await s.execute("SELECT currval('fresh') AS v")
                    raise AssertionError("currval before nextval")
                except AssertionError:
                    raise
                except Exception:
                    pass
            finally:
                await mc.shutdown()
        run(go())

    def test_concurrent_allocation_no_duplicates(self, tmp_path):
        """Server-side block allocation is serialized: interleaved
        alloc RPCs (the read-modify-commit spans a Raft await) must
        never hand two clients overlapping blocks."""
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                clients = [mc.client() for _ in range(4)]
                await clients[0].create_sequence("cc")

                async def hammer(c):
                    return [await c.sequence_next("cc")
                            for _ in range(120)]
                batches = await asyncio.gather(
                    *[hammer(c) for c in clients])
                flat = [v for b in batches for v in b]
                assert len(set(flat)) == len(flat)
            finally:
                await mc.shutdown()
        run(go())
