"""cassandra-driver conformance against the YCQL server (skip-if-absent;
see test_driver_conformance.py for the rationale)."""
import pytest

from tests.driver_cluster import ClusterThread

cassandra = pytest.importorskip("cassandra",
                                reason="cassandra-driver not installed")


def test_cassandra_driver_crud(tmp_path):
    from cassandra.cluster import Cluster
    from yugabyte_db_tpu.ql.cql_server import CqlServer
    with ClusterThread(tmp_path, CqlServer) as ct:
        host, port = ct.addr
        cluster = Cluster([host], port=port, connect_timeout=20)
        session = cluster.connect()
        session.execute(
            "CREATE KEYSPACE IF NOT EXISTS ks WITH replication = "
            "{'class': 'SimpleStrategy', 'replication_factor': 1}")
        session.execute("CREATE TABLE ks.t (k bigint PRIMARY KEY, "
                        "v double, s text)")
        session.execute(
            "INSERT INTO ks.t (k, v, s) VALUES (1, 2.5, 'one')")
        ps = session.prepare(
            "INSERT INTO ks.t (k, v, s) VALUES (?, ?, ?)")
        session.execute(ps, (2, 3.5, "two"))
        rows = list(session.execute("SELECT k, v, s FROM ks.t"))
        assert sorted((r.k, r.v, r.s) for r in rows) == [
            (1, 2.5, "one"), (2, 3.5, "two")]
        cluster.shutdown()
