"""cassandra-driver conformance against the YCQL server (skip-if-absent;
see test_driver_conformance.py for the rationale)."""
import asyncio
import threading

import pytest

from yugabyte_db_tpu.tools.mini_cluster import MiniCluster

cassandra = pytest.importorskip("cassandra",
                                reason="cassandra-driver not installed")


def test_cassandra_driver_crud(tmp_path):
    from cassandra.cluster import Cluster

    loop = asyncio.new_event_loop()
    state = {}
    ready = threading.Event()

    def run():
        asyncio.set_event_loop(loop)

        async def boot():
            from yugabyte_db_tpu.ql.cql_server import CqlServer
            state["mc"] = await MiniCluster(str(tmp_path),
                                            num_tservers=1).start()
            state["srv"] = CqlServer(state["mc"].client())
            state["addr"] = await state["srv"].start()
            ready.set()
        loop.create_task(boot())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert ready.wait(30)
    try:
        host, port = state["addr"]
        cluster = Cluster([host], port=port,
                          connect_timeout=20)
        session = cluster.connect()
        session.execute(
            "CREATE KEYSPACE IF NOT EXISTS ks WITH replication = "
            "{'class': 'SimpleStrategy', 'replication_factor': 1}")
        session.execute("CREATE TABLE ks.t (k bigint PRIMARY KEY, "
                        "v double, s text)")
        session.execute(
            "INSERT INTO ks.t (k, v, s) VALUES (1, 2.5, 'one')")
        ps = session.prepare(
            "INSERT INTO ks.t (k, v, s) VALUES (?, ?, ?)")
        session.execute(ps, (2, 3.5, "two"))
        rows = list(session.execute("SELECT k, v, s FROM ks.t"))
        assert sorted((r.k, r.v, r.s) for r in rows) == [
            (1, 2.5, "one"), (2, 3.5, "two")]
        cluster.shutdown()
    finally:
        async def stop():
            await state["srv"].shutdown()
            await state["mc"].shutdown()
            loop.stop()
        asyncio.run_coroutine_threadsafe(stop(), loop)
        t.join(timeout=10)
