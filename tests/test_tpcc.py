"""TPC-C-style NEW-ORDER/PAYMENT through real distributed transactions
(reference: the TPC-C headline benchmark,
docs/content/stable/benchmark/tpcc/)."""
import asyncio

from yugabyte_db_tpu.models.tpcc import (TpccWorkload,
                                         verify_consistency)
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster


def test_tpcc_mix_and_consistency(tmp_path):
    async def go():
        mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
        try:
            c = mc.client()
            w = TpccWorkload(c, warehouses=1)
            await w.create_tables(num_tablets=1)
            for t in ("warehouse", "district", "customer", "stock",
                      "orders", "order_line", "history"):
                await mc.wait_for_leaders(t)
            await w.load()
            res = await w.run(seconds=4.0, concurrency=3)
            assert res.new_orders > 0 and res.payments > 0
            # the spec's consistency probes must hold after the storm
            checks = await verify_consistency(c, 0)
            assert all(checks.values()), checks
            # order lines exist for committed orders
            from yugabyte_db_tpu.docdb.operations import ReadRequest
            orders = (await c.scan("orders", ReadRequest(""))).rows
            lines = (await c.scan("order_line", ReadRequest(""))).rows
            by_o = {}
            for l in lines:
                okey = l["ol_key"] // 16
                by_o[okey] = by_o.get(okey, 0) + 1
            for o in orders:
                assert by_o.get(o["o_key"], 0) == o["o_ol_cnt"], o
            print(f"tpcc: {res.new_orders} NO / {res.payments} PAY / "
                  f"{res.aborts} aborts -> {res.tpmc:.0f} tpmC*")
        finally:
            await mc.shutdown()
    asyncio.run(go())
