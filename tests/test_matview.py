"""Incremental materialized views (matview/): retraction algebra vs
recompute-from-scratch, seed-vs-incremental bitwise parity under live
DML, MIN/MAX rescan budgets, restart/attach resume, bounded-staleness
reads, flag-off inertness (reference: PG materialized views + the
CDC-SDK consumer shape the maintainer rides on)."""
import asyncio

import numpy as np
import pytest

from yugabyte_db_tpu.docdb import ReadRequest
from yugabyte_db_tpu.dockv.packed_row import (ColumnSchema, ColumnType,
                                              TableSchema)
from yugabyte_db_tpu.matview import (MatviewDisabledError,
                                     MatviewIneligible, ViewDef)
from yugabyte_db_tpu.matview.definition import validate
from yugabyte_db_tpu.matview.errors import (REASON_AGG_OP,
                                            REASON_GROUP_COL_TYPE,
                                            REASON_INEXACT_SUM_LANE,
                                            REASON_NO_GROUP_BY,
                                            REASON_RESCAN_BUDGET)
from yugabyte_db_tpu.ops.grouped_scan import retract_grouped_cpu
from yugabyte_db_tpu.ops.scan import (AggSpec, _keyed_partials,
                                      retract_grouped_partials)
from yugabyte_db_tpu.ql.executor import SqlSession
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
from yugabyte_db_tpu.utils import flags


def run(coro):
    return asyncio.run(coro)


# --- retraction algebra (pure unit: keyed inverse vs recompute) -----------

#: count / sum(v) / min(v) / max(v) — the avg-expanded shape both
#: retraction implementations take
AGGS = (AggSpec("count"), AggSpec("sum", 1),
        AggSpec("min", 1), AggSpec("max", 1))


def fold_rows(rows):
    """Recompute-from-scratch reference: keyed triple over (g, v)."""
    groups = {}
    for g, v in rows:
        st = groups.setdefault(g, [0, 0, None, None])
        st[0] += 1
        st[1] += v
        st[2] = v if st[2] is None else min(st[2], v)
        st[3] = v if st[3] is None else max(st[3], v)
    keys = sorted(groups)
    outs = tuple(np.asarray([groups[k][i] for k in keys])
                 for i in range(4))
    counts = np.asarray([groups[k][0] for k in keys], np.int64)
    return outs, counts, (np.asarray(keys),)


class TestRetractGroupedPartials:
    def test_sum_count_bitwise_vs_recompute(self):
        rows = [(i % 5, i * 7 - 30) for i in range(40)]
        gone = rows[3:19:2]
        kept = [r for i, r in enumerate(rows)
                if not (3 <= i < 19 and (i - 3) % 2 == 0)]
        out, dirty = retract_grouped_partials(
            AGGS, fold_rows(rows), fold_rows(gone))
        got = _keyed_partials(out)
        ref = _keyed_partials(fold_rows(kept))
        assert set(got) == set(ref)
        for k in ref:
            # count and sum lanes are the exact inverse — bit-identical
            assert int(got[k][0][0]) == int(ref[k][0][0])
            assert int(got[k][0][1]) == int(ref[k][0][1])
            assert got[k][1] == ref[k][1]

    def test_minmax_non_extremum_needs_no_rescan(self):
        """Retracting values strictly inside (min, max) leaves every
        lane bit-identical to recompute with an empty dirty list."""
        rows = [(0, v) for v in (1, 5, 9, 5, 7)] + \
               [(1, v) for v in (-4, 0, 12, 3)]
        gone = [(0, 5), (1, 3)]
        kept = [(0, 1), (0, 9), (0, 5), (0, 7), (1, -4), (1, 0), (1, 12)]
        out, dirty = retract_grouped_partials(
            AGGS, fold_rows(rows), fold_rows(gone))
        assert dirty == []
        got, ref = _keyed_partials(out), _keyed_partials(fold_rows(kept))
        assert set(got) == set(ref)
        for k in ref:
            assert [int(x) for x in got[k][0]] == \
                [int(x) for x in ref[k][0]]

    def test_minmax_extremum_reports_dirty_slot(self):
        rows = [(0, 1), (0, 5), (0, 9)]
        out, dirty = retract_grouped_partials(
            AGGS, fold_rows(rows), fold_rows([(0, 1)]))
        # min lane (index 2) is dirty; max lane untouched; the stale
        # survivor is kept verbatim for the caller's re-scan
        assert dirty == [((0,), 2)]
        assert int(_keyed_partials(out)[(0,)][0][2]) == 1

    def test_group_drops_at_zero_and_is_not_dirty(self):
        rows = [(0, 3), (0, 8), (1, 4)]
        out, dirty = retract_grouped_partials(
            AGGS, fold_rows(rows), fold_rows([(0, 3), (0, 8)]))
        assert dirty == []
        assert set(_keyed_partials(out)) == {(1,)}

    def test_over_retract_and_unknown_group_raise(self):
        base = fold_rows([(0, 3)])
        with pytest.raises(ValueError):
            retract_grouped_partials(AGGS, base, fold_rows([(7, 1)]))
        with pytest.raises(ValueError):
            retract_grouped_partials(AGGS, base,
                                     fold_rows([(0, 3), (0, 3)]))

    def test_numpy_twin_matches_keyed_path(self):
        """retract_grouped_cpu over slot-aligned arrays == the keyed
        version on the same data (alive slots; dirty mask == list)."""
        rows = [(s, v) for s in range(6)
                for v in (s * 10, s * 10 + 5, s * 10 + 9)]
        gone = [(0, 0), (2, 25), (3, 39), (5, 50), (5, 55), (5, 59)]
        bo, bc, _ = fold_rows(rows)
        do, dc, _ = fold_rows(gone)
        # align the delta onto base slots (missing slots = identity)
        dvals = [np.zeros_like(np.asarray(bo[i])) for i in range(4)]
        dcnts = np.zeros_like(bc)
        for j, s in enumerate(sorted({g for g, _ in gone})):
            for i in range(4):
                dvals[i][s] = np.asarray(do[i])[j]
            dcnts[s] = dc[j]
        outs, ncnt, dirty = retract_grouped_cpu(
            AGGS, bo, bc, dvals, dcnts)
        kout, kdirty = retract_grouped_partials(
            AGGS, (bo, bc, (np.arange(6),)),
            (do, dc, (np.asarray(sorted({g for g, _ in gone})),)))
        keyed = _keyed_partials(kout)
        for s in range(6):
            if ncnt[s] == 0:
                assert (s,) not in keyed
                continue
            assert int(ncnt[s]) == keyed[(s,)][1]
            for i in (0, 1):                     # exact lanes
                assert int(outs[i][s]) == int(keyed[(s,)][0][i])
        assert {(k[0], i) for k, i in kdirty} == \
            {(s, i) for i in range(4) for s in range(6) if dirty[i][s]}
        with pytest.raises(ValueError):
            retract_grouped_cpu(AGGS, bo, bc, dvals, bc + 1)


# --- eligibility (typed refusals at registration) -------------------------

def _schema():
    return TableSchema(columns=(
        ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
        ColumnSchema(1, "g", ColumnType.INT64),
        ColumnSchema(2, "v", ColumnType.INT64),
        ColumnSchema(3, "f", ColumnType.FLOAT64),
    ), version=1)


def _vd(**kw):
    base = dict(name="mv", table="kv", select_sql="",
                group_by=["g"], aggs=[("count", None, "cnt")])
    base.update(kw)
    return ViewDef(**base)


class TestEligibility:
    def _reason(self, vd):
        with pytest.raises(MatviewIneligible) as ei:
            validate(vd, _schema())
        return ei.value.reason

    def test_typed_refusals(self):
        assert self._reason(_vd(group_by=[])) == REASON_NO_GROUP_BY
        assert self._reason(_vd(group_by=["f"])) == REASON_GROUP_COL_TYPE
        assert self._reason(_vd(
            aggs=[("avg", ("col", "v"), "a")])) == REASON_AGG_OP
        assert self._reason(_vd(
            aggs=[("sum", ("col", "f"), "s")])) == REASON_INEXACT_SUM_LANE
        # int-lane arithmetic is admitted; float constants are not
        validate(_vd(aggs=[("sum", ("arith", "add", ("col", "v"),
                                    ("const", 1)), "s")]), _schema())
        assert self._reason(_vd(
            aggs=[("sum", ("arith", "add", ("col", "v"),
                           ("const", 1.5)), "s")])) \
            == REASON_INEXACT_SUM_LANE

    def test_wire_roundtrip(self):
        from yugabyte_db_tpu.matview import viewdef_from_wire
        vd = _vd(aggs=[("sum", ("col", "v"), "s"),
                       ("count", None, "cnt")],
                 where=("and",
                        ("cmp", "ge", ("col", "v"), ("const", 0)),
                        ("in", ("col", "g"), [1, 2, 3])),
                 group_out={"g": ["g", "grp"]})
        import json
        assert viewdef_from_wire(
            json.loads(json.dumps(vd.to_wire()))) == vd


# --- live cluster: parity, budgets, restart, staleness, flag gate ---------

DDL = "CREATE TABLE kv (k bigint PRIMARY KEY, g bigint, v bigint)"
MV = ("CREATE MATERIALIZED VIEW {n} AS SELECT g, count(*) AS cnt, "
      "sum(v) AS total{mm} FROM kv WHERE v >= 0 GROUP BY g")


async def _cluster(tmp_path):
    mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
    c = mc.client()
    sess = SqlSession(c)
    await sess.execute(DDL)
    await mc.wait_for_leaders("kv")
    return mc, c, sess


async def _reference(c, where_ok, read_ht):
    """Fresh fold at the view's watermark — the parity oracle."""
    resp = await c.scan("kv", ReadRequest("", read_ht=read_ht))
    return {k: [int(v[0]), int(v[1]),
                (None if v[2] is None else int(v[2])),
                (None if v[3] is None else int(v[3]))]
            for k, v in fold_keyed(
                [r for r in resp.rows if where_ok(r)]).items()}


def fold_keyed(rows):
    out = {}
    for r in rows:
        st = out.setdefault((int(r["g"]),), [0, 0, None, None])
        v = int(r["v"])
        st[0] += 1
        st[1] += v
        st[2] = v if st[2] is None else min(st[2], v)
        st[3] = v if st[3] is None else max(st[3], v)
    return out


def view_keyed(rows):
    return {(int(r["g"]),): [int(r["cnt"]), int(r["total"]),
                             (None if r.get("lo") is None
                              else int(r["lo"])),
                             (None if r.get("hi") is None
                              else int(r["hi"]))]
            for r in rows}


class TestIncrementalParity:
    def test_sum_count_parity_zero_rescans(self, tmp_path):
        """Interleaved inserts/updates/deletes: the SUM/COUNT view
        answers bit-identically to a fresh scan at its watermark with
        ZERO per-group rescans and zero full rescans — the exact-
        retraction path carries everything."""
        async def go():
            mc, c, sess = await _cluster(tmp_path)
            try:
                for i in range(30):
                    await sess.execute(
                        f"INSERT INTO kv VALUES ({i}, {i % 4}, {i * 3})")
                await sess.execute(MV.format(n="mv_sc", mm=""))
                for i in range(30, 45):
                    await sess.execute(
                        f"INSERT INTO kv VALUES ({i}, {i % 4}, "
                        f"{(i - 37) * 5})")                 # some v < 0
                for i in range(0, 20, 3):
                    await sess.execute(
                        f"UPDATE kv SET v = {i * 11} WHERE k = {i}")
                for i in range(1, 25, 5):
                    await sess.execute(f"DELETE FROM kv WHERE k = {i}")
                rows, meta = await c.matviews().read_rows(
                    "mv_sc", max_staleness_ms=0.0)
                ref = await _reference(c, lambda r: int(r["v"]) >= 0,
                                       meta["watermark_ht"])
                got = {k: v[:2] for k, v in view_keyed(rows).items()}
                assert got == {k: v[:2] for k, v in ref.items()}
                st = c.matviews().stats("mv_sc")
                assert st["minmax_rescans"] == 0
                assert st["full_rescans"] == 0
                assert st["rows_retracted"] > 0
            finally:
                await c.matviews().stop()
                await mc.shutdown()
        run(go())

    def test_minmax_parity_with_bounded_rescans(self, tmp_path):
        """MIN/MAX under deletes of group extrema: still bit-identical,
        with the per-slot re-scans COUNTED (and only fired when the
        retracted value challenged the survivor)."""
        async def go():
            mc, c, sess = await _cluster(tmp_path)
            try:
                for i in range(24):
                    await sess.execute(
                        f"INSERT INTO kv VALUES ({i}, {i % 3}, {i * 10})")
                await sess.execute(
                    MV.format(n="mv_mm",
                              mm=", min(v) AS lo, max(v) AS hi"))
                # k=21 holds group 0's max (210); k=1 holds group 1's
                # min (10): both deletions force a re-scan
                await sess.execute("DELETE FROM kv WHERE k = 21")
                await sess.execute("DELETE FROM kv WHERE k = 1")
                # non-extremum churn must NOT rescan further
                await sess.execute("UPDATE kv SET v = 55 WHERE k = 4")
                rows, meta = await c.matviews().read_rows(
                    "mv_mm", max_staleness_ms=0.0)
                ref = await _reference(c, lambda r: int(r["v"]) >= 0,
                                       meta["watermark_ht"])
                assert view_keyed(rows) == ref
                st = c.matviews().stats("mv_mm")
                assert 1 <= st["minmax_rescans"] <= \
                    int(flags.get("matview_rescan_budget"))
                assert st["budget_exceeded"] == 0
                assert st["full_rescans"] == 0
            finally:
                await c.matviews().stop()
                await mc.shutdown()
        run(go())

    def test_rescan_budget_exceeded_falls_back_typed(self, tmp_path):
        """budget 0: the first challenged MIN/MAX slot trips the typed
        fallback — counted, reason recorded, view re-seeded and STILL
        bit-correct."""
        async def go():
            mc, c, sess = await _cluster(tmp_path)
            flags.set_flag("matview_rescan_budget", 0)
            try:
                for i in range(12):
                    await sess.execute(
                        f"INSERT INTO kv VALUES ({i}, {i % 2}, {i * 10})")
                await sess.execute(
                    MV.format(n="mv_b",
                              mm=", min(v) AS lo, max(v) AS hi"))
                await sess.execute("DELETE FROM kv WHERE k = 0")  # min g0
                rows, meta = await c.matviews().read_rows(
                    "mv_b", max_staleness_ms=0.0)
                ref = await _reference(c, lambda r: int(r["v"]) >= 0,
                                       meta["watermark_ht"])
                assert view_keyed(rows) == ref
                st = c.matviews().stats("mv_b")
                assert st["budget_exceeded"] >= 1
                assert st["last_fallback_reason"] == REASON_RESCAN_BUDGET
                assert st["full_rescans"] >= 1
                assert st["minmax_rescans"] == 0
            finally:
                flags.REGISTRY.reset("matview_rescan_budget")
                await c.matviews().stop()
                await mc.shutdown()
        run(go())

    def test_refresh_is_a_counted_full_rescan(self, tmp_path):
        async def go():
            mc, c, sess = await _cluster(tmp_path)
            try:
                for i in range(10):
                    await sess.execute(
                        f"INSERT INTO kv VALUES ({i}, {i % 2}, {i})")
                await sess.execute(MV.format(n="mv_r", mm=""))
                await sess.execute("REFRESH MATERIALIZED VIEW mv_r")
                rows, meta = await c.matviews().read_rows(
                    "mv_r", max_staleness_ms=0.0)
                ref = await _reference(c, lambda r: int(r["v"]) >= 0,
                                       meta["watermark_ht"])
                assert {k: v[:2] for k, v in view_keyed(rows).items()} \
                    == {k: v[:2] for k, v in ref.items()}
                st = c.matviews().stats("mv_r")
                assert st["seeds"] == 2 and st["full_rescans"] == 1
            finally:
                await c.matviews().stop()
                await mc.shutdown()
        run(go())


class TestRoundAtomicity:
    def test_mid_round_failure_loses_nothing(self, tmp_path):
        """A transient failure mid-round (a before-image read dying on
        a leader move) must not lose the drained txns or leave a
        half-applied fold behind: the staged state rolls back whole,
        the stream re-attaches from the slot's durable positions, and
        the retry applies the same batch exactly once."""
        async def go():
            from yugabyte_db_tpu.rpc import RpcError
            mc, c, sess = await _cluster(tmp_path)
            try:
                for i in range(12):
                    await sess.execute(
                        f"INSERT INTO kv VALUES ({i}, {i % 3}, {i * 10})")
                await sess.execute(MV.format(n="mv_at", mm=""))
                mt = await c.matviews().lookup("mv_at")
                await mt.stop()              # drive rounds by hand
                # a batch that needs before-image reads
                await sess.execute("UPDATE kv SET v = 777 WHERE k = 5")
                await sess.execute("DELETE FROM kv WHERE k = 7")
                await sess.execute("INSERT INTO kv VALUES (90, 1, 123)")
                pre = {k: [list(v), n] for k, (v, n) in mt.state.items()}
                real, fired = mt._get_at, []

                async def flaky(pk_row, read_ht):
                    if not fired:
                        fired.append(True)
                        raise RpcError("leader moved",
                                       "SERVICE_UNAVAILABLE")
                    return await real(pk_row, read_ht)
                mt._get_at = flaky
                boom = False
                for _ in range(400):
                    try:
                        await mt.round()
                    except RpcError:
                        boom = True
                        break
                    await asyncio.sleep(0.01)
                assert boom, "the injected failure never fired"
                # nothing half-applied, stream flagged for re-attach
                assert {k: [list(v), n] for k, (v, n)
                        in mt.state.items()} == pre
                assert mt._stream_dirty
                # retry path: catch-up replays the batch exactly once
                rows, meta = await c.matviews().read_rows(
                    "mv_at", max_staleness_ms=0.0)
                ref = await _reference(c, lambda r: int(r["v"]) >= 0,
                                       meta["watermark_ht"])
                assert {k: v[:2] for k, v in view_keyed(rows).items()} \
                    == {k: v[:2] for k, v in ref.items()}
                st = c.matviews().stats("mv_at")
                assert st["seeds"] == 1 and st["full_rescans"] == 0
            finally:
                await c.matviews().stop()
                await mc.shutdown()
        run(go())


class TestSeedFailureCleanup:
    def test_failed_seed_drops_fresh_slot(self, tmp_path):
        """A seed that dies after the slot exists but before the
        catalog entry must drop the slot (nothing else ever would —
        it holds back WAL GC) and leave the name registrable."""
        async def go():
            from yugabyte_db_tpu.matview.maintainer import ViewMaintainer
            mc, c, sess = await _cluster(tmp_path)
            orig = ViewMaintainer._seed_scan
            try:
                await sess.execute("INSERT INTO kv VALUES (1, 0, 5)")

                async def boom(self, read_ht):
                    raise RuntimeError("seed scan died")
                ViewMaintainer._seed_scan = boom
                with pytest.raises(RuntimeError):
                    await sess.execute(MV.format(n="mv_lk", mm=""))
                ViewMaintainer._seed_scan = orig
                assert await c._master_call(
                    "list_replication_slots", {}) == {"slots": []}
                assert await c.get_matview("mv_lk") is None
                await sess.execute(MV.format(n="mv_lk", mm=""))
            finally:
                ViewMaintainer._seed_scan = orig
                await c.matviews().stop()
                await mc.shutdown()
        run(go())


class TestNamespaceSymmetry:
    def test_table_and_view_cannot_shadow_matview(self, tmp_path):
        """rpc_create_matview rejects names held by tables/views; the
        reverse direction must hold too, or a later CREATE TABLE/VIEW
        shadows the matview and makes it unreachable."""
        async def go():
            from yugabyte_db_tpu.rpc import RpcError
            mc, c, sess = await _cluster(tmp_path)
            try:
                await sess.execute("INSERT INTO kv VALUES (1, 0, 5)")
                await sess.execute(MV.format(n="mv_ns", mm=""))
                with pytest.raises(RpcError) as ei:
                    await sess.execute(
                        "CREATE TABLE mv_ns (k bigint PRIMARY KEY)")
                assert ei.value.code == "ALREADY_PRESENT"
                with pytest.raises(RpcError) as ei:
                    await sess.execute(
                        "CREATE VIEW mv_ns AS SELECT k FROM kv")
                assert ei.value.code == "ALREADY_PRESENT"
            finally:
                await c.matviews().stop()
                await mc.shutdown()
        run(go())


class TestRestartResume:
    def test_attach_resumes_from_watermark(self, tmp_path):
        """Maintainer host 'crashes' (manager stops, client discarded);
        writes land while nobody watches; a FRESH client attaches from
        the master catalog and folds forward from the persisted
        watermark — no re-seed (seeds stays 1), catalog reload proven
        against the on-disk sys catalog."""
        async def go():
            mc, c, sess = await _cluster(tmp_path)
            try:
                for i in range(16):
                    await sess.execute(
                        f"INSERT INTO kv VALUES ({i}, {i % 2}, {i * 2})")
                await sess.execute(MV.format(n="mv_p", mm=""))
                # quiesce the fold loop at a persisted checkpoint
                await c.matviews().read_rows("mv_p", max_staleness_ms=0.0)
                await c.matviews().stop()
                # the definition + state survive in the on-disk catalog
                from yugabyte_db_tpu.master import Master
                m2 = Master(mc.masters[0].fs_root, uuid="reload-probe")
                assert "mv_p" in m2.matviews
                assert m2.matviews["mv_p"]["state"]["partials"]
                # writes while detached
                await sess.execute("INSERT INTO kv VALUES (100, 0, 999)")
                await sess.execute("DELETE FROM kv WHERE k = 3")
                # fresh process: new client, lookup attaches + resumes
                c2 = mc.client()
                sess2 = SqlSession(c2)
                rows, meta = await c2.matviews().read_rows(
                    "mv_p", max_staleness_ms=0.0)
                ref = await _reference(c2, lambda r: int(r["v"]) >= 0,
                                       meta["watermark_ht"])
                assert {k: v[:2] for k, v in view_keyed(rows).items()} \
                    == {k: v[:2] for k, v in ref.items()}
                st = c2.matviews().stats("mv_p")
                assert st["seeds"] == 1, "attach must not re-seed"
                # and the SQL surface serves it with staleness attached
                res = await sess2.execute("SELECT g, cnt FROM mv_p")
                assert res.staleness_ms is not None
                assert len(res.rows) == len(rows)
                await c2.matviews().stop()
            finally:
                await c.matviews().stop()
                await mc.shutdown()
        run(go())


class TestBoundedStaleness:
    def test_read_surfaces_and_enforces_staleness(self, tmp_path):
        async def go():
            mc, c, sess = await _cluster(tmp_path)
            try:
                for i in range(8):
                    await sess.execute(
                        f"INSERT INTO kv VALUES ({i}, {i % 2}, {i})")
                await sess.execute(MV.format(n="mv_s", mm=""))
                mt = await c.matviews().lookup("mv_s")
                await mt.stop()                 # freeze the fold loop
                await sess.execute("INSERT INTO kv VALUES (50, 1, 7)")
                await asyncio.sleep(0.05)
                # lenient bound: serve stale, but SURFACE the staleness
                rows, meta = await c.matviews().read_rows(
                    "mv_s", max_staleness_ms=60_000.0)
                assert meta["staleness_ms"] >= 0.0
                assert not meta["caught_up"]
                # tight bound: the read must first catch up, then serve
                rows, meta = await c.matviews().read_rows(
                    "mv_s", max_staleness_ms=0.0)
                assert meta["caught_up"]
                ref = await _reference(c, lambda r: int(r["v"]) >= 0,
                                       meta["watermark_ht"])
                assert {k: v[:2] for k, v in view_keyed(rows).items()} \
                    == {k: v[:2] for k, v in ref.items()}
                assert any(int(r["total"]) for r in rows)
            finally:
                await c.matviews().stop()
                await mc.shutdown()
        run(go())


class TestFlagGate:
    def test_flag_off_is_inert(self, tmp_path):
        async def go():
            mc, c, sess = await _cluster(tmp_path)
            try:
                flags.set_flag("matview_enabled", False)
                with pytest.raises(MatviewDisabledError):
                    await sess.execute(MV.format(n="mv_off", mm=""))
                assert await c.matviews().lookup("mv_off") is None
                # SELECT falls through to the plain NOT_FOUND path
                from yugabyte_db_tpu.rpc import RpcError
                with pytest.raises(RpcError) as ei:
                    await sess.execute("SELECT * FROM mv_off")
                assert ei.value.code == "NOT_FOUND"
                flags.REGISTRY.reset("matview_enabled")
                # on again: full lifecycle works and DROP removes the
                # catalog entry + slot
                await sess.execute("INSERT INTO kv VALUES (1, 0, 5)")
                await sess.execute(MV.format(n="mv_on", mm=""))
                assert await c.list_matviews() == ["mv_on"]
                await sess.execute("DROP MATERIALIZED VIEW mv_on")
                assert await c.list_matviews() == []
                assert await c._master_call(
                    "list_replication_slots", {}) == {"slots": []}
            finally:
                flags.REGISTRY.reset("matview_enabled")
                await c.matviews().stop()
                await mc.shutdown()
        run(go())


class TestLoopRefusalAccounting:
    def test_typed_refusals_counted_apart_from_errors(self):
        """Regression: the maintainer loop used to count typed
        MatviewError refusals (no CDC watermark while leaders move,
        catch-up stalls) as loop_errors — a wedged stream looked like
        a bug storm.  The typed arm tallies them as loop_refusals
        with the reason surfaced."""
        from yugabyte_db_tpu.matview import maintainer as M

        async def go():
            vm = M.ViewMaintainer.__new__(M.ViewMaintainer)
            vm.counters = M._fresh_counters()
            calls = {"n": 0}

            async def fake_round():
                calls["n"] += 1
                if calls["n"] == 1:
                    raise M.MatviewError("no_watermark")
                if calls["n"] == 2:
                    raise RuntimeError("boom")
                raise asyncio.CancelledError

            vm.round = fake_round
            with pytest.raises(asyncio.CancelledError):
                await vm._loop()
            assert vm.counters["loop_refusals"] == 1
            assert vm.counters["loop_errors"] == 1
            assert vm.counters["last_fallback_reason"] == "no_watermark"
        run(go())
