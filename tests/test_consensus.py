"""Raft consensus + WAL tests: election, replication, failover, log
recovery (reference analog: src/yb/consensus/raft_consensus-test.cc,
integration-tests/raft_consensus-itest.cc at mini scale)."""
import asyncio
import os

import pytest

from yugabyte_db_tpu.consensus import (
    Log, LogEntry, PeerSpec, RaftConfig, RaftConsensus, Role,
)
from yugabyte_db_tpu.rpc import Messenger
from yugabyte_db_tpu.utils import flags


class TestLog:
    def test_append_read_recover(self, tmp_path):
        log = Log(str(tmp_path))
        log.append([LogEntry(1, 1, "write", b"a"),
                    LogEntry(1, 2, "write", b"b")])
        log.append([LogEntry(2, 3, "write", b"c")])
        assert log.last_index == 3 and log.last_term == 2
        log.close()
        log2 = Log(str(tmp_path))
        assert log2.last_index == 3
        assert [e.payload for e in log2.all_entries()] == [b"a", b"b", b"c"]

    def test_torn_tail_truncated(self, tmp_path):
        log = Log(str(tmp_path))
        log.append([LogEntry(1, i, "write", b"x" * 50) for i in range(1, 6)])
        log.close()
        seg = sorted(os.listdir(tmp_path))[0]
        path = os.path.join(tmp_path, seg)
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(size - 17)   # torn mid-entry
        log2 = Log(str(tmp_path))
        assert log2.last_index == 4

    def test_conflict_truncation(self, tmp_path):
        log = Log(str(tmp_path))
        log.append([LogEntry(1, i, "write", b"old%d" % i)
                    for i in range(1, 5)])
        log.append([LogEntry(2, 3, "write", b"new3")])
        assert log.last_index == 3
        assert log.entry(3).payload == b"new3"
        assert log.entry(4) is None
        log.close()
        log2 = Log(str(tmp_path))
        assert log2.last_index == 3
        assert log2.entry(3).payload == b"new3"


class RaftHarness:
    """In-process multi-peer Raft group over real localhost RPC — the
    MiniCluster pattern (reference: integration-tests/mini_cluster.h)."""

    def __init__(self, tmp_path, n=3):
        self.tmp = tmp_path
        self.n = n
        self.nodes = {}
        self.applied = {f"n{i}": [] for i in range(n)}

    async def start(self):
        messengers = {}
        addrs = {}
        for i in range(self.n):
            uuid = f"n{i}"
            m = Messenger(uuid)
            await m.start()
            messengers[uuid] = m
            addrs[uuid] = m.addr
        config = RaftConfig([PeerSpec(u, addrs[u]) for u in sorted(addrs)])
        for uuid, m in messengers.items():
            await self._start_node(uuid, m, config)
        return self

    async def _start_node(self, uuid, messenger, config):
        d = str(self.tmp / uuid)
        os.makedirs(d, exist_ok=True)
        log = Log(os.path.join(d, "wal"), fsync=False)

        async def apply(entry, uuid=uuid):
            self.applied[uuid].append(entry.payload)

        node = RaftConsensus("tab1", uuid, config, log, messenger, d, apply)
        await node.start()
        self.nodes[uuid] = node

    async def leader(self, timeout=10.0):
        t0 = asyncio.get_event_loop().time()
        while asyncio.get_event_loop().time() - t0 < timeout:
            leaders = [n for n in self.nodes.values()
                       if n.role == Role.LEADER]
            if len(leaders) == 1:
                return leaders[0]
            await asyncio.sleep(0.02)
        raise TimeoutError("no single leader elected")

    async def stop_node(self, uuid):
        node = self.nodes.pop(uuid)
        await node.shutdown()
        await node.messenger.shutdown()

    async def shutdown(self):
        for uuid in list(self.nodes):
            await self.stop_node(uuid)


def run(coro):
    return asyncio.run(coro)


class TestRaft:
    def test_single_peer_self_elects_and_commits(self, tmp_path):
        async def go():
            h = RaftHarness(tmp_path, n=1)
            await h.start()
            leader = await h.leader()
            idx = await leader.replicate("write", b"hello")
            assert idx >= 1
            assert h.applied[leader.uuid] == [b"hello"]
            assert leader.has_leader_lease()
            await h.shutdown()
        run(go())

    def test_three_peer_election_and_replication(self, tmp_path):
        async def go():
            h = RaftHarness(tmp_path, n=3)
            await h.start()
            leader = await h.leader()
            for i in range(5):
                await leader.replicate("write", b"op%d" % i)
            # followers apply asynchronously; wait for convergence
            for _ in range(100):
                if all(len(v) == 5 for v in h.applied.values()):
                    break
                await asyncio.sleep(0.05)
            assert all(v == [b"op%d" % i for i in range(5)]
                       for v in h.applied.values())
            await h.shutdown()
        run(go())

    def test_leader_failover(self, tmp_path):
        async def go():
            h = RaftHarness(tmp_path, n=3)
            await h.start()
            leader = await h.leader()
            await leader.replicate("write", b"before")
            dead = leader.uuid
            await h.stop_node(dead)
            new_leader = await h.leader(timeout=15.0)
            assert new_leader.uuid != dead
            await new_leader.replicate("write", b"after")
            for _ in range(100):
                if all(v == [b"before", b"after"]
                       for u, v in h.applied.items() if u in h.nodes):
                    break
                await asyncio.sleep(0.05)
            for u in h.nodes:
                assert h.applied[u] == [b"before", b"after"]
            await h.shutdown()
        run(go())

    def test_follower_catchup_after_restart_lag(self, tmp_path):
        async def go():
            h = RaftHarness(tmp_path, n=3)
            await h.start()
            leader = await h.leader()
            # stop one follower, write, restart an equivalent? (simpler:
            # stop follower, write, then verify remaining majority works)
            follower = next(u for u in h.nodes if u != leader.uuid)
            await h.stop_node(follower)
            for i in range(3):
                await leader.replicate("write", b"x%d" % i)
            assert len(h.applied[leader.uuid]) == 3
            await h.shutdown()
        run(go())

    def test_not_leader_rejects_replicate(self, tmp_path):
        async def go():
            h = RaftHarness(tmp_path, n=3)
            await h.start()
            leader = await h.leader()
            follower = next(n for n in h.nodes.values()
                            if n.uuid != leader.uuid)
            from yugabyte_db_tpu.rpc import RpcError
            with pytest.raises(RpcError):
                await follower.replicate("write", b"nope")
            await h.shutdown()
        run(go())

    def test_pre_vote_shields_healthy_leader(self, tmp_path):
        """A node whose election timer fires while the leader is healthy
        must NOT inflate the term or depose the leader (pre-vote:
        reference raft pre-elections)."""
        async def go():
            h = RaftHarness(tmp_path, n=3)
            await h.start()
            leader = await h.leader()
            await leader.replicate("write", b"stable")
            term_before = leader.meta.current_term
            follower = next(n for n in h.nodes.values()
                            if n.role != Role.LEADER)
            # force the follower's election timer to fire repeatedly
            for _ in range(5):
                follower._election_deadline = 0.0
                await asyncio.sleep(0.1)
            assert leader.role == Role.LEADER
            assert leader.meta.current_term == term_before
            assert follower.meta.current_term == term_before
            # the cluster still works
            await leader.replicate("write", b"after")
            await h.shutdown()
        run(go())

    def test_lease_expires_without_majority(self, tmp_path):
        async def go():
            h = RaftHarness(tmp_path, n=3)
            await h.start()
            leader = await h.leader()
            await leader.replicate("write", b"z")
            assert leader.has_leader_lease()
            others = [u for u in h.nodes if u != leader.uuid]
            for u in others:
                await h.stop_node(u)
            lease_s = flags.get("leader_lease_duration_ms") / 1000.0
            await asyncio.sleep(lease_s + 0.5)
            assert not leader.has_leader_lease()
            await h.shutdown()
        run(go())


class TestRpcCompression:
    def test_large_frames_compress_and_roundtrip(self):
        from yugabyte_db_tpu.rpc.messenger import (
            _COMPRESS_BIT, _COMPRESS_MIN, _pack,
        )
        import struct as _struct
        # compressible payload >= threshold gets the flag + shrinks
        obj = [0, 0, "svc", "m", {"rows": ["abc" * 10] * 400}]
        framed = _pack(obj)
        (n,) = _struct.unpack("<I", framed[:4])
        assert n & _COMPRESS_BIT
        assert len(framed) < _COMPRESS_MIN
        # incompressible stays raw (no flag)
        import os as _os
        framed = _pack([0, 0, "s", "m", {"b": _os.urandom(8192)}])
        (n,) = _struct.unpack("<I", framed[:4])
        assert not n & _COMPRESS_BIT

    def test_roundtrip_over_socket(self):
        async def go():
            from yugabyte_db_tpu.rpc import Messenger

            class Echo:
                async def rpc_echo(self, payload):
                    return {"echo": payload["msg"]}

            server = Messenger("comp-server")
            server.register_service("svc", Echo())
            addr = await server.start()
            client = Messenger("comp-client")
            big = "x" * 100_000 + "".join(str(i) for i in range(5000))
            r = await client.call(addr, "svc", "echo", {"msg": big})
            assert r == {"echo": big}
            await client.shutdown()
            await server.shutdown()
        run(go())


class TestTlsRpc:
    def test_tls_messenger_roundtrip(self, tmp_path):
        """Encrypted RPC (secure-stream analog): TLS server+client
        messengers interoperate; a plaintext client is rejected."""
        async def go():
            from yugabyte_db_tpu.rpc.messenger import (
                Messenger, generate_self_signed_cert, make_tls_contexts,
            )
            cert, key = generate_self_signed_cert(str(tmp_path))
            tls = make_tls_contexts(cert, key)

            class Echo:
                async def rpc_echo(self, payload):
                    return {"echo": payload["msg"]}

            server = Messenger("tls-server", tls=make_tls_contexts(cert, key))
            server.register_service("svc", Echo())
            addr = await server.start()
            client = Messenger("tls-client", tls=make_tls_contexts(cert, key))
            r = await client.call(addr, "svc", "echo", {"msg": "secure"})
            assert r == {"echo": "secure"}
            # plaintext client cannot talk to a TLS server
            plain = Messenger("plain")
            with pytest.raises(Exception):
                await asyncio.wait_for(
                    plain.call(addr, "svc", "echo", {"msg": "x"}), 3.0)
            await client.shutdown()
            await plain.shutdown()
            await server.shutdown()
        run(go())


class TestObservers:
    def test_observer_replicates_but_does_not_vote_or_commit(self, tmp_path):
        """A 2-voter + 1-observer group: the observer applies the log,
        but majority is over VOTERS (2), so losing one voter blocks
        commits even with the observer alive — and the observer never
        campaigns."""
        async def go():
            h = RaftHarness(tmp_path, n=3)
            # build config manually: n2 is a non-voting observer
            messengers = {}
            addrs = {}
            for i in range(3):
                uuid = f"n{i}"
                m = Messenger(uuid)
                await m.start()
                messengers[uuid] = m
                addrs[uuid] = m.addr
            config = RaftConfig(
                [PeerSpec("n0", addrs["n0"]), PeerSpec("n1", addrs["n1"]),
                 PeerSpec("n2", addrs["n2"], "observer")])
            for uuid, m in messengers.items():
                await h._start_node(uuid, m, config)
            leader = await h.leader()
            assert leader.uuid != "n2"
            await leader.replicate("write", b"seen-by-all")
            for _ in range(100):
                if h.applied["n2"] == [b"seen-by-all"]:
                    break
                await asyncio.sleep(0.05)
            assert h.applied["n2"] == [b"seen-by-all"]   # observer applies
            assert leader.config.majority == 2           # voters only
            # drop the voter follower: observer alone can't form majority
            voter_follower = next(u for u in ("n0", "n1")
                                  if u != leader.uuid)
            await h.stop_node(voter_follower)
            from yugabyte_db_tpu.rpc import RpcError
            with pytest.raises((RpcError, asyncio.TimeoutError)):
                await asyncio.wait_for(
                    leader.replicate("write", b"blocked", timeout=2.0), 4.0)
            # observer never became a candidate/leader
            assert h.nodes["n2"].role == Role.FOLLOWER
            await h.shutdown()
        run(go())
