"""Native PointReader (whole-SST batched point lookup) parity tests.

The C find_many path (native/ybtpu_hot.c PointReader) hand-replicates
point_find's bloom + block-bisect + MVCC-walk semantics; these tests pin
the subtle branches against the per-key Python path so a C regression
cannot hide behind the silent fallback (reference semantics:
src/yb/docdb/doc_rowwise_iterator.cc visibility walk, rocksdb MultiGet).
"""
import numpy as np
import pytest

from yugabyte_db_tpu.docdb import ReadRequest, RowOp, WriteRequest
from yugabyte_db_tpu.tablet import Tablet
from yugabyte_db_tpu.utils import flags
from yugabyte_db_tpu.utils.hybrid_time import HybridClock, MockPhysicalClock
from tests.test_tablet import make_info


def native_available():
    from yugabyte_db_tpu.docdb.hotpath import load
    mod = load()
    return mod is not None and hasattr(mod, "PointReader")


pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native extension unavailable")


@pytest.fixture
def tablet(tmp_path):
    clock = HybridClock(MockPhysicalClock(1_000_000))
    return Tablet("pr-1", make_info(), str(tmp_path), clock=clock)


def _python_results(tablet, pk_rows, read_ht):
    """Ground truth via the per-key Python path (_find_best)."""
    op = tablet._read_op
    mems, ssts = op.store.read_snapshot()
    out = []
    for r in pk_rows:
        f = op._find_best(op.codec.doc_key_prefix(r), read_ht, None,
                          mems, ssts)
        out.append(None if f is None else op._decode_best(f, read_ht))
    return out


def test_parity_overwrites_tombstones_multi_sst(tablet):
    t = tablet
    # SST 1: ks 0..49
    t.apply_write(WriteRequest("t1", [
        RowOp("upsert", {"k": i, "v": float(i), "s": f"a{i}"})
        for i in range(50)]))
    t.flush()
    # SST 2: overwrite evens, delete every 5th
    t.apply_write(WriteRequest("t1", [
        RowOp("upsert", {"k": i, "v": i + 100.0, "s": f"b{i}"})
        for i in range(0, 50, 2)]))
    t.apply_write(WriteRequest("t1", [
        RowOp("delete", {"k": i}) for i in range(0, 50, 5)]))
    t.flush()
    read_ht = t.clock.now().value
    keys = [{"k": i} for i in range(-3, 55)]   # misses on both ends
    got = t.multi_read("t1", keys, read_ht=read_ht)
    want = _python_results(t, keys, read_ht)
    assert got == want
    # spot-check semantics directly: tombstone wins over older version
    assert got[3 + 10] is None                 # k=10: deleted in SST 2
    assert got[3 + 2]["v"] == 102.0            # k=2: overwritten
    assert got[3 + 1]["v"] == 1.0              # k=1: only SST 1
    assert got[3 + 51] is None and got[0] is None


def test_parity_memtable_merge(tablet):
    t = tablet
    t.apply_write(WriteRequest("t1", [
        RowOp("upsert", {"k": i, "v": float(i), "s": "x"})
        for i in range(20)]))
    t.flush()
    # unflushed writes: memtable must win over the SST
    t.apply_write(WriteRequest("t1", [
        RowOp("upsert", {"k": 3, "v": 999.0, "s": "mem"}),
        RowOp("delete", {"k": 4})]))
    read_ht = t.clock.now().value
    keys = [{"k": i} for i in range(6)]
    got = t.multi_read("t1", keys, read_ht=read_ht)
    assert got == _python_results(t, keys, read_ht)
    assert got[3]["v"] == 999.0
    assert got[4] is None


def test_parity_ttl_blocks_fall_back(tablet):
    """TTL'd values never get columnar sidecars -> those SST blocks have
    no finder and find_many returns the fallback sentinel; results must
    still honor TTL expiry."""
    t = tablet
    t.apply_write(WriteRequest("t1", [
        RowOp("upsert", {"k": 1, "v": 1.0, "s": "dies"}, ttl_ms=1000),
        RowOp("upsert", {"k": 2, "v": 2.0, "s": "lives"})]))
    t.flush()
    t.clock._physical.advance_micros(10_000_000)   # TTL expired
    read_ht = t.clock.now().value
    keys = [{"k": 1}, {"k": 2}]
    got = t.multi_read("t1", keys, read_ht=read_ht)
    assert got == _python_results(t, keys, read_ht)
    assert got[0] is None
    assert got[1]["v"] == 2.0


def test_parity_version_runs_across_blocks(tmp_path):
    """Many versions of one doc key spanning a block boundary: the C
    walk must continue into the next block exactly like point_find."""
    clock = HybridClock(MockPhysicalClock(1_000_000))
    t = Tablet("pr-2", make_info(), str(tmp_path), clock=clock)
    # small row blocks force multi-block SSTs through the flush path
    for i in range(40):
        t.apply_write(WriteRequest("t1", [
            RowOp("upsert", {"k": 7, "v": float(i), "s": f"v{i}"}),
            RowOp("upsert", {"k": 7000 + i, "v": 0.0, "s": "pad"})]))
    t.flush()
    read_ht = t.clock.now().value
    keys = [{"k": 7}, {"k": 7005}, {"k": 9999}]
    got = t.multi_read("t1", keys, read_ht=read_ht)
    assert got == _python_results(t, keys, read_ht)
    assert got[0]["v"] == 39.0                 # newest version wins
    assert got[2] is None


def test_row_cap_disables_eager_reader(tablet):
    t = tablet
    t.apply_write(WriteRequest("t1", [
        RowOp("upsert", {"k": i, "v": float(i), "s": "x"})
        for i in range(30)]))
    t.flush()
    flags.set_flag("native_point_reader_max_rows", 10)
    try:
        sst = t.regular.ssts[0]
        sst._point_readers.clear()
        assert sst.point_reader(t._read_op.codec) is None
        read_ht = t.clock.now().value
        keys = [{"k": 5}, {"k": 29}, {"k": 99}]
        got = t.multi_read("t1", keys, read_ht=read_ht)
        assert got == _python_results(t, keys, read_ht)
    finally:
        flags.REGISTRY.reset("native_point_reader_max_rows")
