"""String predicates on the device path via per-batch dictionary
encoding (SURVEY §7 hard-part 3; reference: varlen packed-row handling,
dockv/schema_packing.h, pushdown eval doc_pg_expr.cc)."""
import tempfile

import numpy as np
import pytest

from yugabyte_db_tpu.docdb.operations import (
    ReadRequest, RowOp, WriteRequest,
)
from yugabyte_db_tpu.docdb.table_codec import TableInfo
from yugabyte_db_tpu.dockv.packed_row import (
    ColumnSchema, ColumnType, TableSchema,
)
from yugabyte_db_tpu.dockv.partition import PartitionSchema
from yugabyte_db_tpu.ops import AggSpec, Expr
from yugabyte_db_tpu.tablet import Tablet

C = Expr.col

SHIPMODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
N = 30_000


@pytest.fixture(scope="module")
def tab():
    schema = TableSchema((
        ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
        ColumnSchema(1, "shipmode", ColumnType.STRING),
        ColumnSchema(2, "price", ColumnType.FLOAT64),
        ColumnSchema(3, "qty", ColumnType.FLOAT64),
    ), 1)
    info = TableInfo("li", "li", schema, PartitionSchema("hash", 1))
    t = Tablet("li", info, tempfile.mkdtemp(prefix="strp-"))
    rng = np.random.default_rng(3)
    modes = rng.integers(0, len(SHIPMODES), N)
    t.bulk_load({
        "k": np.arange(N, dtype=np.int64),
        "shipmode": np.array([SHIPMODES[m] for m in modes], object),
        "price": rng.uniform(900, 10_000, N),
        "qty": rng.integers(1, 50, N).astype(np.float64),
    })
    t._rows = {
        "mode": np.array([SHIPMODES[m] for m in modes]),
        "price": None, "qty": None,
    }
    # keep the raw arrays for numpy reference checks
    t._modes = np.array([SHIPMODES[m] for m in modes])
    return t


def _agg(t, where):
    return t.read(ReadRequest(
        "li", where=where, aggregates=(AggSpec("sum", C(2).node),
                                       AggSpec("count"))))


class TestStringPredicatePushdown:
    def test_equality_runs_on_device(self, tab):
        resp = _agg(tab, C(1).eq("RAIL").node)
        assert resp.backend == "tpu"
        m = tab._modes == "RAIL"
        assert int(resp.agg_values[1]) == int(m.sum())

    def test_q6_string_variant_matches_numpy(self, tab):
        """Q6-style: numeric range + string equality, SUM pushdown —
        end-to-end on the TPU path."""
        where = ((C(3) < 24.0) & C(1).eq("SHIP")).node
        resp = tab.read(ReadRequest(
            "li", where=where,
            aggregates=(AggSpec("sum", (C(2) * C(3)).node),)))
        assert resp.backend == "tpu"
        # numpy reference over the same loaded data
        blocks = []
        qty = price = modes = None
        resp_all = tab.read(ReadRequest("li", columns=("qty", "price",
                                                       "shipmode")))
        qty = np.array([r["qty"] for r in resp_all.rows])
        price = np.array([r["price"] for r in resp_all.rows])
        modes = np.array([r["shipmode"] for r in resp_all.rows])
        m = (qty < 24.0) & (modes == "SHIP")
        want = float((price[m] * qty[m]).sum())
        got = float(resp.agg_values[0])
        assert abs(got - want) / max(abs(want), 1e-9) < 1e-3

    def test_range_and_in_and_ne(self, tab):
        cases = [
            (C(1).node, "ge", "REG AIR",
             tab._modes >= "REG AIR"),
            (C(1).node, "lt", "MAIL", tab._modes < "MAIL"),
        ]
        for colnode, op, lit, ref in cases:
            where = ("cmp", op, colnode, ("const", lit))
            resp = _agg(tab, where)
            assert resp.backend == "tpu", (op, lit)
            assert int(resp.agg_values[1]) == int(ref.sum()), (op, lit)
        resp = _agg(tab, C(1).isin(["AIR", "TRUCK", "nope"]).node)
        assert resp.backend == "tpu"
        want = int(np.isin(tab._modes, ["AIR", "TRUCK"]).sum())
        assert int(resp.agg_values[1]) == want
        resp = _agg(tab, C(1).ne("FOB").node)
        assert resp.backend == "tpu"
        assert int(resp.agg_values[1]) == int((tab._modes != "FOB").sum())

    def test_equality_absent_value(self, tab):
        resp = _agg(tab, C(1).eq("ZEBRA").node)
        assert resp.backend == "tpu"
        assert int(resp.agg_values[1]) == 0

    def test_like_on_dictionary(self, tab):
        resp = _agg(tab, ("like", C(1).node, "%AIR"))
        assert resp.backend == "tpu"
        want = int(np.char.endswith(tab._modes.astype(str), "AIR").sum())
        assert int(resp.agg_values[1]) == want
        resp = _agg(tab, ("like", C(1).node, "R__L"))
        assert resp.backend == "tpu"
        assert int(resp.agg_values[1]) == int((tab._modes == "RAIL").sum())

    def test_filter_scan_with_string_predicate(self, tab):
        resp = tab.read(ReadRequest(
            "li", columns=("k", "shipmode"),
            where=("like", C(1).node, "S%")))
        assert resp.backend == "tpu"
        want = int(np.char.startswith(tab._modes.astype(str), "S").sum())
        assert len(resp.rows) == want
        assert all(r["shipmode"].startswith("S") for r in resp.rows)

    def test_cpu_twin_agrees(self, tab):
        from yugabyte_db_tpu.utils import flags
        where = (C(1).between("FOB", "RAIL") & (C(3) >= 10.0)).node
        dev = _agg(tab, where)
        assert dev.backend == "tpu"
        flags.set_flag("tpu_pushdown_enabled", False)
        try:
            cpu = _agg(tab, where)
        finally:
            flags.set_flag("tpu_pushdown_enabled", True)
        assert cpu.backend == "cpu"
        assert int(dev.agg_values[1]) == int(cpu.agg_values[1])
        rel = abs(float(dev.agg_values[0]) - float(cpu.agg_values[0])) / \
            max(abs(float(cpu.agg_values[0])), 1e-9)
        assert rel < 1e-3

    def test_unrewritable_shape_falls_back(self, tab):
        # string column inside arithmetic: no device translation
        where = ("cmp", "eq", ("arith", "add", C(1).node,
                               ("const", "x")), ("const", "yx"))
        resp = _agg(tab, where)
        assert resp.backend == "cpu"


class TestNullStrings:
    def test_null_strings_excluded_by_predicates(self):
        schema = TableSchema((
            ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
            ColumnSchema(1, "s", ColumnType.STRING),
        ), 1)
        info = TableInfo("ns", "ns", schema, PartitionSchema("hash", 1))
        t = Tablet("ns", info, tempfile.mkdtemp(prefix="nstr-"))
        rows = [{"k": i, "s": None if i % 3 == 0 else f"v{i % 5}"}
                for i in range(8000)]
        t.apply_write(WriteRequest("ns", [RowOp("upsert", r)
                                          for r in rows]))
        t.flush()
        resp = t.read(ReadRequest(
            "ns", where=C(1).eq("v1").node,
            aggregates=(AggSpec("count"),)))
        want = len([r for r in rows if r["s"] == "v1"])
        assert int(resp.agg_values[0]) == want
        # IS NULL still works (on whatever path it takes)
        resp = t.read(ReadRequest(
            "ns", where=("isnull", C(1).node),
            aggregates=(AggSpec("count"),)))
        assert int(resp.agg_values[0]) == len(
            [r for r in rows if r["s"] is None])
