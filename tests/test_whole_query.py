"""Whole-query fused plans: multi-join chain parity (device vs the
interpreted join, dangling/NULL FKs at every stage, dict-coded string
payloads), per-stage typed JoinIneligible fallback (the WHOLE query
falls back bit-identically), the growth-never-recompiles contract (one
jitted program, one compile across >=20 launches plus 2x data growth
and within-bucket build growth), server-side window pushdown parity
against an independent Python reference with typed refusals for every
ineligible shape, and the SQL-level 3-table chain + window pushdown
through MiniCluster."""
import asyncio
import tempfile

import numpy as np
import pytest

from yugabyte_db_tpu.docdb.operations import ReadRequest
from yugabyte_db_tpu.docdb.table_codec import TableInfo
from yugabyte_db_tpu.docdb.wire import (read_request_from_wire,
                                        read_request_to_wire)
from yugabyte_db_tpu.dockv.packed_row import (ColumnSchema, ColumnType,
                                              TableSchema)
from yugabyte_db_tpu.dockv.partition import PartitionSchema
from yugabyte_db_tpu.ops.expr import Expr
from yugabyte_db_tpu.ops.grouped_scan import DictGroupSpec
from yugabyte_db_tpu.ops.join_scan import (BUILD_COL_BASE,
                                           REASON_DUPLICATE_KEY,
                                           REASON_STAGE_COUNT,
                                           JoinIneligible, JoinWire,
                                           make_join_runtimes)
from yugabyte_db_tpu.ops.plan_fusion import (LAST_PLAN_STATS,
                                             default_plan_kernel)
from yugabyte_db_tpu.ops.scan import AggSpec
from yugabyte_db_tpu.ops.window_scan import (WINDOW_STATS, WindowWire)
from yugabyte_db_tpu.tablet import Tablet
from yugabyte_db_tpu.utils import flags

C = Expr.col

# chain payload lanes (one shared namespace, like the SQL lowering)
CK = BUILD_COL_BASE          # mid.ck        (stage-1 probe lane)
MNAME = BUILD_COL_BASE + 1   # mid.name      (string)
WT = BUILD_COL_BASE + 2      # mid.weight    (int64)
SEG = BUILD_COL_BASE + 3     # cust.segment  (string, group key)
RG = BUILD_COL_BASE + 4      # cust.region   (stage-2 probe lane)
RNAME = BUILD_COL_BASE + 5   # region.name   (string, group key)


@pytest.fixture(autouse=True)
def _reset_flags():
    yield
    for f in ("join_pushdown_enabled", "plan_fusion_enabled",
              "window_pushdown_enabled", "window_server_pushdown_enabled",
              "multi_join_max_stages", "join_max_build_slots",
              "streaming_chunk_rows", "streaming_scan_enabled",
              "grouped_pushdown_enabled", "tpu_min_rows_for_pushdown",
              "bypass_reader_enabled"):
        flags.REGISTRY.reset(f)


def _probe_tablet(prefix, n=6000, n_mid=400, seed=11, block_rows=4096):
    """Probe (fact) table: k PK, fk -> mid (a slice dangles past
    n_mid), val integer-valued f64 (exact device sums), ship int32."""
    schema = TableSchema((
        ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
        ColumnSchema(1, "fk", ColumnType.INT64),
        ColumnSchema(2, "val", ColumnType.FLOAT64),
        ColumnSchema(3, "ship", ColumnType.INT32),
    ), 1)
    info = TableInfo("probe", "probe", schema, PartitionSchema("hash", 1))
    t = Tablet("probe", info, tempfile.mkdtemp(prefix=prefix))
    rng = np.random.default_rng(seed)
    data = {
        "k": np.arange(n, dtype=np.int64),
        # ~11% dangling stage-0 FKs (inner join drops them)
        "fk": rng.integers(0, int(n_mid * 1.125), n).astype(np.int64),
        "val": rng.integers(1, 100, n).astype(np.float64),
        "ship": rng.integers(0, 100, n).astype(np.int32),
    }
    t.bulk_load(data, block_rows=block_rows)
    return t, data


def _mid_tables(n_mid=400, n_cust=60, n_reg=7, seed=23):
    """Build-side rows for the chain: mid (keyed 0..n_mid-1, ships the
    ck lane with NULLs and values dangling past n_cust), cust (keyed
    0..n_cust-1, ships segment strings + region codes), region."""
    rng = np.random.default_rng(seed)
    mid = {
        "mk": np.arange(n_mid, dtype=np.int64),
        # ~8% dangling stage-1 FKs; NULL mask on top
        "ck": rng.integers(0, int(n_cust * 1.1), n_mid).astype(np.int64),
        "ckn": (np.arange(n_mid) % 13 == 0),
        "name": np.array([f"m{i % 7}" for i in range(n_mid)], object),
        "wt": rng.integers(1, 50, n_mid).astype(np.int64),
    }
    cust = {
        "ck": np.arange(n_cust, dtype=np.int64),
        "seg": np.array([f"S{i % 4}" for i in range(n_cust)], object),
        "rg": rng.integers(0, n_reg, n_cust).astype(np.int64),
    }
    reg = {
        "rk": np.arange(n_reg, dtype=np.int64),
        "name": np.array([f"R{i}" for i in range(n_reg)], object),
    }
    return mid, cust, reg


def _chain_wires(mid, cust, reg=None):
    """Ordered JoinWire stages: probe.fk -> mid.mk (ships ck/name/wt),
    then the CK lane -> cust.ck (ships seg/rg), optionally RG -> reg."""
    wires = [
        JoinWire(probe_col=1, keys=mid["mk"],
                 payload={CK: (mid["ck"], mid["ckn"]),
                          MNAME: (mid["name"], None),
                          WT: (mid["wt"], None)}),
        JoinWire(probe_col=CK, keys=cust["ck"],
                 payload={SEG: (cust["seg"], None),
                          RG: (cust["rg"], None)}),
    ]
    if reg is not None:
        wires.append(JoinWire(probe_col=RG, keys=reg["rk"],
                              payload={RNAME: (reg["name"], None)}))
    return tuple(wires)


_WHERE = (C(3) < 50).node
_AGGS = (AggSpec("sum", C(2).node), AggSpec("count"),
         AggSpec("sum", C(WT).node))


def _req(wires, group_bid, where=_WHERE):
    r = ReadRequest("probe", where=where, aggregates=_AGGS,
                    group_by=DictGroupSpec(cols=(group_bid,)),
                    join=wires)
    # every request crosses the wire codec, like a real RPC — the
    # N-stage join list must round-trip
    return read_request_from_wire(read_request_to_wire(r))


def _by_key(resp):
    counts = np.asarray(resp.group_counts)
    out = {}
    for g in np.nonzero(counts)[0]:
        key = tuple(str(v[g]) for v in resp.group_values)
        out[key] = (int(counts[g]),) + tuple(
            float(np.asarray(v)[g]) for v in resp.agg_values)
    return out


def _np_chain_ref(data, mid, cust, reg, group):
    """Independent numpy fold of the chain (inner semantics: WHERE,
    dangling and NULL FKs drop at their own stage)."""
    fk = data["fk"]
    m = data["ship"] < 50
    m &= fk < len(mid["mk"])                  # stage 0 match
    ck = mid["ck"][np.clip(fk, 0, len(mid["mk"]) - 1)]
    ckn = mid["ckn"][np.clip(fk, 0, len(mid["mk"]) - 1)]
    m &= ~ckn & (ck < len(cust["ck"]))        # stage 1: NULL/dangling
    ckc = np.clip(ck, 0, len(cust["ck"]) - 1)
    if group == "seg":
        gvals = cust["seg"][ckc]
        domain = sorted(set(cust["seg"]))
    else:
        rg = cust["rg"][ckc]
        gvals = reg["name"][rg]
        domain = sorted(set(reg["name"]))
    wt = mid["wt"][np.clip(fk, 0, len(mid["mk"]) - 1)]
    out = {}
    for g in domain:
        mg = m & (gvals == g)
        if mg.any():
            out[(str(g),)] = (int(mg.sum()),
                              float(data["val"][mg].sum()),
                              float(mg.sum()),
                              float(wt[mg].sum()))
    return out


# --- chain parity: device vs interpreted, bitwise ---------------------------

class TestChainParity:
    def test_two_stage_chain_device_vs_interpreted_bitwise(self):
        t, data = _probe_tablet("chain2-")
        mid, cust, reg = _mid_tables()
        wires = _chain_wires(mid, cust)
        flags.set_flag("tpu_min_rows_for_pushdown", 0)
        dev = t.read(_req(wires, SEG))
        assert dev.backend == "tpu", "chain fell back"
        assert LAST_PLAN_STATS.get("join_stages") == 2
        flags.set_flag("join_pushdown_enabled", False)
        interp = t.read(_req(wires, SEG))
        assert interp.backend == "cpu"
        # integer-valued lanes: device and interpreted results are
        # IDENTICAL, dangling stage-0 FKs, dangling stage-1 FKs and
        # NULL ck payloads all dropping at their own stage
        assert _by_key(dev) == _by_key(interp)
        assert _by_key(dev) == _np_chain_ref(data, mid, cust, reg, "seg")

    def test_three_stage_chain_device_vs_interpreted_bitwise(self):
        t, data = _probe_tablet("chain3-")
        mid, cust, reg = _mid_tables()
        wires = _chain_wires(mid, cust, reg)
        flags.set_flag("tpu_min_rows_for_pushdown", 0)
        dev = t.read(_req(wires, RNAME))
        assert dev.backend == "tpu", "3-stage chain fell back"
        assert LAST_PLAN_STATS.get("join_stages") == 3
        flags.set_flag("join_pushdown_enabled", False)
        interp = t.read(_req(wires, RNAME))
        assert interp.backend == "cpu"
        assert _by_key(dev) == _by_key(interp)
        assert _by_key(dev) == _np_chain_ref(data, mid, cust, reg, "reg")

    def test_tpch_chain_specs_match_numpy_reference(self):
        # the gauntlet's adapted Q5 chain at tiny scale: counts exact,
        # revenue within float tolerance of the numpy reference
        from yugabyte_db_tpu.models.tpch import (
            _chain_group, chain_build_wires, generate_customer,
            generate_lineitem, generate_orders_cust, lineitem_join_data,
            lineitem_join_info, numpy_reference_chain, tpch_q5_chain)
        data = generate_lineitem(0.002)
        n_orders, n_cust = 3000, 300
        odata = generate_orders_cust(n_orders, n_cust)
        cdata = generate_customer(n_cust)
        ldata = lineitem_join_data(data, n_orders)
        t = Tablet("li-wq", lineitem_join_info(),
                   tempfile.mkdtemp(prefix="wq-li-"))
        t.bulk_load(ldata, block_rows=8192)
        q = tpch_q5_chain()
        wires = chain_build_wires(q, odata, cdata)
        flags.set_flag("tpu_min_rows_for_pushdown", 0)
        r = ReadRequest("lineitem_j", where=q.probe_where,
                        aggregates=q.aggs,
                        group_by=_chain_group(q.group_col), join=wires)
        resp = t.read(read_request_from_wire(read_request_to_wire(r)))
        assert resp.backend == "tpu"
        ref = numpy_reference_chain(q, ldata, odata, cdata)
        got = _by_key(resp)
        for g, (cnt, rev) in ref.items():
            have = got.get((str(g),))
            if cnt == 0:
                assert have is None
                continue
            assert have[0] == cnt, g
            assert abs(have[1] - rev) <= 1e-6 * max(abs(rev), 1.0), g


# --- per-stage typed fallback: the WHOLE query falls back -------------------

class TestPerStageTypedFallback:
    def test_duplicate_key_names_its_stage(self):
        mid, cust, _ = _mid_tables()
        cust_dup = dict(cust)
        cust_dup["ck"] = cust["ck"].copy()
        cust_dup["ck"][5] = cust_dup["ck"][4]      # stage-1 duplicate
        wires = _chain_wires(mid, cust_dup)
        with pytest.raises(JoinIneligible) as ei:
            make_join_runtimes(wires, {})
        assert ei.value.reason == REASON_DUPLICATE_KEY
        assert ei.value.stage == 1

    def test_stage1_refusal_falls_back_whole_bit_identical(self):
        t, _ = _probe_tablet("fall1-")
        mid, cust, _ = _mid_tables()
        cust_dup = dict(cust)
        cust_dup["ck"] = cust["ck"].copy()
        cust_dup["ck"][5] = cust_dup["ck"][4]
        wires = _chain_wires(mid, cust_dup)
        flags.set_flag("tpu_min_rows_for_pushdown", 0)
        from yugabyte_db_tpu.ops.join_scan import JOIN_STATS
        f0 = JOIN_STATS["fallbacks"]
        resp = t.read(_req(wires, SEG))
        # stage 1 refused -> the WHOLE query serves interpreted, and
        # the refusal is tallied, never silent
        assert resp.backend == "cpu"
        assert JOIN_STATS["fallbacks"] == f0 + 1
        flags.set_flag("join_pushdown_enabled", False)
        interp = t.read(_req(wires, SEG))
        assert _by_key(resp) == _by_key(interp)

    def test_stage_budget_typed_then_whole_query_intact(self):
        t, _ = _probe_tablet("budget-")
        mid, cust, _ = _mid_tables()
        wires = _chain_wires(mid, cust)
        with pytest.raises(JoinIneligible) as ei:
            make_join_runtimes(wires, {}, max_stages=1)
        assert ei.value.reason == REASON_STAGE_COUNT
        flags.set_flag("tpu_min_rows_for_pushdown", 0)
        flags.set_flag("multi_join_max_stages", 1)
        over = t.read(_req(wires, SEG))
        assert over.backend == "cpu"        # typed fallback, whole
        flags.REGISTRY.reset("multi_join_max_stages")
        dev = t.read(_req(wires, SEG))
        assert dev.backend == "tpu"
        assert _by_key(over) == _by_key(dev)


# --- the acceptance contract: one compile, >=20 launches, 2x growth ---------

class TestGrowthNeverRecompiles:
    def test_chain_one_compile_across_launches_and_growth(self):
        # every chunk of the streamed scan shares one pow2 bucket, so
        # the 3-table chain keeps ONE plan signature across 20+
        # launches, 2x probe-side growth and within-bucket build growth
        # (4+ chunks each, so BOTH tablets take the streaming route —
        # under min_chunks the monolithic twin pads to the full scan)
        flags.set_flag("tpu_min_rows_for_pushdown", 0)
        flags.set_flag("streaming_chunk_rows", 2048)
        t_a, _ = _probe_tablet("grow-a-", n=8192, block_rows=2048)
        t_b, _ = _probe_tablet("grow-b-", n=16384, block_rows=2048)
        mid, cust, reg = _mid_tables()
        wires = _chain_wires(mid, cust, reg)
        kern = default_plan_kernel()
        c0, l0 = kern.compiles, kern.launches
        for _ in range(20):
            r = t_a.read(_req(wires, RNAME))
            assert r.backend == "tpu"
        assert kern.compiles - c0 == 1, "launches must share one program"
        # 2x data growth: more chunks, same chunk bucket, same signature
        r = t_b.read(_req(wires, RNAME))
        assert r.backend == "tpu"
        assert kern.compiles - c0 == 1, "2x growth recompiled"
        # build-side growth WITHIN the pow2 bucket (400 -> 500 rows pads
        # to the same 512/1024 buckets): still the same signature
        mid2, _, _ = _mid_tables(n_mid=500)
        r = t_a.read(_req(_chain_wires(mid2, cust, reg), RNAME))
        assert r.backend == "tpu"
        assert kern.compiles - c0 == 1, "in-bucket build growth recompiled"
        assert kern.launches - l0 >= 20
        assert all(v == 1 for v in kern.sig_compiles.values()), \
            "some plan signature compiled more than once"


# --- server-side window pushdown --------------------------------------------

def _window_tablet(prefix, n=300):
    schema = TableSchema((
        ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
        ColumnSchema(1, "g", ColumnType.INT64),
        ColumnSchema(2, "v", ColumnType.INT64),
        ColumnSchema(3, "f", ColumnType.FLOAT64),
    ), 1)
    info = TableInfo("w", "w", schema, PartitionSchema("hash", 1))
    t = Tablet("w", info, tempfile.mkdtemp(prefix=prefix))
    k = np.arange(n, dtype=np.int64)
    t.bulk_load({
        "k": k,
        "g": (k % 5).astype(np.int64),
        # unique order keys per partition: no tie ambiguity for lag
        "v": ((k * 7919) % 100003).astype(np.int64),
        "f": (k * 0.5),
    }, block_rows=128)
    return t


_WIN_WIRE = WindowWire(
    partition_by=("g",), order_by=(("v", False),),
    items=(("rank", 0, None, "rk"), ("sum", 1, "v", "s"),
           ("lag", 1, "v", "lg"), ("count_star", 1, None, "cs")))


def _win_req(wire=_WIN_WIRE, limit=None, where=(C(2) >= 0).node):
    r = ReadRequest("w", columns=("k", "g", "v"), where=where,
                    window=wire, limit=limit)
    return read_request_from_wire(read_request_to_wire(r))


def _py_window_ref(rows):
    """Independent Python fold: per partition sorted by v (unique), so
    rank == row index + 1, cumulative sum/count and lag are exact."""
    from collections import defaultdict
    parts = defaultdict(list)
    for r in rows:
        parts[r["g"]].append(r)
    out = {}
    for rs in parts.values():
        rs = sorted(rs, key=lambda r: r["v"])
        run = 0
        for i, r in enumerate(rs):
            run += r["v"]
            out[r["k"]] = {"rk": i + 1, "s": run,
                           "lg": rs[i - 1]["v"] if i > 0 else None,
                           "cs": i + 1}
    return out


class TestServerWindowPushdown:
    def test_served_rows_match_python_reference(self):
        t = _window_tablet("win-")
        resp = t.read(_win_req())
        assert resp.window_served and resp.window_reason is None
        ref = _py_window_ref([{k: r[k] for k in ("k", "g", "v")}
                              for r in resp.rows])
        for r in resp.rows:
            want = ref[r["k"]]
            got = {c: r[c] for c in ("rk", "s", "lg", "cs")}
            assert got == want, r["k"]

    def test_order_key_ties_peers_share(self):
        # with order-key ties the cumulative frame is PG's RANGE frame:
        # peers share the peer-group-end value; rank counts strictly
        # smaller keys + 1 — both are tie-order independent
        t = _window_tablet("win-tie-", n=64)
        wire = WindowWire(partition_by=(), order_by=(("g", False),),
                          items=(("rank", 0, None, "rk"),
                                 ("sum", 1, "v", "s"),
                                 ("count", 1, "v", "c")))
        resp = t.read(_win_req(wire=wire))
        assert resp.window_served
        rows = resp.rows
        for r in rows:
            below = [x for x in rows if x["g"] < r["g"]]
            at = [x for x in rows if x["g"] <= r["g"]]
            assert r["rk"] == len(below) + 1
            assert r["s"] == sum(x["v"] for x in at)
            assert r["c"] == len(at)

    def test_flag_off_typed_refusal(self):
        t = _window_tablet("win-off-", n=64)
        flags.set_flag("window_server_pushdown_enabled", False)
        f0 = WINDOW_STATS["fallbacks"]
        resp = t.read(_win_req())
        assert not resp.window_served
        assert resp.window_reason == "window_server_off"
        assert WINDOW_STATS["fallbacks"] == f0 + 1
        assert all("rk" not in r for r in resp.rows)   # plain rows

    def test_limit_typed_refusal(self):
        # a limited scan serves a row SUBSET: frames need every
        # partition row, so the server refuses typed and serves plain
        t = _window_tablet("win-lim-", n=64)
        resp = t.read(_win_req(limit=10))
        assert not resp.window_served
        assert resp.window_reason == "window_paged_scan"
        assert all("rk" not in r for r in resp.rows)

    def test_value_kind_typed_refusal(self):
        # float value lane: segment sums would not be bit-identical to
        # the Python fold, so the shape refuses typed
        t = _window_tablet("win-f-", n=64)
        wire = WindowWire(partition_by=("g",),
                          order_by=(("v", False),),
                          items=(("sum", 1, "f", "sf"),))
        r = ReadRequest("w", columns=("k", "g", "v", "f"),
                        where=(C(2) >= 0).node, window=wire)
        resp = t.read(read_request_from_wire(read_request_to_wire(r)))
        assert not resp.window_served
        assert resp.window_reason == "window_value_kind"
        assert all("sf" not in r for r in resp.rows)


# --- SQL: whole-query chain + window pushdown through the cluster ----------

class TestSqlWholeQuery:
    def _run(self, coro):
        return asyncio.run(coro)

    def test_sql_three_table_chain_fused_vs_classic(self, tmp_path):
        from yugabyte_db_tpu.ql import SqlSession
        from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
        from yugabyte_db_tpu.ops.plan_fusion import PLAN_STATS

        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute(
                    "CREATE TABLE facts (k bigint, fk bigint, v bigint,"
                    " PRIMARY KEY (k))")
                await s.execute(
                    "CREATE TABLE mid (mk bigint, mck bigint, mw bigint,"
                    " PRIMARY KEY (mk))")
                await s.execute(
                    "CREATE TABLE cust (ck bigint, cseg text,"
                    " PRIMARY KEY (ck))")
                # fk=i%9 dangles for mid keys >= 7; NULL + dangling mck
                vals = ",".join(f"({i}, {i % 9}, {(i * 3) % 13})"
                                for i in range(420))
                await s.execute(
                    "INSERT INTO facts (k, fk, v) VALUES " + vals)
                mrows = []
                for d in range(7):
                    mck = ("NULL" if d == 2
                           else "9" if d == 5      # dangling (no cust 9)
                           else str(d % 4))
                    mrows.append(f"({d}, {mck}, {d * 10})")
                await s.execute("INSERT INTO mid (mk, mck, mw) VALUES "
                                + ",".join(mrows))
                await s.execute(
                    "INSERT INTO cust (ck, cseg) VALUES (0,'a'),"
                    "(1,'b'),(2,'a'),(3,'c')")
                flags.set_flag("tpu_min_rows_for_pushdown", 0)
                q = ("SELECT cseg, count(*) AS c, sum(v) AS sv, "
                     "sum(mw) AS sw FROM facts "
                     "JOIN mid ON fk = mk JOIN cust ON mck = ck "
                     "WHERE v > 2 GROUP BY cseg ORDER BY cseg")
                l0 = PLAN_STATS["launches"]
                r1 = (await s.execute(q)).rows
                assert PLAN_STATS["launches"] > l0, \
                    "3-table chain never reached the plan kernel"
                assert LAST_PLAN_STATS.get("join_stages") == 2
                flags.set_flag("plan_fusion_enabled", False)
                r2 = (await s.execute(q)).rows
                # integer lanes: the classic client join answer is
                # IDENTICAL — NULL and dangling FKs drop per stage
                assert r1 == r2
                assert r1, "chain produced no groups"
            finally:
                await mc.shutdown()
        self._run(go())

    def test_bypass_window_request_shape_typed_fallback(self, tmp_path):
        # the bypass engine serves whole-tablet AGGREGATES only: a
        # row+window request falls back to the RPC scan with the typed
        # "request_shape" reason — and the RPC path still serves the
        # window, so the refusal costs a route, never the answer
        from yugabyte_db_tpu.ql import SqlSession
        from yugabyte_db_tpu.tools.mini_cluster import MiniCluster

        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute("CREATE TABLE wb (k bigint, g bigint, "
                                "v bigint, PRIMARY KEY (k))")
                vals = ",".join(f"({i}, {i % 3}, {(i * 11) % 97})"
                                for i in range(60))
                await s.execute("INSERT INTO wb (k, g, v) VALUES "
                                + vals)
                c = mc.client()
                flags.set_flag("bypass_reader_enabled", True)
                wire = WindowWire(partition_by=("g",),
                                  order_by=(("v", False),),
                                  items=(("rank", 0, None, "rk"),))
                req = ReadRequest("", columns=("k", "g", "v"),
                                  window=wire)
                resp = await c.scan_bypass("wb", req)
                assert c.last_bypass["used"] is False
                assert c.last_bypass["reason"] == "request_shape"
                assert resp.window_served
                assert all("rk" in r for r in resp.rows)
            finally:
                await mc.shutdown()
        self._run(go())

    def test_sql_window_server_served_and_parity(self, tmp_path):
        from yugabyte_db_tpu.ql import SqlSession
        from yugabyte_db_tpu.tools.mini_cluster import MiniCluster

        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute("CREATE TABLE wt (k bigint, g bigint, "
                                "v bigint, PRIMARY KEY (k))")
                vals = ",".join(f"({i}, {i % 4}, {(i * 7919) % 1009})"
                                for i in range(120))
                await s.execute("INSERT INTO wt (k, g, v) VALUES "
                                + vals)
                q = ("SELECT k, rank() OVER (PARTITION BY g ORDER BY v)"
                     " AS rk, sum(v) OVER (PARTITION BY g ORDER BY v)"
                     " AS sv, lag(v) OVER (PARTITION BY g ORDER BY v)"
                     " AS lg FROM wt ORDER BY k")

                def _boom(*a, **kw):   # pragma: no cover - must not run
                    raise AssertionError(
                        "client recompute ran: server did not serve")
                orig = s._apply_windows
                s._apply_windows = _boom
                try:
                    r1 = (await s.execute(q)).rows
                finally:
                    s._apply_windows = orig
                # flag off: the wire never ships, the client tier
                # recomputes — bit-identical rows either way
                flags.set_flag("window_server_pushdown_enabled", False)
                r2 = (await s.execute(q)).rows
                assert r1 == r2
            finally:
                await mc.shutdown()
        self._run(go())
