"""Tablespaces / geo-placement: per-zone replica minimums, preferred
leader zones, placement-aware balancing (reference:
master/ysql_tablespace_manager.cc, placement handling + preferred-zone
leader affinity in master/cluster_balance.cc)."""
import asyncio

import pytest

from yugabyte_db_tpu.docdb.table_codec import TableInfo
from yugabyte_db_tpu.dockv.packed_row import (ColumnSchema, ColumnType,
                                              TableSchema)
from yugabyte_db_tpu.dockv.partition import PartitionSchema
from yugabyte_db_tpu.ql import SqlSession
from yugabyte_db_tpu.rpc.messenger import RpcError
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster


def _info(name):
    schema = TableSchema(columns=(
        ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
        ColumnSchema(1, "v", ColumnType.FLOAT64),
    ), version=1)
    return TableInfo(name, name, schema, PartitionSchema("hash", 1))


def _zone_of(mc, uuid):
    return mc.master.tservers[uuid]["zone"]


def test_create_honors_tablespace_minimums(tmp_path):
    async def go():
        mc = await MiniCluster(str(tmp_path), num_tservers=4,
                               zones=["z1", "z1", "z2", "z3"]).start()
        try:
            c = mc.client()
            await c.create_tablespace(
                "geo", placement=[{"zone": "z2", "min_replicas": 1},
                                  {"zone": "z3", "min_replicas": 1}],
                preferred_zones=["z2"])
            assert "geo" in await c.list_tablespaces()
            await c.create_table(_info("gt"), num_tablets=2,
                                 replication_factor=3, tablespace="geo")
            for ent in mc.master.tablets.values():
                zones = {_zone_of(mc, u) for u in ent["replicas"]}
                assert {"z2", "z3"} <= zones, zones
            # unknown tablespace is rejected
            with pytest.raises(RpcError):
                await c.create_table(_info("bad"), tablespace="nope")
            # in-use tablespace cannot drop
            with pytest.raises(RpcError):
                await c.drop_tablespace("geo")
        finally:
            await mc.shutdown()
    asyncio.run(go())


def test_universe_placement_default(tmp_path):
    async def go():
        mc = await MiniCluster(str(tmp_path), num_tservers=3,
                               zones=["za", "zb", "zb"]).start()
        try:
            c = mc.client()
            await c.set_placement_info(
                placement=[{"zone": "za", "min_replicas": 1}])
            await c.create_table(_info("ut"), num_tablets=2,
                                 replication_factor=2)
            for ent in mc.master.tablets.values():
                zones = {_zone_of(mc, u) for u in ent["replicas"]}
                assert "za" in zones
        finally:
            await mc.shutdown()
    asyncio.run(go())


def test_lb_repairs_placement_violation(tmp_path):
    """A tablet violating its zone minimums gets a repair move."""
    async def go():
        mc = await MiniCluster(str(tmp_path), num_tservers=4,
                               zones=["z1", "z1", "z2", "z2"]).start()
        try:
            c = mc.client()
            await c.create_table(_info("rt"), num_tablets=1,
                                 replication_factor=2)
            await mc.wait_for_leaders("rt")
            # force both replicas into z1 by rewriting the catalog,
            # then declare a policy requiring one replica in z2
            m = mc.master
            z1 = [u for u in m.tservers if _zone_of(mc, u) == "z1"]
            tid, ent = next((t, e) for t, e in m.tablets.items())
            if set(ent["replicas"]) != set(z1):
                # move any z2 replica to the unused z1 server
                for u in list(ent["replicas"]):
                    if _zone_of(mc, u) == "z2":
                        dst = next(x for x in z1
                                   if x not in ent["replicas"])
                        ok = await m.load_balancer.move_replica(
                            tid, u, dst)
                        assert ok
                        ent = m.tablets[tid]
            assert {_zone_of(mc, u) for u in ent["replicas"]} == {"z1"}
            await c.create_tablespace(
                "need-z2", placement=[{"zone": "z2",
                                       "min_replicas": 1}])
            m.tables[ent["table_id"]]["tablespace"] = "need-z2"
            # LB tick must repair the violation
            for _ in range(6):
                action = await m.load_balancer.tick()
                if action and "placement" in action:
                    break
                await asyncio.sleep(0.1)
            ent = m.tablets[tid]
            zones = {_zone_of(mc, u) for u in ent["replicas"]}
            assert "z2" in zones, zones
        finally:
            await mc.shutdown()
    asyncio.run(go())


def test_preferred_zone_leader_stepdown(tmp_path):
    async def go():
        mc = await MiniCluster(str(tmp_path), num_tservers=3,
                               zones=["z1", "z2", "z2"]).start()
        try:
            c = mc.client()
            await c.set_placement_info(preferred_zones=["z1"])
            await c.create_table(_info("pt"), num_tablets=1,
                                 replication_factor=3)
            await mc.wait_for_leaders("pt")
            m = mc.master
            tid, ent = next((t, e) for t, e in m.tablets.items()
                            if e["table_id"] ==
                            next(i for i, t2 in m.tables.items()
                                 if t2["info"]["name"] == "pt"))
            # drive ticks until the leader lands in z1
            for _ in range(30):
                await m.load_balancer.tick()
                await asyncio.sleep(0.2)
                # heartbeats refresh leadership reports
                ent = m.tablets[tid]
                if ent.get("leader") and \
                        _zone_of(mc, ent["leader"]) == "z1":
                    break
            assert ent.get("leader") is not None
            assert _zone_of(mc, ent["leader"]) == "z1"
        finally:
            await mc.shutdown()
    asyncio.run(go())


def test_sql_create_tablespace_option(tmp_path):
    async def go():
        mc = await MiniCluster(str(tmp_path), num_tservers=2,
                               zones=["z1", "z2"]).start()
        try:
            c = mc.client()
            await c.create_tablespace(
                "sp", placement=[{"zone": "z2", "min_replicas": 1}])
            s = SqlSession(c)
            await s.execute("CREATE TABLE st (k bigint, v double, "
                            "PRIMARY KEY (k)) WITH tablets = 1 "
                            "WITH tablespace = 'sp'")
            m = mc.master
            tid = next(i for i, t in m.tables.items()
                       if t["info"]["name"] == "st")
            assert m.tables[tid].get("tablespace") == "sp"
            ent = m.tablets[m.tables[tid]["tablets"][0]]
            assert {_zone_of(mc, u) for u in ent["replicas"]} == {"z2"}
        finally:
            await mc.shutdown()
    asyncio.run(go())
