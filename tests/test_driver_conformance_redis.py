"""redis-py conformance against the YEDIS server (skip-if-absent; see
test_driver_conformance.py for the rationale)."""
import pytest

from tests.driver_cluster import ClusterThread

redis = pytest.importorskip("redis", reason="redis-py not installed")


def test_redis_py_basic(tmp_path):
    from yugabyte_db_tpu.ql.redis_server import RedisServer
    with ClusterThread(tmp_path, RedisServer) as ct:
        host, port = ct.addr
        r = redis.Redis(host=host, port=port, socket_timeout=20)
        assert r.ping()
        r.set("k1", "v1")
        assert r.get("k1") == b"v1"
        assert r.incr("cnt") == 1
        assert r.incr("cnt") == 2
        r.hset("h", "f", "x")
        assert r.hget("h", "f") == b"x"
        r.rpush("l", "a", "b")
        assert r.lrange("l", 0, -1) == [b"a", b"b"]
        r.sadd("s", "m1", "m2")
        assert r.sismember("s", "m1")
        assert r.delete("k1") == 1
        assert r.get("k1") is None
