"""redis-py conformance against the YEDIS server (skip-if-absent; see
test_driver_conformance.py for the rationale)."""
import asyncio
import threading

import pytest

from yugabyte_db_tpu.tools.mini_cluster import MiniCluster

redis = pytest.importorskip("redis", reason="redis-py not installed")


def test_redis_py_basic(tmp_path):
    loop = asyncio.new_event_loop()
    state = {}
    ready = threading.Event()

    def run():
        asyncio.set_event_loop(loop)

        async def boot():
            from yugabyte_db_tpu.ql.redis_server import RedisServer
            state["mc"] = await MiniCluster(str(tmp_path),
                                            num_tservers=1).start()
            state["srv"] = RedisServer(state["mc"].client())
            state["addr"] = await state["srv"].start()
            ready.set()
        loop.create_task(boot())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert ready.wait(30)
    try:
        host, port = state["addr"]
        r = redis.Redis(host=host, port=port, socket_timeout=20)
        assert r.ping()
        r.set("k1", "v1")
        assert r.get("k1") == b"v1"
        assert r.incr("cnt") == 1
        assert r.incr("cnt") == 2
        r.hset("h", "f", "x")
        assert r.hget("h", "f") == b"x"
        r.rpush("l", "a", "b")
        assert r.lrange("l", 0, -1) == [b"a", b"b"]
        r.sadd("s", "m1", "m2")
        assert r.sismember("s", "m1")
        assert r.delete("k1") == 1
        assert r.get("k1") is None
    finally:
        async def stop():
            await state["srv"].shutdown()
            await state["mc"].shutdown()
            loop.stop()
        asyncio.run_coroutine_threadsafe(stop(), loop)
        t.join(timeout=10)
