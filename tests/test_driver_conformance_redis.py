"""redis-py conformance against the YEDIS server.

Unlike the psycopg/cassandra suites (skip-if-absent — those drivers
cannot be vendored), this one always runs: when no system redis-py is
installed it falls back to the vendored RESP2 client in
third_party/redispy (an API-compatible subset; see its docstring), so
the external-client tier executes in the default tier-1 run and in
bench.py's driver_conformance accounting."""
import os
import sys

from tests.driver_cluster import ClusterThread

try:
    import redis
except ImportError:                      # vendored fallback
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "third_party", "redispy"))
    import redis


def test_redis_py_basic(tmp_path):
    from yugabyte_db_tpu.ql.redis_server import RedisServer
    with ClusterThread(tmp_path, RedisServer) as ct:
        host, port = ct.addr
        r = redis.Redis(host=host, port=port, socket_timeout=20)
        assert r.ping()
        r.set("k1", "v1")
        assert r.get("k1") == b"v1"
        assert r.incr("cnt") == 1
        assert r.incr("cnt") == 2
        r.hset("h", "f", "x")
        assert r.hget("h", "f") == b"x"
        r.rpush("l", "a", "b")
        assert r.lrange("l", 0, -1) == [b"a", b"b"]
        r.sadd("s", "m1", "m2")
        assert r.sismember("s", "m1")
        assert r.delete("k1") == 1
        assert r.get("k1") is None


def test_redis_py_wider_surface(tmp_path):
    """Exercise the rest of the vendored client's command map against
    the server: string ops, hash maps, list mutation, set cardinality
    — the same breadth tests/test_redis_breadth.py drives over the raw
    wire, here through the driver API."""
    from yugabyte_db_tpu.ql.redis_server import RedisServer
    with ClusterThread(tmp_path, RedisServer) as ct:
        host, port = ct.addr
        r = redis.Redis(host=host, port=port, socket_timeout=20)
        assert r.append("a", "foo") == 3
        assert r.append("a", "bar") == 6
        assert r.strlen("a") == 6
        assert r.exists("a") == 1
        r.hset("h2", mapping={"x": "1", "y": "2"})
        assert r.hgetall("h2") == {b"x": b"1", b"y": b"2"}
        assert r.hdel("h2", "x") == 1
        r.rpush("l2", "a", "b", "c")
        assert r.llen("l2") == 3
        assert r.lpop("l2") == b"a"
        assert r.rpop("l2") == b"c"
        r.sadd("s2", "m1", "m2", "m2")
        assert r.scard("s2") == 2
        assert r.srem("s2", "m1") == 1
        assert not r.sismember("s2", "m1")