"""utils/tasks.py — the shared bpo-37658 cancel-until-done drain.

One ``task.cancel()`` is a request, not a guarantee: a completion
racing the cancel inside ``asyncio.wait_for`` can swallow the
CancelledError and leave the task running after shutdown returned.
``cancel_and_drain`` re-cancels until the task is genuinely done;
these tests pin that contract (including the hostile
swallow-one-cancellation shape) so every converted shutdown site
rests on tested machinery.  The static side of the same contract —
new bare ``.cancel()`` sites are flagged — lives in
``test_analysis.py::TestRefusalFlow``.
"""
import asyncio

import pytest

from yugabyte_db_tpu.utils.tasks import cancel_and_drain, drain_all


def _run(coro):
    return asyncio.run(coro)


class TestCancelAndDrain:
    def test_cancels_a_running_task(self):
        async def go():
            async def forever():
                while True:
                    await asyncio.sleep(3600)
            t = asyncio.get_running_loop().create_task(forever())
            await asyncio.sleep(0)
            got = await cancel_and_drain(t)
            assert got is t and t.done() and t.cancelled()
        _run(go())

    def test_survives_swallowed_cancellation(self):
        # the bpo-37658 shape: the task eats the FIRST CancelledError
        # (a racing completion inside wait_for does exactly this) —
        # the drain must re-cancel rather than hang or return early
        async def go():
            swallowed = 0

            async def stubborn():
                nonlocal swallowed
                while True:
                    try:
                        await asyncio.sleep(3600)
                    except asyncio.CancelledError:
                        if swallowed == 0:
                            swallowed += 1
                            continue          # swallow the first one
                        raise
            t = asyncio.get_running_loop().create_task(stubborn())
            await asyncio.sleep(0)
            await cancel_and_drain(t, wait_timeout=0.01)
            assert t.done() and swallowed == 1
        _run(go())

    def test_none_and_finished_are_noops(self):
        async def go():
            assert await cancel_and_drain(None) is None

            async def quick():
                return 7
            t = asyncio.get_running_loop().create_task(quick())
            await t
            got = await cancel_and_drain(t)
            assert got.result() == 7      # result intact, not cancelled
        _run(go())

    def test_failed_task_exception_is_retrieved(self):
        # no "Task exception was never retrieved" warning at GC
        async def go():
            async def boom():
                raise RuntimeError("x")
            t = asyncio.get_running_loop().create_task(boom())
            await asyncio.sleep(0)
            await cancel_and_drain(t)
            assert t.done() and not t.cancelled()
            assert isinstance(t.exception(), RuntimeError)
        _run(go())


class TestDrainAll:
    def test_drains_everything_including_nones(self):
        async def go():
            async def forever():
                while True:
                    await asyncio.sleep(3600)
            loop = asyncio.get_running_loop()
            tasks = [loop.create_task(forever()) for _ in range(3)]
            await asyncio.sleep(0)
            await drain_all(tasks + [None])
            assert all(t.done() for t in tasks)
        _run(go())


@pytest.mark.parametrize("site", [
    "yugabyte_db_tpu/matview/maintainer.py",
    "yugabyte_db_tpu/master/master.py",
    "yugabyte_db_tpu/tserver/tablet_server.py",
    "yugabyte_db_tpu/consensus/raft.py",
    "yugabyte_db_tpu/sched/scheduler.py",
    "yugabyte_db_tpu/cluster/supervisor.py",
    "yugabyte_db_tpu/cdc/consumer.py",
    "yugabyte_db_tpu/client/client.py",
])
def test_converted_sites_use_the_helper(site):
    """The shutdown paths converted off bare .cancel() stay on the
    shared drain (the analyzer flags NEW bare sites; this pins the
    existing conversions by name)."""
    import os
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(here, site)) as f:
        src = f.read()
    assert "cancel_and_drain" in src or "drain_all" in src, site
