"""Device-vs-CPU compaction golden parity suite.

Every scenario builds IDENTICAL inputs (fixed mock clocks / fixed
hybrid times) in separate tablets and asserts the pipelined chunked
engine's output entry stream is byte-identical to the CPU
DocDbCompactionFeed / baseline path — including the chunk-boundary
cases the pipeline introduces (reference behaviors:
src/yb/docdb/docdb_compaction_context.cc retention + tombstone + replay
dedup; src/yb/rocksdb/db/compaction_job.cc merge loop).
"""
import numpy as np
import pytest

from yugabyte_db_tpu.docdb import ReadRequest, RowOp, WriteRequest
from yugabyte_db_tpu.docdb.compaction import (DocDbCompactionFeed,
                                              LAST_COMPACTION_STATS,
                                              tpu_compact)
from yugabyte_db_tpu.ops.compaction import (KeySuffixError, check_ht_suffix,
                                            kernel_cache_stats)
from yugabyte_db_tpu.tablet import Tablet
from yugabyte_db_tpu.utils import flags
from yugabyte_db_tpu.utils.hybrid_time import (HybridClock, HybridTime,
                                               MockPhysicalClock)
from tests.test_tablet import make_info


def entries_of(tablet):
    return [(k, v) for k, v in tablet.regular.iterate()]


def build_pair(tmp_path, builder):
    """Build two identical tablets via `builder(tablet, clock)`."""
    out = []
    for tag in ("a", "b"):
        clock = HybridClock(MockPhysicalClock(1_000_000))
        t = Tablet(f"par-{tag}", make_info(), str(tmp_path / tag),
                   clock=clock)
        builder(t, clock)
        out.append(t)
    return out


def compact_both_ways(ta, tb, backend="native"):
    """CPU feed on `ta`, chunked engine on `tb`; return both entry
    streams."""
    ta.regular.compact(feed=DocDbCompactionFeed(ta.history_cutoff()))
    got = tpu_compact(tb.regular, tb.codec, tb.history_cutoff(),
                      backend=backend)
    assert got is not None
    return entries_of(ta), entries_of(tb)


class TestGoldenParity:
    def test_tombstone_collapse(self, tmp_path):
        def build(t, clock):
            t.apply_write(WriteRequest("t1", [
                RowOp("upsert", {"k": i, "v": float(i), "s": "x"})
                for i in range(300)]))
            t.flush()
            t.apply_write(WriteRequest("t1", [
                RowOp("delete", {"k": i}) for i in range(0, 300, 3)]))
            t.flush()
            clock._physical.advance_micros(2_000_000_000)
        ta, tb = build_pair(tmp_path, build)
        ref, got = compact_both_ways(ta, tb)
        assert got == ref
        # deleted keys are physically gone
        assert not ta.read(ReadRequest("t1", pk_eq={"k": 0})).rows

    def test_exact_duplicate_replay_drop(self, tmp_path):
        """Raft replay writes the same (key, HT, write_id) twice; exactly
        one copy survives on both paths."""
        def build(t, clock):
            req = WriteRequest("t1", [
                RowOp("upsert", {"k": i, "v": 1.0, "s": "r"})
                for i in range(100)])
            ht = clock.now()
            t.apply_write(req, ht=ht, op_id=(1, 1))
            t.flush()
            t.apply_write(req, ht=ht, op_id=(1, 1))   # replay
            t.flush()
            clock._physical.advance_micros(2_000_000_000)
        ta, tb = build_pair(tmp_path, build)
        ref, got = compact_both_ways(ta, tb)
        assert got == ref
        assert len(got) == 100

    def test_history_cutoff_boundary_versions(self, tmp_path):
        """Versions on each side of the cutoff: newest <= cutoff
        survives, older history is dropped, > cutoff all survive."""
        def build(t, clock):
            for ver in range(4):
                t.apply_write(WriteRequest("t1", [
                    RowOp("upsert", {"k": i, "v": float(ver), "s": "v"})
                    for i in range(50)]))
                t.flush()
                clock._physical.advance_micros(400_000_000)
            # two more versions INSIDE the retention window
            for ver in (10, 11):
                t.apply_write(WriteRequest("t1", [
                    RowOp("upsert", {"k": i, "v": float(ver), "s": "w"})
                    for i in range(0, 50, 2)]))
                t.flush()
        ta, tb = build_pair(tmp_path, build)
        cutoff = ta.history_cutoff()
        assert cutoff > 0
        ref, got = compact_both_ways(ta, tb)
        assert got == ref

    def test_ttl_expiry_fallback(self, tmp_path):
        """TTL'd rows never get columnar sidecars, so the chunked engine
        must defer to the row/feed fallback — and still GC expired
        rows."""
        def build(t, clock):
            t.apply_write(WriteRequest("t1", [
                RowOp("upsert", {"k": 1, "v": 1.0, "s": "dead"},
                      ttl_ms=1000),
                RowOp("upsert", {"k": 2, "v": 2.0, "s": "keep"})]))
            t.flush()
            clock._physical.advance_micros(3_000_000_000)
            t.apply_write(WriteRequest("t1", [
                RowOp("upsert", {"k": 3, "v": 3.0, "s": "live"},
                      ttl_ms=10_000_000_000)]))
            t.flush()
        for backend in ("device", "native"):
            ta, tb = build_pair(tmp_path / backend, build)
            ref, got = compact_both_ways(ta, tb, backend=backend)
            assert got == ref
            keys = sorted(r["k"] for r in
                          tb.read(ReadRequest("t1", columns=("k",))).rows)
            assert keys == [2, 3]

    def test_mixed_key_widths_fallback(self, tmp_path):
        """Varlen doc keys of different widths are ineligible for the
        chunked engine; the fallback still produces feed-identical
        output."""
        from yugabyte_db_tpu.dockv.packed_row import (ColumnSchema,
                                                      ColumnType,
                                                      TableSchema)
        from yugabyte_db_tpu.dockv.partition import PartitionSchema
        from yugabyte_db_tpu.docdb.table_codec import TableInfo

        info = TableInfo("t2", "t2", TableSchema(columns=(
            ColumnSchema(0, "k", ColumnType.STRING, is_hash_key=True),
            ColumnSchema(1, "v", ColumnType.FLOAT64),
        ), version=1), PartitionSchema("hash", 1))

        def build(t, clock):
            t.apply_write(WriteRequest("t2", [
                RowOp("upsert", {"k": "a" * (1 + i % 7), "v": float(i)})
                for i in range(40)]))
            t.flush()
            t.apply_write(WriteRequest("t2", [
                RowOp("upsert", {"k": "z" * (1 + i % 5), "v": -float(i)})
                for i in range(40)]))
            t.flush()
            clock._physical.advance_micros(2_000_000_000)

        out = []
        for tag in ("a", "b"):
            clock = HybridClock(MockPhysicalClock(1_000_000))
            t = Tablet(f"mix-{tag}", info, str(tmp_path / tag),
                       clock=clock)
            build(t, clock)
            out.append(t)
        ta, tb = out
        ref, got = compact_both_ways(ta, tb)
        assert got == ref

    def test_chunk_straddling_doc_key(self, tmp_path):
        """All versions of one doc key straddle two chunks: the MVCC
        carry must keep retention decisions exact across the
        boundary."""
        def build(t, clock):
            # many versions of FEW keys so one key's version run spans a
            # whole chunk boundary, plus history beyond the cutoff
            for ver in range(8):
                t.apply_write(WriteRequest("t1", [
                    RowOp("upsert", {"k": i, "v": float(ver), "s": "s"})
                    for i in range(700)]))
                t.flush()
                if ver == 3:
                    clock._physical.advance_micros(2_000_000_000)
        ta, tb = build_pair(tmp_path, build)
        flags.set_flag("compaction_chunk_rows", 4096)
        try:
            ta.regular.compact(
                feed=DocDbCompactionFeed(ta.history_cutoff()))
            tpu_compact(tb.regular, tb.codec, tb.history_cutoff(),
                        block_rows=1024, backend="native")
        finally:
            flags.REGISTRY.reset("compaction_chunk_rows")
        assert LAST_COMPACTION_STATS["chunks"] > 1
        assert entries_of(tb) == entries_of(ta)

    def test_chunk_straddling_device_kernel(self, tmp_path):
        """Same straddle scenario through the device merge kernel (the
        carry terms live in chunk_merge_kernel itself)."""
        def build(t, clock):
            for ver in range(8):
                t.apply_write(WriteRequest("t1", [
                    RowOp("upsert", {"k": i, "v": float(ver), "s": "s"})
                    for i in range(700)]))
                t.flush()
                if ver == 3:
                    clock._physical.advance_micros(2_000_000_000)
        ta, tb = build_pair(tmp_path, build)
        flags.set_flag("compaction_chunk_rows", 4096)
        try:
            ta.regular.compact(
                feed=DocDbCompactionFeed(ta.history_cutoff()))
            tpu_compact(tb.regular, tb.codec, tb.history_cutoff(),
                        block_rows=1024, backend="device")
        finally:
            flags.REGISTRY.reset("compaction_chunk_rows")
        assert LAST_COMPACTION_STATS["chunks"] > 1
        assert entries_of(tb) == entries_of(ta)


class TestV2FormatGoldenParity:
    """v2 on-disk format round-trips through the compaction engines:
    whatever mix of block formats feeds the merge, the output entry
    stream must stay byte-identical to the CPU feed over v1 inputs."""

    def _set(self, v):
        flags.set_flag("sst_format_version", v)

    def _reset(self):
        flags.REGISTRY.reset("sst_format_version")

    def build(self, t, clock):
        t.apply_write(WriteRequest("t1", [
            RowOp("upsert", {"k": i, "v": float(i) * 1.7, "s": f"s{i%5}"})
            for i in range(400)]))
        t.flush()
        t.apply_write(WriteRequest("t1", [
            RowOp("delete", {"k": i}) for i in range(0, 400, 5)]))
        t.flush()
        clock._physical.advance_micros(2_000_000_000)

    def test_v1_written_v2_compacted(self, tmp_path):
        """Inputs written v1, compaction writes v2: parity + the output
        actually moved to v2."""
        try:
            self._set(1)
            ta, tb = build_pair(tmp_path, self.build)
            self._set(2)
            ref, got = compact_both_ways(ta, tb)
            assert got == ref
            assert tb.regular.ssts[0].format_version == 2
        finally:
            self._reset()

    def test_v2_written_v1_compacted(self, tmp_path):
        """Inputs written v2 (keyless blocks), compaction pinned back to
        v1: the derived keys must rebuild exactly for the merge AND the
        output demotes cleanly."""
        try:
            self._set(2)
            ta, tb = build_pair(tmp_path, self.build)
            self._set(1)
            ref, got = compact_both_ways(ta, tb)
            assert got == ref
            assert tb.regular.ssts[0].format_version == 1
        finally:
            self._reset()

    def test_mixed_version_inputs(self, tmp_path):
        """One tablet holding v1 AND v2 SSTs compacts to the same
        stream as an all-v1 twin."""
        def mixed_build(t, clock):
            self._set(1)
            t.apply_write(WriteRequest("t1", [
                RowOp("upsert", {"k": i, "v": 1.0, "s": "a"})
                for i in range(300)]))
            t.flush()
            self._set(2)
            t.apply_write(WriteRequest("t1", [
                RowOp("upsert", {"k": i, "v": 2.0, "s": "b"})
                for i in range(150, 450)]))
            t.flush()
            clock._physical.advance_micros(2_000_000_000)

        def v1_build(t, clock):
            self._set(1)
            t.apply_write(WriteRequest("t1", [
                RowOp("upsert", {"k": i, "v": 1.0, "s": "a"})
                for i in range(300)]))
            t.flush()
            t.apply_write(WriteRequest("t1", [
                RowOp("upsert", {"k": i, "v": 2.0, "s": "b"})
                for i in range(150, 450)]))
            t.flush()
            clock._physical.advance_micros(2_000_000_000)

        try:
            clock = HybridClock(MockPhysicalClock(1_000_000))
            tm = Tablet("mix-par", make_info(), str(tmp_path / "mix"),
                        clock=clock)
            mixed_build(tm, clock)
            assert {r.format_version for r in tm.regular.ssts} == {1, 2}
            clock2 = HybridClock(MockPhysicalClock(1_000_000))
            tv = Tablet("v1-par", make_info(), str(tmp_path / "v1"),
                        clock=clock2)
            v1_build(tv, clock2)
            self._set(2)
            tv.regular.compact(
                feed=DocDbCompactionFeed(tv.history_cutoff()))
            got = tpu_compact(tm.regular, tm.codec, tm.history_cutoff(),
                              backend="native")
            assert got is not None
            assert entries_of(tm) == entries_of(tv)
        finally:
            self._reset()

    def test_incompressible_lanes_fall_back_raw(self, tmp_path):
        """Random f64 values defeat every encoding; encode-only-if-
        smaller must keep them raw with zero size growth and full
        parity."""
        rng = np.random.default_rng(9)
        vals = rng.random(500) * 1e6

        def build(t, clock):
            t.apply_write(WriteRequest("t1", [
                RowOp("upsert", {"k": i, "v": float(vals[i]), "s": "x"})
                for i in range(500)]))
            t.flush()
            clock._physical.advance_micros(2_000_000_000)

        try:
            self._set(2)
            ta, tb = build_pair(tmp_path, build)
            ref, got = compact_both_ways(ta, tb)
            assert got == ref
            lanes = LAST_COMPACTION_STATS["lanes"]
            fv = lanes["fixed_vals"]
            # the v column stayed raw; size never exceeds the v1 dump
            assert fv["post_bytes"] <= fv["pre_bytes"]
            assert fv["encodings"].get("raw", 0) >= 1
        finally:
            self._reset()


class TestCorruptSuffixDegrade:
    def test_check_ht_suffix_raises_structured(self):
        bad = np.zeros((4, 20), np.uint8)       # no kHybridTime marker
        with pytest.raises(KeySuffixError) as ei:
            check_ht_suffix(bad)
        assert ei.value.n_bad == 4 and ei.value.n_total == 4

    def test_split_ht_suffix_raises_under_O(self):
        """The marker check is a real raise, not an assert — it must
        survive `python -O` (asserts stripped)."""
        from yugabyte_db_tpu.ops.compaction import split_ht_suffix
        bad = np.zeros((2, 20), np.uint8)
        with pytest.raises(KeySuffixError):
            split_ht_suffix(bad)

    def test_tpu_compact_degrades_to_feed(self, tmp_path):
        """A corrupt keys matrix degrades tpu_compact to the CPU feed
        instead of crashing; output matches the pure-feed result."""
        clock = HybridClock(MockPhysicalClock(1_000_000))
        t = Tablet("corrupt", make_info(), str(tmp_path), clock=clock)
        t.apply_write(WriteRequest("t1", [
            RowOp("upsert", {"k": i, "v": float(i), "s": "x"})
            for i in range(200)]))
        t.flush()
        clock._physical.advance_micros(2_000_000_000)
        # zero-copy reads are views of the immutable file, so corruption
        # is injected via a patched reader
        import yugabyte_db_tpu.storage.sst as sst_mod
        orig = sst_mod.SstReader.read_columnar
        def corrupt_read(self, i):
            blk = orig(self, i)
            if blk is not None and blk.keys is not None:
                k = blk.keys.copy()
                k[:, -13] = 0
                blk.keys = k
            return blk
        sst_mod.SstReader.read_columnar = corrupt_read
        try:
            path = tpu_compact(t.regular, t.codec, t.history_cutoff(),
                               backend="native")
        finally:
            sst_mod.SstReader.read_columnar = orig
        assert path is not None
        assert len(entries_of(t)) == 200


class TestKernelCache:
    def test_same_shape_second_compaction_compiles_nothing(self, tmp_path):
        def make(tag):
            clock = HybridClock(MockPhysicalClock(1_000_000))
            t = Tablet(f"kc-{tag}", make_info(), str(tmp_path / tag),
                       clock=clock)
            for _ in range(3):
                t.apply_write(WriteRequest("t1", [
                    RowOp("upsert", {"k": i, "v": 1.0, "s": "k"})
                    for i in range(500)]))
                t.flush()
            clock._physical.advance_micros(2_000_000_000)
            return t
        t1 = make("one")
        tpu_compact(t1.regular, t1.codec, t1.history_cutoff(),
                    backend="device")
        first = LAST_COMPACTION_STATS["kernel_compiles"]
        t2 = make("two")
        tpu_compact(t2.regular, t2.codec, t2.history_cutoff(),
                    backend="device")
        second = LAST_COMPACTION_STATS["kernel_compiles"]
        assert first <= 3
        assert second == 0
        assert LAST_COMPACTION_STATS["kernel_cache_hits"] >= 1


@pytest.mark.slow
class TestLargeParity:
    def test_large_multi_sst_parity(self, tmp_path):
        """100-SST-shaped parity at reduced scale (slow tier): bulk
        loads with overlapping re-written keys, byte-identical output
        across baseline and both chunked backends."""
        from yugabyte_db_tpu.models.tpch import (LineitemTable,
                                                 generate_lineitem)
        data = generate_lineitem(0.05)
        n = len(data["rowid"])
        outs = {}
        for mode in ("baseline", "native", "device"):
            t = LineitemTable(str(tmp_path / mode),
                              num_tablets=1).tablets[0]
            for i in range(20):
                fresh = (i * 10000) % max(n - 10000, 1)
                sel = np.arange(fresh, fresh + 10000) % n
                if i > 0:
                    prev = (sel - 2500) % n
                    sel[:2500] = prev[:2500]
                batch = {k: v[sel] for k, v in data.items()}
                t.bulk_load(batch,
                            ht=HybridTime.from_micros(
                                1_700_000_000_000_000 + i * 1000),
                            block_rows=8192)
            tpu_compact(t.regular, t.codec,
                        1_700_000_000_005_000 << 12, backend=mode)
            outs[mode] = entries_of(t)
        assert outs["native"] == outs["baseline"]
        assert outs["device"] == outs["baseline"]
