"""tools/analyze/ wired into tier-1.

Three layers:

1. PASS FIXTURES — for each of the five passes: a true positive the
   pass must catch, the same hazard suppressed with a reasoned
   annotation, and a clean negative that must NOT fire (the negatives
   encode the idioms the real tree depends on — `.shape` math inside
   jit bodies, executor-target sync defs, async-with on asyncio locks).
2. WHOLE-TREE — the real `yugabyte_db_tpu/` must produce ZERO
   unannotated findings, so any new hazard is a failing build from the
   day the pass shipped.
3. CONTRACTS — the run.py --json schema (pass ids, counts, findings,
   suppression tally, per-pass wall time), the suppression-vs-baseline
   tally bench.py WARNs on, and the wall-time budget that keeps the
   sweep from bloating the tier-1 timeout.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(HERE, "tools"))
from analyze import (ALL_PASSES, ProjectIndex, get_pass,  # noqa: E402
                     run_analysis)

#: generous ceiling for the whole five-pass sweep over the full tree —
#: the sweep measures ~2-6s here; the budget exists so a pass that goes
#: accidentally quadratic fails tier-1 instead of eating the 870s cap.
WALL_BUDGET_MS = 60_000


def _run(tmp_path, files, pass_id):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    index = ProjectIndex(str(tmp_path), roots=("pkg",))
    return run_analysis(index, [get_pass(pass_id)])


def _findings(report):
    return [(f["path"], f["line"], f["detail"]) for f in report["findings"]]


# --- 1. per-pass fixtures --------------------------------------------------

class TestAsyncBlocking:
    def test_true_positive(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import time, os, subprocess
            async def handler():
                time.sleep(1)
                os.fsync(3)
                subprocess.run(["ls"])
            """}, "async_blocking")
        assert sorted(d for _, _, d in _findings(r)) == [
            "os.fsync", "subprocess.run", "time.sleep"]

    def test_suppressed_with_reason(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import time
            async def handler():
                time.sleep(1)   # analysis-ok(async_blocking): test stall
                time.sleep(2)   # blocking-ok: legacy alias honored
            """}, "async_blocking")
        assert r["findings"] == []
        assert r["suppressions"]["async_blocking"] == 2

    def test_bare_marker_suppresses_nothing(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import time
            async def handler():
                time.sleep(1)   # analysis-ok(async_blocking):
            """}, "async_blocking")
        assert len(r["findings"]) == 1   # reason is mandatory

    def test_clean_negative(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import asyncio, time
            def sync_helper():
                time.sleep(1)            # sync context: fine
            async def handler():
                await asyncio.sleep(1)   # the correct spelling
                def executor_target():
                    time.sleep(1)        # nested sync def: fine
                return executor_target
            """}, "async_blocking")
        assert r["findings"] == []


class TestLockHeldAwait:
    def test_true_positive(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                async def work(self):
                    with self._lock:
                        await self.other()
            """}, "lock_held_await")
        assert [(l, d) for _, l, d in _findings(r)] == [(7, "self._lock")]

    def test_suppressed_with_reason(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            class C:
                async def work(self):
                    with self._lock:
                        # analysis-ok(lock_held_await): lock-free await
                        await self.other()
            """}, "lock_held_await")
        assert r["findings"] == []
        assert r["suppressions"]["lock_held_await"] == 1

    def test_clean_negative(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            class C:
                async def work(self):
                    async with self._alock:     # asyncio lock: fine
                        await self.other()
                    with self._lock:
                        self.x = 1              # no await held: fine
                    with self._lock:
                        def helper():           # nested def: its own
                            pass                # awaits, its own locks
                    await self.other()
            """}, "lock_held_await")
        assert r["findings"] == []


class TestJitHazards:
    def test_true_positives(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import jax, numpy as np
            import jax.numpy as jnp
            from functools import partial
            @partial(jax.jit, static_argnames=("k",))
            def kern(x, y, k):
                if y > 0:                 # python branch on traced
                    x = x + 1
                v = float(x)              # host cast
                w = np.asarray(y)         # host numpy mid-trace
                s = x.sum().item()        # host sync
                return x + k
            def driver(a):
                return kern(jnp.zeros(50000), a, k=4)   # literal shape
            """}, "jit_hazards")
        details = sorted(d for _, _, d in _findings(r))
        assert details == ["kern:float", "kern:if", "kern:item",
                           "kern:jnp.zeros", "kern:np.asarray"]

    def test_suppressed_with_reason(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import jax
            @jax.jit
            def kern(x):
                if x > 0:  # analysis-ok(jit_hazards): proven static
                    return x
                return -x
            """}, "jit_hazards")
        assert r["findings"] == []
        assert r["suppressions"]["jit_hazards"] == 1

    def test_clean_negative_shape_math_untaints(self, tmp_path):
        # the exact idiom ops/compaction.py + vector/ivf.py live on:
        # .shape unpacking yields static python ints, branches and
        # range() over them are fine, as is jax.jit-by-assignment
        r = _run(tmp_path, {"pkg/a.py": """\
            import jax
            import jax.numpy as jnp
            from functools import partial
            @partial(jax.jit, static_argnames=("num_words",))
            def kern(words, ht, num_words):
                n = words.shape[0]
                ops = tuple(words[:, i] for i in range(1, num_words))
                if num_words > 2:            # static arg: fine
                    ht = ht + 1
                first = jnp.where(ht > 0, words[:, 0], jnp.uint64(0))
                c = min(num_words, n)        # static math: fine
                return first, ops, c
            def _raw(x):
                m = x.shape[1]
                return x.reshape(x.shape[0] * m)
            fn = jax.jit(_raw)
            def debug_path():
                # direct raw call runs EAGERLY — no compile, no trap
                return _raw(jnp.zeros((4, 500)))
            class Unrelated:
                def kern(self):          # leaf-name collision: fine
                    return jnp.ones(128)
            """}, "jit_hazards")
        assert r["findings"] == []


class TestFlagDrift:
    FILES = {
        "pkg/flags.py": """\
            def DEFINE_RUNTIME(name, default, help=""):
                pass
            DEFINE_RUNTIME("used_flag", 7, "wired below")
            DEFINE_RUNTIME("dead_flag", 1, "nobody reads this")
            DEFINE_RUNTIME("sched_point_read_depth", 512, "dynamic read")
            DEFINE_RUNTIME("doc_flag", 4, "defaults to 9")
            DEFINE_RUNTIME("doc_flag2", 4, "window size (default: 3)")
            DEFINE_RUNTIME("doc_flag3", 4, "uses the default backend")
            """,
        "pkg/user.py": """\
            from . import flags
            def f(lane):
                a = flags.get("used_flag")
                b = flags.get(f"sched_{lane}_depth")
                c = flags.get("missing_flag")
                return a, b, c
            """,
    }

    def test_true_positives(self, tmp_path):
        r = _run(tmp_path, dict(self.FILES), "flag_drift")
        got = {(p, d) for p, _, d in _findings(r)}
        assert ("pkg/flags.py", "dead_flag") in got       # never read
        assert ("pkg/user.py", "missing_flag") in got     # never defined
        assert ("pkg/flags.py", "doc_flag") in got        # help disagrees
        assert ("pkg/flags.py", "doc_flag2") in got       # "(default: 3)"
        # prose "the default backend" is not a value claim
        assert not any(d == "doc_flag3" and "documents default" in
                       f["message"] for f, (_, _, d) in
                       zip(r["findings"], _findings(r)))
        # dynamic f-string read covers the sched_*_depth flag
        assert not any(d == "sched_point_read_depth" for _, _, d in
                       _findings(r))

    def test_duplicate_default_drift(self, tmp_path):
        files = dict(self.FILES)
        files["pkg/extra.py"] = """\
            from .flags import DEFINE_RUNTIME
            DEFINE_RUNTIME("used_flag", 8, "second default loses")
            """
        r = _run(tmp_path, files, "flag_drift")
        assert any(d == "used_flag" and "re-defined" in m for (_, _, d), m
                   in zip(_findings(r),
                          [f["message"] for f in r["findings"]]))

    def test_suppressed_with_reason(self, tmp_path):
        files = dict(self.FILES)
        files["pkg/flags.py"] = files["pkg/flags.py"].replace(
            'DEFINE_RUNTIME("dead_flag", 1, "nobody reads this")',
            'DEFINE_RUNTIME("dead_flag", 1, "r")  '
            '# analysis-ok(flag_drift): reserved for r07')
        r = _run(tmp_path, files, "flag_drift")
        assert not any(d == "dead_flag" for _, _, d in _findings(r))
        assert r["suppressions"]["flag_drift"] == 1

    def test_clean_negative(self, tmp_path):
        r = _run(tmp_path, {
            "pkg/flags.py": """\
                def DEFINE_RUNTIME(name, default, help=""):
                    pass
                DEFINE_RUNTIME("wired", True, "read next door")
                """,
            "pkg/user.py": """\
                from . import flags
                def f():
                    data = {}
                    data.get("not_a_flag")    # dict get: out of scope
                    return flags.get("wired")
                """}, "flag_drift")
        assert r["findings"] == []


class TestSharedStateRaces:
    def test_true_positive(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import asyncio
            class Srv:
                def flush(self):
                    self.stats["flushes"] = 1      # thread side
                async def handler(self):
                    self.stats["reads"] = 2        # loop side
                async def kick(self):
                    loop = asyncio.get_running_loop()
                    await loop.run_in_executor(None, self.flush)
            """}, "shared_state_races")
        assert [d for _, _, d in _findings(r)] == ["Srv.stats"]

    def test_executor_lambda_counts_as_thread_side(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import asyncio
            class Srv:
                async def handler(self):
                    self.stats["reads"] = 2          # loop side
                async def kick(self):
                    loop = asyncio.get_running_loop()
                    await loop.run_in_executor(
                        None, lambda: self.stats.update(x=1))
            """}, "shared_state_races")
        assert [d for _, _, d in _findings(r)] == ["Srv.stats"]

    def test_suppressed_with_reason(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import asyncio
            class Srv:
                def flush(self):
                    # analysis-ok(shared_state_races): torn-read-safe
                    self.n = 1
                async def handler(self):
                    self.n = 2
                async def kick(self):
                    loop = asyncio.get_running_loop()
                    await loop.run_in_executor(None, self.flush)
            """}, "shared_state_races")
        assert r["findings"] == []
        assert r["suppressions"]["shared_state_races"] == 1

    def test_clean_negative_locked_both_sides(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import asyncio, threading
            class Srv:
                def flush(self):
                    with self._lock:
                        self.stats["flushes"] = 1
                async def handler(self):
                    with self._lock:
                        self.stats["reads"] = 2
                async def kick(self):
                    loop = asyncio.get_running_loop()
                    await loop.run_in_executor(None, self.flush)
            class NotATarget:
                def helper(self):
                    self.x = 1      # never shipped to an executor
                async def h(self):
                    self.x = 2
            """}, "shared_state_races")
        assert r["findings"] == []


class TestUnawaitedCoroutine:
    def test_true_positives(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import asyncio
            async def refresh():
                pass
            class Srv:
                async def _flush(self):
                    pass
                async def handler(self):
                    self._flush()                    # coroutine dropped
                    refresh()                        # module-level coro
                    asyncio.gather(self._flush())    # builtin awaitable
                    asyncio.create_task(self._flush())   # F&F task
                    asyncio.ensure_future(refresh())     # F&F task
            """}, "unawaited_coroutine")
        details = sorted(d for _, _, d in _findings(r))
        assert details == ["asyncio.create_task", "asyncio.ensure_future",
                           "asyncio.gather", "refresh", "self._flush"]

    def test_suppressed_with_reason(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import asyncio
            class Srv:
                async def _bg(self):
                    pass
                async def go(self):
                    # analysis-ok(unawaited_coroutine): supervised set
                    asyncio.create_task(self._bg())
            """}, "unawaited_coroutine")
        assert r["findings"] == []
        assert r["suppressions"]["unawaited_coroutine"] == 1

    def test_clean_negatives(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import asyncio
            def close():                   # sync twin un-flags the name
                pass
            class Srv:
                async def _bg(self):
                    pass
                async def close(self):
                    pass
                async def run(self):
                    await self._bg()                    # awaited: fine
                    t = asyncio.create_task(self._bg())     # handle kept
                    self.tasks.append(
                        asyncio.create_task(self._bg()))    # stored
                    asyncio.create_task(
                        self._bg()).add_done_callback(print)  # chained
                    await t
                    close()            # sync/async collision: not ours
                    self.writer.write(b"x")   # non-self receiver: the
                                              # stdlib sync write shape
            """}, "unawaited_coroutine")
        assert [d for _, _, d in _findings(r)
                if d not in ("asyncio.create_task",)] == []
        # the kept/stored/chained create_task forms must NOT fire either
        assert r["findings"] == []

    def test_taskgroup_spawn_not_flagged(self, tmp_path):
        # TaskGroup holds strong refs + propagates exceptions: its
        # discarded create_task handle is the documented safe pattern
        r = _run(tmp_path, {"pkg/a.py": """\
            import asyncio
            class Srv:
                async def _bg(self):
                    pass
                async def run(self):
                    async with asyncio.TaskGroup() as tg:
                        tg.create_task(self._bg())       # fine
                    loop = asyncio.get_running_loop()
                    loop.create_task(self._bg())         # weak set: bug
                    asyncio.get_running_loop().create_task(
                        self._bg())                      # weak set: bug
            """}, "unawaited_coroutine")
        assert sorted(d for _, _, d in _findings(r)) == \
            ["create_task", "loop.create_task"]

    def test_nested_class_rescopes(self, tmp_path):
        # a class nested inside a method must NOT inherit the outer
        # class's async-method set (its sync self.flush() is fine) —
        # and a dropped coroutine inside an except block IS caught
        r = _run(tmp_path, {"pkg/a.py": """\
            class Outer:
                async def flush(self):
                    pass
                def make(self):
                    class Inner:
                        def flush(self):
                            pass
                        def go(self):
                            self.flush()        # sync: fine
                    return Inner
                async def run(self):
                    try:
                        await self.flush()
                    except Exception:
                        self.flush()            # dropped coroutine
            """}, "unawaited_coroutine")
        assert [(l, d) for _, l, d in _findings(r)] == \
            [(15, "self.flush")]


class TestFormatGate:
    FILES = {
        "pkg/writer.py": """\
            from .sstlib import SstWriter
            def dump(path, cb):
                w = SstWriter(path, format_version=2)
                head, bufs = cb.serialize_parts(version=2)
                return w, head, bufs
            """,
        "pkg/sstlib.py": """\
            class SstWriter:
                def __init__(self, path, format_version=None):
                    self.path = path
            """,
    }

    def test_true_positives(self, tmp_path):
        r = _run(tmp_path, dict(self.FILES), "format_gate")
        got = {(p, d) for p, _, d in _findings(r)}
        assert ("pkg/writer.py", "format_version=2") in got
        assert ("pkg/writer.py", "version=2") in got

    def test_generic_version_kwarg_not_flagged(self, tmp_path):
        """`version=2` on non-serializer callees (schema versions etc.)
        is unrelated to the on-disk format and must not fire."""
        files = dict(self.FILES)
        files["pkg/writer.py"] = """\
            def make():
                return TableSchema(columns=(), version=2)
            """
        r = _run(tmp_path, files, "format_gate")
        assert _findings(r) == []

    def test_pinning_v1_allowed(self, tmp_path):
        files = dict(self.FILES)
        files["pkg/writer.py"] = """\
            from .sstlib import SstWriter
            def dump(path, cb, fmt):
                w = SstWriter(path, format_version=1)   # baseline pin
                return cb.serialize_parts(version=fmt)  # flag-resolved
            """
        r = _run(tmp_path, files, "format_gate")
        assert _findings(r) == []

    def test_suppressed_with_reason(self, tmp_path):
        files = dict(self.FILES)
        files["pkg/writer.py"] = """\
            from .sstlib import SstWriter
            def dump(path, cb):
                # analysis-ok(format_gate): migration tool writes v2 on purpose
                w = SstWriter(path, format_version=2)
                return w
            """
        r = _run(tmp_path, files, "format_gate")
        assert _findings(r) == []
        assert r["suppressions"]["format_gate"] == 1


class TestLayering:
    """bypass/ must not import tserver/sched/rpc — the subsystem's
    isolation guarantee as a tier-1 fact."""

    def _run_scoped(self, tmp_path, files):
        import textwrap as _tw
        for rel, src in files.items():
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(_tw.dedent(src))
        index = ProjectIndex(str(tmp_path),
                             roots=("yugabyte_db_tpu",))
        return run_analysis(index, [get_pass("layering")])

    def test_true_positives(self, tmp_path):
        r = self._run_scoped(tmp_path, {
            "yugabyte_db_tpu/bypass/bad.py": """\
                import yugabyte_db_tpu.tserver.tablet_server
                from yugabyte_db_tpu.rpc import messenger
                from ..sched.lanes import Lane
                from .. import rpc
                def f():
                    from ..tserver import tablet_server
                    return tablet_server
                """})
        layers = sorted(d.split(":")[0] for _, _, d in _findings(r))
        assert layers == ["rpc", "rpc", "sched", "tserver", "tserver"]

    def test_suppressed_with_reason(self, tmp_path):
        r = self._run_scoped(tmp_path, {
            "yugabyte_db_tpu/bypass/bad.py": """\
                from ..rpc import messenger  # analysis-ok(layering): fixture
                """})
        assert r["findings"] == []
        assert r["suppressions"]["layering"] == 1

    def test_clean_negatives(self, tmp_path):
        """Allowed seams (storage/ops/parallel/docdb), sibling-package
        imports of the same names, and other layers importing tserver
        must not fire."""
        r = self._run_scoped(tmp_path, {
            "yugabyte_db_tpu/bypass/ok.py": """\
                from ..storage.lsm import LsmStore
                from ..ops import stream_scan
                from ..parallel.distributed_scan import ShardedBatch
                from ..docdb.operations import ReadResponse
                from .errors import BypassIneligible
                import numpy.rpc_like as rpcx    # not our layer
                """,
            "yugabyte_db_tpu/client/uses_rpc.py": """\
                from ..rpc.messenger import Messenger
                from ..tserver import tablet_server
                """})
        assert _findings(r) == []


# --- 2 + 3. whole tree, schema, budget, baseline ---------------------------

@pytest.fixture(scope="module")
def tree_report():
    index = ProjectIndex(HERE)
    return run_analysis(index, ALL_PASSES)


def test_whole_tree_zero_unannotated_findings(tree_report):
    assert tree_report["parse_errors"] == [], tree_report["parse_errors"]
    assert tree_report["findings"] == [], (
        "unannotated static-analysis findings — fix them or annotate "
        "with `# analysis-ok(<pass>): <reason>`:\n" + "\n".join(
            f"{f['path']}:{f['line']}: [{f['pass']}] {f['message']}"
            for f in tree_report["findings"]))


def test_all_passes_ran(tree_report):
    assert [p["id"] for p in tree_report["passes"]] == [
        "async_blocking", "lock_held_await", "jit_hazards",
        "flag_drift", "shared_state_races", "unawaited_coroutine",
        "format_gate", "layering"]


def test_wall_time_budget(tree_report):
    # r05 carry-over hygiene: the sweep must not bloat tier-1
    assert tree_report["wall_ms"] < WALL_BUDGET_MS, tree_report["passes"]
    for p in tree_report["passes"]:
        assert p["wall_ms"] >= 0.0


def test_suppressions_do_not_exceed_baseline(tree_report):
    with open(os.path.join(HERE, "tools", "analyze",
                           "baseline.json")) as f:
        baseline = json.load(f)["suppressions"]
    for pass_id, n in tree_report["suppressions"].items():
        assert n <= baseline.get(pass_id, 0), (
            f"suppression count for {pass_id} grew to {n} vs committed "
            f"baseline {baseline.get(pass_id, 0)} — fix the hazard or "
            f"bump tools/analyze/baseline.json deliberately")


def test_run_py_json_schema():
    r = subprocess.run(
        [sys.executable, os.path.join(HERE, "tools", "analyze", "run.py"),
         "--json"], capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    report = json.loads(r.stdout)
    for key in ("passes", "findings", "suppressions", "total_findings",
                "total_suppressed", "wall_ms", "parse_errors"):
        assert key in report, key
    assert report["total_findings"] == 0
    assert set(report["suppressions"]) == {p.id for p in ALL_PASSES}
    for p in report["passes"]:
        assert {"id", "title", "findings", "suppressed",
                "wall_ms"} <= set(p)


def test_run_py_exits_nonzero_on_findings(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "bad.py").write_text(
        "import time\nasync def h():\n    time.sleep(1)\n")
    r = subprocess.run(
        [sys.executable, os.path.join(HERE, "tools", "analyze", "run.py"),
         "--base", str(tmp_path), "--pass", "async_blocking", "pkg"],
        capture_output=True, text=True)
    assert r.returncode == 1, r.stdout
    assert "time.sleep" in r.stdout
