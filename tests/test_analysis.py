"""tools/analyze/ wired into tier-1.

Four layers:

1. PASS FIXTURES — for each pass: a true positive the pass must
   catch, the same hazard suppressed with a reasoned annotation, and a
   clean negative that must NOT fire (the negatives encode the idioms
   the real tree depends on — `.shape` math inside jit bodies,
   executor-target sync defs, async-with on asyncio locks).  The
   interprocedural passes add a TRANSITIVE triple each (hazard behind
   a helper), plus the pre-fix product shapes the engine was built to
   catch (master._persist's fsync under an async commit).
2. CALL GRAPH — the shared interprocedural layer's own contract:
   alias chains, method resolution across (multi-module) inheritance,
   recursion termination, and the persisted facts-cache speedup.
3. WHOLE-TREE — the real `yugabyte_db_tpu/` must produce ZERO
   unannotated findings, so any new hazard is a failing build from the
   day the pass shipped.
4. CONTRACTS — the run.py --json schema (pass ids, counts, findings,
   suppression tally, per-pass wall time), the --changed incremental
   mode, the suppression-vs-baseline tally bench.py WARNs on, and the
   wall-time budget that keeps the sweep from bloating tier-1.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(HERE, "tools"))
from analyze import (ALL_PASSES, ProjectIndex, get_pass,  # noqa: E402
                     run_analysis)

#: generous ceiling for the whole five-pass sweep over the full tree —
#: the sweep measures ~2-6s here; the budget exists so a pass that goes
#: accidentally quadratic fails tier-1 instead of eating the 870s cap.
WALL_BUDGET_MS = 60_000


def _run(tmp_path, files, pass_id):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    index = ProjectIndex(str(tmp_path), roots=("pkg",))
    return run_analysis(index, [get_pass(pass_id)])


def _findings(report):
    return [(f["path"], f["line"], f["detail"]) for f in report["findings"]]


# --- 1. per-pass fixtures --------------------------------------------------

class TestAsyncBlocking:
    def test_true_positive(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import time, os, subprocess
            async def handler():
                time.sleep(1)
                os.fsync(3)
                subprocess.run(["ls"])
            """}, "async_blocking")
        assert sorted(d for _, _, d in _findings(r)) == [
            "os.fsync", "subprocess.run", "time.sleep"]

    def test_suppressed_with_reason(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import time
            async def handler():
                time.sleep(1)   # analysis-ok(async_blocking): test stall
                time.sleep(2)   # blocking-ok: legacy alias honored
            """}, "async_blocking")
        assert r["findings"] == []
        assert r["suppressions"]["async_blocking"] == 2

    def test_bare_marker_suppresses_nothing(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import time
            async def handler():
                time.sleep(1)   # analysis-ok(async_blocking):
            """}, "async_blocking")
        assert len(r["findings"]) == 1   # reason is mandatory

    def test_clean_negative(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import asyncio, time
            def sync_helper():
                time.sleep(1)            # sync context: fine
            async def handler():
                await asyncio.sleep(1)   # the correct spelling
                def executor_target():
                    time.sleep(1)        # nested sync def: fine
                return executor_target
            """}, "async_blocking")
        assert r["findings"] == []


class TestLockHeldAwait:
    def test_true_positive(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                async def work(self):
                    with self._lock:
                        await self.other()
            """}, "lock_held_await")
        assert [(l, d) for _, l, d in _findings(r)] == [(7, "self._lock")]

    def test_suppressed_with_reason(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            class C:
                async def work(self):
                    with self._lock:
                        # analysis-ok(lock_held_await): lock-free await
                        await self.other()
            """}, "lock_held_await")
        assert r["findings"] == []
        assert r["suppressions"]["lock_held_await"] == 1

    def test_clean_negative(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            class C:
                async def work(self):
                    async with self._alock:     # asyncio lock: fine
                        await self.other()
                    with self._lock:
                        self.x = 1              # no await held: fine
                    with self._lock:
                        def helper():           # nested def: its own
                            pass                # awaits, its own locks
                    await self.other()
            """}, "lock_held_await")
        assert r["findings"] == []


class TestJitHazards:
    def test_true_positives(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import jax, numpy as np
            import jax.numpy as jnp
            from functools import partial
            @partial(jax.jit, static_argnames=("k",))
            def kern(x, y, k):
                if y > 0:                 # python branch on traced
                    x = x + 1
                v = float(x)              # host cast
                w = np.asarray(y)         # host numpy mid-trace
                s = x.sum().item()        # host sync
                return x + k
            def driver(a):
                return kern(jnp.zeros(50000), a, k=4)   # literal shape
            """}, "jit_hazards")
        details = sorted(d for _, _, d in _findings(r))
        assert details == ["kern:float", "kern:if", "kern:item",
                           "kern:jnp.zeros", "kern:np.asarray"]

    def test_suppressed_with_reason(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import jax
            @jax.jit
            def kern(x):
                if x > 0:  # analysis-ok(jit_hazards): proven static
                    return x
                return -x
            """}, "jit_hazards")
        assert r["findings"] == []
        assert r["suppressions"]["jit_hazards"] == 1

    def test_clean_negative_shape_math_untaints(self, tmp_path):
        # the exact idiom ops/compaction.py + vector/ivf.py live on:
        # .shape unpacking yields static python ints, branches and
        # range() over them are fine, as is jax.jit-by-assignment
        r = _run(tmp_path, {"pkg/a.py": """\
            import jax
            import jax.numpy as jnp
            from functools import partial
            @partial(jax.jit, static_argnames=("num_words",))
            def kern(words, ht, num_words):
                n = words.shape[0]
                ops = tuple(words[:, i] for i in range(1, num_words))
                if num_words > 2:            # static arg: fine
                    ht = ht + 1
                first = jnp.where(ht > 0, words[:, 0], jnp.uint64(0))
                c = min(num_words, n)        # static math: fine
                return first, ops, c
            def _raw(x):
                m = x.shape[1]
                return x.reshape(x.shape[0] * m)
            fn = jax.jit(_raw)
            def debug_path():
                # direct raw call runs EAGERLY — no compile, no trap
                return _raw(jnp.zeros((4, 500)))
            class Unrelated:
                def kern(self):          # leaf-name collision: fine
                    return jnp.ones(128)
            """}, "jit_hazards")
        assert r["findings"] == []

    def test_grouped_scatter_idiom_clean(self, tmp_path):
        # the grouped-aggregation kernel's segment-sum/scatter-add shape
        # (ops/grouped_scan.grouped_reduce): group-slot count S is a
        # STATIC pow2 (part of the signature — branching on it is
        # fine), dictionary domain sizes arrive as TRACED scalars and
        # only ever feed jnp arithmetic, the spill count leaves the
        # kernel as an output instead of steering trace-time control
        # flow
        r = _run(tmp_path, {"pkg/a.py": """\
            import jax
            import jax.numpy as jnp
            from functools import partial
            @partial(jax.jit, static_argnames=("S",))
            def grouped(codes, vals, mask, domains, S):
                gid = None
                stride = jnp.int64(1)
                for i in range(len(domains)):   # static arity: fine
                    c = codes.astype(jnp.int64)
                    gid = c * stride if gid is None else gid + c * stride
                    stride = stride * domains[i].astype(jnp.int64)
                spill_slot = S - 1           # static math on S: fine
                in_range = gid < spill_slot
                spilled = jnp.sum(mask & jnp.logical_not(in_range))
                gid_c = jnp.where(mask & in_range, gid,
                                  spill_slot).astype(jnp.int32)
                out = jnp.zeros(S, jnp.int64).at[gid_c].add(
                    jnp.where(mask, vals, 0))
                if S > 4:                    # static branch: fine
                    out = out + 0
                return out, spilled
            """}, "jit_hazards")
        assert r["findings"] == []

    def test_grouped_scatter_idiom_true_positives(self, tmp_path):
        # the shapes the grouped kernel must NEVER take: the traced
        # spill count / domain product steering Python control flow, or
        # a host round-trip mid-trace to size the slot array
        r = _run(tmp_path, {"pkg/a.py": """\
            import jax
            import jax.numpy as jnp
            @jax.jit
            def bad_grouped(codes, vals, mask, dom):
                prod = dom * 2
                if prod > 4096:            # python branch on traced
                    return jnp.zeros(1, jnp.int64), jnp.int64(0)
                spilled = jnp.sum(mask)
                n = int(spilled)           # host cast of traced count
                gid = codes.astype(jnp.int32)
                out = jnp.zeros(4096, jnp.int64).at[gid].add(
                    jnp.where(mask, vals, 0))
                while spilled > 0:         # python loop on traced
                    spilled = spilled - 1
                return out, spilled
            """}, "jit_hazards")
        details = sorted(d for _, _, d in _findings(r))
        assert details == ["bad_grouped:if", "bad_grouped:int",
                           "bad_grouped:while"]


class TestJitHazardsJoinWindow:
    """The join build/probe and window segment-scan idioms
    (ops/join_scan.probe_table, ops/window_scan kernels): table size
    static per pow2 bucket, the true build count a traced runtime
    scalar, chain-walking via lax.while_loop — and the shapes those
    kernels must NEVER take."""

    def test_join_probe_idiom_clean(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import jax
            import jax.numpy as jnp
            from functools import partial
            @partial(jax.jit, static_argnames=("num_slots",))
            def probe(pk, table_used, table_key, table_val, n_build,
                      num_slots):
                bits = num_slots.bit_length() - 1    # static math: fine
                mask = num_slots - 1
                k64 = pk.astype(jnp.int64)
                h = k64.astype(jnp.uint64) * jnp.uint64(0x9E3779B97F4A7C15)
                slot = (h >> jnp.uint64(64 - bits)).astype(jnp.int32)
                n = pk.shape[0]                      # static shape: fine
                midx0 = jnp.full(n, -1, jnp.int32)
                done0 = jnp.zeros(n, bool)
                def cond(state):
                    _, _, done = state
                    return jnp.logical_not(jnp.all(done))
                def body(state):
                    slot, midx, done = state
                    tk = table_key[slot]
                    hit = table_used[slot] & (tk == k64) & ~done
                    stop = ~table_used[slot] & ~done
                    midx = jnp.where(hit, table_val[slot], midx)
                    done = done | hit | stop
                    slot = jnp.where(done, slot, (slot + 1) & mask)
                    return slot, midx, done
                _, midx, _ = jax.lax.while_loop(cond, body,
                                                (slot, midx0, done0))
                # the runtime build count guards matches as ARITHMETIC,
                # never as Python control flow
                return jnp.where(midx < n_build, midx, -1)
            """}, "jit_hazards")
        assert r["findings"] == []

    def test_join_probe_idiom_true_positives(self, tmp_path):
        # the shapes the probe must never take: a Python while over the
        # traced done-mask, a host cast of the traced build count, and
        # a literal-shaped table at the jitted call site
        r = _run(tmp_path, {"pkg/a.py": """\
            import jax
            import jax.numpy as jnp
            @jax.jit
            def bad_probe(pk, table_key, n_build):
                done = table_key[pk] == pk
                while not done.all():      # python loop on traced
                    done = done | (table_key[pk] == pk)
                nb = int(n_build)          # host cast of traced count
                return done, nb
            def driver(pk, n_build):
                return bad_probe(pk, jnp.zeros(65536), n_build)
            """}, "jit_hazards")
        details = sorted(d for _, _, d in _findings(r))
        assert details == ["bad_probe:int", "bad_probe:jnp.zeros",
                           "bad_probe:while"]

    def test_window_segment_idiom_clean(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import jax
            import jax.numpy as jnp
            def _raw(seg_start, peer_start, valid, vals):
                n = seg_start.shape[0]
                idx = jnp.arange(n, dtype=jnp.int32)
                start = jax.lax.cummax(jnp.where(seg_start, idx, -1))
                rn = idx - start + 1
                q = jnp.where(valid, vals, 0).astype(jnp.int64)
                c = jnp.cumsum(q)
                base = jnp.where(start > 0,
                                 c[jnp.clip(start - 1, 0, None)], 0)
                return rn, c - base
            fn = jax.jit(_raw)
            """}, "jit_hazards")
        assert r["findings"] == []

    def test_multi_stage_probe_idiom_clean(self, tmp_path):
        # the N-stage probe idiom (ops/plan_fusion FusedPlanKernel:
        # multi-join chains): the stage list is a STATIC tuple baked
        # into the plan signature — a Python for over it unrolls at
        # trace time; each stage ANDs its match into ONE shared
        # visibility mask, gathers its payload lanes into the column
        # namespace (clipped indices — masked rows gather garbage that
        # the mask keeps out of every aggregate), and a later stage may
        # probe an earlier stage's payload lane
        r = _run(tmp_path, {"pkg/a.py": """\
            import jax
            import jax.numpy as jnp
            from functools import partial
            @partial(jax.jit, static_argnames=("join_shape",))
            def fused(cols, mask, joins, join_shape):
                for si in range(len(join_shape)):   # static arity: fine
                    probe_col, num_slots, rows_pad, payload = \\
                        join_shape[si]
                    tu, tk, tv, pvals = joins[si]
                    bits = num_slots.bit_length() - 1  # static: fine
                    pk = cols[probe_col].astype(jnp.int64)
                    h = pk.astype(jnp.uint64) \\
                        * jnp.uint64(0x9E3779B97F4A7C15)
                    slot = (h >> jnp.uint64(64 - bits)).astype(
                        jnp.int32)
                    hit = tu[slot] & (tk[slot] == pk)
                    midx = jnp.where(hit, tv[slot], -1)
                    mask = mask & (midx >= 0)   # ONE shared mask
                    gidx = jnp.clip(midx, 0, rows_pad - 1)
                    cols = dict(cols)
                    for bi in range(len(payload)):  # static: fine
                        cols[payload[bi]] = pvals[bi][gidx]
                return mask, cols
            """}, "jit_hazards")
        assert r["findings"] == []

    def test_multi_stage_probe_idiom_true_positives(self, tmp_path):
        # the shapes the N-stage chain must NEVER take: early-exit
        # Python branching on a stage's traced match count (the whole
        # point of the shared mask is that dead rows ride along), a
        # host sync of the surviving-row count between stages, and a
        # Python while chasing convergence of the traced mask
        r = _run(tmp_path, {"pkg/a.py": """\
            import jax
            import jax.numpy as jnp
            @jax.jit
            def bad_chain(pk, used, key, val):
                hit = used[pk] & (key[pk] == pk)
                midx = jnp.where(hit, val[pk], -1)
                mask = midx >= 0
                if mask.sum() == 0:        # python branch on traced
                    return midx
                alive = mask.sum().item()  # host sync between stages
                while mask.sum() > 0:      # python loop on traced
                    mask = mask & ~mask
                return midx, alive
            """}, "jit_hazards")
        details = sorted(d for _, _, d in _findings(r))
        assert details == ["bad_chain:if", "bad_chain:item",
                           "bad_chain:while"]

    def test_window_segment_idiom_true_positive(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import jax
            import jax.numpy as jnp
            @jax.jit
            def bad_window(seg_start, vals):
                starts = jnp.flatnonzero(seg_start).tolist()  # host sync
                out = vals
                for s in starts:           # python for over traced
                    out = out.at[s].set(0)
                return out
            """}, "jit_hazards")
        details = sorted(d for _, _, d in _findings(r))
        assert "bad_window:for" in details
        assert "bad_window:tolist" in details


class TestFlagDrift:
    FILES = {
        "pkg/flags.py": """\
            def DEFINE_RUNTIME(name, default, help=""):
                pass
            DEFINE_RUNTIME("used_flag", 7, "wired below")
            DEFINE_RUNTIME("dead_flag", 1, "nobody reads this")
            DEFINE_RUNTIME("sched_point_read_depth", 512, "dynamic read")
            DEFINE_RUNTIME("doc_flag", 4, "defaults to 9")
            DEFINE_RUNTIME("doc_flag2", 4, "window size (default: 3)")
            DEFINE_RUNTIME("doc_flag3", 4, "uses the default backend")
            """,
        "pkg/user.py": """\
            from . import flags
            def f(lane):
                a = flags.get("used_flag")
                b = flags.get(f"sched_{lane}_depth")
                c = flags.get("missing_flag")
                return a, b, c
            """,
    }

    def test_true_positives(self, tmp_path):
        r = _run(tmp_path, dict(self.FILES), "flag_drift")
        got = {(p, d) for p, _, d in _findings(r)}
        assert ("pkg/flags.py", "dead_flag") in got       # never read
        assert ("pkg/user.py", "missing_flag") in got     # never defined
        assert ("pkg/flags.py", "doc_flag") in got        # help disagrees
        assert ("pkg/flags.py", "doc_flag2") in got       # "(default: 3)"
        # prose "the default backend" is not a value claim
        assert not any(d == "doc_flag3" and "documents default" in
                       f["message"] for f, (_, _, d) in
                       zip(r["findings"], _findings(r)))
        # dynamic f-string read covers the sched_*_depth flag
        assert not any(d == "sched_point_read_depth" for _, _, d in
                       _findings(r))

    def test_duplicate_default_drift(self, tmp_path):
        files = dict(self.FILES)
        files["pkg/extra.py"] = """\
            from .flags import DEFINE_RUNTIME
            DEFINE_RUNTIME("used_flag", 8, "second default loses")
            """
        r = _run(tmp_path, files, "flag_drift")
        assert any(d == "used_flag" and "re-defined" in m for (_, _, d), m
                   in zip(_findings(r),
                          [f["message"] for f in r["findings"]]))

    def test_matview_flag_surface(self, tmp_path):
        """The matview flag family (ISSUE 17) as a drift fixture: every
        flag read in the subsystem is wired clean; an aspirational flag
        nobody folded in yet (the classic way a knob rots) fires."""
        r = _run(tmp_path, {
            "pkg/flags.py": """\
                def DEFINE_RUNTIME(name, default, help=""):
                    pass
                DEFINE_RUNTIME("matview_enabled", True, "gate")
                DEFINE_RUNTIME("matview_rescan_budget", 8, "cap")
                DEFINE_RUNTIME("matview_max_staleness_ms", 500.0, "bound")
                DEFINE_RUNTIME("matview_poll_ms", 50, "cadence")
                DEFINE_RUNTIME("matview_parallel_seed", 4, "unwired")
                """,
            "pkg/maintainer.py": """\
                from . import flags
                def f():
                    return (flags.get("matview_enabled"),
                            flags.get("matview_rescan_budget"),
                            flags.get("matview_max_staleness_ms"),
                            flags.get("matview_poll_ms"))
                """}, "flag_drift")
        got = {d for _, _, d in _findings(r)}
        assert got == {"matview_parallel_seed"}

    def test_suppressed_with_reason(self, tmp_path):
        files = dict(self.FILES)
        files["pkg/flags.py"] = files["pkg/flags.py"].replace(
            'DEFINE_RUNTIME("dead_flag", 1, "nobody reads this")',
            'DEFINE_RUNTIME("dead_flag", 1, "r")  '
            '# analysis-ok(flag_drift): reserved for r07')
        r = _run(tmp_path, files, "flag_drift")
        assert not any(d == "dead_flag" for _, _, d in _findings(r))
        assert r["suppressions"]["flag_drift"] == 1

    def test_clean_negative(self, tmp_path):
        r = _run(tmp_path, {
            "pkg/flags.py": """\
                def DEFINE_RUNTIME(name, default, help=""):
                    pass
                DEFINE_RUNTIME("wired", True, "read next door")
                """,
            "pkg/user.py": """\
                from . import flags
                def f():
                    data = {}
                    data.get("not_a_flag")    # dict get: out of scope
                    return flags.get("wired")
                """}, "flag_drift")
        assert r["findings"] == []

    def test_join_window_plan_flags_covered(self, tmp_path):
        # the PR-13 flag set under the pass's four drift shapes: wired
        # reads stay clean, an unwired clone and a typo'd read fire
        r = _run(tmp_path, {
            "pkg/flags.py": """\
                def DEFINE_RUNTIME(name, default, help=""):
                    pass
                DEFINE_RUNTIME("join_pushdown_enabled", True, "wired")
                DEFINE_RUNTIME("window_pushdown_enabled", True, "w")
                DEFINE_RUNTIME("plan_fusion_enabled", True, "p")
                DEFINE_RUNTIME("join_max_build_slots", 65536,
                               "slots (default 65536)")
                DEFINE_RUNTIME("join_pushdown_enabled_v2", True,
                               "nobody reads this clone")
                """,
            "pkg/user.py": """\
                from . import flags
                def f():
                    a = flags.get("join_pushdown_enabled")
                    b = flags.get("window_pushdown_enabled")
                    c = flags.get("plan_fusion_enabled")
                    d = flags.get("join_max_build_slots")
                    e = flags.get("plan_fuson_enabled")   # typo
                    return a, b, c, d, e
                """}, "flag_drift")
        got = {(p, d) for p, _, d in _findings(r)}
        assert ("pkg/flags.py", "join_pushdown_enabled_v2") in got
        assert ("pkg/user.py", "plan_fuson_enabled") in got
        wired = {"join_pushdown_enabled", "window_pushdown_enabled",
                 "plan_fusion_enabled", "join_max_build_slots"}
        assert not {d for _, d in got} & wired

    def test_real_flag_defaults_match_docs(self):
        # the REAL tree: the four new flags are defined, read by
        # product code, and their documented defaults agree (the
        # whole-tree zero-findings gate covers this too; this pins the
        # specific names so a rename can't silently drop coverage)
        index = ProjectIndex(HERE)
        r = run_analysis(index, [get_pass("flag_drift")])
        assert r["findings"] == []
        from yugabyte_db_tpu.utils import flags as _f
        for name in ("join_pushdown_enabled", "window_pushdown_enabled",
                     "plan_fusion_enabled", "join_max_build_slots",
                     "grouped_spill_merge_enabled"):
            assert name in _f.REGISTRY.all()


class TestSharedStateRaces:
    def test_true_positive(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import asyncio
            class Srv:
                def flush(self):
                    self.stats["flushes"] = 1      # thread side
                async def handler(self):
                    self.stats["reads"] = 2        # loop side
                async def kick(self):
                    loop = asyncio.get_running_loop()
                    await loop.run_in_executor(None, self.flush)
            """}, "shared_state_races")
        assert [d for _, _, d in _findings(r)] == ["Srv.stats"]

    def test_executor_lambda_counts_as_thread_side(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import asyncio
            class Srv:
                async def handler(self):
                    self.stats["reads"] = 2          # loop side
                async def kick(self):
                    loop = asyncio.get_running_loop()
                    await loop.run_in_executor(
                        None, lambda: self.stats.update(x=1))
            """}, "shared_state_races")
        assert [d for _, _, d in _findings(r)] == ["Srv.stats"]

    def test_suppressed_with_reason(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import asyncio
            class Srv:
                def flush(self):
                    # analysis-ok(shared_state_races): torn-read-safe
                    self.n = 1
                async def handler(self):
                    self.n = 2
                async def kick(self):
                    loop = asyncio.get_running_loop()
                    await loop.run_in_executor(None, self.flush)
            """}, "shared_state_races")
        assert r["findings"] == []
        assert r["suppressions"]["shared_state_races"] == 1

    def test_clean_negative_locked_both_sides(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import asyncio, threading
            class Srv:
                def flush(self):
                    with self._lock:
                        self.stats["flushes"] = 1
                async def handler(self):
                    with self._lock:
                        self.stats["reads"] = 2
                async def kick(self):
                    loop = asyncio.get_running_loop()
                    await loop.run_in_executor(None, self.flush)
            class NotATarget:
                def helper(self):
                    self.x = 1      # never shipped to an executor
                async def h(self):
                    self.x = 2
            """}, "shared_state_races")
        assert r["findings"] == []


class TestUnawaitedCoroutine:
    def test_true_positives(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import asyncio
            async def refresh():
                pass
            class Srv:
                async def _flush(self):
                    pass
                async def handler(self):
                    self._flush()                    # coroutine dropped
                    refresh()                        # module-level coro
                    asyncio.gather(self._flush())    # builtin awaitable
                    asyncio.create_task(self._flush())   # F&F task
                    asyncio.ensure_future(refresh())     # F&F task
            """}, "unawaited_coroutine")
        details = sorted(d for _, _, d in _findings(r))
        assert details == ["asyncio.create_task", "asyncio.ensure_future",
                           "asyncio.gather", "refresh", "self._flush"]

    def test_suppressed_with_reason(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import asyncio
            class Srv:
                async def _bg(self):
                    pass
                async def go(self):
                    # analysis-ok(unawaited_coroutine): supervised set
                    asyncio.create_task(self._bg())
            """}, "unawaited_coroutine")
        assert r["findings"] == []
        assert r["suppressions"]["unawaited_coroutine"] == 1

    def test_clean_negatives(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import asyncio
            def close():                   # sync twin un-flags the name
                pass
            class Srv:
                async def _bg(self):
                    pass
                async def close(self):
                    pass
                async def run(self):
                    await self._bg()                    # awaited: fine
                    t = asyncio.create_task(self._bg())     # handle kept
                    self.tasks.append(
                        asyncio.create_task(self._bg()))    # stored
                    asyncio.create_task(
                        self._bg()).add_done_callback(print)  # chained
                    await t
                    close()            # sync/async collision: not ours
                    self.writer.write(b"x")   # non-self receiver: the
                                              # stdlib sync write shape
            """}, "unawaited_coroutine")
        assert [d for _, _, d in _findings(r)
                if d not in ("asyncio.create_task",)] == []
        # the kept/stored/chained create_task forms must NOT fire either
        assert r["findings"] == []

    def test_taskgroup_spawn_not_flagged(self, tmp_path):
        # TaskGroup holds strong refs + propagates exceptions: its
        # discarded create_task handle is the documented safe pattern
        r = _run(tmp_path, {"pkg/a.py": """\
            import asyncio
            class Srv:
                async def _bg(self):
                    pass
                async def run(self):
                    async with asyncio.TaskGroup() as tg:
                        tg.create_task(self._bg())       # fine
                    loop = asyncio.get_running_loop()
                    loop.create_task(self._bg())         # weak set: bug
                    asyncio.get_running_loop().create_task(
                        self._bg())                      # weak set: bug
            """}, "unawaited_coroutine")
        assert sorted(d for _, _, d in _findings(r)) == \
            ["create_task", "loop.create_task"]

    def test_nested_class_rescopes(self, tmp_path):
        # a class nested inside a method must NOT inherit the outer
        # class's async-method set (its sync self.flush() is fine) —
        # and a dropped coroutine inside an except block IS caught
        r = _run(tmp_path, {"pkg/a.py": """\
            class Outer:
                async def flush(self):
                    pass
                def make(self):
                    class Inner:
                        def flush(self):
                            pass
                        def go(self):
                            self.flush()        # sync: fine
                    return Inner
                async def run(self):
                    try:
                        await self.flush()
                    except Exception:
                        self.flush()            # dropped coroutine
            """}, "unawaited_coroutine")
        assert [(l, d) for _, l, d in _findings(r)] == \
            [(15, "self.flush")]


class TestFormatGate:
    FILES = {
        "pkg/writer.py": """\
            from .sstlib import SstWriter
            def dump(path, cb):
                w = SstWriter(path, format_version=2)
                head, bufs = cb.serialize_parts(version=2)
                return w, head, bufs
            """,
        "pkg/sstlib.py": """\
            class SstWriter:
                def __init__(self, path, format_version=None):
                    self.path = path
            """,
    }

    def test_true_positives(self, tmp_path):
        r = _run(tmp_path, dict(self.FILES), "format_gate")
        got = {(p, d) for p, _, d in _findings(r)}
        assert ("pkg/writer.py", "format_version=2") in got
        assert ("pkg/writer.py", "version=2") in got

    def test_generic_version_kwarg_not_flagged(self, tmp_path):
        """`version=2` on non-serializer callees (schema versions etc.)
        is unrelated to the on-disk format and must not fire."""
        files = dict(self.FILES)
        files["pkg/writer.py"] = """\
            def make():
                return TableSchema(columns=(), version=2)
            """
        r = _run(tmp_path, files, "format_gate")
        assert _findings(r) == []

    def test_pinning_v1_allowed(self, tmp_path):
        files = dict(self.FILES)
        files["pkg/writer.py"] = """\
            from .sstlib import SstWriter
            def dump(path, cb, fmt):
                w = SstWriter(path, format_version=1)   # baseline pin
                return cb.serialize_parts(version=fmt)  # flag-resolved
            """
        r = _run(tmp_path, files, "format_gate")
        assert _findings(r) == []

    def test_suppressed_with_reason(self, tmp_path):
        files = dict(self.FILES)
        files["pkg/writer.py"] = """\
            from .sstlib import SstWriter
            def dump(path, cb):
                # analysis-ok(format_gate): migration tool writes v2 on purpose
                w = SstWriter(path, format_version=2)
                return w
            """
        r = _run(tmp_path, files, "format_gate")
        assert _findings(r) == []
        assert r["suppressions"]["format_gate"] == 1

    def test_shred_cols_literal_on_serializer_flagged(self, tmp_path):
        """A serializer call feeding a non-empty literal shred_cols
        would emit shredded doc lanes even with doc_shred_enabled off
        — the writer gate lives in SstWriter, nowhere else."""
        files = dict(self.FILES)
        files["pkg/writer.py"] = """\
            def dump(cb, fmt, kb):
                return cb.serialize_parts(fmt, kb, None,
                                          shred_cols=(1, 2))
            """
        r = _run(tmp_path, files, "format_gate")
        assert [d for _, _, d in _findings(r)] == ["shred_cols literal"]

    def test_shred_cols_through_writer_allowed(self, tmp_path):
        """SstWriter(shred_cols=...) resolves the doc_shred_enabled
        flag itself — threading codec.shred_cols through it (or an
        empty/None literal on a serializer) is the sanctioned path."""
        files = dict(self.FILES)
        files["pkg/writer.py"] = """\
            from .sstlib import SstWriter
            def dump(path, cb, codec, fmt, kb):
                w = SstWriter(path, shred_cols=codec.shred_cols)
                head, bufs = cb.serialize_parts(fmt, kb, None,
                                                shred_cols=())
                return w, head, bufs
            """
        r = _run(tmp_path, files, "format_gate")
        assert _findings(r) == []


class TestLayering:
    """bypass/ must not import tserver/sched/rpc — the subsystem's
    isolation guarantee as a tier-1 fact."""

    def _run_scoped(self, tmp_path, files):
        import textwrap as _tw
        for rel, src in files.items():
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(_tw.dedent(src))
        index = ProjectIndex(str(tmp_path),
                             roots=("yugabyte_db_tpu",))
        return run_analysis(index, [get_pass("layering")])

    def test_true_positives(self, tmp_path):
        r = self._run_scoped(tmp_path, {
            "yugabyte_db_tpu/bypass/bad.py": """\
                import yugabyte_db_tpu.tserver.tablet_server
                from yugabyte_db_tpu.rpc import messenger
                from ..sched.lanes import Lane
                from .. import rpc
                def f():
                    from ..tserver import tablet_server
                    return tablet_server
                """})
        layers = sorted(d.split(":")[0] for _, _, d in _findings(r))
        assert layers == ["rpc", "rpc", "sched", "tserver", "tserver"]

    def test_suppressed_with_reason(self, tmp_path):
        r = self._run_scoped(tmp_path, {
            "yugabyte_db_tpu/bypass/bad.py": """\
                from ..rpc import messenger  # analysis-ok(layering): fixture
                """})
        assert r["findings"] == []
        assert r["suppressions"]["layering"] == 1

    def test_clean_negatives(self, tmp_path):
        """Allowed seams (storage/ops/parallel/docdb), sibling-package
        imports of the same names, and other layers importing tserver
        must not fire."""
        r = self._run_scoped(tmp_path, {
            "yugabyte_db_tpu/bypass/ok.py": """\
                from ..storage.lsm import LsmStore
                from ..ops import stream_scan
                from ..parallel.distributed_scan import ShardedBatch
                from ..docdb.operations import ReadResponse
                from .errors import BypassIneligible
                import numpy.rpc_like as rpcx    # not our layer
                """,
            "yugabyte_db_tpu/client/uses_rpc.py": """\
                from ..rpc.messenger import Messenger
                from ..tserver import tablet_server
                """})
        assert _findings(r) == []

    def test_cluster_rule(self, tmp_path):
        """cluster/ may import client/rpc/utils/models but never
        server-side internals (tserver/tablet/master/storage/...) —
        the harness talks to servers ONLY over RPC."""
        r = self._run_scoped(tmp_path, {
            "yugabyte_db_tpu/cluster/ok.py": """\
                from ..client import YBClient
                from ..rpc.messenger import Messenger
                from ..utils.metrics import REGISTRY
                from ..models.ycsb import usertable_info
                """,
            "yugabyte_db_tpu/cluster/bad.py": """\
                from ..tserver import TabletServer
                from ..tablet.tablet_peer import TabletPeer
                import yugabyte_db_tpu.storage.lsm
                from ..master import Master
                def f():
                    from ..bypass import BypassSession
                    return BypassSession
                """})
        layers = sorted(d.split(":")[0] for _, _, d in _findings(r))
        assert layers == ["bypass", "master", "storage", "tablet",
                         "tserver"]
        assert all(f == "yugabyte_db_tpu/cluster/bad.py"
                   for f, _, _ in _findings(r))

    def test_docstore_rule(self, tmp_path):
        """docstore/ is a pure library: storage/dockv/ops/utils (and
        docdb for the shared rewrite) are fine; tserver/tablet/rpc
        never — shredding must not reach into server layers."""
        r = self._run_scoped(tmp_path, {
            "yugabyte_db_tpu/docstore/ok.py": """\
                from ..storage import lane_codec
                from ..dockv.packed_row import ColumnType
                from ..ops.scan import AggSpec
                from ..utils import flags
                """,
            "yugabyte_db_tpu/docstore/bad.py": """\
                from ..tserver import TabletServer
                from ..tablet.tablet import Tablet
                import yugabyte_db_tpu.rpc.messenger
                """})
        layers = sorted(d.split(":")[0] for _, _, d in _findings(r))
        assert layers == ["rpc", "tablet", "tserver"]
        assert all(f == "yugabyte_db_tpu/docstore/bad.py"
                   for f, _, _ in _findings(r))

    def test_matview_rule(self, tmp_path):
        """matview/ folds exclusively through client RPCs, the CDC slot
        API and the ops combine seam (cdc/client/ops/utils/models are
        fine); importing tserver/tablet/storage/consensus would let a
        maintainer read a memtable directly, bypassing the pinned read
        point the whole design hangs on."""
        r = self._run_scoped(tmp_path, {
            "yugabyte_db_tpu/matview/ok.py": """\
                from ..cdc.virtual_wal import VirtualWal
                from ..client.client import YBClient
                from ..ops.scan import combine_grouped_partials
                from ..utils import flags
                from ..models.ycsb import usertable_info
                from .errors import MatviewIneligible
                """,
            "yugabyte_db_tpu/matview/bad.py": """\
                from ..tserver import TabletServer
                from ..tablet.tablet_peer import TabletPeer
                import yugabyte_db_tpu.storage.lsm
                def f():
                    from ..consensus import RaftConsensus
                    return RaftConsensus
                """})
        layers = sorted(d.split(":")[0] for _, _, d in _findings(r))
        assert layers == ["consensus", "storage", "tablet", "tserver"]
        assert all(f == "yugabyte_db_tpu/matview/bad.py"
                   for f, _, _ in _findings(r))


# --- interprocedural: the call graph itself --------------------------------

class TestTraceDiscipline:
    """wait_status() states are a closed vocabulary: every call-site
    literal must come from the canonical trace.WAIT_STATES table (a
    typo'd state silently vanishes from every ASH histogram)."""

    TABLE = """\
        WAIT_STATES = frozenset({
            "Idle",
            "WAL_Fsync",
            "Flush_SstWrite",
        })
        def wait_status(state, component=""):
            pass
        """

    def _run_with_table(self, tmp_path, files):
        import textwrap as _tw
        files = dict(files)
        files["yugabyte_db_tpu/utils/trace.py"] = self.TABLE
        for rel, src in files.items():
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(_tw.dedent(src))
        index = ProjectIndex(str(tmp_path), roots=("yugabyte_db_tpu",))
        return run_analysis(index, [get_pass("trace_discipline")])

    def test_true_positive_free_text(self, tmp_path):
        r = self._run_with_table(tmp_path, {
            "yugabyte_db_tpu/a.py": """\
                from .utils.trace import wait_status
                def f():
                    with wait_status("WalFsyncTypo"):
                        pass
                """})
        assert [d for _, _, d in _findings(r)] == ["WalFsyncTypo"]

    def test_true_positive_non_literal(self, tmp_path):
        r = self._run_with_table(tmp_path, {
            "yugabyte_db_tpu/a.py": """\
                from .utils import trace
                def f(state):
                    with trace.wait_status(state):
                        pass
                """})
        assert [d for _, _, d in _findings(r)] == ["non-literal"]

    def test_suppressed_with_reason(self, tmp_path):
        r = self._run_with_table(tmp_path, {
            "yugabyte_db_tpu/a.py": """\
                from .utils.trace import wait_status
                def f():
                    with wait_status("Legacy"):   # analysis-ok(trace_discipline): fixture
                        pass
                """})
        assert r["findings"] == []
        assert r["suppressions"]["trace_discipline"] == 1

    def test_clean_negative(self, tmp_path):
        """Canonical literals (bare and attribute-qualified calls) and
        unrelated call names must not fire."""
        r = self._run_with_table(tmp_path, {
            "yugabyte_db_tpu/a.py": """\
                from .utils import trace
                from .utils.trace import wait_status
                def f():
                    with wait_status("WAL_Fsync"):
                        pass
                    with trace.wait_status("Flush_SstWrite",
                                           component="flush"):
                        pass
                    return trace.current_wait_state()
                """})
        assert _findings(r) == []

    def test_no_table_no_findings(self, tmp_path):
        """A tree without a WAIT_STATES table (bare fixture) produces
        nothing rather than flagging every call."""
        import textwrap as _tw
        p = tmp_path / "yugabyte_db_tpu" / "a.py"
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(_tw.dedent("""\
            def wait_status(s):
                pass
            def f():
                with wait_status("Whatever"):
                    pass
            """))
        index = ProjectIndex(str(tmp_path), roots=("yugabyte_db_tpu",))
        r = run_analysis(index, [get_pass("trace_discipline")])
        assert _findings(r) == []

    def test_real_tree_table_discovered(self):
        """The pass finds the REAL canonical table in utils/trace.py
        (so it tracks table growth with zero pass edits)."""
        sys.path.insert(0, os.path.join(HERE, "tools"))
        from analyze.passes.trace_discipline import find_state_table
        from yugabyte_db_tpu.utils.trace import WAIT_STATES
        index = ProjectIndex(HERE, roots=("yugabyte_db_tpu",))
        mod, states = find_state_table(index)
        assert mod is not None
        assert mod.rel.replace("\\", "/").endswith("utils/trace.py")
        assert states == set(WAIT_STATES)


class TestCallGraph:
    def _graph(self, tmp_path, files):
        for rel, src in files.items():
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(textwrap.dedent(src))
        index = ProjectIndex(str(tmp_path), roots=("pkg",))
        return index.call_graph()

    def test_alias_chain_resolution(self, tmp_path):
        g = self._graph(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/util.py": """\
                def helper():
                    pass
                """,
            "pkg/a.py": """\
                from pkg import util
                fn = util.helper
                fn2 = fn
                def caller():
                    fn2()
                """})
        assert g.resolve("pkg/a.py", "caller", "fn2") \
            == "pkg/util.py::helper"

    def test_method_resolution_across_inheritance(self, tmp_path):
        g = self._graph(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/base.py": """\
                class Base:
                    def close(self):
                        pass
                """,
            "pkg/sub.py": """\
                from pkg.base import Base
                class Mid(Base):
                    pass
                class Sub(Mid):
                    def open(self):
                        self.close()     # binds Base.close via the MRO
                """})
        assert g.resolve("pkg/sub.py", "Sub.open", "self.close") \
            == "pkg/base.py::Base.close"
        # an override wins over the base definition
        g2 = self._graph(tmp_path / "o", {
            "pkg/__init__.py": "",
            "pkg/m.py": """\
                class A:
                    def f(self):
                        pass
                class B(A):
                    def f(self):
                        pass
                    def g(self):
                        self.f()
                """})
        assert g2.resolve("pkg/m.py", "B.g", "self.f") == "pkg/m.py::B.f"

    def test_classname_and_module_qualified_calls(self, tmp_path):
        g = self._graph(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/m.py": """\
                import pkg.util
                from pkg.util import helper as h
                class C:
                    def m(self):
                        pass
                def f():
                    C.m(None)
                    pkg.util.helper()
                    h()
                """,
            "pkg/util.py": """\
                def helper():
                    pass
                """})
        assert g.resolve("pkg/m.py", "f", "C.m") == "pkg/m.py::C.m"
        assert g.resolve("pkg/m.py", "f", "pkg.util.helper") \
            == "pkg/util.py::helper"
        assert g.resolve("pkg/m.py", "f", "h") == "pkg/util.py::helper"

    def test_recursion_terminates(self, tmp_path):
        g = self._graph(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/r.py": """\
                import time
                def a():
                    b()
                def b():
                    a()
                    time.sleep(1)
                def solo():
                    solo()
                    time.sleep(2)
                """})

        def direct(key):
            d = g.def_fact(key)
            return {t: ln for ln, t in d["calls"]
                    if t == "time.sleep"} if d else {}

        # mutual recursion: summaries converge and still see the hazard
        s = g.summarize(g.key("pkg/r.py", "a"), "t", direct,
                        lambda k: True)
        assert "time.sleep" in s
        s2 = g.summarize(g.key("pkg/r.py", "solo"), "t", direct,
                         lambda k: True)
        assert "time.sleep" in s2

    def test_facts_cache_hit_speedup(self, tmp_path):
        files = {"pkg/__init__.py": ""}
        for i in range(30):
            files[f"pkg/m{i}.py"] = "def f():\n    pass\n" * 40
        for rel, src in files.items():
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(src)
        cache = str(tmp_path / ".analyze_cache")
        i1 = ProjectIndex(str(tmp_path), roots=("pkg",), cache_dir=cache)
        g1 = i1.call_graph()
        assert g1.stats["cache_misses"] == len(files)
        i2 = ProjectIndex(str(tmp_path), roots=("pkg",), cache_dir=cache)
        g2 = i2.call_graph()
        assert g2.stats["cache_hits"] == len(files)
        assert g2.stats["cache_misses"] == 0
        # the cached run must actually be cheaper, not just "hit"
        assert g2.stats["build_ms"] < g1.stats["build_ms"]
        # identical facts either way
        assert g2.facts == g1.facts
        # an edited file is re-extracted, the rest stay cached
        p = tmp_path / "pkg/m0.py"
        p.write_text("def f():\n    pass\ndef g():\n    pass\n")
        os.utime(p, (1, 1))
        i3 = ProjectIndex(str(tmp_path), roots=("pkg",), cache_dir=cache)
        g3 = i3.call_graph()
        assert g3.stats["cache_misses"] == 1
        assert "g" in g3.facts["pkg/m0.py"]["defs"]


# --- interprocedural: transitive pass upgrades ------------------------------

class TestAsyncBlockingTransitive:
    def test_true_positive_reports_chain(self, tmp_path):
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/a.py": """\
            import shutil
            def nuke(path):
                shutil.rmtree(path)
            def indirection(path):
                nuke(path)
            async def handler(path):
                indirection(path)
            """}, "async_blocking")
        assert [(l, d) for _, l, d in _findings(r)] == [
            (7, "shutil.rmtree")]
        msg = r["findings"][0]["message"]
        # the full helper chain is the finding's evidence
        assert "indirection" in msg and "nuke" in msg \
            and "shutil.rmtree" in msg

    def test_cross_module_chain(self, tmp_path):
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/helpers.py": """\
            import subprocess
            def run_tool():
                subprocess.run(["x"])
            """,
                            "pkg/srv.py": """\
            from pkg.helpers import run_tool
            async def handler():
                run_tool()
            """}, "async_blocking")
        assert [(p, l, d) for p, l, d in _findings(r)] == [
            ("pkg/srv.py", 3, "subprocess.run")]

    def test_suppression_at_direct_site_does_not_taint(self, tmp_path):
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/a.py": """\
            import time
            def bounded_wait():
                time.sleep(0.001)  # analysis-ok(async_blocking): bounded
            async def handler():
                bounded_wait()
            """}, "async_blocking")
        assert r["findings"] == []

    def test_suppression_at_call_site(self, tmp_path):
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/a.py": """\
            import time
            def helper():
                time.sleep(1)
            async def handler():
                helper()   # analysis-ok(async_blocking): startup only
            """}, "async_blocking")
        assert r["findings"] == []
        assert r["suppressions"]["async_blocking"] == 1

    def test_clean_negatives(self, tmp_path):
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/a.py": """\
            import asyncio, time
            def tiny_config():
                open("/tmp/x")        # lexical-only offender: does NOT
                #                       taint callers (accepted idiom)
            def stall():
                time.sleep(1)
            async def co_helper():
                await asyncio.sleep(0)
            async def handler():
                tiny_config()
                await co_helper()     # async callee: scanned on its own
                await asyncio.get_running_loop().run_in_executor(
                    None, stall)      # executor dispatch, not a call
            """}, "async_blocking")
        assert r["findings"] == []


class TestLockHeldAwaitTransitive:
    def test_true_positive_blocking_under_lock(self, tmp_path):
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/a.py": """\
            import time
            class C:
                def _drain(self):
                    time.sleep(1)
                async def work(self):
                    with self._lock:
                        self._drain()
            """}, "lock_held_await")
        assert [(l, d) for _, l, d in _findings(r)] == [
            (7, "self._lock->time.sleep")]
        assert "_drain" in r["findings"][0]["message"]

    def test_suppressed_with_reason(self, tmp_path):
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/a.py": """\
            import time
            class C:
                def _drain(self):
                    time.sleep(1)
                async def work(self):
                    with self._lock:
                        # analysis-ok(lock_held_await): bounded drain
                        self._drain()
            """}, "lock_held_await")
        assert r["findings"] == []
        assert r["suppressions"]["lock_held_await"] == 1

    def test_clean_negatives(self, tmp_path):
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/a.py": """\
            import time
            class C:
                def _fast(self):
                    return self.x + 1
                def _stall(self):
                    time.sleep(1)
                async def work(self):
                    with self._lock:
                        self._fast()       # no blocking in the summary
                    self._stall()          # blocking, but no lock held
            """}, "lock_held_await")
        assert r["findings"] == []


class TestSharedStateRacesResolved:
    def test_name_collision_no_longer_overapproximates(self, tmp_path):
        # Shipper hands ITS OWN self.flush to an executor; Bystander
        # merely shares the method NAME.  The class-resolved pass must
        # flag Shipper only (terminal-name matching flagged both).
        src = {"pkg/__init__.py": "",
               "pkg/a.py": """\
            class Shipper:
                def flush(self):
                    self.buf = []
                async def go(self):
                    self.buf = [1]
                    await self._loop.run_in_executor(None, self.flush)
            class Bystander:
                def flush(self):
                    self.buf = []
                async def go(self):
                    self.buf = [1]
            """}
        r = _run(tmp_path, src, "shared_state_races")
        paths = {(p, l) for p, l, _ in _findings(r)}
        assert ("pkg/a.py", 3) in paths or ("pkg/a.py", 5) in paths
        assert all(l < 7 for _, l in paths), (
            "Bystander got flagged through a shared method name:\n"
            + str(r["findings"]))

    def test_subclass_override_stays_thread_side(self, tmp_path):
        # Base ships self.flush to an executor; Sub OVERRIDES flush —
        # for Sub instances the override is what runs on the thread,
        # so its unlocked writes must still race Sub's async methods
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/base.py": """\
            class Base:
                def flush(self):
                    pass
                async def go(self):
                    await self._loop.run_in_executor(None, self.flush)
            """,
                            "pkg/sub.py": """\
            from pkg.base import Base
            class Sub(Base):
                def flush(self):
                    self.dirty = []
                async def serve(self):
                    self.dirty = [1]
            """}, "shared_state_races")
        assert any(p == "pkg/sub.py" for p, _, _ in _findings(r)), (
            "the override lost its thread-side marking:\n"
            + str(r["findings"]))

    def test_unresolvable_target_still_falls_back(self, tmp_path):
        # `peer.tablet.flush` has an unknowable receiver: the terminal-
        # name fallback must keep flagging a same-named sync mutator
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/a.py": """\
            def ship(peer, loop):
                loop.run_in_executor(None, peer.tablet.flush)
            class T:
                def flush(self):
                    self.rows = []
                async def ingest(self):
                    self.rows = [1]
            """}, "shared_state_races")
        assert len(r["findings"]) >= 1


# --- new graph-powered passes ----------------------------------------------

class TestLockOrder:
    def test_true_positive_ab_ba_cycle(self, tmp_path):
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/a.py": """\
            class S:
                async def handler(self):
                    with self._meta_lock:
                        with self._flush_lock:
                            self.x = 1
                def compact(self):
                    with self._flush_lock:
                        with self._meta_lock:
                            self.y = 1
            """}, "lock_order")
        assert len(r["findings"]) == 1
        msg = r["findings"][0]["message"]
        assert "_meta_lock" in msg and "_flush_lock" in msg
        assert "deadlock" in msg

    def test_transitive_cycle_through_helper(self, tmp_path):
        # handler holds A and CALLS a helper that takes B; compact
        # takes B then A directly — the cycle spans a call edge
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/a.py": """\
            class S:
                def _drain(self):
                    with self._flush_lock:
                        self.q = []
                async def handler(self):
                    with self._meta_lock:
                        self._drain()
                def compact(self):
                    with self._flush_lock:
                        with self._meta_lock:
                            self.y = 1
            """}, "lock_order")
        assert len(r["findings"]) == 1
        assert "via" in r["findings"][0]["message"]

    def test_suppressed_with_reason(self, tmp_path):
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/a.py": """\
            class S:
                async def handler(self):
                    with self._a_lock:
                        # analysis-ok(lock_order): B-holders never take A
                        with self._b_lock:
                            self.x = 1
                def compact(self):
                    with self._b_lock:
                        with self._a_lock:
                            self.y = 1
            """}, "lock_order")
        assert r["findings"] == []
        assert r["suppressions"]["lock_order"] == 1

    def test_clean_negatives(self, tmp_path):
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/a.py": """\
            class S:
                async def handler(self):
                    with self._a_lock:
                        with self._b_lock:
                            self.x = 1
                def compact(self):
                    with self._a_lock:     # same global order: fine
                        with self._b_lock:
                            self.y = 1
            class T:
                def one(self):
                    with self._b_lock:     # same NAMES, different class
                        with self._a_lock: # = different locks: no cycle
                            self.z = 1
            """}, "lock_order")
        assert r["findings"] == []

    def test_base_class_lock_is_one_lock(self, tmp_path):
        # the lock lives on the base; two subclasses ordering it
        # against their own lock INCONSISTENTLY is a real cycle
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/a.py": """\
            import threading
            class Base:
                def __init__(self):
                    self._install_lock = threading.Lock()
            class U(Base):
                def f(self):
                    with self._install_lock:
                        with self._side_lock:
                            self.x = 1
            class V(Base):
                def g(self):
                    with self._side_lock:
                        with self._install_lock:
                            self.y = 1
            """}, "lock_order")
        # U._side and V._side are DIFFERENT locks (each class assigns
        # its own), so no cycle exists here; only the shared base lock
        # could close one.  The negative pins the identity rule.
        assert r["findings"] == []
        r2 = _run(tmp_path / "pos", {"pkg/__init__.py": "",
                                     "pkg/a.py": """\
            import threading
            class Base:
                def __init__(self):
                    self._install_lock = threading.Lock()
                    self._gc_lock = threading.Lock()
            class U(Base):
                def f(self):
                    with self._install_lock:
                        with self._gc_lock:
                            self.x = 1
            class V(Base):
                def g(self):
                    with self._gc_lock:
                        with self._install_lock:
                            self.y = 1
            """}, "lock_order")
        assert len(r2["findings"]) == 1

class TestResourceBalance:
    def test_discarded_lease_always_leaks(self, tmp_path):
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/a.py": """\
            def pin(store):
                store.pin_ssts(require_empty_memtable=True)
            """}, "resource_balance")
        assert [(l, d) for _, l, d in _findings(r)] == [
            (2, "pin_ssts:discarded")]

    def test_early_return_skips_release(self, tmp_path):
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/a.py": """\
            def scan(store, cond):
                lease = store.pin_ssts()
                if cond:
                    return None
                lease.release()
                return 1
            """}, "resource_balance")
        assert [(l, d) for _, l, d in _findings(r)] == [
            (4, "pin_ssts:lease")]

    def test_fall_through_never_released(self, tmp_path):
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/a.py": """\
            def peek(path):
                f = open(path)
                f.read(4)
            """}, "resource_balance")
        assert [(l, d) for _, l, d in _findings(r)] == [(2, "open:f")]

    def test_gauge_early_return_skips_decrement(self, tmp_path):
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/a.py": """\
            class L:
                def admit(self, shed):
                    self._inflight += 1
                    if shed:
                        return False
                    self.dispatch()
                    self._inflight -= 1
                    return True
            """}, "resource_balance")
        assert [(l, d) for _, l, d in _findings(r)] == [
            (5, "gauge:self._inflight")]

    def test_suppressed_with_reason(self, tmp_path):
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/a.py": """\
            def scan(store, cond):
                lease = store.pin_ssts()
                if cond:
                    # analysis-ok(resource_balance): owner releases
                    return None
                lease.release()
            """}, "resource_balance")
        assert r["findings"] == []
        assert r["suppressions"]["resource_balance"] == 1

    def test_clean_negatives(self, tmp_path):
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/a.py": """\
            import contextlib

            def ctx_owned(path):
                with open(path) as f:      # context manager owns it
                    return f.read()

            def try_finally(store):
                lease = store.pin_ssts()
                try:
                    return work(lease)
                finally:
                    lease.release()

            def transfer(store):
                lease = store.pin_ssts()
                return Snapshot(lease=lease)   # ownership moved out

            def stored(self, store):
                lease = store.pin_ssts()
                self._lease = lease            # escapes to the owner

            def with_stmt_release(path):
                f = open(path)
                with contextlib.closing(f):
                    return f.read()

            def release_then_raise(store, cond):
                lease = store.pin_ssts()
                if not lease.paths:
                    lease.release()
                    raise ValueError("empty")  # raising exits exempt
                lease.release()
                return 1

            class Cache:
                def put(self, k, v, size):
                    self._bytes += size
                    while self._bytes > self.cap:
                        self._bytes -= self.evict()
                    return v                   # dec behind the return:
                    #                            eviction accounting,
                    #                            not an in-flight pair

            def parser(s):
                depth = 0
                for ch in s:
                    depth += 1
                    if ch == ")":
                        depth -= 1
                    if depth > 40:
                        return None            # bare local: no gauge
                return depth

            def monotonic(self):
                self._stats += 1               # inc-only: a counter
                return self._stats
            """}, "resource_balance")
        assert r["findings"] == []

    def test_pinner_shape_is_clean(self, tmp_path):
        # the REAL bypass/pinner.py shape: acquire in a retry loop,
        # release+raise on the empty branch, transfer via the returned
        # snapshot — zero findings, pinned as a regression fixture
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/a.py": """\
            def pin_snapshot(store, attempts):
                lease = None
                for attempt in range(attempts):
                    lease = store.pin_ssts(require_empty_memtable=True)
                    if lease is not None:
                        break
                if lease is None:
                    raise RuntimeError("memtable active")
                if not lease.paths:
                    lease.release()
                    raise RuntimeError("no ssts")
                return Snapshot(lease=lease, paths=list(lease.paths))
            """}, "resource_balance")
        assert r["findings"] == []


# --- the pre-fix product shapes the engine was built to catch ---------------

class TestPreFixProductShapes:
    """Minimal reproductions of hazards that lived in yugabyte_db_tpu/
    BEFORE this PR's fixes — invisible to the lexical passes, caught by
    the interprocedural engine.  These pin the engine's reason to
    exist: if a refactor re-introduces the shape, tier-1 names it."""

    def test_master_persist_fsync_under_async_commit(self, tmp_path):
        # pre-fix master.py: async _commit_catalog -> sync _persist()
        # -> open/fsync/replace inline on the event loop
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/master.py": """\
            import json, os
            class Master:
                def _persist(self):
                    with open(self._path + ".tmp", "w") as f:
                        json.dump(self.tables, f)
                        f.flush()
                        os.fsync(f.fileno())
                    os.replace(self._path + ".tmp", self._path)
                async def _commit_catalog(self, ops):
                    self.apply(ops)
                    self._persist()
            """}, "async_blocking")
        details = sorted(d for _, _, d in _findings(r))
        assert "os.fsync" in details, r["findings"]
        assert all(l == 11 for _, l, _ in _findings(r)), (
            "the finding must land on the async-side call line")

    def test_tserver_meta_write_under_async_split(self, tmp_path):
        # pre-fix tablet_server.py: async _apply_split calling the
        # sync _atomic_json helper (fsync + cross-FS-safe replace)
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/ts.py": """\
            import json, os
            def _atomic_json(path, obj):
                with open(path + ".tmp", "w") as f:
                    json.dump(obj, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(path + ".tmp", path)
            class TabletServer:
                async def _apply_split(self, meta):
                    _atomic_json(self._marker(meta["id"]), meta)
            """}, "async_blocking")
        assert any(d == "os.fsync" for _, _, d in _findings(r))

    def test_fixed_master_shape_is_clean(self, tmp_path):
        # the POST-fix shape: serialize on the loop, fsync in the
        # executor — the engine must see it as clean (else the fix
        # would have needed an annotation, which the tentpole forbids)
        r = _run(tmp_path, {"pkg/__init__.py": "",
                            "pkg/master.py": """\
            import asyncio, json, os
            class Master:
                def _write(self, data):
                    with open(self._path + ".tmp", "w") as f:
                        f.write(data)
                        f.flush()
                        os.fsync(f.fileno())
                    os.replace(self._path + ".tmp", self._path)
                async def _commit_catalog(self, ops):
                    self.apply(ops)
                    data = json.dumps(self.tables)
                    await asyncio.get_running_loop().run_in_executor(
                        None, self._write, data)
            """}, "async_blocking")
        assert r["findings"] == []


#: the pre-fix PR-11 write-path stall, minimally: Raft apply (async)
#: -> sync _apply_payload -> self.tablet.apply_write (attr typed by
#: the annotated __init__ param) -> Tablet.flush -> self.regular.flush
#: (attr typed by its constructor) -> SST write + fsync ON THE APPLY
#: THREAD.  Both attr hops need the call graph's attribute typing —
#: the lexical layers and the PR-8 engine were blind to this chain.
_APPLY_FLUSH_SHAPE = {
    "pkg/__init__.py": "",
    "pkg/store.py": """\
        import os
        class LsmStore:
            def flush(self):
                with open(self._path + ".tmp", "w") as f:
                    f.write(self._mem)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(self._path + ".tmp", self._path)
    """,
    "pkg/tablet.py": """\
        from .store import LsmStore
        class Tablet:
            def __init__(self, directory):
                self.regular = LsmStore(directory)
            def apply_write(self, req):
                self.regular.apply(req)
                if self.regular.should_flush():
                    self.flush()
            def flush(self):
                return self.regular.flush()
    """,
    "pkg/peer.py": """\
        from .tablet import Tablet
        class TabletPeer:
            def __init__(self, tablet: Tablet):
                self.tablet = tablet
            def _apply_payload(self, entry):
                self.tablet.apply_write(entry.req)
            async def _apply_entry(self, entry):
                self._apply_payload(entry)
    """,
}


class TestWritePathHotPath:
    """PR-11 rule: a synchronous LsmStore.flush reachable from the
    Raft apply path is an apply-thread stall — the ~20x p99 round
    swing the cluster harness measured.  Pinned pre-fix; the post-fix
    tree (frozen-memtable handoff to the flush executor) gates clean
    via test_whole_tree_zero_unannotated_findings."""

    def test_prefix_apply_write_flush_shape_flagged(self, tmp_path):
        r = _run(tmp_path, _APPLY_FLUSH_SHAPE, "async_blocking")
        details = {d for _, _, d in _findings(r)}
        assert "os.fsync" in details, r["findings"]
        # the finding lands on the async-side call in _apply_entry
        assert any(p.endswith("peer.py") and l == 8
                   for p, l, _ in _findings(r)), r["findings"]

    def test_edge_annotation_stops_taint_without_silencing_helper(
            self, tmp_path):
        # annotating the INTERMEDIATE flush call (the flag-gated
        # legacy revert shape) stops the taint at that edge only: an
        # unannotated second path through the same helper still flags
        files = dict(_APPLY_FLUSH_SHAPE)
        files["pkg/tablet.py"] = """\
            from .store import LsmStore
            class Tablet:
                def __init__(self, directory):
                    self.regular = LsmStore(directory)
                def apply_write(self, req):
                    self.regular.apply(req)
                    if self.regular.should_flush():
                        # analysis-ok(async_blocking): bounded revert
                        self.flush()
                def flush(self):
                    return self.regular.flush()
        """
        files["pkg/other.py"] = """\
            from .tablet import Tablet
            class Maintenance:
                def __init__(self, tablet: Tablet):
                    self.tablet = tablet
                async def tick(self):
                    self.tablet.flush()
        """
        r = _run(tmp_path, files, "async_blocking")
        paths = {p for p, _, _ in _findings(r)}
        assert not any(p.endswith("peer.py") for p in paths), (
            "annotated edge must stop the apply-path taint",
            r["findings"])
        assert any(p.endswith("other.py") for p in paths), (
            "the unannotated path through Tablet.flush must still "
            "flag", r["findings"])

    def test_attr_type_conflict_poisons_resolution(self, tmp_path):
        # an attr assigned two different classes resolves to neither
        # (under-approximate, never guess)
        files = dict(_APPLY_FLUSH_SHAPE)
        files["pkg/peer.py"] = """\
            from .tablet import Tablet
            class Other:
                def noop(self):
                    return 1
            class TabletPeer:
                def __init__(self, tablet: Tablet):
                    self.tablet = tablet
                    if tablet is None:
                        self.tablet = Other()
                def _apply_payload(self, entry):
                    self.tablet.apply_write(entry.req)
                async def _apply_entry(self, entry):
                    self._apply_payload(entry)
        """
        r = _run(tmp_path, files, "async_blocking")
        assert not any(p.endswith("peer.py")
                       for p, _, _ in _findings(r)), r["findings"]


def _run_pass(tmp_path, files, pass_obj):
    """Like _run but with a pass INSTANCE — the registry-driven passes
    (cache_key_completeness, wire_drift) take synthetic registries."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    index = ProjectIndex(str(tmp_path), roots=("pkg",))
    return run_analysis(index, [pass_obj])


class TestRefusalFlow:
    ERRORS = """\
        class ScanIneligible(Exception):
            pass
        """

    def test_true_positive_transitive(self, tmp_path):
        # the raise and the broad except are two calls apart — the
        # laundering shape no lexical check can see
        r = _run(tmp_path, {
            "pkg/errors.py": self.ERRORS,
            "pkg/fast.py": """\
                from pkg.errors import ScanIneligible
                def fast_path(x):
                    if x < 0:
                        raise ScanIneligible("neg")
                    return x
                def mid(x):
                    return fast_path(x)
                def caller(x):
                    try:
                        return mid(x)
                    except Exception:
                        return None
                """}, "refusal_flow")
        assert _findings(r) == [("pkg/fast.py", 11, "ScanIneligible")]
        # witness: the call that lets the refusal into this def
        assert "mid()" in r["findings"][0]["message"]

    def test_typed_catch_before_broad_is_clean(self, tmp_path):
        r = _run(tmp_path, {
            "pkg/errors.py": self.ERRORS,
            "pkg/fast.py": """\
                from pkg.errors import ScanIneligible
                def fast_path(x):
                    raise ScanIneligible("no")
                def caller(x):
                    try:
                        return fast_path(x)
                    except ScanIneligible:
                        return None          # routed to fallback
                    except Exception:
                        return -1            # real bugs only
                """}, "refusal_flow")
        assert r["findings"] == []

    def test_reraise_and_isinstance_route_are_clean(self, tmp_path):
        r = _run(tmp_path, {
            "pkg/errors.py": self.ERRORS,
            "pkg/fast.py": """\
                from pkg.errors import ScanIneligible
                def fast_path(x):
                    raise ScanIneligible("no")
                def translating(x):
                    try:
                        return fast_path(x)
                    except Exception:
                        raise RuntimeError("ctx")   # not swallowed
                def routing(x):
                    try:
                        return fast_path(x)
                    except Exception as e:
                        if isinstance(e, ScanIneligible):
                            return None
                        return -1
                """}, "refusal_flow")
        assert r["findings"] == []

    def test_marker_class_caught_via_ancestor(self, tmp_path):
        # marker-declared refusal outside an errors module; catching
        # its stdlib ancestor (ValueError) is a typed catch
        r = _run(tmp_path, {"pkg/keys.py": """\
            # analysis: refusal-class
            class KeyRefusal(ValueError):
                pass
            def parse(k):
                raise KeyRefusal(k)
            def ok(k):
                try:
                    return parse(k)
                except ValueError:
                    return None
            def bad(k):
                try:
                    return parse(k)
                except Exception:
                    return None
            """}, "refusal_flow")
        assert _findings(r) == [("pkg/keys.py", 14, "KeyRefusal")]

    def test_suppressed_with_reason(self, tmp_path):
        r = _run(tmp_path, {
            "pkg/errors.py": self.ERRORS,
            "pkg/fast.py": """\
                from pkg.errors import ScanIneligible
                def fast_path(x):
                    raise ScanIneligible("no")
                def boundary(x):
                    try:
                        return fast_path(x)
                    # analysis-ok(refusal_flow): protocol boundary
                    except Exception:
                        return None
                """}, "refusal_flow")
        assert r["findings"] == []
        assert r["suppressions"]["refusal_flow"] == 1

    def test_task_cancel_true_positive(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import asyncio
            async def shutdown(job):
                task = asyncio.create_task(job())
                task.cancel()
            """}, "refusal_flow")
        assert _findings(r) == [("pkg/a.py", 4, "task.cancel")]
        assert "bpo-37658" in r["findings"][0]["message"]

    def test_task_cancel_drain_loop_and_non_task_clean(self, tmp_path):
        r = _run(tmp_path, {"pkg/a.py": """\
            import asyncio
            async def shutdown(job, timer):
                t = asyncio.create_task(job())
                while not t.done():
                    t.cancel()            # the bpo-37658 drain shape
                    try:
                        await t
                    except asyncio.CancelledError:
                        pass
                timer.cancel()            # not a task: fine
            def sync_stop(task):
                task.cancel()             # sync def: out of scope
            """}, "refusal_flow")
        assert r["findings"] == []


class TestCacheKeyCompleteness:
    CACHE_MOD = """\
        flags = {}
        def _compute(x):
            mode = flags.get("exact_mode")
            return (x, mode)
        class Engine:
            def __init__(self):
                self._cache = {}
            def run(self, x):
                key = ("k", x)
                if key not in self._cache:
                    self._cache[key] = _compute(x)
                return self._cache[key]
        """

    @staticmethod
    def _entry(**over):
        ent = {"key_builder": ("pkg/cachemod.py", "Engine.run"),
               "roots": [("pkg/cachemod.py", "Engine.run")],
               "key_helpers": [], "allow": {}, "must_mention": []}
        ent.update(over)
        return ent

    def _pass(self, **over):
        from analyze.passes.cache_key_completeness import (
            CacheKeyCompletenessPass)
        return CacheKeyCompletenessPass([self._entry(**over)])

    def test_flag_read_missing_from_key(self, tmp_path):
        # the PR-9 shape: the keyed computation reads a flag the key
        # never includes, one call below the key constructor
        r = _run_pass(tmp_path, {"pkg/cachemod.py": self.CACHE_MOD},
                      self._pass())
        assert _findings(r) == [("pkg/cachemod.py", 8,
                                 "Engine.run:exact_mode")]
        assert "_compute" in r["findings"][0]["message"]  # witness chain

    def test_flag_in_key_literal_is_clean(self, tmp_path):
        fixed = self.CACHE_MOD.replace(
            'key = ("k", x)',
            'key = ("k", x, flags.get("exact_mode"))')
        r = _run_pass(tmp_path, {"pkg/cachemod.py": fixed}, self._pass())
        assert r["findings"] == []

    def test_allow_reason_is_clean(self, tmp_path):
        r = _run_pass(
            tmp_path, {"pkg/cachemod.py": self.CACHE_MOD},
            self._pass(allow={"exact_mode": "rebuilt outside the "
                                            "cached lambda"}))
        assert r["findings"] == []

    def test_must_mention_lost_component(self, tmp_path):
        r = _run_pass(
            tmp_path, {"pkg/cachemod.py": self.CACHE_MOD},
            self._pass(allow={"exact_mode": "n/a"},
                       must_mention=[("prune_sig", "pruned identity")]))
        assert _findings(r) == [("pkg/cachemod.py", 8,
                                 "Engine.run:prune_sig")]

    def test_stale_registry_entry(self, tmp_path):
        r = _run_pass(
            tmp_path, {"pkg/cachemod.py": self.CACHE_MOD},
            self._pass(key_builder=("pkg/cachemod.py", "Engine.gone")))
        assert [d for _, _, d in _findings(r)] == [
            "pkg/cachemod.py::Engine.gone"]

    def test_real_registry_pins_known_constructors(self):
        # the registry is the contract: the known keyed caches stay
        # registered, and the PR-9 regression input stays pinned
        from analyze.passes.cache_key_completeness import REGISTRY
        quals = {e["key_builder"][1] for e in REGISTRY}
        assert {"DocReadOperation._batch_cache_key", "ScanKernel.run",
                "FusedPlanKernel.run"} <= quals
        batch = next(e for e in REGISTRY if e["key_builder"][1]
                     == "DocReadOperation._batch_cache_key")
        assert "device_float_dtype" in dict(batch["must_mention"])


class TestWireDrift:
    @staticmethod
    def _entry(**over):
        ent = {"dataclass": ("pkg/msg.py", "Ping"),
               "encode": ("pkg/msg.py", "ping_to_wire"),
               "decode": ("pkg/msg.py", "ping_from_wire"),
               "ignore": {}, "combined": {}}
        ent.update(over)
        return ent

    def _pass(self, **over):
        from analyze.passes.wire_drift import WireDriftPass
        return WireDriftPass([self._entry(**over)])

    def test_field_dropped_by_both_codecs(self, tmp_path):
        r = _run_pass(tmp_path, {"pkg/msg.py": """\
            from dataclasses import dataclass
            @dataclass
            class Ping:
                a: int
                b: int
                c: int = 0
            def ping_to_wire(p):
                return {"a": p.a, "b": p.b}
            def ping_from_wire(d):
                return Ping(a=d["a"], b=d["b"])
            """}, self._pass())
        assert sorted(d for _, _, d in _findings(r)) == [
            "Ping.c:decode", "Ping.c:encode"]

    def test_round_trip_and_positional_cover_clean(self, tmp_path):
        r = _run_pass(tmp_path, {"pkg/msg.py": """\
            from dataclasses import dataclass
            @dataclass
            class Ping:
                a: int
                b: int
                c: int = 0
            def ping_to_wire(p):
                return (p.a, p.b, p.c)
            def ping_from_wire(w):
                first, second, third = w
                return Ping(first, second, third)
            """}, self._pass())
        assert r["findings"] == []

    def test_ignore_reason_is_clean(self, tmp_path):
        r = _run_pass(tmp_path, {"pkg/msg.py": """\
            from dataclasses import dataclass
            @dataclass
            class Ping:
                a: int
                c: int = 0
            def ping_to_wire(p):
                return {"a": p.a}
            def ping_from_wire(d):
                return Ping(a=d["a"])
            """}, self._pass(ignore={"c": "server-local"}))
        assert r["findings"] == []

    def test_combiner_drops_partial_field(self, tmp_path):
        files = {"pkg/msg.py": """\
            from dataclasses import dataclass
            @dataclass
            class Ping:
                a: int
            def ping_to_wire(p):
                return {"a": p.a}
            def ping_from_wire(d):
                return Ping(a=d["a"])
            def combine(parts):
                return sum(p.b for p in parts)
            """}
        combined = {"a": [("pkg/msg.py", "combine")]}
        r = _run_pass(tmp_path, files, self._pass(combined=combined))
        assert _findings(r) == [("pkg/msg.py", 9, "Ping.a:combine")]

    def test_stale_registry_entry(self, tmp_path):
        r = _run_pass(tmp_path, {"pkg/msg.py": "x = 1\n"}, self._pass())
        assert [d for _, _, d in _findings(r)] == ["pkg/msg.py::Ping"]

    def test_real_registry_pins_known_wire_types(self):
        from analyze.passes.wire_drift import REGISTRY
        names = {e["dataclass"][1] for e in REGISTRY}
        assert {"ReadRequest", "ReadResponse", "WriteRequest", "RowOp",
                "ViewDef"} <= names
        req = next(e for e in REGISTRY
                   if e["dataclass"][1] == "ReadRequest")
        # server-assigned read point must never cross the wire
        assert "server_assigned_read_ht" in req["ignore"]
        resp = next(e for e in REGISTRY
                    if e["dataclass"][1] == "ReadResponse")
        assert set(resp["combined"]) >= {"agg_values", "group_counts",
                                         "group_values"}


class TestNumericExactness:
    def test_narrow_sum_true_positive(self, tmp_path):
        r = _run(tmp_path, {"pkg/k.py": """\
            import jax.numpy as jnp
            def f(col):
                x = col.astype(jnp.int32)
                return jnp.sum(x)
            """}, "numeric_exactness")
        assert _findings(r) == [("pkg/k.py", 4, "sum-dtype")]

    def test_float_accumulator_true_positive(self, tmp_path):
        r = _run(tmp_path, {"pkg/k.py": """\
            import jax.numpy as jnp
            def g(mask):
                m = mask.astype(jnp.int32)
                fm = m.astype(jnp.float32)
                return jnp.sum(fm)
            """}, "numeric_exactness")
        assert _findings(r) == [("pkg/k.py", 5, "float-accumulator")]

    def test_exact_accumulators_clean(self, tmp_path):
        r = _run(tmp_path, {"pkg/k.py": """\
            import jax.numpy as jnp
            def f(col):
                x = col.astype(jnp.int32)
                a = jnp.sum(x, dtype=jnp.int64)   # explicit widen
                y = col.astype(jnp.int64)
                b = jnp.sum(y)                    # already wide
                return a + b
            """}, "numeric_exactness")
        assert r["findings"] == []

    def test_zone_envelope_rule(self, tmp_path):
        r = _run(tmp_path, {
            "pkg/consumer.py": """\
                def prune(block, lo):
                    return block.zmap[0] >= lo
                """,
            "pkg/ops/scan.py": """\
                def _f32_widen(block):
                    return block.zmap          # envelope impl: allowed
                """}, "numeric_exactness")
        assert _findings(r) == [("pkg/consumer.py", 2, "zone-envelope")]

    def test_consts_offset_regression(self, tmp_path):
        # the PR-12 shape: second compile_expr in the same def without
        # offset= re-reads the first expression's constant table
        r = _run(tmp_path, {"pkg/p.py": """\
            from pkg.expr import compile_expr
            def plan(e1, e2):
                a = compile_expr(e1)
                b = compile_expr(e2)
                return a, b
            def fixed(e1, e2):
                a, n = compile_expr(e1)
                b, _ = compile_expr(e2, offset=n)
                return a, b
            """, "pkg/expr.py": "def compile_expr(e, offset=0):\n"
                                "    return e, offset\n"},
            "numeric_exactness")
        assert _findings(r) == [("pkg/p.py", 4, "consts-offset")]

    def test_suppressed_with_reason(self, tmp_path):
        r = _run(tmp_path, {"pkg/k.py": """\
            import jax.numpy as jnp
            def f(col):
                x = col.astype(jnp.int32)
                # analysis-ok(numeric_exactness): block-local partial
                return jnp.sum(x)
            """}, "numeric_exactness")
        assert r["findings"] == []
        assert r["suppressions"]["numeric_exactness"] == 1


# --- 2 + 3. whole tree, schema, budget, baseline ---------------------------

@pytest.fixture(scope="module")
def tree_report():
    index = ProjectIndex(HERE)
    return run_analysis(index, ALL_PASSES)


def test_whole_tree_zero_unannotated_findings(tree_report):
    assert tree_report["parse_errors"] == [], tree_report["parse_errors"]
    assert tree_report["findings"] == [], (
        "unannotated static-analysis findings — fix them or annotate "
        "with `# analysis-ok(<pass>): <reason>`:\n" + "\n".join(
            f"{f['path']}:{f['line']}: [{f['pass']}] {f['message']}"
            for f in tree_report["findings"]))


def test_all_passes_ran(tree_report):
    assert [p["id"] for p in tree_report["passes"]] == [
        "async_blocking", "lock_held_await", "jit_hazards",
        "flag_drift", "shared_state_races", "unawaited_coroutine",
        "format_gate", "layering", "lock_order", "resource_balance",
        "trace_discipline", "refusal_flow", "cache_key_completeness",
        "wire_drift", "numeric_exactness"]


def test_wall_time_budget(tree_report):
    # r05 carry-over hygiene: the sweep must not bloat tier-1
    assert tree_report["wall_ms"] < WALL_BUDGET_MS, tree_report["passes"]
    for p in tree_report["passes"]:
        assert p["wall_ms"] >= 0.0


def test_suppressions_do_not_exceed_baseline(tree_report):
    with open(os.path.join(HERE, "tools", "analyze",
                           "baseline.json")) as f:
        baseline = json.load(f)["suppressions"]
    for pass_id, n in tree_report["suppressions"].items():
        assert n <= baseline.get(pass_id, 0), (
            f"suppression count for {pass_id} grew to {n} vs committed "
            f"baseline {baseline.get(pass_id, 0)} — fix the hazard or "
            f"bump tools/analyze/baseline.json deliberately")


def test_run_py_json_schema():
    r = subprocess.run(
        [sys.executable, os.path.join(HERE, "tools", "analyze", "run.py"),
         "--json"], capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    report = json.loads(r.stdout)
    for key in ("passes", "findings", "suppressions", "total_findings",
                "total_suppressed", "wall_ms", "parse_errors"):
        assert key in report, key
    assert report["total_findings"] == 0
    assert set(report["suppressions"]) == {p.id for p in ALL_PASSES}
    for p in report["passes"]:
        assert {"id", "title", "findings", "suppressed",
                "wall_ms"} <= set(p)


def test_run_py_changed_mode(tmp_path):
    """--changed <range>: whole-tree index, findings gated to the
    changed files — the CI / pre-push incremental contract."""
    pkg = tmp_path / "yugabyte_db_tpu"
    pkg.mkdir()
    (pkg / "clean.py").write_text("def ok():\n    return 1\n")
    env = {**os.environ, "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
           "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"}

    def git(*a):
        subprocess.run(["git", *a], cwd=tmp_path, check=True, env=env,
                       capture_output=True)

    git("init", "-q", ".")
    git("add", "-A")
    git("commit", "-qm", "seed")
    # a NEW hazard lands in the tree (git add so the range diff sees
    # the untracked file); clean.py is untouched
    (pkg / "bad.py").write_text(
        "import time\nasync def h():\n    time.sleep(1)\n")
    git("add", "-A")
    run_py = os.path.join(HERE, "tools", "analyze", "run.py")
    r = subprocess.run(
        [sys.executable, run_py, "--base", str(tmp_path),
         "--changed", "HEAD", "--json", "--no-cache"],
        capture_output=True, text=True)
    assert r.returncode == 1, r.stdout + r.stderr
    report = json.loads(r.stdout)
    assert {f["path"] for f in report["findings"]} == \
        {"yugabyte_db_tpu/bad.py"}
    # an unresolvable range is a hard error, not a silent full run
    r2 = subprocess.run(
        [sys.executable, run_py, "--base", str(tmp_path),
         "--changed", "no-such-ref..HEAD", "--no-cache"],
        capture_output=True, text=True)
    assert r2.returncode == 2, r2.stdout + r2.stderr
    # nothing changed in range => trivially clean exit
    git("add", "-A")
    git("commit", "-qm", "hazard (committed so the range is empty)")
    r3 = subprocess.run(
        [sys.executable, run_py, "--base", str(tmp_path),
         "--changed", "HEAD", "--no-cache"],
        capture_output=True, text=True)
    assert r3.returncode == 0, r3.stdout + r3.stderr


def test_run_py_exits_nonzero_on_findings(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "bad.py").write_text(
        "import time\nasync def h():\n    time.sleep(1)\n")
    r = subprocess.run(
        [sys.executable, os.path.join(HERE, "tools", "analyze", "run.py"),
         "--base", str(tmp_path), "--pass", "async_blocking", "pkg"],
        capture_output=True, text=True)
    assert r.returncode == 1, r.stdout
    assert "time.sleep" in r.stdout


def test_run_py_sarif_contract(tmp_path):
    """--sarif writes a one-run SARIF 2.1.0 log: pass ids as rule ids,
    findings as level=error results anchored at path:line."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "bad.py").write_text(
        "import time\nasync def h():\n    time.sleep(1)\n")
    out = tmp_path / "r.sarif"
    r = subprocess.run(
        [sys.executable, os.path.join(HERE, "tools", "analyze", "run.py"),
         "--base", str(tmp_path), "--pass", "async_blocking",
         "--sarif", str(out), "pkg"],
        capture_output=True, text=True)
    assert r.returncode == 1, r.stdout + r.stderr   # exit unchanged
    log = json.loads(out.read_text())
    assert log["version"] == "2.1.0"
    run0 = log["runs"][0]
    rules = run0["tool"]["driver"]["rules"]
    assert [rl["id"] for rl in rules] == ["async_blocking"]
    assert rules[0]["help"]["text"]          # the pass hint
    results = run0["results"]
    assert len(results) == 1
    res = results[0]
    assert res["ruleId"] == "async_blocking"
    assert rules[res["ruleIndex"]]["id"] == res["ruleId"]
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "pkg/bad.py"
    assert loc["region"]["startLine"] == 3
    assert "time.sleep" in res["message"]["text"]
