"""SQL layer tests: parser + end-to-end SQL over a MiniCluster
(reference analog: PG regress-style coverage at mini scale,
java/yb-pgsql BasePgSQLTest)."""
import asyncio

import pytest

from yugabyte_db_tpu.ql import SqlSession, parse_statement
from yugabyte_db_tpu.ql.parser import (
    CreateTableStmt, InsertStmt, SelectStmt,
)
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster


class TestParser:
    def test_create_table(self):
        s = parse_statement(
            "CREATE TABLE t (k bigint, v double, s text, "
            "PRIMARY KEY (k)) WITH tablets = 4 WITH replication = 3")
        assert isinstance(s, CreateTableStmt)
        assert s.columns == [("k", "bigint"), ("v", "double"),
                             ("s", "text")]
        assert s.primary_key == ["k"]
        assert s.num_tablets == 4 and s.replication_factor == 3

    def test_insert_multirow(self):
        s = parse_statement(
            "INSERT INTO t (k, v) VALUES (1, 2.5), (2, -3.5), (3, NULL)")
        assert isinstance(s, InsertStmt)
        assert s.rows == [[1, 2.5], [2, -3.5], [3, None]]

    def test_select_full(self):
        s = parse_statement(
            "SELECT sum(v * (1 - d)) AS rev, count(*), k FROM t "
            "WHERE v < 10 AND d BETWEEN 0.05 AND 0.07 OR NOT k IN (1,2) "
            "GROUP BY k ORDER BY k DESC LIMIT 5")
        assert isinstance(s, SelectStmt)
        assert s.items[0][0] == "agg" and s.items[0][1] == "sum"
        assert s.items[1] == ("agg", "count", None)
        assert s.group_by == ["k"]
        assert s.order_by == [("k", True)]
        assert s.limit == 5

    def test_string_literals_and_escapes(self):
        s = parse_statement("INSERT INTO t (s) VALUES ('it''s')")
        assert s.rows == [["it's"]]

    def test_errors(self):
        with pytest.raises(ValueError):
            parse_statement("CREATE TABLE t (k bigint)")  # no PK
        with pytest.raises(ValueError):
            parse_statement("SELEC * FROM t")


def run(coro):
    return asyncio.run(coro)


@pytest.fixture
def cluster(tmp_path):
    return str(tmp_path)


async def _session(root, n=1):
    mc = await MiniCluster(root, num_tservers=n).start()
    return mc, SqlSession(mc.client())


class TestSqlEndToEnd:
    def test_ddl_dml_select(self, cluster):
        async def go():
            mc, s = await _session(cluster)
            try:
                await s.execute(
                    "CREATE TABLE items (k bigint, qty double, price double,"
                    " flag int, name text, PRIMARY KEY (k)) WITH tablets = 2")
                await mc.wait_for_leaders("items")
                await s.execute(
                    "INSERT INTO items (k, qty, price, flag, name) VALUES "
                    + ", ".join(f"({i}, {i * 0.5}, {100 - i}, {i % 3}, "
                                f"'n{i}')" for i in range(30)))
                r = await s.execute("SELECT * FROM items WHERE k = 7")
                assert r.rows[0]["name"] == "n7"
                r = await s.execute(
                    "SELECT k, qty FROM items WHERE qty > 10 "
                    "ORDER BY k LIMIT 4")
                assert [row["k"] for row in r.rows] == [21, 22, 23, 24]
                r = await s.execute(
                    "SELECT sum(qty * price) AS x, count(*), avg(qty) "
                    "FROM items WHERE flag = 1")
                expect = sum(i * 0.5 * (100 - i) for i in range(30)
                             if i % 3 == 1)
                assert abs(r.rows[0]["x"] - expect) < 1e-6   # AS alias
                assert r.rows[0]["count"] == 10
            finally:
                await mc.shutdown()
        run(go())

    def test_group_by_clientside_and_pushdown(self, cluster):
        async def go():
            mc, s = await _session(cluster)
            try:
                await s.execute(
                    "CREATE TABLE g (k bigint, v double, f int, "
                    "PRIMARY KEY (k))")
                await mc.wait_for_leaders("g")
                await s.execute(
                    "INSERT INTO g (k, v, f) VALUES "
                    + ", ".join(f"({i}, {float(i)}, {i % 4})"
                                for i in range(40)))
                r1 = await s.execute(
                    "SELECT f, sum(v), count(*) FROM g GROUP BY f "
                    "ORDER BY f")
                assert len(r1.rows) == 4
                assert r1.rows[0]["sum_v"] == sum(range(0, 40, 4))
                # now declare stats → device-eligible pushdown path
                s.stats["g"] = {"f": (4, 0)}
                r2 = await s.execute(
                    "SELECT f, sum(v), count(*) FROM g GROUP BY f "
                    "ORDER BY f")
                for a, b in zip(r1.rows, r2.rows):
                    assert a["f"] == b["f"]
                    assert abs(a["sum_v"] - b["sum_v"]) < 1e-6
                    assert a["count"] == b["count"]
            finally:
                await mc.shutdown()
        run(go())

    def test_update_delete(self, cluster):
        async def go():
            mc, s = await _session(cluster)
            try:
                await s.execute(
                    "CREATE TABLE u (k bigint, v double, PRIMARY KEY (k))")
                await mc.wait_for_leaders("u")
                await s.execute("INSERT INTO u (k, v) VALUES (1, 1), (2, 2),"
                                " (3, 3)")
                await s.execute("UPDATE u SET v = 99 WHERE k = 2")
                r = await s.execute("SELECT v FROM u WHERE k = 2")
                assert r.rows[0]["v"] == 99.0
                await s.execute("DELETE FROM u WHERE v < 2")
                r = await s.execute("SELECT count(*) FROM u")
                assert r.rows[0]["count"] == 2
            finally:
                await mc.shutdown()
        run(go())


class TestJson:
    def test_json_operators(self, cluster):
        async def go():
            mc, s = await _session(cluster)
            try:
                await s.execute(
                    "CREATE TABLE docs (k bigint, data jsonb, "
                    "PRIMARY KEY (k))")
                await mc.wait_for_leaders("docs")
                await s.execute(
                    "INSERT INTO docs (k, data) VALUES "
                    "(1, '{\"name\": \"ada\", \"age\": 36}'), "
                    "(2, '{\"name\": \"bob\", \"age\": 41}'), "
                    "(3, '{\"name\": \"cyd\"}')")
                r = await s.execute(
                    "SELECT k FROM docs WHERE data ->> 'name' = 'bob'")
                assert [row["k"] for row in r.rows] == [2]
                # ->> on a missing key is NULL -> row filtered out
                r = await s.execute(
                    "SELECT k FROM docs WHERE data ->> 'age' = '36'")
                assert [row["k"] for row in r.rows] == [1]
                r = await s.execute(
                    "SELECT data ->> 'name' FROM docs WHERE k = 3")
                assert r.rows[0]["expr"] == "cyd"
            finally:
                await mc.shutdown()
        run(go())


class TestSqlBreadth:
    def test_like_distinct_offset(self, cluster):
        async def go():
            mc, s = await _session(cluster)
            try:
                await s.execute(
                    "CREATE TABLE w (k bigint, name text, grp int, "
                    "PRIMARY KEY (k))")
                await mc.wait_for_leaders("w")
                await s.execute(
                    "INSERT INTO w (k, name, grp) VALUES "
                    "(1, 'alpha', 1), (2, 'beta', 1), (3, 'alpine', 2), "
                    "(4, 'gamma', 2), (5, 'beta', 1)")
                r = await s.execute(
                    "SELECT k FROM w WHERE name LIKE 'al%' ORDER BY k")
                assert [x["k"] for x in r.rows] == [1, 3]
                r = await s.execute(
                    "SELECT k FROM w WHERE name LIKE '_eta' ORDER BY k")
                assert [x["k"] for x in r.rows] == [2, 5]
                r = await s.execute("SELECT DISTINCT name FROM w "
                                    "ORDER BY name")
                assert [x["name"] for x in r.rows] == \
                    ["alpha", "alpine", "beta", "gamma"]
                r = await s.execute(
                    "SELECT k FROM w ORDER BY k LIMIT 2 OFFSET 2")
                assert [x["k"] for x in r.rows] == [3, 4]
            finally:
                await mc.shutdown()
        run(go())


class TestAlterTable:
    def test_add_column_online(self, cluster):
        async def go():
            mc, s = await _session(cluster)
            try:
                await s.execute("CREATE TABLE at (k bigint, v double, "
                                "PRIMARY KEY (k)) WITH tablets = 2")
                await mc.wait_for_leaders("at")
                await s.execute("INSERT INTO at (k, v) VALUES (1, 1), (2, 2)")
                r = await s.execute(
                    "ALTER TABLE at ADD COLUMN note text, ADD COLUMN n int")
                assert "v2" in r.status
                # old rows read with NULL in the new column
                s2 = SqlSession(mc.client())
                r = await s2.execute("SELECT k, note FROM at ORDER BY k")
                assert r.rows[0]["note"] is None
                # new writes carry the new column; mixed versions coexist
                await s2.execute(
                    "INSERT INTO at (k, v, note, n) VALUES (3, 3, 'hi', 7)")
                r = await s2.execute("SELECT note, n FROM at WHERE k = 3")
                assert r.rows[0]["note"] == "hi" and r.rows[0]["n"] == 7
                r = await s2.execute("SELECT count(*) FROM at")
                assert r.rows[0]["count"] == 3
                # survives restart (schema persisted in tablet meta)
                await mc.restart_tserver(0)
                await mc.wait_for_leaders("at")
                s3 = SqlSession(mc.client())
                r = await s3.execute("SELECT note FROM at WHERE k = 3")
                assert r.rows[0]["note"] == "hi"
            finally:
                await mc.shutdown()
        run(go())


class TestLimitNoOrder:
    def test_limit_without_order_by(self, cluster):
        """Regression: LIMIT without ORDER BY goes through the client
        paging path (found as a positional-arg bug in review)."""
        async def go():
            mc, s = await _session(cluster)
            try:
                await s.execute("CREATE TABLE ln (k bigint, v double, "
                                "PRIMARY KEY (k)) WITH tablets = 2")
                await mc.wait_for_leaders("ln")
                await s.execute("INSERT INTO ln (k, v) VALUES "
                                + ", ".join(f"({i}, {i})"
                                            for i in range(30)))
                r = await s.execute("SELECT k FROM ln LIMIT 7")
                assert len(r.rows) == 7
                # transactional snapshot scan with limit
                await s.execute("BEGIN")
                r = await s.execute("SELECT k FROM ln LIMIT 5")
                assert len(r.rows) == 5
                await s.execute("ROLLBACK")
            finally:
                await mc.shutdown()
        run(go())


class TestRangeSharding:
    def test_range_table_ordered_scans(self, cluster):
        async def go():
            mc, s = await _session(cluster)
            try:
                await s.execute(
                    "CREATE TABLE events (ts bigint, name text, "
                    "PRIMARY KEY (ts ASC)) WITH tablets = 1")
                await mc.wait_for_leaders("events")
                import random
                ks = list(range(20))
                random.Random(3).shuffle(ks)
                for k in ks:
                    await s.execute(
                        f"INSERT INTO events (ts, name) VALUES ({k}, 'e{k}')")
                # rows come back in range-key order without ORDER BY
                r = await s.execute("SELECT ts FROM events")
                assert [x["ts"] for x in r.rows] == sorted(ks)
                r = await s.execute(
                    "SELECT ts FROM events WHERE ts BETWEEN 5 AND 8")
                assert [x["ts"] for x in r.rows] == [5, 6, 7, 8]
                assert (await s.execute(
                    "SELECT name FROM events WHERE ts = 7")
                    ).rows[0]["name"] == "e7"
            finally:
                await mc.shutdown()
        run(go())

    def test_range_split_points_client(self, cluster):
        async def go():
            from yugabyte_db_tpu.docdb.table_codec import TableInfo
            from yugabyte_db_tpu.dockv.packed_row import (
                ColumnSchema, ColumnType, TableSchema)
            from yugabyte_db_tpu.dockv.partition import PartitionSchema
            mc, s = await _session(cluster)
            try:
                c = mc.client()
                info = TableInfo("", "rt", TableSchema((
                    ColumnSchema(0, "k", ColumnType.INT64,
                                 is_range_key=True),
                    ColumnSchema(1, "v", ColumnType.FLOAT64)), 1),
                    PartitionSchema("range", 0))
                await c.create_table(info, split_rows=[{"k": 100}])
                await mc.wait_for_leaders("rt")
                ct = await c._table("rt")
                assert len(ct.locations) == 2
                await c.insert("rt", [{"k": 5, "v": 1.0},
                                      {"k": 200, "v": 2.0}])
                assert (await c.get("rt", {"k": 5}))["v"] == 1.0
                assert (await c.get("rt", {"k": 200}))["v"] == 2.0
                # rows landed on different tablets
                counts = [sum(1 for _ in p.tablet.regular.iterate())
                          for ts in mc.tservers
                          for p in ts.peers.values()
                          if p.tablet.info.name == "rt"]
                assert sorted(counts) == [1, 1]
            finally:
                await mc.shutdown()
        run(go())


class TestJoins:
    def test_inner_and_left_join(self, cluster):
        async def go():
            mc, s = await _session(cluster)
            try:
                await s.execute("CREATE TABLE customers (id bigint, "
                                "name text, PRIMARY KEY (id))")
                await s.execute("CREATE TABLE orders2 (oid bigint, cust "
                                "bigint, total double, PRIMARY KEY (oid))")
                await mc.wait_for_leaders("customers")
                await mc.wait_for_leaders("orders2")
                await s.execute("INSERT INTO customers (id, name) VALUES "
                                "(1, 'ada'), (2, 'bob'), (3, 'cyd')")
                await s.execute(
                    "INSERT INTO orders2 (oid, cust, total) VALUES "
                    "(10, 1, 5.0), (11, 1, 7.0), (12, 2, 3.0)")
                r = await s.execute(
                    "SELECT name, total FROM customers "
                    "JOIN orders2 ON customers.id = orders2.cust "
                    "ORDER BY total")
                assert [(x["name"], x["total"]) for x in r.rows] == \
                    [("bob", 3.0), ("ada", 5.0), ("ada", 7.0)]
                # residual WHERE on the joined row
                r = await s.execute(
                    "SELECT name FROM customers "
                    "JOIN orders2 ON customers.id = orders2.cust "
                    "WHERE total > 4")
                assert sorted(x["name"] for x in r.rows) == ["ada", "ada"]
                # LEFT JOIN keeps unmatched customers
                r = await s.execute(
                    "SELECT name, total FROM customers "
                    "LEFT JOIN orders2 ON customers.id = orders2.cust "
                    "ORDER BY name")
                names = [x["name"] for x in r.rows]
                assert names.count("cyd") == 1
                cyd = next(x for x in r.rows if x["name"] == "cyd")
                assert cyd["total"] is None
            finally:
                await mc.shutdown()
        run(go())


class TestScanBounds:
    def test_range_table_bounded_scan(self, cluster):
        """Range predicates on a range-PK table become seek bounds —
        verified via the metrics-free observable: correctness + the
        bounded iterator not visiting out-of-range keys (checked through
        a wrapped store)."""
        async def go():
            mc, s = await _session(cluster)
            try:
                await s.execute("CREATE TABLE b (ts bigint, v double, "
                                "PRIMARY KEY (ts ASC)) WITH tablets = 1")
                await mc.wait_for_leaders("b")
                await s.execute("INSERT INTO b (ts, v) VALUES "
                                + ", ".join(f"({i}, {i})"
                                            for i in range(100)))
                peer = next(p for ts in mc.tservers
                            for p in ts.peers.values()
                            if p.tablet.info.name == "b")
                store = peer.tablet.regular
                visited = []
                orig = store.iterate

                def spy(lower=None, upper=None):
                    visited.append((lower, upper))
                    return orig(lower=lower, upper=upper)

                store.iterate = spy
                r = await s.execute(
                    "SELECT ts FROM b WHERE ts >= 40 AND ts <= 44")
                assert [x["ts"] for x in r.rows] == [40, 41, 42, 43, 44]
                # the scan passed real bounds, not a full-table sweep
                lo, hi = visited[-1]
                assert lo is not None and hi is not None
                r = await s.execute(
                    "SELECT ts FROM b WHERE ts BETWEEN 90 AND 200")
                assert [x["ts"] for x in r.rows] == list(range(90, 100))
                # mixed predicate: bound + residual
                r = await s.execute(
                    "SELECT ts FROM b WHERE ts < 10 AND v > 5")
                assert [x["ts"] for x in r.rows] == [6, 7, 8, 9]
            finally:
                await mc.shutdown()
        run(go())


class TestSqlSerializable:
    def test_sql_write_skew_blocked(self, tmp_path):
        """Two SQL sessions in SERIALIZABLE: each SELECTs both rows then
        UPDATEs the other one. At most one may commit (the SELECT read
        set takes row locks; see executor _select)."""
        async def go():
            from yugabyte_db_tpu.ql import SqlSession
            from yugabyte_db_tpu.rpc import RpcError
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s0 = SqlSession(mc.client())
                await s0.execute("CREATE TABLE oncall (k bigint, "
                                 "on_duty bigint, PRIMARY KEY (k))")
                await mc.wait_for_leaders("oncall")
                await s0.execute(
                    "INSERT INTO oncall (k, on_duty) VALUES (1, 1), (2, 1)")
                await mc.master.rpc_get_status_tablet({})
                await mc.wait_for_leaders("system.transactions")
                a = SqlSession(mc.client())
                b = SqlSession(mc.client())
                for s in (a, b):
                    await s.execute("BEGIN ISOLATION LEVEL SERIALIZABLE")
                outcomes = []

                async def step(sess, tag, me, other):
                    try:
                        r = await sess.execute(
                            f"SELECT on_duty FROM oncall WHERE k = {me} "
                            f"OR k = {other}")
                        assert len(r.rows) == 2
                        await sess.execute(
                            f"UPDATE oncall SET on_duty = 0 WHERE "
                            f"k = {me}")
                        await sess.execute("COMMIT")
                        outcomes.append(f"{tag}-committed")
                    except (RpcError, Exception) as e:   # noqa: BLE001
                        outcomes.append(f"{tag}-failed")
                        try:
                            await sess.execute("ROLLBACK")
                        except Exception:
                            pass

                await asyncio.gather(step(a, "a", 1, 2), step(b, "b", 2, 1))
                committed = [o for o in outcomes if o.endswith("committed")]
                assert len(committed) <= 1, outcomes
                # invariant: at least one on-call remains
                r = await s0.execute(
                    "SELECT sum(on_duty) AS total FROM oncall")
                assert list(r.rows[0].values())[0] >= 1, (outcomes, r.rows)
            finally:
                await mc.shutdown()
        run(go())

    def test_sql_write_skew_blocked_with_aggregate_read(self, tmp_path):
        """Same skew but the read is SELECT sum(...) — the aggregate
        branch must lock its read set too (it scans pk rows first)."""
        async def go():
            from yugabyte_db_tpu.ql import SqlSession
            from yugabyte_db_tpu.rpc import RpcError
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s0 = SqlSession(mc.client())
                await s0.execute("CREATE TABLE oncall (k bigint, "
                                 "on_duty bigint, PRIMARY KEY (k))")
                await mc.wait_for_leaders("oncall")
                await s0.execute(
                    "INSERT INTO oncall (k, on_duty) VALUES (1, 1), (2, 1)")
                await mc.master.rpc_get_status_tablet({})
                await mc.wait_for_leaders("system.transactions")
                a = SqlSession(mc.client())
                b = SqlSession(mc.client())
                for s in (a, b):
                    await s.execute("BEGIN ISOLATION LEVEL SERIALIZABLE")
                outcomes = []

                async def step(sess, tag, me):
                    try:
                        r = await sess.execute(
                            "SELECT sum(on_duty) AS total FROM oncall")
                        assert list(r.rows[0].values())[0] == 2
                        await sess.execute(
                            f"UPDATE oncall SET on_duty = 0 WHERE "
                            f"k = {me}")
                        await sess.execute("COMMIT")
                        outcomes.append(f"{tag}-committed")
                    except Exception:   # noqa: BLE001
                        outcomes.append(f"{tag}-failed")
                        try:
                            await sess.execute("ROLLBACK")
                        except Exception:
                            pass

                await asyncio.gather(step(a, "a", 1), step(b, "b", 2))
                committed = [o for o in outcomes if o.endswith("committed")]
                assert len(committed) <= 1, outcomes
                r = await s0.execute(
                    "SELECT sum(on_duty) AS total FROM oncall")
                assert list(r.rows[0].values())[0] >= 1, (outcomes, r.rows)
            finally:
                await mc.shutdown()
        run(go())


class TestHaving:
    def test_having_filters_groups(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.ql import SqlSession
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute("CREATE TABLE sales (k bigint, region "
                                "bigint, amt double, PRIMARY KEY (k))")
                await mc.wait_for_leaders("sales")
                rows = []
                for i in range(30):
                    rows.append(f"({i}, {i % 3}, {float(i)})")
                await s.execute("INSERT INTO sales (k, region, amt) "
                                f"VALUES {', '.join(rows)}")
                r = await s.execute(
                    "SELECT region, sum(amt) FROM sales GROUP BY region "
                    "HAVING sum(amt) > 140 ORDER BY region")
                # region sums: 0->135, 1->145, 2->155
                assert [row["region"] for row in r.rows] == [1, 2]
                r = await s.execute(
                    "SELECT region, count(*) FROM sales GROUP BY region "
                    "HAVING count(*) >= 10 AND region < 2")
                assert sorted(row["region"] for row in r.rows) == [0, 1]
                # HAVING without aggregates errors out cleanly
                with pytest.raises(ValueError):
                    await s.execute("SELECT k FROM sales HAVING k > 1")
            finally:
                await mc.shutdown()
        run(go())


class TestHavingEdgeCases:
    def test_unprojected_and_ungrouped_having(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.ql import SqlSession
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute("CREATE TABLE hx (k bigint, region bigint, "
                                "amt double, PRIMARY KEY (k))")
                await mc.wait_for_leaders("hx")
                await s.execute(
                    "INSERT INTO hx (k, region, amt) VALUES "
                    "(1, 0, 10), (2, 0, 20), (3, 1, 100), (4, 1, 200)")
                # HAVING aggregate NOT in the projection
                r = await s.execute(
                    "SELECT region FROM hx GROUP BY region "
                    "HAVING sum(amt) > 50")
                assert [row["region"] for row in r.rows] == [1]
                assert all("__h0" not in row for row in r.rows)
                # HAVING avg (two-slot expansion) not projected
                r = await s.execute(
                    "SELECT region, count(*) FROM hx GROUP BY region "
                    "HAVING avg(amt) >= 150")
                assert [row["region"] for row in r.rows] == [1]
                # ungrouped aggregate select with HAVING (implicit group)
                r = await s.execute(
                    "SELECT count(*) FROM hx HAVING count(*) > 10")
                assert r.rows == []
                r = await s.execute(
                    "SELECT count(*) FROM hx HAVING sum(amt) > 100")
                assert r.rows[0]["count"] == 4
                # invalid: sum(*) / HAVING without aggregates
                with pytest.raises(Exception):
                    await s.execute("SELECT region, count(*) FROM hx "
                                    "GROUP BY region HAVING sum(*) > 5")
                with pytest.raises(Exception):
                    await s.execute("SELECT k FROM hx HAVING k > 1")
            finally:
                await mc.shutdown()
        run(go())


class TestExplain:
    def test_explain_reports_routing(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.ql import SqlSession
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute("CREATE TABLE ex (k bigint, g bigint, "
                                "v double, PRIMARY KEY (k))")
                await mc.wait_for_leaders("ex")
                r = await s.execute("EXPLAIN SELECT count(*) FROM ex "
                                    "WHERE v > 1")
                text = "\n".join(row["QUERY PLAN"] for row in r.rows)
                assert "Aggregate" in text and "Filter" in text
                assert r.status == "EXPLAIN"
                # device group pushdown is reported when stats exist
                s.stats["ex"] = {"g": (4, 0)}
                r = await s.execute("EXPLAIN SELECT g, sum(v) FROM ex "
                                    "GROUP BY g")
                text = "\n".join(row["QUERY PLAN"] for row in r.rows)
                assert "DEVICE pushdown" in text
                # plain scan
                r = await s.execute("EXPLAIN SELECT k FROM ex LIMIT 2")
                text = "\n".join(row["QUERY PLAN"] for row in r.rows)
                assert "Seq Scan" in text and "pushed down" in text
                # aggregate with indexed predicate: EXPLAIN must NOT
                # claim an index lookup (the executor aggregates)
                await s.execute("CREATE INDEX exg ON ex (g)")
                await mc.wait_for_leaders("exg")
                r = await s.execute("EXPLAIN SELECT count(*) FROM ex "
                                    "WHERE g = 1")
                text = "\n".join(row["QUERY PLAN"] for row in r.rows)
                assert "Aggregate" in text and "Index" not in text
                # ...but a plain select DOES use the index
                r = await s.execute("EXPLAIN SELECT k FROM ex "
                                    "WHERE g = 1")
                text = "\n".join(row["QUERY PLAN"] for row in r.rows)
                assert "Index Lookup" in text
                # GROUP BY with HAVING-only aggregates is a grouped plan
                r = await s.execute("EXPLAIN SELECT g FROM ex GROUP BY g "
                                    "HAVING count(*) > 1")
                text = "\n".join(row["QUERY PLAN"] for row in r.rows)
                assert "Grouped Aggregate" in text
                # EXPLAIN does not execute: no rows were touched
                r = await s.execute("SELECT count(*) FROM ex")
                assert r.rows[0]["count"] == 0
            finally:
                await mc.shutdown()
        run(go())


class TestInSubquery:
    def test_semi_join_in_select_update_delete(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.ql import SqlSession
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute("CREATE TABLE users (id bigint, region "
                                "bigint, PRIMARY KEY (id))")
                await s.execute("CREATE TABLE orders2 (oid bigint, uid "
                                "bigint, amt double, PRIMARY KEY (oid))")
                await mc.wait_for_leaders("users")
                await mc.wait_for_leaders("orders2")
                await s.execute("INSERT INTO users (id, region) VALUES "
                                "(1, 0), (2, 1), (3, 0), (4, 1)")
                await s.execute(
                    "INSERT INTO orders2 (oid, uid, amt) VALUES "
                    "(10, 1, 5.0), (11, 2, 6.0), (12, 2, 7.0), "
                    "(13, 9, 8.0)")
                r = await s.execute(
                    "SELECT oid FROM orders2 WHERE uid IN "
                    "(SELECT id FROM users WHERE region = 1) "
                    "ORDER BY oid")
                assert [x["oid"] for x in r.rows] == [11, 12]
                # nested in a larger predicate
                r = await s.execute(
                    "SELECT oid FROM orders2 WHERE amt > 5.5 AND uid IN "
                    "(SELECT id FROM users WHERE region = 1)")
                assert sorted(x["oid"] for x in r.rows) == [11, 12]
                # UPDATE/DELETE through the same resolution
                await s.execute(
                    "UPDATE orders2 SET amt = 0 WHERE uid IN "
                    "(SELECT id FROM users WHERE region = 0)")
                r = await s.execute("SELECT amt FROM orders2 WHERE oid = 10")
                assert r.rows[0]["amt"] == 0.0
                await s.execute(
                    "DELETE FROM orders2 WHERE uid IN "
                    "(SELECT id FROM users WHERE region = 1)")
                r = await s.execute("SELECT count(*) FROM orders2")
                assert r.rows[0]["count"] == 2
                # empty subquery result matches nothing
                r = await s.execute(
                    "SELECT oid FROM orders2 WHERE uid IN "
                    "(SELECT id FROM users WHERE region = 99)")
                assert r.rows == []
                # multi-column subquery rejected (even on empty tables)
                with pytest.raises(Exception):
                    await s.execute(
                        "SELECT oid FROM orders2 WHERE uid IN "
                        "(SELECT id, region FROM users WHERE region = 77)")
                # SQL three-valued NOT IN: a NULL in the subquery result
                # makes every NOT IN row UNKNOWN -> zero rows
                await s.execute("ALTER TABLE users ADD COLUMN alt bigint")
                await s.execute(
                    "INSERT INTO users (id, region, alt) VALUES (9, 5, 2)")
                r = await s.execute(
                    "SELECT oid FROM orders2 WHERE NOT uid IN "
                    "(SELECT alt FROM users)")   # alt NULL for old rows
                assert r.rows == []
            finally:
                await mc.shutdown()
        run(go())


class TestAliases:
    def test_as_renames_projection(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.ql import SqlSession
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute("CREATE TABLE al (k bigint, v double, "
                                "PRIMARY KEY (k))")
                await mc.wait_for_leaders("al")
                await s.execute("INSERT INTO al (k, v) VALUES (1, 2.0), "
                                "(2, 4.0)")
                r = await s.execute("SELECT k AS id, v AS price FROM al "
                                    "ORDER BY k")
                assert r.rows[0] == {"id": 1, "price": 2.0}
                r = await s.execute(
                    "SELECT sum(v) AS total, count(*) AS n FROM al")
                assert r.rows[0] == {"total": 6.0, "n": 2}
                r = await s.execute(
                    "SELECT k, avg(v) AS m FROM al GROUP BY k "
                    "HAVING avg(v) > 3")
                assert r.rows == [{"k": 2, "m": 4.0}]
                # alias colliding with another projected column name
                r = await s.execute("SELECT v AS k, k FROM al "
                                    "ORDER BY k LIMIT 1")
                assert set(r.rows[0].keys()) == {"k"} or \
                    len(r.rows[0]) == 2   # positional: both survive
                r = await s.execute("SELECT v AS a, k AS b FROM al "
                                    "WHERE k = 1")
                assert r.rows[0] == {"a": 2.0, "b": 1}
                # two expression items with aliases keep both columns
                r = await s.execute("SELECT k + 1 AS a, k * 2 AS b "
                                    "FROM al WHERE k = 2")
                assert r.rows[0] == {"a": 3, "b": 4}
                # ORDER BY an alias
                r = await s.execute("SELECT v AS price FROM al "
                                    "ORDER BY price DESC")
                assert [x["price"] for x in r.rows] == [4.0, 2.0]
                # ORDER BY the SOURCE name of an aliased column
                r = await s.execute("SELECT v AS price FROM al "
                                    "ORDER BY v DESC")
                assert [x["price"] for x in r.rows] == [4.0, 2.0]
                assert set(r.rows[0]) == {"price"}   # sort col stripped
                # ORDER BY a non-projected column
                r = await s.execute("SELECT k FROM al ORDER BY v DESC")
                assert [x["k"] for x in r.rows] == [2, 1]
                assert set(r.rows[0]) == {"k"}
                # duplicate aggregates with distinct aliases both survive
                r = await s.execute(
                    "SELECT k, sum(v) AS a, sum(v) AS b FROM al "
                    "GROUP BY k ORDER BY k LIMIT 1")
                assert r.rows[0] == {"k": 1, "a": 2.0, "b": 2.0}
                # join projection honors aliases
                await s.execute("CREATE TABLE al2 (k bigint, t double, "
                                "PRIMARY KEY (k))")
                await mc.wait_for_leaders("al2")
                await s.execute("INSERT INTO al2 (k, t) VALUES (1, 7.0)")
                r = await s.execute(
                    "SELECT al.k AS id, t AS tax FROM al "
                    "JOIN al2 ON k = k WHERE al.k = 1")
                assert r.rows and r.rows[0] == {"id": 1, "tax": 7.0}
            finally:
                await mc.shutdown()
        run(go())


class TestDropColumn:
    def test_drop_column_lifecycle(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.ql import SqlSession
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute("CREATE TABLE dc (k bigint, v double, "
                                "s text, PRIMARY KEY (k))")
                await mc.wait_for_leaders("dc")
                await s.execute("INSERT INTO dc (k, v, s) VALUES "
                                "(1, 2.0, 'aa'), (2, 4.0, 'bb')")
                await s.execute("ALTER TABLE dc DROP COLUMN s")
                r = await s.execute("SELECT * FROM dc ORDER BY k")
                assert all("s" not in row for row in r.rows)
                assert r.rows[0] == {"k": 1, "v": 2.0}
                # key columns protected; unknown rejected
                with pytest.raises(Exception):
                    await s.execute("ALTER TABLE dc DROP COLUMN k")
                with pytest.raises(Exception):
                    await s.execute("ALTER TABLE dc DROP COLUMN nope")
                # indexed columns protected until the index is dropped
                await s.execute("CREATE INDEX dcv ON dc (v)")
                await mc.wait_for_leaders("dcv")
                with pytest.raises(Exception):
                    await s.execute("ALTER TABLE dc DROP COLUMN v")
                # combined ADD+DROP with a failing half applies NOTHING
                with pytest.raises(Exception):
                    await s.execute("ALTER TABLE dc ADD COLUMN tmp "
                                    "bigint, DROP COLUMN k")
                r = await s.execute("SELECT * FROM dc WHERE k = 1")
                assert "tmp" not in r.rows[0]
                # compaction repacks without the dropped column
                peer = next(p for ts in mc.tservers
                            for p in ts.peers.values())
                peer.tablet.flush()
                peer.tablet.compact(major=True)
                r = await s.execute("SELECT * FROM dc ORDER BY k")
                assert r.rows[0] == {"k": 1, "v": 2.0}
                # re-adding the NAME gets a fresh id: old data must NOT
                # resurface
                await s.execute("ALTER TABLE dc ADD COLUMN s text")
                r = await s.execute("SELECT k, s FROM dc ORDER BY k")
                assert [row["s"] for row in r.rows] == [None, None]
                await s.execute("INSERT INTO dc (k, v, s) VALUES "
                                "(3, 6.0, 'new')")
                r = await s.execute("SELECT s FROM dc WHERE k = 3")
                assert r.rows[0]["s"] == "new"
                # survives restart (schema history persisted)
                await mc.restart_tserver(0)
                await mc.wait_for_leaders("dc")
                s2 = SqlSession(mc.client())
                r = await s2.execute("SELECT * FROM dc ORDER BY k")
                assert r.rows[0] == {"k": 1, "v": 2.0, "s": None}
            finally:
                await mc.shutdown()
        run(go())


class TestAnalyze:
    def test_analyze_enables_device_group_pushdown(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.ql import SqlSession
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute("CREATE TABLE an (k bigint, region bigint, "
                                "big bigint, amt double, PRIMARY KEY (k))")
                await mc.wait_for_leaders("an")
                await s.execute(
                    "INSERT INTO an (k, region, big, amt) VALUES " +
                    ", ".join(f"({i}, {i % 4}, {i * 100000}, {float(i)})"
                              for i in range(20)))
                r = await s.execute("EXPLAIN SELECT region, sum(amt) "
                                    "FROM an GROUP BY region")
                # even without stats, numeric keys now push down (hash)
                assert "sort + segment" in r.rows[0]["QUERY PLAN"]
                r = await s.execute("ANALYZE an")
                cols = {row["column"]: (row["domain"], row["offset"])
                        for row in r.rows}
                assert cols["region"] == (4, 0)
                assert "big" not in cols    # domain too wide
                r = await s.execute("EXPLAIN SELECT region, sum(amt) "
                                    "FROM an GROUP BY region")
                assert "DEVICE pushdown" in r.rows[0]["QUERY PLAN"]
                # results agree with the client-side path
                r = await s.execute("SELECT region, sum(amt) AS t FROM an "
                                    "GROUP BY region ORDER BY region")
                assert [row["t"] for row in r.rows] == [
                    sum(float(i) for i in range(20) if i % 4 == g)
                    for g in range(4)]
                # DML invalidates the correctness-bearing stats: a row
                # outside the recorded domain must NOT clip into group 3
                await s.execute("INSERT INTO an (k, region, big, amt) "
                                "VALUES (100, 9, 0, 1000.0)")
                r = await s.execute("EXPLAIN SELECT region, sum(amt) "
                                    "FROM an GROUP BY region")
                # stats invalidated -> the domain-free hash path serves
                assert "sort + segment" in r.rows[0]["QUERY PLAN"]
                r = await s.execute("SELECT region, sum(amt) AS t FROM an "
                                    "GROUP BY region ORDER BY region")
                assert r.rows[-1]["region"] == 9 and r.rows[-1]["t"] == 1000.0
                # NULL-bearing columns are skipped by ANALYZE
                await s.execute("ALTER TABLE an ADD COLUMN maybe bigint")
                await s.execute("INSERT INTO an (k, region, big, amt, "
                                "maybe) VALUES (101, 1, 0, 1.0, 2)")
                r = await s.execute("ANALYZE an")
                cols = {row["column"] for row in r.rows}
                assert "maybe" not in cols   # old rows have NULL maybe
                assert "region" in cols
            finally:
                await mc.shutdown()
        run(go())


class TestInsertSelect:
    def test_insert_from_select(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.ql import SqlSession
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute("CREATE TABLE src (k bigint, v double, "
                                "PRIMARY KEY (k))")
                await s.execute("CREATE TABLE dst (k bigint, v double, "
                                "PRIMARY KEY (k))")
                for t in ("src", "dst"):
                    await mc.wait_for_leaders(t)
                await s.execute("INSERT INTO src (k, v) VALUES "
                                "(1, 5.0), (2, 6.0), (3, 7.0)")
                r = await s.execute("INSERT INTO dst (k, v) "
                                    "SELECT k, v FROM src WHERE v > 5.5")
                assert "2" in r.status
                r = await s.execute("SELECT count(*) AS n FROM dst")
                assert r.rows[0]["n"] == 2
                # expression projection + alias maps by position
                await s.execute("INSERT INTO dst (k, v) "
                                "SELECT k + 100 AS nk, v FROM src "
                                "WHERE k = 1")
                r = await s.execute("SELECT v FROM dst WHERE k = 101")
                assert r.rows[0]["v"] == 5.0
                # duplicate select columns map by position
                await s.execute("INSERT INTO dst (k, v) "
                                "SELECT k + 200, k FROM src WHERE k = 1")
                r = await s.execute("SELECT v FROM dst WHERE k = 201")
                assert r.rows[0]["v"] == 1.0
                # column-count mismatch rejected; empty select inserts 0
                with pytest.raises(Exception):
                    await s.execute("INSERT INTO dst (k, v) "
                                    "SELECT k FROM src")
                r = await s.execute("INSERT INTO dst (k, v) "
                                    "SELECT k, v FROM src WHERE v > 99")
                assert r.status == "INSERT 0"
            finally:
                await mc.shutdown()
        run(go())


class TestNaturalOrderPushdown:
    def test_range_pk_order_by_pushes_limit(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.ql import SqlSession
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute("CREATE TABLE ro (k bigint, v double, "
                                "PRIMARY KEY (k ASC))")
                await mc.wait_for_leaders("ro")
                await s.execute("INSERT INTO ro (k, v) VALUES " + ", ".join(
                    f"({i}, {float(i)})" for i in range(50)))
                r = await s.execute("EXPLAIN SELECT k FROM ro "
                                    "ORDER BY k LIMIT 3")
                text = "\n".join(row["QUERY PLAN"] for row in r.rows)
                assert "natural range-shard" in text and "pushed down" in text
                r = await s.execute("SELECT k FROM ro ORDER BY k LIMIT 3")
                assert [x["k"] for x in r.rows] == [0, 1, 2]
                # with a predicate
                r = await s.execute("SELECT k FROM ro WHERE k >= 40 "
                                    "ORDER BY k LIMIT 5")
                assert [x["k"] for x in r.rows] == [40, 41, 42, 43, 44]
                # DESC over an ASC pk is NOT natural: client sort, right
                # answer anyway
                r = await s.execute("EXPLAIN SELECT k FROM ro "
                                    "ORDER BY k DESC LIMIT 2")
                text = "\n".join(row["QUERY PLAN"] for row in r.rows)
                assert "client-side sort" in text
                r = await s.execute("SELECT k FROM ro ORDER BY k DESC "
                                    "LIMIT 2")
                assert [x["k"] for x in r.rows] == [49, 48]
            finally:
                await mc.shutdown()
        run(go())
