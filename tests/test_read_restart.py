"""Read-restart (clock uncertainty) tests: strong reads encountering a
record inside (read_ht, read_ht + max_skew] restart at the record's HT;
explicit snapshot reads never restart (reference: read restart handling
around tserver/read_query.cc PickReadTime)."""
import numpy as np
import pytest

from yugabyte_db_tpu.docdb import ReadRequest, RowOp, WriteRequest
from yugabyte_db_tpu.ops import AggSpec, Expr
from yugabyte_db_tpu.tablet import Tablet
from yugabyte_db_tpu.utils import flags
from yugabyte_db_tpu.utils.hybrid_time import (
    HybridClock, HybridTime, MockPhysicalClock,
)
from tests.test_tablet import make_info

C = Expr.col


class TestReadRestart:
    def test_strong_read_sees_ahead_of_clock_write(self, tmp_path):
        """A write stamped by a FAST clock (ahead of the reader's) must be
        visible to a subsequent strong read — via restart."""
        clock = HybridClock(MockPhysicalClock(1_000_000))
        t = Tablet("rr-1", make_info(), str(tmp_path), clock=clock)
        # writer's clock runs 200ms ahead (within the 500ms skew bound)
        ahead = HybridTime.from_micros(1_000_000 + 200_000)
        t.apply_write(WriteRequest("t1", [
            RowOp("upsert", {"k": 1, "v": 42.0, "s": "ahead"})]), ht=ahead)
        # strong read picks read_ht from the local (slow) clock — without
        # restarts it would miss the committed row
        resp = t.read(ReadRequest("t1", pk_eq={"k": 1}))
        assert resp.rows and resp.rows[0]["v"] == 42.0
        # scans too
        resp = t.read(ReadRequest("t1", columns=("k",)))
        assert len(resp.rows) == 1

    def test_snapshot_read_does_not_restart(self, tmp_path):
        clock = HybridClock(MockPhysicalClock(1_000_000))
        t = Tablet("rr-2", make_info(), str(tmp_path), clock=clock)
        t.apply_write(WriteRequest("t1", [
            RowOp("upsert", {"k": 1, "v": 1.0, "s": "old"})]),
            ht=HybridTime.from_micros(999_000))
        snapshot_ht = clock.now().value
        # later write inside what WOULD be the uncertainty window
        t.apply_write(WriteRequest("t1", [
            RowOp("upsert", {"k": 1, "v": 2.0, "s": "new"})]),
            ht=HybridTime.from_micros(1_100_000))
        resp = t.read(ReadRequest("t1", pk_eq={"k": 1},
                                  read_ht=snapshot_ht))
        assert resp.rows[0]["v"] == 1.0   # explicit snapshot: no restart

    def test_far_future_write_not_visible(self, tmp_path):
        """Writes beyond the skew bound don't trigger restarts (they are
        genuinely in the future)."""
        clock = HybridClock(MockPhysicalClock(1_000_000))
        t = Tablet("rr-3", make_info(), str(tmp_path), clock=clock)
        far = HybridTime.from_micros(1_000_000 + 10_000_000)  # +10s
        t.apply_write(WriteRequest("t1", [
            RowOp("upsert", {"k": 1, "v": 9.0, "s": "future"})]), ht=far)
        resp = t.read(ReadRequest("t1", pk_eq={"k": 1}))
        assert not resp.rows
