"""Device grouped aggregation (dict-key GROUP BY): device-vs-CPU-twin
parity across dictionary remaps, NaN payloads, empty groups, slot
overflow -> interpreter fallback, chunk-straddling groups, flag revert,
mixed v1+v2 SST inputs — plus the dict-identity device-cache key
regression and the shared group-keyed partial combine."""
import tempfile

import numpy as np
import pytest

from yugabyte_db_tpu.docdb.operations import ReadRequest
from yugabyte_db_tpu.docdb.table_codec import TableInfo
from yugabyte_db_tpu.docdb.wire import (read_request_from_wire,
                                        read_request_to_wire)
from yugabyte_db_tpu.dockv.packed_row import (ColumnSchema, ColumnType,
                                              TableSchema)
from yugabyte_db_tpu.dockv.partition import PartitionSchema
from yugabyte_db_tpu.ops import AggSpec, stream_scan
from yugabyte_db_tpu.ops.device_batch import DeviceBlockCache, build_batch
from yugabyte_db_tpu.ops.expr import Expr
from yugabyte_db_tpu.ops.grouped_scan import (GROUPED_STATS, DictGroupSpec,
                                              decode_slot_groups,
                                              grouped_aggregate_cpu,
                                              make_dict_plan)
from yugabyte_db_tpu.ops.scan import ScanKernel, combine_grouped_partials
from yugabyte_db_tpu.storage import lane_codec
from yugabyte_db_tpu.tablet import Tablet
from yugabyte_db_tpu.utils import flags

C = Expr.col
RF = np.array(["A", "N", "R"], object)
LS = np.array(["F", "O"], object)
N = 24_000


def _make_tablet(prefix, n=N, seed=3, block_rows=4096, nan_every=0,
                 frac=False):
    schema = TableSchema((
        ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
        ColumnSchema(1, "rf", ColumnType.STRING),
        ColumnSchema(2, "ls", ColumnType.STRING),
        ColumnSchema(3, "qty", ColumnType.FLOAT64),
    ), 1)
    info = TableInfo("li", "li", schema, PartitionSchema("hash", 1))
    t = Tablet("li", info, tempfile.mkdtemp(prefix=prefix))
    rng = np.random.default_rng(seed)
    rf = rng.integers(0, 3, n)
    ls = rng.integers(0, 2, n)
    # integer-valued qty by default: the device's exact int64 SUM lane
    # makes grouped results BYTE-identical to the interpreted path;
    # frac=True exercises the fixed-point float lane (bitwise only vs
    # the CPU twin, which replays the quantization contract)
    qty = (rng.uniform(1.0, 50.0, n) if frac
           else rng.integers(1, 50, n).astype(np.float64))
    if nan_every:
        qty[::nan_every] = np.nan
    data = {
        "k": np.arange(n, dtype=np.int64),
        "rf": RF[rf], "ls": LS[ls], "qty": qty,
    }
    t.bulk_load(data, block_rows=block_rows)
    return t, data


def _blocks(t):
    out = []
    for r in t.regular.ssts:
        for i in range(r.num_blocks()):
            out.append(r.columnar_block(i))
    return out


def _grouped_read(t, where=None, spec=None):
    spec = spec or DictGroupSpec(cols=(1, 2))
    return t.read(ReadRequest(
        "li", where=where,
        aggregates=(AggSpec("sum", C(3).node), AggSpec("count")),
        group_by=spec))


def _by_key(resp):
    """{group key tuple: (count, *agg values)} — order-free comparison
    between device (slot-ordered) and interpreted (first-seen) paths."""
    counts = np.asarray(resp.group_counts)
    out = {}
    for g in np.nonzero(counts)[0]:
        key = tuple(str(v[g]) for v in resp.group_values)
        out[key] = (int(counts[g]),) + tuple(
            np.asarray(v)[g] for v in resp.agg_values)
    return out


@pytest.fixture(scope="module")
def strtab():
    t, data = _make_tablet("grp-")
    return t, data


@pytest.fixture(autouse=True)
def _reset_flags():
    yield
    for f in ("grouped_pushdown_enabled", "grouped_max_slots",
              "streaming_chunk_rows", "streaming_scan_enabled",
              "sst_format_version", "tpu_min_rows_for_pushdown",
              "grouped_spill_merge_enabled"):
        flags.REGISTRY.reset(f)


# --- dictionary plan / remap units ----------------------------------------

class TestDictPlan:
    def test_merge_disjoint_and_overlapping(self):
        a = np.array(["A", "N"], object)
        b = np.array(["N", "R", "Z"], object)
        g, remaps = lane_codec.merge_dicts([a, b])
        assert list(g) == ["A", "N", "R", "Z"]
        assert list(remaps[0]) == [0, 1]
        assert list(remaps[1]) == [1, 2, 3]

    def test_dict_identity_distinguishes_contents(self):
        a = lane_codec.dict_identity(np.array(["A", "N"], object))
        b = lane_codec.dict_identity(np.array(["A", "R"], object))
        c = lane_codec.dict_identity(np.array(["A", "N"], object))
        assert a != b and a == c

    def test_varlen_code_rows_trailing_nul_distinct(self):
        # "a" and "a\x00" must code as DIFFERENT dictionary entries
        payload = b"a" + b"a\x00" + b"a"
        ends = np.array([1, 3, 4], np.uint32)
        got = lane_codec.varlen_code_rows(ends, payload)
        assert got is not None
        ulens, uheap, codes = got
        assert len(ulens) == 2
        assert codes[0] == codes[2] != codes[1]

    def test_plan_remaps_block_local_codes(self, strtab):
        t, data = strtab
        blocks = _blocks(t)
        plan = make_dict_plan(blocks, [1])
        assert plan is not None
        assert list(plan.dicts[1]) == ["A", "N", "R"]
        dec = np.concatenate(
            [plan.dicts[1][plan.block_codes(1, b)] for b in blocks])
        # block order == load order for a single bulk-loaded SST run;
        # compare as multisets per value to stay order-robust
        assert len(dec) == len(data["rf"])
        for v in ("A", "N", "R"):
            assert (dec == v).sum() == (data["rf"] == v).sum()


# --- device kernel vs CPU twin, bitwise -----------------------------------

class TestGroupedParity:
    def test_device_matches_cpu_twin_bitwise(self):
        # FRACTIONAL payloads: the fixed-point SUM lane quantizes, and
        # the twin replays that exact contract — bitwise on x64
        t, _data = _make_tablet("twin-", frac=True)
        blocks = _blocks(t)
        spec = DictGroupSpec(cols=(1, 2))
        aggs = (AggSpec("sum", C(3).node), AggSpec("count"),
                AggSpec("min", C(3).node), AggSpec("max", C(3).node))
        plan = make_dict_plan(blocks, [1, 2, 3])
        kernel = ScanKernel()
        batch = build_batch(blocks, [1, 2, 3], dict_plan=plan)
        if len(blocks) > 1:
            batch.unique_keys = False
        douts, dcounts, _, spill = kernel.run(batch, None, aggs, spec,
                                              None)
        assert int(spill) == 0
        couts, ccounts, cspill = grouped_aggregate_cpu(
            blocks, [1, 2, 3], None, aggs, spec, plan=plan)
        assert cspill == 0
        nslots = len(np.asarray(ccounts))
        assert np.array_equal(np.asarray(dcounts)[:nslots],
                              np.asarray(ccounts))
        for dv, cv in zip(douts, couts):
            da = np.asarray(dv)[:nslots]
            ca = np.asarray(cv)
            # min/max carry sentinel values in empty slots; compare on
            # occupied slots bitwise (x64 backend)
            occ = np.asarray(ccounts) > 0
            assert np.array_equal(da[occ].astype(np.float64),
                                  ca[occ].astype(np.float64)), (da, ca)

    def test_parity_across_dict_remaps(self):
        # two SSTs with DIFFERENT string universes: per-block dicts
        # disagree, so the scan-global remap is non-trivial
        t, _ = _make_tablet("remap-", n=6000, seed=5)
        n2 = 6000
        rng = np.random.default_rng(11)
        t.bulk_load({
            "k": np.arange(N, N + n2, dtype=np.int64),
            "rf": np.array(["R", "X", "Z"], object)[
                rng.integers(0, 3, n2)],
            "ls": LS[rng.integers(0, 2, n2)],
            "qty": rng.integers(1, 50, n2).astype(np.float64),
        }, block_rows=4096)
        on = _grouped_read(t)
        assert on.backend == "tpu"
        flags.set_flag("grouped_pushdown_enabled", False)
        off = _grouped_read(t)
        assert off.backend == "cpu"
        assert _by_key(on) == _by_key(off)
        # 5 distinct rf values survived the merge
        assert len({k[0] for k in _by_key(on)}) == 5

    def test_nan_payloads(self):
        t, _ = _make_tablet("nan-", n=8000, nan_every=7)
        on = _grouped_read(t)
        flags.set_flag("grouped_pushdown_enabled", False)
        off = _grouped_read(t)
        ka, kb = _by_key(on), _by_key(off)
        assert set(ka) == set(kb)
        for k in ka:
            assert ka[k][0] == kb[k][0]                      # counts
            np.testing.assert_array_equal(
                np.isnan(float(ka[k][1])), np.isnan(float(kb[k][1])))

    def test_empty_groups_compact_away(self, strtab):
        t, data = strtab
        # WHERE excludes every 'R' row: the 'R' dictionary entries stay
        # in the scan-global dictionary but their slots count zero and
        # must NOT appear in the response
        on = _grouped_read(t, where=C(1).ne("R").node)
        assert on.backend == "tpu"
        keys = {k[0] for k in _by_key(on)}
        assert keys == {"A", "N"}
        flags.set_flag("grouped_pushdown_enabled", False)
        off = _grouped_read(t, where=C(1).ne("R").node)
        assert _by_key(on) == _by_key(off)

    def test_no_rows_match(self, strtab):
        t, _ = strtab
        resp = _grouped_read(t, where=C(1).eq("ZZZ").node)
        counts = np.asarray(resp.group_counts)
        assert counts.sum() == 0 or len(counts) == 0

    def test_chunk_straddling_groups_stream(self, strtab):
        t, _ = strtab
        flags.set_flag("streaming_chunk_rows", 4096)
        stream_scan.LAST_STREAM_STATS.clear()
        on = _grouped_read(t)
        assert on.backend == "tpu"
        from yugabyte_db_tpu.ops.grouped_scan import LAST_GROUPED_STATS
        assert LAST_GROUPED_STATS.get("path") == "streaming"
        # every group is present in every chunk: per-chunk partials had
        # to combine across chunk boundaries
        flags.set_flag("grouped_pushdown_enabled", False)
        off = _grouped_read(t)
        assert _by_key(on) == _by_key(off)


# --- fallbacks ------------------------------------------------------------

class TestFallbacks:
    def test_slot_overflow_merges_on_monolithic_route(self, strtab):
        # DEFAULT behavior since the monolithic partial-spill merge:
        # an over-cardinality scan on the MONOLITHIC dict-group route
        # keeps its exact in-range device partials and re-aggregates
        # only the spilled rows interpreted — backend stays tpu, no
        # full re-scan fallback (the streamed route got this first;
        # this is its monolithic twin)
        t, _ = strtab
        m0 = GROUPED_STATS["spill_merges"]
        fb0 = GROUPED_STATS["spill_fallbacks"]
        resp = _grouped_read(t, spec=DictGroupSpec(cols=(1, 2),
                                                   max_slots=4))
        assert resp.backend == "tpu"
        assert GROUPED_STATS["spill_merges"] == m0 + 1
        assert GROUPED_STATS["spill_fallbacks"] == fb0
        flags.set_flag("grouped_pushdown_enabled", False)
        off = _grouped_read(t)
        assert _by_key(resp) == _by_key(off)

    def test_slot_overflow_reverts_when_merge_disabled(self, strtab):
        t, _ = strtab
        flags.set_flag("grouped_spill_merge_enabled", False)
        fb0 = GROUPED_STATS["spill_fallbacks"]
        resp = _grouped_read(t, spec=DictGroupSpec(cols=(1, 2),
                                                   max_slots=4))
        assert resp.backend == "cpu"       # interpreted GROUP BY served
        # EXACTLY one spill fallback per query: the monolithic path must
        # not re-run (and re-spill) a scan the streamed path already
        # proved over-cardinality
        assert GROUPED_STATS["spill_fallbacks"] == fb0 + 1
        flags.set_flag("grouped_pushdown_enabled", False)
        off = _grouped_read(t)
        assert _by_key(resp) == _by_key(off)

    def test_streamed_spill_skips_monolithic_pass(self, strtab):
        # with streaming active and the partial-spill MERGE disabled,
        # an over-cardinality scan must pay ONE device pass (the
        # streamed one that detected the spill), then go straight to
        # the interpreter: one spill fallback, and no extra grouped
        # kernel launches beyond the streamed chunks
        t, _ = strtab
        flags.set_flag("streaming_chunk_rows", 4096)
        flags.set_flag("grouped_spill_merge_enabled", False)
        _grouped_read(t)                     # warm the chunk plan/cache
        fb0 = GROUPED_STATS["spill_fallbacks"]
        l0 = GROUPED_STATS["launches"]
        resp = _grouped_read(t, spec=DictGroupSpec(cols=(1, 2),
                                                   max_slots=4))
        chunks = stream_scan.LAST_STREAM_STATS.get("chunks", 0)
        assert resp.backend == "cpu"
        assert GROUPED_STATS["spill_fallbacks"] == fb0 + 1
        assert chunks >= 3
        assert GROUPED_STATS["launches"] - l0 == chunks

    def test_streamed_spill_merges_partials(self, strtab):
        # DEFAULT spill behavior since the partial-spill merge: device
        # slots below the spill slot keep their exact partials, the
        # spilled rows re-aggregate on the interpreted tail, and the
        # combined answer equals the full interpreted GROUP BY — no
        # full re-scan, backend stays tpu
        t, _ = strtab
        flags.set_flag("streaming_chunk_rows", 4096)
        m0 = GROUPED_STATS["spill_merges"]
        fb0 = GROUPED_STATS["spill_fallbacks"]
        resp = _grouped_read(t, spec=DictGroupSpec(cols=(1, 2),
                                                   max_slots=4))
        assert resp.backend == "tpu"
        assert GROUPED_STATS["spill_merges"] == m0 + 1
        assert GROUPED_STATS["spill_fallbacks"] == fb0
        flags.set_flag("grouped_pushdown_enabled", False)
        off = _grouped_read(t)
        assert _by_key(resp) == _by_key(off)

    def test_flag_off_reverts(self, strtab):
        t, _ = strtab
        flags.set_flag("grouped_pushdown_enabled", False)
        l0 = GROUPED_STATS["launches"]
        resp = _grouped_read(t)
        assert resp.backend == "cpu"
        assert GROUPED_STATS["launches"] == l0
        assert sum(c for c, *_ in _by_key(resp).values()) == N

    def test_overlong_strings_stay_correct(self):
        # rows longer than the dict-lane coder's max_len can't ride the
        # scan-global plan (streaming declines) but the monolithic
        # batch's legacy decode dictionary still serves them — whatever
        # path wins, results must match the interpreter
        t, _ = _make_tablet("long-", n=6000)
        long_tail = np.array(["x" * 300, "y" * 300], object)
        rng = np.random.default_rng(2)
        t.bulk_load({
            "k": np.arange(N, N + 6000, dtype=np.int64),
            "rf": long_tail[rng.integers(0, 2, 6000)],
            "ls": LS[rng.integers(0, 2, 6000)],
            "qty": rng.integers(1, 50, 6000).astype(np.float64),
        }, block_rows=4096)
        flags.set_flag("streaming_chunk_rows", 4096)
        from yugabyte_db_tpu.ops.grouped_scan import LAST_GROUPED_STATS
        LAST_GROUPED_STATS.clear()
        on = _grouped_read(t)
        assert LAST_GROUPED_STATS.get("path") != "streaming"
        flags.set_flag("grouped_pushdown_enabled", False)
        off = _grouped_read(t)
        assert _by_key(on) == _by_key(off)


# --- mixed v1 + v2 SST inputs ---------------------------------------------

class TestMixedFormats:
    def test_mixed_v1_v2_ssts(self):
        flags.set_flag("sst_format_version", 1)
        t, _ = _make_tablet("mixed-", n=8000)
        flags.set_flag("sst_format_version", 2)
        rng = np.random.default_rng(9)
        t.bulk_load({
            "k": np.arange(N, N + 8000, dtype=np.int64),
            "rf": RF[rng.integers(0, 3, 8000)],
            "ls": LS[rng.integers(0, 2, 8000)],
            "qty": rng.integers(1, 50, 8000).astype(np.float64),
        }, block_rows=4096)
        vs = {r.format_version for r in t.regular.ssts}
        assert vs == {1, 2}
        on = _grouped_read(t)
        assert on.backend == "tpu"
        flags.set_flag("grouped_pushdown_enabled", False)
        off = _grouped_read(t)
        assert _by_key(on) == _by_key(off)

    def test_v2_dict_lane_round_trips(self):
        # a v2-written block with dict-coded varlen lanes must decode
        # to the exact original (ends, heap) pair AND serve dict_varlen
        # straight from the stored parts
        t, data = _make_tablet("v2rt-", n=6000)
        blocks = _blocks(t)
        got = [b for b in blocks if b._vdicts]
        assert got, "v2 writer never dict-coded the string lanes"
        b = got[0]
        uniq, codes = b.dict_varlen(1)
        assert sorted(set(uniq)) == list(uniq)
        dec = uniq[codes]
        ends, heap, null = b.varlen[1]
        raw = [bytes(heap[(0 if i == 0 else ends[i - 1]):ends[i]]).decode()
               for i in range(b.n)]
        assert list(dec) == raw


# --- the device-cache key regression (satellite) --------------------------

class TestDeviceCacheKey:
    def test_dict_identity_keys_cached_chunks(self):
        """Two streamed scans under the SAME cache key but different
        merged dictionaries must never share a cached batch of remapped
        codes — the dict identity rides in the chunk key."""
        t1, _ = _make_tablet("ck1-", n=16000, seed=21)
        t2, _ = _make_tablet("ck2-", n=16000, seed=22)
        # different universe on t2: same shapes, different dictionary
        rng = np.random.default_rng(23)
        n = 16000
        t2b = Tablet("li", t2.info, tempfile.mkdtemp(prefix="ck3-"))
        t2b.bulk_load({
            "k": np.arange(n, dtype=np.int64),
            "rf": np.array(["X", "Y", "Z"], object)[
                rng.integers(0, 3, n)],
            "ls": LS[rng.integers(0, 2, n)],
            "qty": rng.integers(1, 50, n).astype(np.float64),
        }, block_rows=4096)
        blocks1, blocks2 = _blocks(t1), _blocks(t2b)
        spec = DictGroupSpec(cols=(1, 2))
        aggs = (AggSpec("count"),)
        cache = DeviceBlockCache()
        kernel = ScanKernel()
        key = ("same", "store", "key")
        out = []
        for blocks in (blocks1, blocks2):
            gout: dict = {}
            got = stream_scan.streaming_scan_aggregate(
                blocks, [1, 2], None, aggs, spec, None, kernel=kernel,
                chunk_rows=4096, cache=cache, cache_key=key,
                grouped_out=gout)
            assert got is not None
            outs, counts = got
            out.append(decode_slot_groups(spec, gout["dicts"], outs,
                                          counts))
        # the second scan's decoded keys must be ITS universe — a
        # shared cached batch would leak t1's codes under t2's dicts
        keys2 = {v for v in out[1][2][0]}
        assert keys2 <= {"X", "Y", "Z"}
        assert int(np.asarray(out[0][1]).sum()) == 16000
        assert int(np.asarray(out[1][1]).sum()) == 16000
        # and both scans' batches are distinct cache entries
        assert cache.misses >= 8

    def test_same_dicts_reuse_cache(self):
        t, _ = _make_tablet("ckr-", n=16000, seed=31)
        blocks = _blocks(t)
        spec = DictGroupSpec(cols=(1, 2))
        aggs = (AggSpec("count"),)
        cache = DeviceBlockCache()
        kernel = ScanKernel()
        key = ("k",)
        for _ in range(2):
            got = stream_scan.streaming_scan_aggregate(
                blocks, [1, 2], None, aggs, spec, None, kernel=kernel,
                chunk_rows=4096, cache=cache, cache_key=key,
                grouped_out={})
            assert got is not None
        assert cache.hits >= 4      # warm re-scan reused every chunk


# --- wire + shared combine -------------------------------------------------

class TestWireAndCombine:
    def test_wire_roundtrip_dict_group(self):
        req = ReadRequest("li", aggregates=(AggSpec("count"),),
                          group_by=DictGroupSpec(cols=(1, 2),
                                                 max_slots=64))
        got = read_request_from_wire(read_request_to_wire(req))
        assert isinstance(got.group_by, DictGroupSpec)
        assert got.group_by.cols == (1, 2)
        assert got.group_by.max_slots == 64

    def test_combine_grouped_partials_string_keys(self):
        aggs = (AggSpec("sum", C(3).node), AggSpec("count"),
                AggSpec("min", C(3).node))
        p1 = ((np.array([10.0, 5.0]), np.array([2, 1], np.int64),
               np.array([3.0, 7.0])),
              np.array([2, 1], np.int64),
              (np.array(["A", "N"], object),))
        p2 = ((np.array([4.0, 6.0]), np.array([1, 2], np.int64),
               np.array([1.0, 9.0])),
              np.array([1, 2], np.int64),
              (np.array(["N", "R"], object),))
        outs, counts, gvals = combine_grouped_partials(aggs, [p1, p2])
        m = {g: (float(outs[0][i]), int(outs[1][i]), float(outs[2][i]),
                 int(counts[i]))
             for i, g in enumerate(gvals[0])}
        assert m["A"] == (10.0, 2, 3.0, 2)
        assert m["N"] == (9.0, 2, 1.0, 2)      # 5+4, 1+1, min(7,1)
        assert m["R"] == (6.0, 2, 9.0, 2)

    def test_bypass_grouped_keyless(self):
        from yugabyte_db_tpu.bypass import BypassSession
        from yugabyte_db_tpu.storage.columnar import KEY_REBUILD_STATS
        t, _ = _make_tablet("byp-", n=16000, seed=41)
        rb0 = KEY_REBUILD_STATS["rebuilds"]
        with BypassSession([t]) as s:
            gout: dict = {}
            outs, counts, stats = s.scan_aggregate(
                None, (AggSpec("sum", C(3).node), AggSpec("count")),
                DictGroupSpec(cols=(1, 2)), grouped_out=gout)
        assert KEY_REBUILD_STATS["rebuilds"] == rb0
        assert int(np.asarray(counts).sum()) == 16000
        assert len(gout["group_values"]) == 2
        flags.set_flag("grouped_pushdown_enabled", False)
        off = _grouped_read(t)
        ref = _by_key(off)
        for i in range(len(np.asarray(counts))):
            key = tuple(str(v[i]) for v in gout["group_values"])
            assert ref[key][0] == int(np.asarray(counts)[i])

    def test_bypass_slot_overflow_typed(self):
        from yugabyte_db_tpu.bypass import (REASON_SLOT_OVERFLOW,
                                            BypassIneligible,
                                            BypassSession)
        t, _ = _make_tablet("bypof-", n=16000, seed=43)
        with BypassSession([t]) as s:
            with pytest.raises(BypassIneligible) as ei:
                s.scan_aggregate(
                    None, (AggSpec("count"),),
                    DictGroupSpec(cols=(1, 2), max_slots=4))
        assert ei.value.reason == REASON_SLOT_OVERFLOW

    def test_bypass_undecodable_binary_typed(self):
        # a BINARY varlen column with non-UTF8 payloads can't
        # dictionary-encode: the typed-fallback contract must hold (a
        # BypassIneligible the client routing catches, never a raw
        # KeyError escaping build_batch's decode fallback)
        from yugabyte_db_tpu.bypass import (REASON_COLUMN_NOT_FIXED,
                                            BypassIneligible,
                                            BypassSession)
        from yugabyte_db_tpu.docdb.table_codec import TableInfo
        from yugabyte_db_tpu.dockv.partition import PartitionSchema
        schema = TableSchema((
            ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
            ColumnSchema(1, "blob", ColumnType.BINARY),
            ColumnSchema(2, "qty", ColumnType.FLOAT64),
        ), 1)
        t = Tablet("bin", TableInfo("bin", "bin", schema,
                                    PartitionSchema("hash", 1)),
                   tempfile.mkdtemp(prefix="bypbin-"))
        n = 6000
        rng = np.random.default_rng(4)
        t.bulk_load({
            "k": np.arange(n, dtype=np.int64),
            "blob": np.array([b"\xff\xfe\x01", b"\x80\x81"],
                             object)[rng.integers(0, 2, n)],
            "qty": rng.integers(1, 50, n).astype(np.float64),
        }, block_rows=4096)
        with BypassSession([t]) as s:
            with pytest.raises(BypassIneligible) as ei:
                s.scan_aggregate(
                    None, (AggSpec("sum", C(2).node), AggSpec("count")),
                    DictGroupSpec(cols=(1,)))
        assert ei.value.reason == REASON_COLUMN_NOT_FIXED


# --- streamed filter-pushdown ROW path ------------------------------------

class TestStreamedRowPath:
    def test_rows_match_monolithic(self):
        t, data = _make_tablet("rows-", n=16000, seed=51)
        flags.set_flag("streaming_chunk_rows", 4096)
        stream_scan.LAST_STREAM_STATS.clear()
        on = t.read(ReadRequest("li", where=C(1).eq("A").node,
                                columns=["k", "rf", "qty"]))
        assert on.backend == "tpu"
        assert stream_scan.LAST_STREAM_STATS.get("chunks_run", 0) >= 2
        flags.set_flag("streaming_scan_enabled", False)
        off = t.read(ReadRequest("li", where=C(1).eq("A").node,
                                 columns=["k", "rf", "qty"]))
        assert on.rows == off.rows
        assert len(on.rows) == int((data["rf"] == "A").sum())

    def test_limit_early_exit(self):
        t, _ = _make_tablet("rowlim-", n=16000, seed=52)
        flags.set_flag("streaming_chunk_rows", 4096)
        stream_scan.LAST_STREAM_STATS.clear()
        resp = t.read(ReadRequest("li", where=C(1).eq("A").node,
                                  columns=["k"], limit=5))
        assert len(resp.rows) == 5
        st = stream_scan.LAST_STREAM_STATS
        assert st.get("chunks_run", 99) < st.get("chunks", 0)
