"""State-invariant sanitizer (the TSAN/DCHECK-build analog; reference:
yb_build.sh sanitizer builds + per-subsystem consistency DCHECKs).
Positive checks: clean clusters sweep clean after real workloads.
Negative checks: seeded corruptions of each invariant class are
caught."""
import asyncio

from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
from yugabyte_db_tpu.utils import sanitizer
from tests.test_load_balancer import kv_info


def run(coro):
    return asyncio.run(coro)


class TestSanitizer:
    def test_clean_after_txn_workload(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=2)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": i, "v": float(i)}
                                      for i in range(50)])
                await c.messenger.call(mc.master.messenger.addr,
                                       "master", "get_status_tablet", {})
                await mc.wait_for_leaders("system.transactions")
                txn = await c.transaction().begin()
                await txn.insert("kv", [{"k": 100, "v": 1.0}])
                await txn.get("kv", {"k": 5}, for_update=True)
                await txn.commit()
                t2 = await c.transaction().begin()
                await t2.insert("kv", [{"k": 101, "v": 2.0}])
                await t2.abort()
                assert sanitizer.check_cluster(mc) == []
            finally:
                await mc.shutdown()
        run(go())

    def test_detects_leaked_claim(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=1)
                await mc.wait_for_leaders("kv")
                peer = next(iter(mc.tservers[0].peers.values()))
                # seed: a claim with no intent entry
                peer.participant._key_holder[b"ghost"] = "txn-x"
                vs = sanitizer.check_cluster(mc)
                del peer.participant._key_holder[b"ghost"]
                assert any("leaked claim" in v for v in vs), vs
            finally:
                await mc.shutdown()
        run(go())

    def test_detects_double_writer(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=1)
                await mc.wait_for_leaders("kv")
                peer = next(iter(mc.tservers[0].peers.values()))
                p = peer.participant
                p._key_holder[b"dup"] = "txn-a"
                p._intents["txn-a"] = {b"dup": [(0, "t", ["upsert", {}])]}
                p._intents["txn-b"] = {b"dup": [(0, "t", ["upsert", {}])]}
                vs = sanitizer.check_cluster(mc)
                p._intents.clear()
                del p._key_holder[b"dup"]
                assert any("two writers" in v for v in vs), vs
            finally:
                await mc.shutdown()
        run(go())

    def test_detects_memtable_guard_false_negative(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=1)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": 1, "v": 1.0}])
                peer = next(iter(mc.tservers[0].peers.values()))
                mem = peer.tablet.regular._mem
                assert not mem.empty()
                # seed: drop a prefix from the guard set — point reads
                # would miss the row; the sanitizer must flag it
                mem._row_prefixes.clear()
                vs = sanitizer.check_cluster(mc)
                # restore BEFORE asserting so a failed assert can't
                # cascade into the shutdown sweep's own error
                from yugabyte_db_tpu.storage.memtable import _HT_SUFFIX
                for k in mem._map.keys():
                    mem._row_prefixes.add(k[:-_HT_SUFFIX])
                assert any("FALSE NEGATIVE" in v for v in vs), vs
            finally:
                await mc.shutdown()
        run(go())

    def test_detects_missing_sst_file(self, tmp_path):
        async def go():
            import os
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=1)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": i, "v": 0.0}
                                      for i in range(10)])
                peer = next(iter(mc.tservers[0].peers.values()))
                peer.tablet.flush()
                _, ssts = peer.tablet.regular.read_snapshot()
                os.rename(ssts[0].path, ssts[0].path + ".hidden")
                vs = sanitizer.check_cluster(mc)
                os.rename(ssts[0].path + ".hidden", ssts[0].path)
                assert any("missing SST" in v for v in vs), vs
            finally:
                await mc.shutdown()
        run(go())
