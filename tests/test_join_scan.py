"""Device hash join + fused plans + window kernels: device-vs-CPU-twin
bitwise parity (empty build side, nulls, dangling FKs, dict-coded
string keys, chunk-straddling probes, bucket-growth-without-recompile,
flag revert), fused-plan vs operator-at-a-time identity across the
monolithic, streaming and bypass routes, the SQL fused-join pushdown,
window segment-scan parity, and the shared-consts-offset regression
the fused-plan work exposed in the scan kernel."""
import asyncio
import tempfile

import numpy as np
import pytest

from yugabyte_db_tpu.bypass import BypassIneligible, BypassSession
from yugabyte_db_tpu.docdb.operations import (ReadRequest, RowOp,
                                              WriteRequest)
from yugabyte_db_tpu.docdb.table_codec import TableInfo
from yugabyte_db_tpu.docdb.wire import (read_request_from_wire,
                                        read_request_to_wire)
from yugabyte_db_tpu.dockv.packed_row import (ColumnSchema, ColumnType,
                                              TableSchema)
from yugabyte_db_tpu.dockv.partition import PartitionSchema
from yugabyte_db_tpu.ops.expr import Expr
from yugabyte_db_tpu.ops.grouped_scan import (DictGroupSpec,
                                              decode_slot_groups)
from yugabyte_db_tpu.ops.join_scan import (BUILD_COL_BASE, JoinIneligible,
                                           JoinWire, build_hash_table,
                                           hash_join_cpu,
                                           make_join_runtime,
                                           table_bucket)
from yugabyte_db_tpu.ops.plan_fusion import (FusedPlanKernel,
                                             fused_plan_cpu,
                                             monolithic_plan_aggregate,
                                             streaming_plan_aggregate)
from yugabyte_db_tpu.ops.scan import AggSpec
from yugabyte_db_tpu.ops.window_scan import (WindowKernel, window_cpu)
from yugabyte_db_tpu.tablet import Tablet
from yugabyte_db_tpu.utils import flags

C = Expr.col
BID = BUILD_COL_BASE
N = 24_000


def _probe_tablet(prefix, n=N, seed=3, block_rows=4096, n_keys=600,
                  frac=False):
    """Probe table: k (PK), fk int64 (FK, some dangling past n_keys//?),
    val f64, ship int32."""
    schema = TableSchema((
        ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
        ColumnSchema(1, "fk", ColumnType.INT64),
        ColumnSchema(2, "val", ColumnType.FLOAT64),
        ColumnSchema(3, "ship", ColumnType.INT32),
    ), 1)
    info = TableInfo("probe", "probe", schema, PartitionSchema("hash", 1))
    t = Tablet("probe", info, tempfile.mkdtemp(prefix=prefix))
    rng = np.random.default_rng(seed)
    data = {
        "k": np.arange(n, dtype=np.int64),
        "fk": rng.integers(0, n_keys, n).astype(np.int64),
        "val": (rng.uniform(1.0, 100.0, n) if frac
                else rng.integers(1, 100, n).astype(np.float64)),
        "ship": rng.integers(0, 100, n).astype(np.int32),
    }
    t.bulk_load(data, block_rows=block_rows)
    return t, data


def _blocks(t):
    return [r.columnar_block(i) for r in t.regular.ssts
            for i in range(r.num_blocks())]


def _build_wire(n_build=500, probe_col=1, with_null_payload=False,
                seed=7):
    """Build side: keys 0..n_build-1, string priority payload +
    numeric weight payload (weight nulls injected on request)."""
    rng = np.random.default_rng(seed)
    prio = np.array([f"P{i % 5}" for i in range(n_build)], object)
    w = rng.integers(1, 10, n_build).astype(np.int64)
    wn = (np.arange(n_build) % 7 == 0) if with_null_payload else None
    return JoinWire(probe_col=probe_col,
                    keys=np.arange(n_build, dtype=np.int64),
                    payload={BID: (prio, None), BID + 1: (w, wn)})


_WHERE = (C(3) < 50).node
_AGGS = (AggSpec("sum", C(2).node), AggSpec("count"),
         AggSpec("sum", C(BID + 1).node))
_GROUP = DictGroupSpec(cols=(BID,))


def _join_req(wire, aggs=_AGGS, group=_GROUP, where=_WHERE):
    r = ReadRequest("probe", where=where, aggregates=aggs,
                    group_by=group, join=wire)
    # every request crosses the wire codec, like a real RPC
    return read_request_from_wire(read_request_to_wire(r))


def _by_key(resp):
    counts = np.asarray(resp.group_counts)
    out = {}
    for g in np.nonzero(counts)[0]:
        key = tuple(str(v[g]) for v in resp.group_values)
        out[key] = (int(counts[g]),) + tuple(
            np.asarray(v)[g] for v in resp.agg_values)
    return out


@pytest.fixture(autouse=True)
def _reset_flags():
    yield
    for f in ("join_pushdown_enabled", "plan_fusion_enabled",
              "window_pushdown_enabled", "join_max_build_slots",
              "streaming_chunk_rows", "streaming_scan_enabled",
              "grouped_pushdown_enabled", "tpu_min_rows_for_pushdown",
              "bypass_reader_enabled"):
        flags.REGISTRY.reset(f)


# --- unit: build table / probe twin ---------------------------------------

class TestJoinUnits:
    def test_table_bucket_load_factor(self):
        assert table_bucket(0, 1 << 16) == 8
        assert table_bucket(4, 1 << 16) == 8
        assert table_bucket(5, 1 << 16) == 16
        assert table_bucket(256, 1 << 16) == 512
        with pytest.raises(JoinIneligible):
            table_bucket(40_000, 1 << 16)   # needs 131072 > cap

    def test_linear_probe_invariant(self):
        # adversarial clustering: many keys hashing near each other —
        # every key must be reachable from its home slot with no empty
        # slot in between (the device walk's exactness condition)
        rng = np.random.default_rng(0)
        keys = rng.choice(1 << 40, size=300, replace=False).astype(
            np.int64)
        S = table_bucket(len(keys), 1 << 16)
        used, tkey, tval = build_hash_table(keys, S)
        from yugabyte_db_tpu.ops.join_scan import _home_slots
        homes = _home_slots(keys, S)
        for i, k in enumerate(keys):
            s = int(homes[i])
            steps = 0
            while True:
                assert used[s], f"empty slot inside chain of key {k}"
                if tkey[s] == k:
                    assert tval[s] == i
                    break
                s = (s + 1) & (S - 1)
                steps += 1
                assert steps < S

    def test_duplicate_keys_raise(self):
        with pytest.raises(JoinIneligible):
            build_hash_table(np.array([3, 5, 3], np.int64), 8)

    def test_hash_join_cpu_dangling_and_empty(self):
        probe = np.array([5, 0, 99, 2], np.int64)
        build = np.array([2, 5, 7], np.int64)
        got = hash_join_cpu(probe, build)
        assert list(got) == [1, -1, -1, 0]
        assert list(hash_join_cpu(probe, np.zeros(0, np.int64))) \
            == [-1, -1, -1, -1]

    def test_string_keys_map_through_probe_dict(self):
        d = np.array(["A", "C", "D"], object)
        wire = JoinWire(probe_col=9,
                        keys=np.array(["C", "B", "A"], object),
                        payload={})
        rt = make_join_runtime(wire, {9: d})
        # C->1, B absent -> distinct negative sentinel, A->0
        assert rt.keys_mapped[0] == 1 and rt.keys_mapped[2] == 0
        assert rt.keys_mapped[1] < 0

    def test_string_keys_without_dict_refused(self):
        wire = JoinWire(probe_col=9,
                        keys=np.array(["C"], object), payload={})
        with pytest.raises(JoinIneligible):
            make_join_runtime(wire, {})


# --- fused plan: device vs CPU twin, bitwise ------------------------------

class TestFusedPlanParity:
    def test_device_matches_twin_bitwise(self):
        # FRACTIONAL probe values: the fixed-point SUM lane quantizes
        # and the twin replays that exact contract — bitwise on x64
        t, _ = _probe_tablet("twin-", frac=True)
        blocks = _blocks(t)
        wire = _build_wire(with_null_payload=True)
        aggs = _AGGS + (AggSpec("min", C(2).node),
                        AggSpec("max", C(BID + 1).node))
        kern = FusedPlanKernel()
        gout = {}
        douts, dcounts = monolithic_plan_aggregate(
            blocks, [1, 2, 3], _WHERE, aggs, _GROUP, None, wire,
            kernel=kern, grouped_out=gout)
        assert not gout.get("spill")
        from yugabyte_db_tpu.ops.device_batch import bucket_rows
        touts, tcounts, tspill, tdicts = fused_plan_cpu(
            blocks, [1, 2, 3], _WHERE, aggs, _GROUP, wire, None,
            n_total=bucket_rows(N))
        assert tspill == 0
        nslots = len(np.asarray(tcounts))
        assert np.array_equal(np.asarray(dcounts)[:nslots],
                              np.asarray(tcounts))
        occ = np.asarray(tcounts) > 0
        for dv, cv in zip(douts, touts):
            da = np.asarray(dv)[:nslots]
            assert np.array_equal(da[occ].astype(np.float64),
                                  np.asarray(cv)[occ].astype(
                                      np.float64)), (da, cv)

    def test_fused_vs_interpreted_byte_identity(self):
        # integer-valued lanes end to end: the device's exact int64
        # accumulation makes fused results BYTE-identical to the
        # interpreted join, keyed by group value
        t, _ = _probe_tablet("int-")
        fused = t.read(_join_req(_build_wire()))
        assert fused.backend == "tpu"
        flags.set_flag("join_pushdown_enabled", False)
        interp = t.read(_join_req(_build_wire()))
        assert interp.backend == "cpu"
        fk, ik = _by_key(fused), _by_key(interp)
        assert set(fk) == set(ik)
        for k in fk:
            assert fk[k][0] == ik[k][0]
            assert float(fk[k][1]) == float(ik[k][1])   # sum(val)
            assert float(fk[k][3]) == float(ik[k][3])   # sum(weight)

    def test_dangling_fks_drop(self):
        # n_keys=600 but build side only covers 0..499: rows with fk
        # >= 500 are dangling and must drop from BOTH paths
        t, data = _probe_tablet("dang-")
        wire = _build_wire(n_build=500)
        fused = t.read(_join_req(wire))
        m = (data["ship"] < 50) & (data["fk"] < 500)
        total = sum(c for c, *_ in _by_key(fused).values())
        assert total == int(m.sum())

    def test_empty_build_side(self):
        t, _ = _probe_tablet("empty-", n=8000)
        wire = JoinWire(probe_col=1, keys=np.zeros(0, np.int64),
                        payload={BID: (np.zeros(0, object), None),
                                 BID + 1: (np.zeros(0, np.int64),
                                           None)})
        fused = t.read(_join_req(wire))
        assert sum(np.asarray(fused.group_counts)) == 0
        flags.set_flag("join_pushdown_enabled", False)
        interp = t.read(_join_req(wire))
        assert _by_key(fused) == _by_key(interp) == {}

    def test_null_fk_and_null_payload(self):
        # NULL FKs (written through the row path) never match; NULL
        # payload values are excluded from their aggregate but the row
        # still counts — identical in fused and interpreted paths
        schema = TableSchema((
            ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
            ColumnSchema(1, "fk", ColumnType.INT64),
            ColumnSchema(2, "val", ColumnType.FLOAT64),
            ColumnSchema(3, "ship", ColumnType.INT32),
        ), 1)
        info = TableInfo("probe", "probe", schema,
                         PartitionSchema("hash", 1))
        t = Tablet("probe", info, tempfile.mkdtemp(prefix="nullfk-"))
        rows = [{"k": i, "fk": None if i % 5 == 0 else i % 20,
                 "val": float(i % 9), "ship": i % 100}
                for i in range(6000)]
        t.apply_write(WriteRequest("probe", [RowOp("upsert", r)
                                             for r in rows]))
        flags.set_flag("tpu_min_rows_for_pushdown", 0)
        wire = _build_wire(n_build=20, with_null_payload=True)
        fused = t.read(_join_req(wire))
        flags.set_flag("join_pushdown_enabled", False)
        interp = t.read(_join_req(wire))
        fk, ik = _by_key(fused), _by_key(interp)
        assert set(fk) == set(ik) and fk
        for k in fk:
            assert fk[k][0] == ik[k][0]
            assert float(fk[k][1]) == float(ik[k][1])
            assert float(fk[k][3]) == float(ik[k][3])

    def test_string_join_keys_dict_coded(self):
        # the probe FK is a STRING column: build keys map through the
        # scan-global dictionary, unmapped build keys can never match
        schema = TableSchema((
            ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
            ColumnSchema(1, "fks", ColumnType.STRING),
            ColumnSchema(2, "val", ColumnType.FLOAT64),
        ), 1)
        info = TableInfo("probe", "probe", schema,
                         PartitionSchema("hash", 1))
        t = Tablet("probe", info, tempfile.mkdtemp(prefix="strk-"))
        rng = np.random.default_rng(5)
        n = 12_000
        fkv = rng.integers(0, 40, n)
        t.bulk_load({
            "k": np.arange(n, dtype=np.int64),
            "fks": np.array([f"K{v:02d}" for v in fkv], object),
            "val": rng.integers(1, 50, n).astype(np.float64),
        }, block_rows=4096)
        keys = np.array([f"K{v:02d}" for v in range(30)]
                        + ["ZZ-never"], object)
        prio = np.array([f"P{i % 3}" for i in range(31)], object)
        wire = JoinWire(probe_col=1, keys=keys,
                        payload={BID: (prio, None)})
        req = ReadRequest("probe",
                          aggregates=(AggSpec("sum", C(2).node),
                                      AggSpec("count")),
                          group_by=DictGroupSpec(cols=(BID,)),
                          join=wire)
        fused = t.read(read_request_from_wire(read_request_to_wire(req)))
        assert fused.backend == "tpu"
        flags.set_flag("join_pushdown_enabled", False)
        req2 = ReadRequest("probe",
                           aggregates=(AggSpec("sum", C(2).node),
                                       AggSpec("count")),
                           group_by=DictGroupSpec(cols=(BID,)),
                           join=wire)
        interp = t.read(req2)
        fk, ik = _by_key(fused), _by_key(interp)
        assert set(fk) == set(ik) and fk
        for k in fk:
            assert fk[k][0] == ik[k][0]
            assert float(fk[k][1]) == float(ik[k][1])
        assert sum(c for c, *_ in fk.values()) \
            == int((fkv < 30).sum())

    def test_duplicate_build_keys_fall_back_interpreted(self):
        # duplicate build keys are a typed device refusal; the
        # interpreted landing path serves them with FULL inner-join
        # semantics — one output per matching build row (a probe row
        # whose FK matches 3 build rows counts 3 times), never a
        # silent last-wins overwrite
        t, data = _probe_tablet("dup-", n=6000)
        keys = np.zeros(3, np.int64)        # all duplicate (key 0)
        wire = JoinWire(probe_col=1, keys=keys,
                        payload={BID: (np.array(["A", "B", "A"],
                                                object), None)})
        from yugabyte_db_tpu.ops.join_scan import JOIN_STATS
        fb0 = JOIN_STATS["fallbacks"]
        resp = t.read(_join_req(wire))
        assert resp.backend == "cpu"        # typed fallback, served
        assert JOIN_STATS["fallbacks"] == fb0 + 1
        n_match = int(((data["fk"] == 0) & (data["ship"] < 50)).sum())
        got = _by_key(resp)
        assert got[("A",)][0] == 2 * n_match   # two 'A' build rows
        assert got[("B",)][0] == n_match

    def test_float_build_keys_never_truncate(self):
        # float build keys ship VERBATIM over the wire; non-integer
        # values are a typed device refusal and the interpreted join
        # matches the TRUE float values — 3.5 must not become 3
        t, data = _probe_tablet("fkeys-", n=6000)
        wire = JoinWire(probe_col=1,
                        keys=np.array([2.0, 3.5]),
                        payload={BID: (np.array(["X", "Y"], object),
                                       None)})
        resp = t.read(_join_req(wire))
        assert resp.backend == "cpu"        # non-integer key: typed
        got = _by_key(resp)
        n2 = int(((data["fk"] == 2) & (data["ship"] < 50)).sum())
        assert got.get(("X",), (0,))[0] == n2
        assert ("Y",) not in got            # 3.5 matches NO int fk
        # integer-VALUED float keys are exact and serve on device
        wire2 = JoinWire(probe_col=1,
                         keys=np.arange(500).astype(np.float64),
                         payload={BID: (np.array(
                             [f"P{i % 5}" for i in range(500)],
                             object), None),
                             BID + 1: (np.ones(500, np.int64), None)})
        resp2 = t.read(_join_req(wire2))
        assert resp2.backend == "tpu"

    def test_flag_revert(self):
        t, _ = _probe_tablet("flag-", n=8000)
        from yugabyte_db_tpu.ops.plan_fusion import PLAN_STATS
        l0 = PLAN_STATS["launches"]
        flags.set_flag("join_pushdown_enabled", False)
        resp = t.read(_join_req(_build_wire()))
        assert resp.backend == "cpu"
        assert PLAN_STATS["launches"] == l0


# --- routes: streaming / monolithic / bypass byte-identity ----------------

class TestFusedPlanRoutes:
    def test_chunk_straddling_probes_stream_exactly(self):
        # small chunks: probe rows for one build key straddle many
        # chunk boundaries; streamed partials must combine to the
        # monolithic answer BIT-for-bit on integer lanes
        t, _ = _probe_tablet("strad-", block_rows=2048)
        flags.set_flag("streaming_chunk_rows", 2048)
        from yugabyte_db_tpu.ops.plan_fusion import LAST_PLAN_STATS
        streamed = t.read(_join_req(_build_wire()))
        assert streamed.backend == "tpu"
        assert LAST_PLAN_STATS.get("path") == "streaming"
        assert LAST_PLAN_STATS["chunks"] >= 3
        flags.set_flag("streaming_scan_enabled", False)
        mono = t.read(_join_req(_build_wire()))
        assert LAST_PLAN_STATS.get("path") == "monolithic"
        sk, mk = _by_key(streamed), _by_key(mono)
        assert set(sk) == set(mk)
        for k in sk:
            assert sk[k][0] == mk[k][0]
            assert float(sk[k][1]) == float(mk[k][1])
            assert float(sk[k][3]) == float(mk[k][3])

    def test_bypass_route_byte_identical(self):
        # the bypass session's fused plan must equal the RPC route's
        # answer byte-for-byte at the same chunk plan (streaming) and
        # under min_chunks (monolithic twin)
        t, _ = _probe_tablet("byp-", block_rows=4096)
        flags.set_flag("streaming_chunk_rows", 4096)
        wire = _build_wire()
        rpc = t.read(_join_req(wire))
        assert rpc.backend == "tpu"
        gout = {}
        with BypassSession([t], read_ht=None) as s:
            outs, counts, stats = s.scan_aggregate(
                _WHERE, _AGGS, _GROUP, grouped_out=gout, join=wire)
        assert stats["key_rebuilds"] == 0
        bk = {}
        for g in np.nonzero(np.asarray(counts))[0]:
            key = tuple(str(v[g]) for v in gout["group_values"])
            bk[key] = (int(np.asarray(counts)[g]),) + tuple(
                np.asarray(v)[g] for v in outs)
        rk = _by_key(rpc)
        assert set(bk) == set(rk)
        for k in bk:
            assert bk[k][0] == rk[k][0]
            assert float(bk[k][1]) == float(rk[k][1])
            assert float(bk[k][3]) == float(rk[k][3])

    def test_bypass_typed_reasons(self):
        t, _ = _probe_tablet("bypr-", n=6000)
        wire = _build_wire()
        with BypassSession([t], read_ht=None) as s:
            flags.set_flag("join_pushdown_enabled", False)
            with pytest.raises(BypassIneligible) as e1:
                s.scan_aggregate(_WHERE, _AGGS, _GROUP, join=wire)
            assert e1.value.reason == "join_pushdown_off"
            flags.REGISTRY.reset("join_pushdown_enabled")
            dup = JoinWire(probe_col=1,
                           keys=np.zeros(4, np.int64),
                           payload={BID: (np.array(["A"] * 4, object),
                                          None)})
            with pytest.raises(BypassIneligible) as e2:
                s.scan_aggregate(_WHERE, _AGGS, _GROUP, join=dup)
            assert e2.value.reason == "join_shape"
            assert "duplicate" in e2.value.detail

    def test_growth_never_recompiles_at_same_plan_shape(self):
        # the acceptance gate: MORE data (more chunks, same shared
        # pow2 chunk bucket) and a BIGGER build side (same pow2 table
        # bucket) reuse the cached program — compile count stays flat
        flags.set_flag("streaming_chunk_rows", 4096)
        kern = FusedPlanKernel()
        wire_a = _build_wire(n_build=100)
        wire_b = _build_wire(n_build=120)   # same 256-slot bucket
        t1, _ = _probe_tablet("g1-", n=3 * 4096, block_rows=4096)
        t2, _ = _probe_tablet("g2-", n=9 * 4096, block_rows=4096)
        aggs = (AggSpec("sum", C(2).node), AggSpec("count"))
        got = streaming_plan_aggregate(
            _blocks(t1), [1, 2, 3], _WHERE, aggs, _GROUP, None,
            wire_a, kernel=kern, chunk_rows=4096)
        assert got is not None
        c0 = kern.compiles
        assert c0 == 1
        for t, wire in ((t2, wire_a), (t1, wire_b), (t2, wire_b)):
            got = streaming_plan_aggregate(
                _blocks(t), [1, 2, 3], _WHERE, aggs, _GROUP, None,
                wire, kernel=kern, chunk_rows=4096)
            assert got is not None
        assert kern.compiles == c0, "recompiled at the same plan shape"
        assert len(kern.sig_compiles) == 1
        assert all(v == 1 for v in kern.sig_compiles.values())


# --- the consts-offset regression the fused-plan work exposed -------------

class TestSharedConstsOffsets:
    def test_where_and_agg_constants_do_not_collide(self):
        # BEFORE the offset fix every compiled expression indexed the
        # shared runtime-consts list from 0, so an aggregate
        # expression's literal read the WHERE's first constant: TPC-H
        # Q1's revenue sums were silently wrong on the device path.
        from yugabyte_db_tpu.models.tpch import (TPCH_Q1,
                                                 generate_lineitem,
                                                 lineitem_info)
        from yugabyte_db_tpu.ops.device_batch import build_batch
        from yugabyte_db_tpu.ops.scan import ScanKernel
        data = {k: v[:32768] for k, v in generate_lineitem(0.1).items()}
        t = Tablet("li", lineitem_info(),
                   tempfile.mkdtemp(prefix="consts-"))
        t.bulk_load(data, block_rows=32768)
        blocks = _blocks(t)
        batch = build_batch(blocks, sorted(TPCH_Q1.columns))
        outs, counts, _ = ScanKernel().run(batch, TPCH_Q1.where,
                                           TPCH_Q1.aggs, TPCH_Q1.group)
        m = data["l_shipdate"] <= 10471
        gid = data["l_returnflag"] + 3 * data["l_linestatus"]
        price, disc, tax = (data["l_extendedprice"],
                            data["l_discount"], data["l_tax"])
        for g in range(6):
            mg = m & (gid == g)
            want_disc = (price[mg] * (1 - disc[mg])).sum()
            want_charge = (price[mg] * (1 - disc[mg])
                           * (1 + tax[mg])).sum()
            got_disc = float(np.asarray(outs[2])[g])
            got_charge = float(np.asarray(outs[3])[g])
            assert abs(got_disc - want_disc) / want_disc < 1e-5
            assert abs(got_charge - want_charge) / want_charge < 1e-5


# --- window kernels -------------------------------------------------------

class TestWindowKernel:
    OPS = [("row_number",), ("rank",), ("dense_rank",), ("lag", 1),
           ("lead", 2), ("sum", 1), ("sum", 0), ("count", 1),
           ("rolling_sum", 3), ("min", 0), ("max", 1),
           ("count_star", 1)]

    def _sorted_case(self, n=5000, seed=1):
        rng = np.random.default_rng(seed)
        part = rng.integers(0, 37, n)
        order = rng.integers(0, 15, n)
        vals = rng.integers(-50, 50, n).astype(np.int64)
        vnull = rng.random(n) < 0.1
        perm = np.lexsort((order, part))
        p_s, o_s = part[perm], order[perm]
        seg = np.ones(n, bool)
        seg[1:] = p_s[1:] != p_s[:-1]
        peer = np.zeros(n, bool)
        peer[1:] = (o_s[1:] != o_s[:-1]) & ~seg[1:]
        return seg, peer, vals[perm], vnull[perm]

    def test_device_matches_twin(self):
        seg, peer, v, vn = self._sorted_case()
        values = [None if op[0] in ("row_number", "rank", "dense_rank",
                                    "count_star") else v
                  for op in self.OPS]
        nulls = [None if x is None else vn for x in values]
        kern = WindowKernel()
        dev = kern.run(self.OPS, seg, peer, values, nulls)
        twin = window_cpu(self.OPS, seg, peer, values, nulls)
        for op, (dv, dm), (tv, tm) in zip(self.OPS, dev, twin):
            assert np.array_equal(dm, tm), op
            ok = ~dm
            assert np.array_equal(np.asarray(dv)[ok], tv[ok]), op

    def test_compile_cache_holds(self):
        seg, peer, v, vn = self._sorted_case(n=3000, seed=2)
        kern = WindowKernel()
        kern.run([("rank",), ("sum", 1)], seg, peer, [None, v],
                 [None, vn])
        c0 = kern.compiles
        seg2, peer2, v2, vn2 = self._sorted_case(n=3000, seed=9)
        kern.run([("rank",), ("sum", 1)], seg2, peer2, [None, v2],
                 [None, vn2])
        assert kern.compiles == c0

    def test_cumulative_sum_peers_share(self):
        # one partition, an order-key tie: peers share the cumulative
        # value at the peer-group end (PG's default RANGE frame)
        seg = np.array([True, False, False, False])
        peer = np.array([False, False, True, False])
        v = np.array([1, 2, 4, 8], np.int64)
        vn = np.zeros(4, bool)
        kern = WindowKernel()
        (out, om), = kern.run([("sum", 1)], seg, peer, [v], [vn])
        assert list(out) == [3, 3, 15, 15]
        assert not om.any()


# --- SQL: the fused join pushdown end to end ------------------------------

class TestSqlFusedJoin:
    def _run(self, coro):
        return asyncio.run(coro)

    def test_sql_join_group_fused_vs_classic(self, tmp_path):
        from yugabyte_db_tpu.ql import SqlSession
        from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
        from yugabyte_db_tpu.ops.plan_fusion import PLAN_STATS

        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute(
                    "CREATE TABLE facts (k bigint, fk bigint, v double,"
                    " PRIMARY KEY (k))")
                await s.execute(
                    "CREATE TABLE dims (dk bigint, name text, w bigint,"
                    " PRIMARY KEY (dk))")
                vals = ",".join(f"({i}, {i % 7}, {float(i % 11)})"
                                for i in range(400))
                await s.execute(
                    "INSERT INTO facts (k, fk, v) VALUES " + vals)
                dv = ",".join(f"({d}, 'name{d % 3}', {d * 10})"
                              for d in range(5))
                await s.execute(
                    "INSERT INTO dims (dk, name, w) VALUES " + dv)
                flags.set_flag("tpu_min_rows_for_pushdown", 0)
                q = ("SELECT name, sum(v) AS sv, count(*) AS c, "
                     "sum(w) AS sw FROM facts JOIN dims ON fk = dk "
                     "WHERE v > 2 AND w < 40 GROUP BY name "
                     "ORDER BY name")
                l0 = PLAN_STATS["launches"]
                r1 = (await s.execute(q)).rows
                assert PLAN_STATS["launches"] > l0, \
                    "SQL fused join never reached the plan kernel"
                flags.set_flag("plan_fusion_enabled", False)
                r2 = (await s.execute(q)).rows
                # integer-valued lanes: results must be identical
                assert r1 == r2
                # scalar shape too
                flags.REGISTRY.reset("plan_fusion_enabled")
                q2 = ("SELECT count(*) AS c, sum(v) AS sv FROM facts "
                      "JOIN dims ON fk = dk WHERE w < 40")
                r3 = (await s.execute(q2)).rows
                flags.set_flag("plan_fusion_enabled", False)
                r4 = (await s.execute(q2)).rows
                assert r3 == r4
            finally:
                await mc.shutdown()
        self._run(go())

    def test_sql_join_decimal_where_matches_classic(self, tmp_path):
        # DECIMAL columns store as text: the fused binder must wrap
        # them in cast_numeric exactly like _bind, or the interpreted
        # fallback compares text against numbers (review regression)
        from yugabyte_db_tpu.ql import SqlSession
        from yugabyte_db_tpu.tools.mini_cluster import MiniCluster

        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute(
                    "CREATE TABLE f2 (k bigint, fk bigint, d numeric,"
                    " PRIMARY KEY (k))")
                await s.execute(
                    "CREATE TABLE d2 (dk bigint, name text,"
                    " PRIMARY KEY (dk))")
                vals = ",".join(f"({i}, {i % 3}, {(i % 9) / 100})"
                                for i in range(60))
                await s.execute(
                    "INSERT INTO f2 (k, fk, d) VALUES " + vals)
                await s.execute(
                    "INSERT INTO d2 (dk, name) VALUES (0,'a'),"
                    "(1,'b'),(2,'a')")
                flags.set_flag("tpu_min_rows_for_pushdown", 0)
                q = ("SELECT name, count(*) AS c FROM f2 JOIN d2 "
                     "ON fk = dk WHERE d > 0.05 GROUP BY name "
                     "ORDER BY name")
                r1 = (await s.execute(q)).rows
                # i%9 in {6,7,8} passes d > 0.05; fk=i%3 maps those to
                # 'a' (fk 0,2) 6+6 and 'b' (fk 1) 6.  (The CLASSIC
                # client join can't serve this residual shape — decimal
                # text vs float in _eval_by_name is a pre-existing
                # limitation — so the fused path is compared against
                # the arithmetic, not against it.)
                assert r1 == [{"name": "a", "c": 12},
                              {"name": "b", "c": 6}]
            finally:
                await mc.shutdown()
        self._run(go())

    def test_sql_windows_device_bit_identical(self, tmp_path):
        from yugabyte_db_tpu.ql import SqlSession
        from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
        from yugabyte_db_tpu.ops.window_scan import WINDOW_STATS

        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute("CREATE TABLE w (k bigint, g bigint, "
                                "v bigint, PRIMARY KEY (k))")
                vals = ",".join(f"({i}, {i % 5}, {(i * 7) % 23})"
                                for i in range(200))
                await s.execute("INSERT INTO w (k, g, v) VALUES " + vals)
                q = ("SELECT k, rank() OVER (PARTITION BY g ORDER BY v)"
                     " AS rk, sum(v) OVER (PARTITION BY g ORDER BY v) "
                     "AS s, lag(v) OVER (PARTITION BY g ORDER BY v) "
                     "AS lg, row_number() OVER (PARTITION BY g "
                     "ORDER BY v DESC) AS rn FROM w ORDER BY k")
                l0 = WINDOW_STATS["launches"]
                r1 = (await s.execute(q)).rows
                assert WINDOW_STATS["launches"] > l0, \
                    "window kernel never launched"
                flags.set_flag("window_pushdown_enabled", False)
                r2 = (await s.execute(q)).rows
                assert r1 == r2
            finally:
                await mc.shutdown()
        self._run(go())
