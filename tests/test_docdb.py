"""DocDB layer tests: write/read round trips, MVCC semantics, CPU vs TPU
scan equivalence, bulk-loaded columnar-only SSTs.

Modeled on the reference's docdb tests (reference:
src/yb/docdb/docdb-test.cc, docrowwiseiterator-test.cc) plus the
cross-backend checking its in_mem_docdb.cc model provides.
"""
import numpy as np
import pytest

from yugabyte_db_tpu.docdb import (
    DocReadOperation, DocWriteOperation, ReadRequest, RowOp, TableCodec,
    TableInfo, WriteRequest,
)
from yugabyte_db_tpu.dockv.packed_row import (
    ColumnSchema, ColumnType, TableSchema,
)
from yugabyte_db_tpu.dockv.partition import PartitionSchema
from yugabyte_db_tpu.ops import AggSpec, Expr
from yugabyte_db_tpu.ops.scan import GroupSpec
from yugabyte_db_tpu.storage.lsm import LsmStore
from yugabyte_db_tpu.utils import flags
from yugabyte_db_tpu.utils.hybrid_time import HybridTime

C = Expr.col


def make_table():
    schema = TableSchema(columns=(
        ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
        ColumnSchema(1, "qty", ColumnType.FLOAT64),
        ColumnSchema(2, "price", ColumnType.FLOAT64),
        ColumnSchema(3, "flag", ColumnType.INT32),
        ColumnSchema(4, "name", ColumnType.STRING),
    ), version=1)
    return TableInfo("t1", "items", schema, PartitionSchema("hash", 1))


@pytest.fixture
def env(tmp_path):
    info = make_table()
    codec = TableCodec(info)
    store = LsmStore(str(tmp_path), columnar_builder=codec.columnar_builder,
                     row_decoder=codec.row_decoder)
    read = DocReadOperation(codec, store)
    return info, codec, store, read


def write_rows(codec, store, rows, ht_micros, kind="upsert"):
    req = WriteRequest("t1", [RowOp(kind, r) for r in rows])
    batch, n = DocWriteOperation(codec, req).apply(
        HybridTime.from_micros(ht_micros))
    store.apply(batch)
    return n


def ht(micros):
    return HybridTime.from_micros(micros).value


class TestWriteRead:
    def test_upsert_get(self, env):
        info, codec, store, read = env
        write_rows(codec, store, [
            {"k": 1, "qty": 2.5, "price": 10.0, "flag": 0, "name": "a"},
            {"k": 2, "qty": 7.5, "price": 20.0, "flag": 1, "name": "b"},
        ], 100)
        row = read.get_row({"k": 2}, ht(200))
        assert row == {"k": 2, "qty": 7.5, "price": 20.0, "flag": 1,
                       "name": "b"}
        assert read.get_row({"k": 3}, ht(200)) is None

    def test_mvcc_versions(self, env):
        info, codec, store, read = env
        write_rows(codec, store, [{"k": 1, "qty": 1.0, "price": 1.0,
                                   "flag": 0, "name": "v1"}], 100)
        write_rows(codec, store, [{"k": 1, "qty": 2.0, "price": 2.0,
                                   "flag": 0, "name": "v2"}], 200)
        assert read.get_row({"k": 1}, ht(150))["name"] == "v1"
        assert read.get_row({"k": 1}, ht(250))["name"] == "v2"
        assert read.get_row({"k": 1}, ht(50)) is None

    def test_delete_tombstone(self, env):
        info, codec, store, read = env
        write_rows(codec, store, [{"k": 1, "qty": 1.0, "price": 1.0,
                                   "flag": 0, "name": "x"}], 100)
        write_rows(codec, store, [{"k": 1}], 200, kind="delete")
        assert read.get_row({"k": 1}, ht(150)) is not None
        assert read.get_row({"k": 1}, ht(250)) is None

    def test_get_survives_flush(self, env):
        info, codec, store, read = env
        write_rows(codec, store, [{"k": i, "qty": float(i), "price": 1.0,
                                   "flag": 0, "name": str(i)}
                                  for i in range(20)], 100)
        store.flush()
        assert read.get_row({"k": 13}, ht(200))["qty"] == 13.0


def load_rows(codec, store, n=500, ht_micros=100):
    rng = np.random.default_rng(0)
    rows = []
    for i in range(n):
        rows.append({"k": i, "qty": float(rng.uniform(0, 50)),
                     "price": float(rng.uniform(1, 100)),
                     "flag": int(rng.integers(0, 3)), "name": f"n{i}"})
    write_rows(codec, store, rows, ht_micros)
    return rows


class TestScan:
    def test_cpu_scan_filter_project(self, env):
        info, codec, store, read = env
        rows = load_rows(codec, store)
        resp = read.execute(ReadRequest(
            "t1", columns=("k", "qty"), where=(C(1) > 40.0).node,
            read_ht=ht(200)))
        expect = [r for r in rows if r["qty"] > 40.0]
        assert resp.backend == "cpu"
        assert len(resp.rows) == len(expect)
        assert all(set(r) == {"k", "qty"} for r in resp.rows)

    def test_cpu_paging(self, env):
        info, codec, store, read = env
        load_rows(codec, store, n=100)
        got = []
        paging = None
        pages = 0
        while True:
            resp = read.execute(ReadRequest(
                "t1", columns=("k",), limit=17, paging_state=paging,
                read_ht=ht(200)))
            got += resp.rows
            pages += 1
            if resp.paging_state is None:
                break
            paging = resp.paging_state
        assert len(got) == 100
        assert len({r["k"] for r in got}) == 100
        assert pages >= 6

    def test_cpu_tpu_aggregate_equivalence(self, env):
        info, codec, store, read = env
        rows = load_rows(codec, store, n=3000)
        store.flush()
        req = ReadRequest(
            "t1", where=(C(1) < 25.0).node,
            aggregates=(AggSpec("sum", (C(1) * C(2)).node), AggSpec("count")),
            read_ht=ht(200))
        flags.set_flag("tpu_min_rows_for_pushdown", 100)
        try:
            tpu = read.execute(req)
            flags.set_flag("tpu_pushdown_enabled", False)
            cpu = read.execute(req)
        finally:
            flags.REGISTRY.reset("tpu_pushdown_enabled")
            flags.REGISTRY.reset("tpu_min_rows_for_pushdown")
        assert tpu.backend == "tpu" and cpu.backend == "cpu"
        np.testing.assert_allclose(float(tpu.agg_values[0]),
                                   float(cpu.agg_values[0]), rtol=1e-4)
        assert int(tpu.agg_values[1]) == int(cpu.agg_values[1])

    def test_grouped_equivalence(self, env):
        info, codec, store, read = env
        load_rows(codec, store, n=3000)
        store.flush()
        req = ReadRequest(
            "t1",
            aggregates=(AggSpec("sum", C(1).node), AggSpec("count")),
            group_by=GroupSpec(cols=((3, 3, 0),)), read_ht=ht(200))
        flags.set_flag("tpu_min_rows_for_pushdown", 100)
        try:
            tpu = read.execute(req)
            flags.set_flag("tpu_pushdown_enabled", False)
            cpu = read.execute(req)
        finally:
            flags.REGISTRY.reset("tpu_pushdown_enabled")
            flags.REGISTRY.reset("tpu_min_rows_for_pushdown")
        np.testing.assert_allclose(np.asarray(tpu.agg_values[0]),
                                   np.asarray(cpu.agg_values[0]), rtol=1e-3)
        np.testing.assert_array_equal(np.asarray(tpu.agg_values[1]),
                                      np.asarray(cpu.agg_values[1]))

    def test_tpu_aggregate_with_unflushed_updates(self, env):
        """Memtable rows overlap an SST: the dedup path must pick the
        newest version."""
        info, codec, store, read = env
        load_rows(codec, store, n=2000, ht_micros=100)
        store.flush()
        # update 100 rows later
        rows2 = [{"k": i, "qty": 1000.0, "price": 1.0, "flag": 0,
                  "name": "upd"} for i in range(100)]
        write_rows(codec, store, rows2, 300)
        req = ReadRequest(
            "t1", aggregates=(AggSpec("max", C(1).node), AggSpec("count")),
            read_ht=ht(400))
        flags.set_flag("tpu_min_rows_for_pushdown", 100)
        try:
            tpu = read.execute(req)
            flags.set_flag("tpu_pushdown_enabled", False)
            cpu = read.execute(req)
        finally:
            flags.REGISTRY.reset("tpu_pushdown_enabled")
            flags.REGISTRY.reset("tpu_min_rows_for_pushdown")
        assert tpu.backend == "tpu"
        assert float(tpu.agg_values[0]) == float(cpu.agg_values[0]) == 1000.0
        assert int(tpu.agg_values[1]) == int(cpu.agg_values[1]) == 2000


class TestBulkLoad:
    def test_bulk_blocks_roundtrip(self, env):
        info, codec, store, read = env
        n = 1000
        cols = {
            "k": np.arange(n, dtype=np.int64),
            "qty": np.linspace(0, 50, n),
            "price": np.linspace(1, 100, n),
            "flag": (np.arange(n) % 3).astype(np.int32),
            "name": np.array([f"s{i}" for i in range(n)], object),
        }
        blocks = codec.bulk_blocks(cols, HybridTime.from_micros(100),
                                   block_rows=256)

        def build(w):
            for b in blocks:
                w.add_columnar_block(b)
        store.ingest_sst(build)
        # point get via row_decoder on columnar-only SST
        row = read.get_row({"k": 500}, ht(200))
        assert row["name"] == "s500"
        np.testing.assert_allclose(row["qty"], cols["qty"][500])
        # TPU aggregate over columnar-only blocks
        flags.set_flag("tpu_min_rows_for_pushdown", 100)
        try:
            resp = read.execute(ReadRequest(
                "t1", aggregates=(AggSpec("sum", C(1).node),),
                where=(C(1) < 10.0).node, read_ht=ht(200)))
        finally:
            flags.REGISTRY.reset("tpu_min_rows_for_pushdown")
        assert resp.backend == "tpu"
        m = cols["qty"] < 10.0
        np.testing.assert_allclose(float(resp.agg_values[0]),
                                   cols["qty"][m].sum(), rtol=1e-4)

    def test_bulk_partition_filter(self, env):
        info, codec, store, read = env
        from yugabyte_db_tpu.dockv.partition import PartitionSchema
        parts = info.partition_schema.create_partitions(4)
        n = 400
        cols = {
            "k": np.arange(n, dtype=np.int64),
            "qty": np.ones(n), "price": np.ones(n),
            "flag": np.zeros(n, np.int32),
            "name": np.array(["x"] * n, object),
        }
        total = 0
        for p in parts:
            blocks = codec.bulk_blocks(cols, HybridTime.from_micros(1),
                                       partition=p)
            total += sum(b.n for b in blocks)
        assert total == n   # every row lands in exactly one partition
