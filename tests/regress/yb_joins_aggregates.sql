-- joins, grouping, HAVING, CTEs, windows
CREATE TABLE dept (d bigint, dname text, PRIMARY KEY (d)) WITH tablets = 1;
CREATE TABLE emp (e bigint, d bigint, sal double, PRIMARY KEY (e)) WITH tablets = 2;
INSERT INTO dept (d, dname) VALUES (1, 'eng'), (2, 'ops'), (3, 'empty');
INSERT INTO emp (e, d, sal) VALUES (10, 1, 100.0), (11, 1, 200.0), (12, 2, 150.0), (13, 99, 10.0);
SELECT dname, sal FROM emp JOIN dept ON emp.d = dept.d ORDER BY sal;
SELECT dname, sal FROM emp LEFT JOIN dept ON emp.d = dept.d ORDER BY sal;
SELECT dname FROM emp RIGHT JOIN dept ON emp.d = dept.d WHERE sal IS NULL ORDER BY dname;
SELECT d, sum(sal) AS total FROM emp GROUP BY d HAVING sum(sal) > 50 ORDER BY d;
WITH rich AS (SELECT e, sal FROM emp WHERE sal >= 150) SELECT count(*) FROM rich;
SELECT e, sal, rank() OVER (ORDER BY sal DESC) AS r FROM emp ORDER BY r LIMIT 3;
SELECT d, avg(sal) FROM emp GROUP BY d ORDER BY d;
DROP TABLE emp;
DROP TABLE dept;
CREATE TABLE agt (k bigint PRIMARY KEY, v bigint, f double) WITH tablets = 2;
INSERT INTO agt (k, v, f) VALUES (1, 10, 1.5), (2, 20, 2.5);
SELECT sum(v), min(v), max(v) FROM agt;
SELECT sum(f), avg(v) FROM agt;
DROP TABLE agt;
