-- round-5 SQL breadth: TRUNCATE / COUNT(DISTINCT) / ILIKE / NULLS
CREATE TABLE w (k bigint PRIMARY KEY, grp text, v bigint) WITH tablets = 2;
INSERT INTO w (k, grp, v) VALUES (1, 'A', 5), (2, 'a', 5), (3, 'b', 7), (4, 'A', NULL), (5, 'b', 5);
SELECT count(distinct v) FROM w;
SELECT count(distinct grp) FROM w;
SELECT grp, count(distinct v) FROM w GROUP BY grp ORDER BY grp;
SELECT k FROM w WHERE grp ILIKE 'a%' ORDER BY k;
SELECT grp FROM w WHERE grp LIKE 'a%' ORDER BY k;
SELECT k FROM w ORDER BY v ASC NULLS LAST, k LIMIT 3;
SELECT k FROM w ORDER BY v DESC NULLS FIRST LIMIT 2;
-- non-default NULLS ordering is rejected, not silently wrong
SELECT k FROM w ORDER BY v ASC NULLS FIRST;
TRUNCATE TABLE w;
SELECT count(*) FROM w;
INSERT INTO w (k, grp, v) VALUES (10, 'fresh', 1);
SELECT k, grp FROM w;
-- ORDER BY expressions and ordinals
INSERT INTO w (k, grp, v) VALUES (11, 'Mid', 3), (12, 'zz', 2);
SELECT upper(grp) FROM w ORDER BY upper(grp);
SELECT k, grp FROM w ORDER BY 2 DESC, 1;
SELECT length(grp) AS n, k FROM w ORDER BY length(grp), k;
SELECT grp FROM w ORDER BY 9;
DROP TABLE w;
