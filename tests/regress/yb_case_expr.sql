-- CASE / COALESCE / NULLIF / string + math scalar functions
CREATE TABLE ppl (id bigint, name text, age bigint, nick text, PRIMARY KEY (id)) WITH tablets = 1;
INSERT INTO ppl (id, name, age, nick) VALUES (1, 'Alice', 17, NULL), (2, 'bob', 34, 'B'), (3, 'Carol', 70, NULL);
SELECT name, CASE WHEN age < 18 THEN 'minor' WHEN age < 65 THEN 'adult' ELSE 'senior' END AS bracket FROM ppl ORDER BY id;
SELECT name, COALESCE(nick, name) AS display FROM ppl ORDER BY id;
SELECT NULLIF(1, 1) AS a, NULLIF(2, 1) AS b;
SELECT GREATEST(3, 7, 5) AS g, LEAST(3, 7, 5) AS l;
SELECT upper(name) AS u, lower(name) AS lo, length(name) AS n FROM ppl WHERE id = 1;
SELECT substr(name, 1, 3) AS pre, reverse(name) AS rev FROM ppl WHERE id = 3;
SELECT concat(name, '/', age) AS tag FROM ppl ORDER BY id;
SELECT replace(name, 'o', '0') AS s FROM ppl WHERE id = 2;
SELECT abs(-7) AS a, round(2.718, 2) AS r;
SELECT id, age % 7 AS m FROM ppl ORDER BY id;
SELECT CAST(age AS text) AS t FROM ppl WHERE id = 2;
SELECT count(*) FROM ppl WHERE nick IS NULL;
DROP TABLE ppl;
-- simple-form CASE (base WHEN value) rewrites to searched CASE
CREATE TABLE sc (k bigint PRIMARY KEY, b boolean) WITH tablets = 1;
INSERT INTO sc (k, b) VALUES (1, true), (2, false), (3, NULL);
SELECT k, CASE b WHEN true THEN 'yes' WHEN false THEN 'no' ELSE 'unk' END AS a FROM sc ORDER BY k;
SELECT CASE k WHEN 1 THEN 'one' WHEN 2 THEN 'two' END AS n FROM sc ORDER BY k;
DROP TABLE sc;
-- binary type alias maps to bytea storage
CREATE TABLE bt (k bigint PRIMARY KEY, payload binary) WITH tablets = 1;
INSERT INTO bt (k) VALUES (1);
SELECT k FROM bt WHERE payload IS NULL;
DROP TABLE bt;
CREATE SEQUENCE vseq;
SELECT CASE nextval('vseq') WHEN 1 THEN 'one' ELSE 'other' END AS c;
DROP SEQUENCE vseq;
