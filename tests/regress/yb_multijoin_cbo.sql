-- multi-join cost-based ordering: greedy left-deep from ANALYZE
-- cardinalities (reference: PG planner join ordering + batched-NL
-- costing, nodeYbBatchedNestloop.c)
CREATE TABLE fact (id bigint PRIMARY KEY, d1_id bigint, qty bigint) WITH tablets = 1;
CREATE TABLE dim1 (id bigint PRIMARY KEY, d2_id bigint, name text) WITH tablets = 1;
CREATE TABLE dim2 (id bigint PRIMARY KEY, region text) WITH tablets = 1;
INSERT INTO dim2 (id, region) VALUES (1, 'north'), (2, 'south');
INSERT INTO dim1 (id, d2_id, name) SELECT g, 1 + g % 2, 'd' || g FROM generate_series(1, 20) AS g;
INSERT INTO fact (id, d1_id, qty) SELECT g, 1 + g % 20, g % 7 FROM generate_series(1, 200) AS g;
-- without stats: written order stands
EXPLAIN SELECT fact.id, dim2.region FROM fact JOIN dim1 ON fact.d1_id = dim1.id JOIN dim2 ON dim1.d2_id = dim2.id;
SELECT fact.id, dim2.region FROM fact JOIN dim1 ON fact.d1_id = dim1.id JOIN dim2 ON dim1.d2_id = dim2.id ORDER BY fact.id LIMIT 4;
ANALYZE fact;
ANALYZE dim1;
ANALYZE dim2;
-- with stats: EXPLAIN shows the non-written greedy order (smallest outer)
EXPLAIN SELECT fact.id, dim2.region FROM fact JOIN dim1 ON fact.d1_id = dim1.id JOIN dim2 ON dim1.d2_id = dim2.id;
-- and the reordered plan returns the same rows
SELECT fact.id, dim2.region FROM fact JOIN dim1 ON fact.d1_id = dim1.id JOIN dim2 ON dim1.d2_id = dim2.id ORDER BY fact.id LIMIT 4;
SELECT dim2.region, sum(fact.qty) FROM fact JOIN dim1 ON fact.d1_id = dim1.id JOIN dim2 ON dim1.d2_id = dim2.id GROUP BY dim2.region ORDER BY dim2.region;
DROP TABLE fact;
DROP TABLE dim1;
DROP TABLE dim2;
