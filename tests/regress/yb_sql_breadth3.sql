-- round-5 SQL breadth batch 3: postfix NOT, IS DISTINCT FROM,
-- string_agg, LIMIT ALL
CREATE TABLE b3 (k bigint PRIMARY KEY, g text, v bigint, s text) WITH tablets = 1;
INSERT INTO b3 (k, g, v, s) VALUES (1, 'a', 5, 'ax'), (2, 'a', NULL, 'by'), (3, 'b', 5, 'az'), (4, 'b', 7, NULL);
SELECT k FROM b3 WHERE s NOT LIKE 'a%' ORDER BY k;
SELECT k FROM b3 WHERE s NOT ILIKE 'A%' ORDER BY k;
SELECT k FROM b3 WHERE k NOT IN (1, 3) ORDER BY k;
SELECT k FROM b3 WHERE k NOT BETWEEN 2 AND 3 ORDER BY k;
SELECT k FROM b3 WHERE v IS DISTINCT FROM 5 ORDER BY k;
SELECT k FROM b3 WHERE v IS NOT DISTINCT FROM NULL ORDER BY k;
SELECT string_agg(s, ',') FROM b3;
SELECT g, string_agg(s, '-') FROM b3 GROUP BY g ORDER BY g;
SELECT string_agg(s, ',') FROM b3 WHERE k > 100;
SELECT k FROM b3 ORDER BY k LIMIT ALL;
DROP TABLE b3;
-- GROUP BY expressions + ANY/ALL over arrays
CREATE TABLE gx (k bigint PRIMARY KEY, g text, v bigint) WITH tablets = 1;
INSERT INTO gx (k, g, v) VALUES (1, 'Ab', 5), (2, 'ab', 6), (3, 'cd', 1);
SELECT upper(g), count(*) FROM gx GROUP BY upper(g) ORDER BY 1;
SELECT CASE WHEN v > 5 THEN 'hi' ELSE 'lo' END AS band, sum(v) FROM gx GROUP BY CASE WHEN v > 5 THEN 'hi' ELSE 'lo' END ORDER BY band;
SELECT k FROM gx WHERE g = ANY(ARRAY['Ab', 'zz']) ORDER BY k;
SELECT k FROM gx WHERE v > ALL(ARRAY[1, 4]) ORDER BY k;
DROP TABLE gx;
-- GROUP BY ordinals, expression HAVING, no-aggregate grouping
CREATE TABLE gy (k bigint PRIMARY KEY, g text, v bigint) WITH tablets = 1;
INSERT INTO gy (k, g, v) VALUES (1, 'Ab', 5), (2, 'ab', 6), (3, 'cd', 1);
SELECT upper(g), count(*) FROM gy GROUP BY 1 ORDER BY 1;
SELECT upper(g) FROM gy GROUP BY upper(g) ORDER BY 1;
SELECT g, v FROM gy GROUP BY g, v ORDER BY g;
SELECT upper(g), sum(v) FROM gy GROUP BY upper(g) HAVING upper(g) = 'AB';
DROP TABLE gy;
