-- JSON operators (reference: jsonb -> / ->> through YSQL pushdown)
-- DOCUMENTED DEVIATION: our evaluator folds a JSON null into SQL NULL
-- (PG keeps 'null'::jsonb distinct, so doc->'b' IS NOT NULL counts
-- the {"b": null} row in PG but not here)
CREATE TABLE j (k bigint PRIMARY KEY, doc json) WITH tablets = 1;
INSERT INTO j (k, doc) VALUES (1, '{"a": 1, "b": {"c": [10, 20]}, "tag": "x"}');
INSERT INTO j (k, doc) VALUES (2, '{"a": 2, "b": null, "tag": "y"}');
INSERT INTO j (k, doc) VALUES (3, '{"a": 3, "tag": "x"}');
SELECT k, doc->'a' AS a FROM j ORDER BY k;
SELECT doc->'b'->'c'->0 AS c0 FROM j WHERE k = 1;
SELECT k, doc->>'tag' AS tag FROM j ORDER BY k;
SELECT k FROM j WHERE doc->>'tag' = 'x' ORDER BY k;
SELECT count(*) FROM j WHERE doc->'b' IS NOT NULL;
SELECT doc->>'tag' AS tag, count(*) FROM j GROUP BY doc->>'tag' ORDER BY tag;
UPDATE j SET doc = '{"a": 9, "tag": "z"}' WHERE k = 3;
SELECT doc->>'tag' FROM j WHERE k = 3;
DROP TABLE j;
