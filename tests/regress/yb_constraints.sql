-- UNIQUE + FOREIGN KEY constraints (reference: unique indexes via
-- yb_access/yb_lsm.c:233-366 and FK checks through the PG executor)
CREATE TABLE country (code text PRIMARY KEY, name text UNIQUE) WITH tablets = 1;
CREATE TABLE city (id bigint PRIMARY KEY, name text, country_code text REFERENCES country (code)) WITH tablets = 1;
INSERT INTO country (code, name) VALUES ('no', 'norway'), ('jp', 'japan');
INSERT INTO country (code, name) VALUES ('xx', 'norway');
INSERT INTO city (id, name, country_code) VALUES (1, 'oslo', 'no'), (2, 'kyoto', 'jp');
INSERT INTO city (id, name, country_code) VALUES (3, 'atlantis', 'zz');
INSERT INTO city (id, name, country_code) VALUES (4, 'unknown', NULL);
UPDATE city SET country_code = 'zz' WHERE id = 1;
UPDATE city SET country_code = 'jp' WHERE id = 1;
SELECT id, name, country_code FROM city ORDER BY id;
-- freeing a unique value by UPDATE, then reusing it
UPDATE country SET name = 'nippon' WHERE code = 'jp';
INSERT INTO country (code, name) VALUES ('xj', 'japan');
SELECT code, name FROM country ORDER BY code;
-- CREATE UNIQUE INDEX on a column with existing duplicates fails
CREATE TABLE dup (k bigint PRIMARY KEY, v bigint) WITH tablets = 1;
INSERT INTO dup (k, v) VALUES (1, 7), (2, 7);
CREATE UNIQUE INDEX dup_v ON dup (v);
-- multi-row statement with an internal duplicate is rejected whole
CREATE TABLE mr (k bigint PRIMARY KEY, v text UNIQUE) WITH tablets = 1;
INSERT INTO mr (k, v) VALUES (1, 'a'), (2, 'a');
SELECT count(*) FROM mr;
-- parent-delete RESTRICT: a referenced parent row cannot be deleted
DELETE FROM country WHERE code = 'jp';
DELETE FROM city WHERE country_code = 'jp';
DELETE FROM country WHERE code = 'jp';
SELECT code FROM country ORDER BY code;
DROP TABLE city;
DROP TABLE country;
-- composite UNIQUE: duplicates collide on the full tuple
CREATE TABLE pair (id bigint PRIMARY KEY, a bigint, b text, UNIQUE (a, b)) WITH tablets = 1;
INSERT INTO pair (id, a, b) VALUES (1, 1, 'x'), (2, 1, 'y');
INSERT INTO pair (id, a, b) VALUES (3, 1, 'x');
INSERT INTO pair (id, a, b) VALUES (4, 2, 'x');
SELECT id FROM pair ORDER BY id;
DROP TABLE pair;
DROP TABLE dup;
DROP TABLE mr;
-- CHECK constraints (column and table level, NULL passes)
CREATE TABLE ck (k bigint PRIMARY KEY, v bigint CHECK (v > 0), w bigint, CHECK (w < 100)) WITH tablets = 1;
INSERT INTO ck (k, v, w) VALUES (1, 5, 50);
INSERT INTO ck (k, v, w) VALUES (2, -1, 50);
UPDATE ck SET w = 200 WHERE k = 1;
INSERT INTO ck (k, v, w) VALUES (3, NULL, NULL);
SELECT k FROM ck ORDER BY k;
DROP TABLE ck;
