-- sequences + serial columns (reference: PG sequences over YB)
CREATE SEQUENCE s1;
SELECT nextval('s1') AS a;
SELECT nextval('s1') AS b;
SELECT currval('s1') AS c;
CREATE TABLE ser (id bigserial PRIMARY KEY, tag text) WITH tablets = 1;
INSERT INTO ser (tag) VALUES ('p');
INSERT INTO ser (tag) VALUES ('q');
SELECT id, tag FROM ser ORDER BY id;
CREATE SEQUENCE s2 START WITH 100;
INSERT INTO ser (id, tag) VALUES (nextval('s2'), 'r');
SELECT count(*) FROM ser;
SELECT id FROM ser ORDER BY id;
DROP SEQUENCE s2;
DROP TABLE ser;
DROP SEQUENCE s1;
