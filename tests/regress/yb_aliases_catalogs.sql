-- table aliases + pg_catalog / information_schema introspection
CREATE TABLE acc (id bigint, owner text, bal double, PRIMARY KEY (id)) WITH tablets = 1;
INSERT INTO acc (id, owner, bal) VALUES (1, 'ann', 10.5), (2, 'bo', 20.0), (3, 'cy', 0.0);
SELECT a.owner FROM acc a WHERE a.id = 2;
SELECT a.owner, a.bal FROM acc AS a WHERE a.bal > 5 ORDER BY a.bal DESC;
SELECT a.owner AS who, sum(a.bal) AS total FROM acc a GROUP BY a.owner ORDER BY who;
SELECT relname, relkind FROM pg_catalog.pg_class ORDER BY relname;
SELECT tablename FROM pg_tables ORDER BY tablename;
SELECT table_name, table_type FROM information_schema.tables ORDER BY table_name;
SELECT column_name, data_type, is_nullable FROM information_schema.columns WHERE table_name = 'acc' ORDER BY ordinal_position;
SELECT constraint_name, constraint_type FROM information_schema.table_constraints ORDER BY constraint_name;
SELECT c.column_name FROM information_schema.key_column_usage c WHERE c.table_name = 'acc';
SELECT a.attname, a.attnum FROM pg_attribute a JOIN pg_class c ON a.attrelid = c.oid WHERE c.relname = 'acc' ORDER BY a.attnum;
SELECT typname FROM pg_type WHERE oid = 701;
SELECT nspname FROM pg_namespace ORDER BY nspname;
DROP TABLE acc
