-- transactions: atomicity, rollback, read-your-own-writes
CREATE TABLE t (k bigint, v double, PRIMARY KEY (k)) WITH tablets = 2;
INSERT INTO t (k, v) VALUES (1, 10.0);
BEGIN;
INSERT INTO t (k, v) VALUES (2, 20.0);
UPDATE t SET v = 11.0 WHERE k = 1;
SELECT k, v FROM t ORDER BY k;
ROLLBACK;
SELECT k, v FROM t ORDER BY k;
BEGIN;
DELETE FROM t WHERE k = 1;
SELECT count(*) FROM t WHERE k = 2;
COMMIT;
SELECT k FROM t;
DROP TABLE t
