-- window functions (reference: PG WindowAgg through the YSQL executor)
CREATE TABLE w (k bigint PRIMARY KEY, g text, v bigint) WITH tablets = 1;
INSERT INTO w (k, g, v) VALUES (1, 'a', 10), (2, 'a', 30), (3, 'b', 20), (4, 'b', 20), (5, 'a', 20);
SELECT k, row_number() OVER (ORDER BY k) FROM w ORDER BY k;
SELECT k, v, rank() OVER (ORDER BY v) AS r FROM w ORDER BY k;
SELECT k, v, dense_rank() OVER (ORDER BY v) AS dr FROM w ORDER BY k;
SELECT k, g, sum(v) OVER (PARTITION BY g ORDER BY k) AS run FROM w ORDER BY k;
SELECT k, lag(v, 1) OVER (ORDER BY k) AS prev, lead(v, 1) OVER (ORDER BY k) AS nxt FROM w ORDER BY k;
SELECT k, count(*) OVER (PARTITION BY g) AS cnt FROM w ORDER BY k;
SELECT k, avg(v) OVER (PARTITION BY g) AS mean FROM w ORDER BY k;
DROP TABLE w;
