-- string functions, || concat (text and arrays), UPDATE with
-- expressions over the pre-image, NULLIF / GREATEST / LEAST
CREATE TABLE st (k bigint, n text, v double, tags text[], PRIMARY KEY (k)) WITH tablets = 1;
INSERT INTO st (k, n, v, tags) VALUES (1, 'alpha beta', 10.0, ARRAY['x']), (2, 'gamma', 20.0, ARRAY['y','z']);
SELECT k, upper(n) AS up, length(n) AS ln FROM st ORDER BY k;
SELECT substr(n, 7) AS tail, substr(n, 1, 5) AS head FROM st WHERE k = 1;
SELECT replace(n, 'a', '@') AS rep, strpos(n, 'beta') AS pos FROM st WHERE k = 1;
SELECT left(n, 3) AS l3, right(n, 2) AS r2, left(n, -2) AS lneg FROM st WHERE k = 2;
SELECT lpad(n, 8, '.') AS lp, rpad(n, 8, '.') AS rp FROM st WHERE k = 2;
SELECT split_part(n, ' ', 1) AS p1, split_part(n, ' ', 9) AS p9 FROM st WHERE k = 1;
SELECT initcap(n) AS ic, reverse(n) AS rv, trim('  pad  ') AS tr FROM st WHERE k = 1;
SELECT n || '-' || k AS joined FROM st ORDER BY k;
SELECT concat(n, NULL, '!') AS skips_null FROM st WHERE k = 2;
SELECT nullif(v, 10.0) AS nf1, nullif(v, 99.0) AS nf2 FROM st WHERE k = 1;
SELECT greatest(v, 15.0, NULL) AS g, least(v, 15.0) AS l FROM st ORDER BY k;
SELECT tags || ARRAY['w'] AS appended FROM st WHERE k = 1;
SELECT k FROM st WHERE starts_with(n, 'al');
UPDATE st SET v = v * 2 + 1 WHERE k = 1;
SELECT v FROM st WHERE k = 1;
UPDATE st SET n = upper(n), v = v - 0.5 WHERE k = 2;
SELECT n, v FROM st WHERE k = 2;
UPDATE st SET tags = array_append(tags, 'new') WHERE k = 2;
SELECT array_length(tags, 1) AS n FROM st WHERE k = 2;
DROP TABLE st
