-- basic DDL / DML / constraints of the core engine
CREATE TABLE accounts (id bigint, owner text, balance double, PRIMARY KEY (id)) WITH tablets = 2;
INSERT INTO accounts (id, owner, balance) VALUES (1, 'alice', 100.0), (2, 'bob', 250.5), (3, 'carol', 0.0);
SELECT owner, balance FROM accounts WHERE balance > 50 ORDER BY id;
UPDATE accounts SET balance = 300.0 WHERE owner = 'bob';
SELECT sum(balance), count(*), min(balance), max(balance) FROM accounts;
DELETE FROM accounts WHERE balance = 0.0;
SELECT count(*) FROM accounts;
SELECT owner FROM accounts WHERE owner LIKE 'a%';
INSERT INTO accounts (id, owner) VALUES (4, 'dave');
SELECT owner, balance FROM accounts WHERE balance IS NULL;
SELECT id FROM accounts WHERE id IN (1, 4, 99) ORDER BY id;
SELECT count(*) FROM accounts WHERE owner IN (SELECT owner FROM accounts WHERE balance > 200);
DROP TABLE accounts;
SELECT count(*) FROM accounts
