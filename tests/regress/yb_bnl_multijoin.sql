-- multi-way joins with pushed predicates + batched inner fetch
CREATE TABLE region (r bigint, rname text, PRIMARY KEY (r)) WITH tablets = 1;
CREATE TABLE nation (n bigint, r bigint, nname text, PRIMARY KEY (n)) WITH tablets = 1;
CREATE TABLE city (c bigint, n bigint, cname text, pop bigint, PRIMARY KEY (c)) WITH tablets = 2;
INSERT INTO region (r, rname) VALUES (1, 'west'), (2, 'east');
INSERT INTO nation (n, r, nname) VALUES (10, 1, 'aa'), (11, 1, 'bb'), (12, 2, 'cc');
INSERT INTO city (c, n, cname, pop) VALUES (100, 10, 'u', 5), (101, 10, 'v', 9), (102, 11, 'w', 3), (103, 12, 'x', 7), (104, 99, 'orphan', 1);
SELECT cname, nname FROM city JOIN nation ON city.n = nation.n ORDER BY cname;
SELECT cname, nname, rname FROM city JOIN nation ON city.n = nation.n JOIN region ON nation.r = region.r WHERE city.pop > 4 ORDER BY cname;
SELECT cname, nname FROM city LEFT JOIN nation ON city.n = nation.n WHERE city.pop < 2 ORDER BY cname;
SELECT rname, count(*) AS cities, sum(pop) AS people FROM city JOIN nation ON city.n = nation.n JOIN region ON nation.r = region.r GROUP BY rname ORDER BY rname;
SELECT n.nname, count(*) AS k FROM city c JOIN nation n ON c.n = n.n GROUP BY n.nname HAVING count(*) > 1 ORDER BY k;
SELECT nname FROM nation LEFT JOIN city ON nation.n = city.n WHERE cname IS NULL;
DROP TABLE city;
DROP TABLE nation;
DROP TABLE region
