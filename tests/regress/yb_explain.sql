-- EXPLAIN plan shapes (reference: PG EXPLAIN over YB scan/agg pushdown)
CREATE TABLE ex1 (k bigint PRIMARY KEY, g bigint, v bigint) WITH tablets = 1;
CREATE TABLE ex2 (k bigint PRIMARY KEY, w bigint) WITH tablets = 1;
CREATE INDEX exg ON ex1 (g);
EXPLAIN SELECT * FROM ex1 WHERE k = 1;
EXPLAIN SELECT v FROM ex1 WHERE g = 5;
EXPLAIN SELECT sum(v) FROM ex1;
EXPLAIN SELECT g, count(*) FROM ex1 GROUP BY g;
EXPLAIN SELECT ex1.v, ex2.w FROM ex1 JOIN ex2 ON ex1.k = ex2.k WHERE ex2.w > 3;
EXPLAIN SELECT v FROM ex1 ORDER BY v LIMIT 3;
DROP INDEX exg;
EXPLAIN SELECT v FROM ex1 WHERE g = 5;
DROP TABLE ex2;
DROP TABLE ex1;
