-- CTEs and views (reference: PG WITH + view expansion in YSQL)
CREATE TABLE base (k bigint PRIMARY KEY, g text, v bigint) WITH tablets = 1;
INSERT INTO base (k, g, v) VALUES (1, 'x', 5), (2, 'y', 7), (3, 'x', 9);
WITH t AS (SELECT g, v FROM base WHERE v > 5) SELECT g, sum(v) FROM t GROUP BY g ORDER BY g;
WITH a AS (SELECT k, v FROM base), b AS (SELECT k FROM a WHERE v > 6) SELECT count(*) FROM b;
CREATE VIEW big_rows AS SELECT k, g FROM base WHERE v >= 7;
SELECT k, g FROM big_rows ORDER BY k;
CREATE OR REPLACE VIEW big_rows AS SELECT k FROM base WHERE v >= 9;
SELECT k FROM big_rows;
DROP VIEW big_rows;
SELECT k FROM big_rows;
DROP TABLE base;
