-- views (catalog-persisted SELECT bodies), column DEFAULT / NOT NULL,
-- RETURNING on INSERT/UPDATE/DELETE
CREATE TABLE tk (k bigint, status text DEFAULT 'new' NOT NULL, v double DEFAULT 1.5, PRIMARY KEY (k)) WITH tablets = 1;
INSERT INTO tk (k) VALUES (1) RETURNING *;
INSERT INTO tk (k, status, v) VALUES (2, 'open', 4.0), (3, 'done', 9.0) RETURNING k, status;
INSERT INTO tk (k, status) VALUES (4, NULL);
UPDATE tk SET v = v + 1 WHERE status = 'open' RETURNING k, v;
UPDATE tk SET status = NULL WHERE k = 1;
DELETE FROM tk WHERE k = 3 RETURNING k, v;
SELECT k, status, v FROM tk ORDER BY k;
CREATE VIEW live AS SELECT k, v FROM tk WHERE v > 2.0;
SELECT k FROM live ORDER BY k;
SELECT count(*), max(v) FROM live;
CREATE OR REPLACE VIEW live AS SELECT k, v FROM tk WHERE v > 0.0;
SELECT count(*) FROM live;
DROP VIEW live;
SELECT k FROM live;
DROP VIEW IF EXISTS live;
DROP TABLE tk
