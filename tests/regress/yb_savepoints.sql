-- SAVEPOINT / ROLLBACK TO / RELEASE subtransactions (reference:
-- SetActiveSubTransaction + RollbackToSubTransaction through pggate,
-- src/yb/tserver/pg_client.proto)
CREATE TABLE ledger (id bigint, amt bigint, PRIMARY KEY (id)) WITH tablets = 2;
INSERT INTO ledger (id, amt) VALUES (1, 10), (2, 20);
BEGIN;
INSERT INTO ledger (id, amt) VALUES (3, 30);
SAVEPOINT a;
INSERT INTO ledger (id, amt) VALUES (4, 40);
UPDATE ledger SET amt = 11 WHERE id = 1;
SELECT id, amt FROM ledger ORDER BY id;
ROLLBACK TO SAVEPOINT a;
SELECT id, amt FROM ledger ORDER BY id;
INSERT INTO ledger (id, amt) VALUES (5, 50);
SAVEPOINT b;
DELETE FROM ledger WHERE id = 2;
SELECT count(*) FROM ledger;
ROLLBACK TO b;
SELECT count(*) FROM ledger;
RELEASE SAVEPOINT b;
COMMIT;
SELECT id, amt FROM ledger ORDER BY id;
-- nested savepoints: rolling back the outer discards the inner too
BEGIN;
SAVEPOINT outer_sp;
UPDATE ledger SET amt = 999 WHERE id = 1;
SAVEPOINT inner_sp;
UPDATE ledger SET amt = 888 WHERE id = 2;
ROLLBACK TO outer_sp;
SELECT id, amt FROM ledger ORDER BY id;
COMMIT;
-- the savepoint survives its own rollback and can be reused
BEGIN;
SAVEPOINT s;
INSERT INTO ledger (id, amt) VALUES (6, 60);
ROLLBACK TO s;
INSERT INTO ledger (id, amt) VALUES (7, 70);
ROLLBACK TO s;
COMMIT;
SELECT id FROM ledger ORDER BY id;
DROP TABLE ledger;
