-- DISTINCT / OFFSET / multi-key ORDER BY / IN / BETWEEN / LIKE / RETURNING
CREATE TABLE ev (id bigint, kind text, sev bigint, host text, PRIMARY KEY (id)) WITH tablets = 2;
INSERT INTO ev (id, kind, sev, host) VALUES (1, 'warn', 2, 'a'), (2, 'err', 3, 'a'), (3, 'warn', 2, 'b'), (4, 'info', 1, 'b'), (5, 'err', 3, 'c'), (6, 'warn', 1, 'c');
SELECT DISTINCT kind FROM ev ORDER BY kind;
SELECT kind, sev FROM ev ORDER BY sev DESC, kind ASC LIMIT 3;
SELECT id FROM ev ORDER BY id LIMIT 2 OFFSET 3;
SELECT id FROM ev WHERE kind IN ('err', 'info') ORDER BY id;
SELECT id FROM ev WHERE sev BETWEEN 2 AND 3 ORDER BY id;
SELECT id FROM ev WHERE kind LIKE 'w%' ORDER BY id;
SELECT id FROM ev WHERE kind LIKE '%r%' AND host = 'a' ORDER BY id;
UPDATE ev SET sev = 9 WHERE kind = 'err' RETURNING id, sev;
DELETE FROM ev WHERE sev = 9 RETURNING id;
SELECT count(*) FROM ev;
DROP TABLE ev
