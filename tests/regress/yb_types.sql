-- type depth: timestamps + intervals, decimals, booleans, text ops
CREATE TABLE ev (id bigint, at timestamp, amt decimal, flag bool, note text, PRIMARY KEY (id)) WITH tablets = 1;
INSERT INTO ev (id, at, amt, flag, note) VALUES (1, TIMESTAMP '2024-01-01 00:00:00', 10.25, true, 'alpha'), (2, TIMESTAMP '2024-06-15 12:30:00', 0.75, false, 'beta'), (3, TIMESTAMP '2025-01-01 00:00:00', 100.00, true, 'gamma');
SELECT id FROM ev WHERE at >= TIMESTAMP '2024-06-01 00:00:00' ORDER BY id;
SELECT id FROM ev WHERE at < TIMESTAMP '2024-01-01 00:00:00' + INTERVAL '45 days';
SELECT sum(amt) FROM ev;
SELECT id, amt * 2 AS dbl FROM ev WHERE flag = true ORDER BY id;
SELECT count(*) FROM ev WHERE flag = false;
SELECT note FROM ev WHERE note LIKE '%a' ORDER BY note;
SELECT id, CASE WHEN amt > 50 THEN 'big' ELSE 'small' END AS size FROM ev ORDER BY id;
SELECT min(at) FROM ev;
UPDATE ev SET amt = 12.50 WHERE id = 2;
SELECT id, amt FROM ev WHERE id = 2;
CREATE TABLE ev2 (id bigint, amt decimal, PRIMARY KEY (id)) WITH tablets = 1;
INSERT INTO ev2 (id, amt) SELECT id, amt * 2 FROM ev WHERE flag = true;
SELECT id, amt FROM ev2 ORDER BY id;
DROP TABLE ev2;
DROP TABLE ev
