-- tablespaces: DDL, catalog view, placement-bound tables
CREATE TABLESPACE hot WITH placement = 'zone-default:1' WITH preferred = 'zone-default';
SELECT spcname, spcoptions FROM pg_tablespace ORDER BY spcname;
CREATE TABLE metrics (k bigint, v double, PRIMARY KEY (k)) WITH tablets = 1 WITH tablespace = 'hot';
INSERT INTO metrics (k, v) VALUES (1, 1.5), (2, 2.5);
SELECT sum(v) FROM metrics;
CREATE TABLE bad (k bigint, PRIMARY KEY (k)) WITH tablespace = 'missing';
DROP TABLESPACE hot;
DROP TABLE metrics;
DROP TABLESPACE hot;
SELECT spcname FROM pg_tablespace;
DROP TABLESPACE hot
