-- correlated subqueries: per-row subplans (reference: PG correlated
-- SubPlans — Vars with varlevelsup > 0 — through the YSQL executor)
CREATE TABLE author (id bigint PRIMARY KEY, name text) WITH tablets = 1;
CREATE TABLE book (id bigint PRIMARY KEY, author_id bigint, pages bigint) WITH tablets = 1;
INSERT INTO author (id, name) VALUES (1, 'ann'), (2, 'bob'), (3, 'cyd');
INSERT INTO book (id, author_id, pages) VALUES (1, 1, 100), (2, 1, 250), (3, 2, 50);
-- correlated EXISTS / NOT EXISTS
SELECT name FROM author WHERE EXISTS (SELECT 1 FROM book WHERE book.author_id = author.id AND book.pages > 200) ORDER BY name;
SELECT name FROM author WHERE NOT EXISTS (SELECT 1 FROM book WHERE book.author_id = author.id) ORDER BY name;
-- correlated scalar subquery in the select list
SELECT name, (SELECT count(*) FROM book WHERE book.author_id = author.id) AS books FROM author ORDER BY name;
SELECT name, (SELECT max(pages) FROM book WHERE book.author_id = author.id) AS longest FROM author ORDER BY name;
-- correlated scalar in WHERE, mixed with a pushable conjunct
SELECT name FROM author WHERE id < 3 AND (SELECT count(*) FROM book WHERE book.author_id = author.id) = 1 ORDER BY name;
-- correlated IN
SELECT name FROM author WHERE id IN (SELECT author_id FROM book WHERE book.pages < author.id * 100) ORDER BY name;
DROP TABLE book;
DROP TABLE author;
-- correlated DML: per-row subplans in UPDATE/DELETE WHERE
CREATE TABLE a2 (id bigint PRIMARY KEY, v bigint DEFAULT 9) WITH tablets = 1;
CREATE TABLE b2 (id bigint PRIMARY KEY, a_id bigint) WITH tablets = 1;
INSERT INTO a2 (id, v) VALUES (1, 10), (2, 20), (3, 30);
INSERT INTO b2 (id, a_id) VALUES (1, 1), (2, 3);
UPDATE a2 SET v = 0 WHERE EXISTS (SELECT 1 FROM b2 WHERE b2.a_id = a2.id);
SELECT id, v FROM a2 ORDER BY id;
DELETE FROM a2 WHERE NOT EXISTS (SELECT 1 FROM b2 WHERE b2.a_id = a2.id);
SELECT id FROM a2 ORDER BY id;
UPDATE a2 SET v = DEFAULT WHERE id = 1;
SELECT v FROM a2 WHERE id = 1;
DROP TABLE b2;
DROP TABLE a2;
-- join DML: UPDATE ... FROM and DELETE ... USING
CREATE TABLE acc (id bigint PRIMARY KEY, bal bigint) WITH tablets = 1;
CREATE TABLE adj (id bigint PRIMARY KEY, acc_id bigint, delta bigint) WITH tablets = 1;
INSERT INTO acc (id, bal) VALUES (1, 100), (2, 200), (3, 300);
INSERT INTO adj (id, acc_id, delta) VALUES (1, 1, 5), (2, 3, 7);
UPDATE acc SET bal = bal + adj.delta FROM adj WHERE adj.acc_id = acc.id;
SELECT id, bal FROM acc ORDER BY id;
DELETE FROM acc USING adj WHERE adj.acc_id = acc.id AND adj.delta > 6;
SELECT id FROM acc ORDER BY id;
DROP TABLE adj;
DROP TABLE acc;
