-- correlated subqueries: per-row subplans (reference: PG correlated
-- SubPlans — Vars with varlevelsup > 0 — through the YSQL executor)
CREATE TABLE author (id bigint PRIMARY KEY, name text) WITH tablets = 1;
CREATE TABLE book (id bigint PRIMARY KEY, author_id bigint, pages bigint) WITH tablets = 1;
INSERT INTO author (id, name) VALUES (1, 'ann'), (2, 'bob'), (3, 'cyd');
INSERT INTO book (id, author_id, pages) VALUES (1, 1, 100), (2, 1, 250), (3, 2, 50);
-- correlated EXISTS / NOT EXISTS
SELECT name FROM author WHERE EXISTS (SELECT 1 FROM book WHERE book.author_id = author.id AND book.pages > 200) ORDER BY name;
SELECT name FROM author WHERE NOT EXISTS (SELECT 1 FROM book WHERE book.author_id = author.id) ORDER BY name;
-- correlated scalar subquery in the select list
SELECT name, (SELECT count(*) FROM book WHERE book.author_id = author.id) AS books FROM author ORDER BY name;
SELECT name, (SELECT max(pages) FROM book WHERE book.author_id = author.id) AS longest FROM author ORDER BY name;
-- correlated scalar in WHERE, mixed with a pushable conjunct
SELECT name FROM author WHERE id < 3 AND (SELECT count(*) FROM book WHERE book.author_id = author.id) = 1 ORDER BY name;
-- correlated IN
SELECT name FROM author WHERE id IN (SELECT author_id FROM book WHERE book.pages < author.id * 100) ORDER BY name;
DROP TABLE book;
DROP TABLE author;
