-- generate_series + INSERT ... SELECT (bulk population idiom)
SELECT count(*) AS n, sum(i) AS s FROM generate_series(1, 100) i;
SELECT i FROM generate_series(2, 11, 3) i ORDER BY i;
SELECT i * 2 AS dbl FROM generate_series(1, 4) i ORDER BY dbl;
CREATE TABLE gs (k bigint, v double, g bigint, PRIMARY KEY (k)) WITH tablets = 2;
INSERT INTO gs SELECT i, i * 1.5, i % 3 FROM generate_series(1, 1000) i;
SELECT count(*) FROM gs;
SELECT sum(v) FROM gs;
SELECT g, count(*) AS c FROM gs GROUP BY g ORDER BY g;
SELECT k FROM gs WHERE k > 997 ORDER BY k;
DROP TABLE gs
