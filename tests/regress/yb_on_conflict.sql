-- PG-strict INSERT + ON CONFLICT arbitration + SELECT FOR UPDATE
-- (reference: PG ON CONFLICT over arbiter indexes and RowMarkType
-- locks through the YB executor)
CREATE TABLE kv (k bigint PRIMARY KEY, v bigint, tag text UNIQUE) WITH tablets = 1;
INSERT INTO kv (k, v, tag) VALUES (1, 10, 'a'), (2, 20, 'b');
-- plain INSERT is strict: duplicate PK errors
INSERT INTO kv (k, v, tag) VALUES (1, 99, 'z');
-- DO NOTHING skips the conflicting row, inserts the fresh one
INSERT INTO kv (k, v, tag) VALUES (1, 99, 'z'), (3, 30, 'c') ON CONFLICT DO NOTHING;
SELECT k, v, tag FROM kv ORDER BY k;
-- DO UPDATE applies SET over the existing row (excluded.* = proposed)
INSERT INTO kv (k, v, tag) VALUES (1, 99, 'a1') ON CONFLICT (k) DO UPDATE SET v = excluded.v, tag = excluded.tag;
SELECT k, v, tag FROM kv ORDER BY k;
-- SET expressions may read the existing row (the counter idiom)
INSERT INTO kv (k, v, tag) VALUES (2, 5, 'b') ON CONFLICT (k) DO UPDATE SET v = v + excluded.v;
SELECT v FROM kv WHERE k = 2;
-- arbitrating on a UNIQUE column: conflict found via its index
INSERT INTO kv (k, v, tag) VALUES (9, 1, 'c') ON CONFLICT (tag) DO UPDATE SET v = 31;
SELECT k, v, tag FROM kv ORDER BY k;
-- unique violation still errors when the target does not arbitrate it
INSERT INTO kv (k, v, tag) VALUES (10, 1, 'c');
-- RETURNING reports what was actually written
INSERT INTO kv (k, v, tag) VALUES (1, 77, 'r1') ON CONFLICT (k) DO UPDATE SET v = excluded.v RETURNING k, v, tag;
INSERT INTO kv (k, v, tag) VALUES (1, 88, 'r2') ON CONFLICT DO NOTHING RETURNING k, v;
-- the declared arbiter must cover the violated constraint
INSERT INTO kv (k, v, tag) VALUES (1, 0, 'fresh') ON CONFLICT (tag) DO NOTHING;
-- DO UPDATE may re-key the row (delete + strict insert)
INSERT INTO kv (k, v, tag) VALUES (2, 0, 'x') ON CONFLICT (k) DO UPDATE SET k = 20;
SELECT k, v, tag FROM kv ORDER BY k;
-- FOR UPDATE: locking reads inside a transaction (lock + latest read)
BEGIN;
SELECT v FROM kv WHERE k = 1 FOR UPDATE;
UPDATE kv SET v = v + 1 WHERE k = 1;
COMMIT;
SELECT v FROM kv WHERE k = 1;
-- FOR UPDATE restrictions match PG
SELECT count(*) FROM kv FOR UPDATE;
SELECT k FROM kv UNION SELECT k FROM kv FOR UPDATE;
DROP TABLE kv;
