-- set operations: UNION [ALL] / INTERSECT [ALL] / EXCEPT [ALL]
-- (reference: PG set ops, optimizer/prep/prepunion.c)
CREATE TABLE north (id bigint, city text, pop bigint, PRIMARY KEY (id)) WITH tablets = 2;
CREATE TABLE south (id bigint, city text, pop bigint, PRIMARY KEY (id)) WITH tablets = 2;
INSERT INTO north (id, city, pop) VALUES (1, 'oslo', 700), (2, 'turku', 200), (3, 'kyoto', 1400);
INSERT INTO south (id, city, pop) VALUES (1, 'lima', 900), (2, 'turku', 200), (3, 'kyoto', 1400), (4, 'perth', 2000);
SELECT city FROM north UNION SELECT city FROM south ORDER BY city;
SELECT city FROM north UNION ALL SELECT city FROM south ORDER BY city;
SELECT city, pop FROM north INTERSECT SELECT city, pop FROM south ORDER BY city;
SELECT city FROM north EXCEPT SELECT city FROM south;
SELECT city FROM south EXCEPT SELECT city FROM north ORDER BY city;
SELECT city FROM south EXCEPT ALL SELECT city FROM north ORDER BY city;
-- precedence: INTERSECT binds tighter than UNION
SELECT city FROM north INTERSECT SELECT city FROM south UNION SELECT 'extra' ORDER BY city;
-- trailing LIMIT/OFFSET applies to the whole result
SELECT city FROM north UNION SELECT city FROM south ORDER BY city DESC LIMIT 3 OFFSET 1;
-- set ops over aggregates and expressions
SELECT count(*) FROM north UNION SELECT count(*) FROM south ORDER BY count;
-- parenthesized right operand keeps its own LIMIT
SELECT city FROM north UNION ALL (SELECT city FROM south ORDER BY city LIMIT 1) ORDER BY city;
-- trailing clause binds to the WHOLE result even through an INTERSECT chain
SELECT city FROM north UNION SELECT city FROM south INTERSECT SELECT city FROM south ORDER BY city LIMIT 2;
EXPLAIN SELECT city FROM north UNION SELECT city FROM south;
DROP TABLE north;
DROP TABLE south;
