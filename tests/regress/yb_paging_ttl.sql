-- LIMIT/OFFSET paging + row TTL (reference: paging state + expiring rows)
CREATE TABLE pg (k bigint PRIMARY KEY, v bigint) WITH tablets = 2;
INSERT INTO pg (k, v) SELECT g, g * 10 FROM generate_series(1, 25) AS g;
SELECT k FROM pg ORDER BY k LIMIT 5;
SELECT k FROM pg ORDER BY k LIMIT 5 OFFSET 10;
SELECT k FROM pg ORDER BY k DESC LIMIT 3;
SELECT count(*) FROM pg WHERE k BETWEEN 5 AND 24;
INSERT INTO pg (k, v) VALUES (100, 1) USING TTL 30;
SELECT count(*) FROM pg WHERE k = 100;
DROP TABLE pg;
