-- EXISTS / NOT EXISTS, scalar subqueries, FROM-less SELECT,
-- sequences and serial defaults
CREATE SEQUENCE rs START 10;
SELECT nextval('rs') AS v1;
SELECT nextval('rs') AS v2;
SELECT currval('rs') AS cur;
CREATE TABLE qa (k bigint, v double, PRIMARY KEY (k)) WITH tablets = 1;
CREATE TABLE qb (k bigint, w double, PRIMARY KEY (k)) WITH tablets = 1;
INSERT INTO qa (k, v) VALUES (1, 1.0), (2, 2.0), (3, 3.0);
INSERT INTO qb (k, w) VALUES (2, 9.0);
SELECT k FROM qa WHERE EXISTS (SELECT k FROM qb) ORDER BY k;
SELECT k FROM qa WHERE NOT EXISTS (SELECT k FROM qb WHERE w > 50.0) ORDER BY k;
SELECT k FROM qa WHERE EXISTS (SELECT k FROM qb WHERE w > 50.0);
SELECT k FROM qa WHERE v < (SELECT max(w) FROM qb) - 6.5 ORDER BY k;
SELECT k, (SELECT count(*) FROM qb) AS nb FROM qa WHERE k = 3;
SELECT k FROM qa WHERE v = (SELECT w FROM qb WHERE k = 77);
SELECT 2 + 3 AS five, upper('ok') AS u;
CREATE TABLE qs (id bigserial, tag text, PRIMARY KEY (id)) WITH tablets = 1;
INSERT INTO qs (tag) VALUES ('first'), ('second');
SELECT id, tag FROM qs ORDER BY id;
INSERT INTO qs (id, tag) VALUES (nextval('rs'), 'manual');
SELECT id FROM qs WHERE tag = 'manual';
DROP SEQUENCE rs;
DROP TABLE qs;
DROP TABLE qa;
DROP TABLE qb
