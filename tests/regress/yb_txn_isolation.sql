-- transaction semantics observable in one session: read-your-writes,
-- rollback, isolation BEGIN variants, txn aggregate overlays
CREATE TABLE tx (k bigint PRIMARY KEY, v bigint) WITH tablets = 2;
INSERT INTO tx (k, v) VALUES (1, 10), (2, 20);
BEGIN;
INSERT INTO tx (k, v) VALUES (3, 30);
SELECT count(*), sum(v) FROM tx;
UPDATE tx SET v = 11 WHERE k = 1;
SELECT v FROM tx WHERE k = 1;
DELETE FROM tx WHERE k = 2;
SELECT k FROM tx ORDER BY k;
ROLLBACK;
SELECT k, v FROM tx ORDER BY k;
BEGIN TRANSACTION ISOLATION LEVEL SERIALIZABLE;
SELECT v FROM tx WHERE k = 1;
UPDATE tx SET v = 99 WHERE k = 1;
COMMIT;
SELECT v FROM tx WHERE k = 1;
DROP TABLE tx;
