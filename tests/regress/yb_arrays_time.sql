-- arrays (PG t[] syntax over the JSON storage path) with ANY/ALL,
-- array functions, array_agg, EXTRACT, date_trunc, mod, trunc, sqrt, power
CREATE TABLE arr (k bigint, tags text[], nums bigint[], at timestamp, amt decimal, PRIMARY KEY (k)) WITH tablets = 1;
INSERT INTO arr (k, tags, nums, at, amt) VALUES (1, ARRAY['x','y'], ARRAY[1,2,3], TIMESTAMP '2024-03-15 10:30:45', 10.25), (2, ARRAY['z'], ARRAY[4,5], TIMESTAMP '2025-01-01 00:00:00', 3.50), (3, ARRAY['x'], ARRAY[2,9], TIMESTAMP '2024-12-31 23:59:59', -7.125);
SELECT k, nums[1] AS first, nums[2] AS second FROM arr ORDER BY k;
SELECT k FROM arr WHERE nums[1] = 1;
SELECT k FROM arr WHERE 2 = ANY(nums) ORDER BY k;
SELECT k FROM arr WHERE 'x' = ANY(tags) ORDER BY k;
SELECT k FROM arr WHERE 3 < ALL(nums);
SELECT k FROM arr WHERE 99 = ANY(nums);
SELECT k, array_length(nums, 1) AS n, cardinality(tags) AS c FROM arr ORDER BY k;
SELECT array_position(nums, 9) AS pos FROM arr WHERE k = 3;
SELECT array_append(nums, 100) AS app FROM arr WHERE k = 2;
SELECT array_agg(k) AS ks FROM arr;
SELECT k, array_agg(nums[1]) AS firsts FROM arr GROUP BY k ORDER BY k;
SELECT nums[7] AS missing FROM arr WHERE k = 1;
SELECT extract(year FROM at) AS y, extract(month FROM at) AS m, extract(day FROM at) AS d FROM arr ORDER BY k;
SELECT extract(hour FROM at) AS h, extract(dow FROM at) AS dow FROM arr WHERE k = 1;
SELECT k FROM arr WHERE at >= date_trunc('year', TIMESTAMP '2024-06-15 12:00:00') ORDER BY k;
SELECT k FROM arr WHERE date_trunc('month', at) = TIMESTAMP '2024-12-01 00:00:00';
SELECT k % 2 AS m, mod(k, 2) AS m2 FROM arr ORDER BY k;
SELECT mod(-7, 3) AS neg_mod, trunc(amt) AS t, trunc(amt, 1) AS t1 FROM arr WHERE k = 3;
SELECT sqrt(16.0) AS sq, power(2, 8) AS p, power(2.5, 2) AS pf FROM arr WHERE k = 1;
SELECT sum(amt) AS s, avg(amt) AS a, min(amt) AS lo FROM arr;
SELECT round(amt, 1) AS r FROM arr ORDER BY k;
SELECT k FROM arr WHERE amt % 2 = 0.25 ORDER BY k;
DROP TABLE arr
