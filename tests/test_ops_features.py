"""Ops-tier features: snapshots + restore-as-clone, tablet splitting,
CDC streams, xCluster replication (reference analogs:
snapshot-test.cc, tablet-split-itest.cc, xcluster-test.cc)."""
import asyncio

import pytest

from yugabyte_db_tpu.cdc import CdcStream, XClusterReplicator
from yugabyte_db_tpu.docdb import ReadRequest
from yugabyte_db_tpu.docdb.table_codec import TableInfo
from yugabyte_db_tpu.dockv.packed_row import (
    ColumnSchema, ColumnType, TableSchema,
)
from yugabyte_db_tpu.dockv.partition import PartitionSchema
from yugabyte_db_tpu.ops import AggSpec, Expr
from yugabyte_db_tpu.rpc import RpcError
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster

C = Expr.col


def kv_info(name="kv"):
    schema = TableSchema(columns=(
        ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
        ColumnSchema(1, "v", ColumnType.FLOAT64),
    ), version=1)
    return TableInfo("", name, schema, PartitionSchema("hash", 1))


def run(coro):
    return asyncio.run(coro)


class TestSnapshots:
    def test_snapshot_restore_clone(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=2)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": i, "v": float(i)}
                                      for i in range(30)])
                snap = await c.messenger.call(
                    mc.master.messenger.addr, "master", "create_snapshot",
                    {"table": "kv"}, timeout=30.0)
                # mutate after snapshot
                await c.insert("kv", [{"k": 0, "v": 999.0}])
                r = await c.messenger.call(
                    mc.master.messenger.addr, "master", "restore_snapshot",
                    {"snapshot_id": snap["snapshot_id"],
                     "new_name": "kv_restored"}, timeout=30.0)
                await mc.wait_for_leaders("kv_restored")
                row = await c.get("kv_restored", {"k": 0})
                assert row["v"] == 0.0           # pre-mutation image
                assert (await c.get("kv", {"k": 0}))["v"] == 999.0
                agg = await c.scan("kv_restored", ReadRequest(
                    "", aggregates=(AggSpec("count"),)))
                assert int(agg.agg_values[0]) == 30
            finally:
                await mc.shutdown()
        run(go())


class TestSnapshotConsistentCut:
    def test_trim_above_ht_drops_later_versions(self, tmp_path):
        """Unit cut: versions written after the cut HT disappear; the
        pre-cut image (including older versions of the same row) stays."""
        from yugabyte_db_tpu.docdb import ReadRequest, RowOp, WriteRequest
        from yugabyte_db_tpu.tablet import Tablet
        from yugabyte_db_tpu.utils.hybrid_time import (
            HybridClock, MockPhysicalClock,
        )
        from tests.test_tablet import make_info
        clock = HybridClock(MockPhysicalClock(1_000_000))
        t = Tablet("cut1", make_info(), str(tmp_path), clock=clock)
        t.apply_write(WriteRequest("t1", [
            RowOp("upsert", {"k": 1, "v": 1.0, "s": "a"}),
            RowOp("upsert", {"k": 2, "v": 2.0, "s": "b"})]))
        cutoff = clock.now().value
        clock._physical.advance_micros(1000)
        t.apply_write(WriteRequest("t1", [
            RowOp("upsert", {"k": 1, "v": 100.0, "s": "a"}),   # overwrite
            RowOp("upsert", {"k": 3, "v": 3.0, "s": "c"})]))   # new row
        dropped = t.trim_above_ht(cutoff)
        assert dropped == 2
        now = clock.now().value
        r1 = t.read(ReadRequest("t1", pk_eq={"k": 1}, read_ht=now))
        assert r1.rows[0]["v"] == 1.0        # rolled back to the cut
        assert not t.read(ReadRequest("t1", pk_eq={"k": 3},
                                      read_ht=now)).rows
        assert t.read(ReadRequest("t1", pk_eq={"k": 2},
                                  read_ht=now)).rows[0]["v"] == 2.0
        # idempotent: nothing else above the cut
        assert t.trim_above_ht(cutoff) == 0

    def test_snapshot_cut_never_loses_acked_writes(self, tmp_path):
        """The cut HT samples every tserver clock, so a write acked
        BEFORE create_snapshot — even one that merged the tablet HLC
        far ahead via an external (xCluster) HT — is in the restore."""
        async def go():
            import time as _t
            from yugabyte_db_tpu.utils.hybrid_time import HybridTime
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=2)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": i, "v": float(i)}
                                      for i in range(10)])
                future_ht = HybridTime.from_micros(
                    _t.time_ns() // 1000 + 10_000_000).value
                from yugabyte_db_tpu.docdb import RowOp
                await c.write("kv", [RowOp("upsert", {"k": 99, "v": 9.0})],
                              external_ht=future_ht)
                # acked AFTER the HLC jumped ahead: normal write whose HT
                # is ~now+10s — the regression case for a wall-clock cut
                await c.insert("kv", [{"k": 50, "v": 50.0}])
                snap = await c._master_call("create_snapshot",
                                            {"table": "kv"})
                await c._master_call(
                    "restore_snapshot",
                    {"snapshot_id": snap["snapshot_id"],
                     "new_name": "kv_cut"})
                await mc.wait_for_leaders("kv_cut")
                for k, v in [(0, 0.0), (9, 9.0), (99, 9.0), (50, 50.0)]:
                    row = await c.get("kv_cut", {"k": k})
                    assert row is not None and row["v"] == v, (k, row)
            finally:
                await mc.shutdown()
        run(go())


class TestTabletSplit:
    def test_split_preserves_data_and_routing(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=1)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": i, "v": float(i)}
                                      for i in range(50)])
                ct = await c._table("kv")
                parent = ct.locations[0].tablet_id
                await c.messenger.call(
                    mc.master.messenger.addr, "master", "split_tablet",
                    {"tablet_id": parent}, timeout=60.0)
                await mc.wait_for_leaders("kv")
                c2 = mc.client()
                ct2 = await c2._table("kv")
                assert len(ct2.locations) == 2
                # every key still readable post-split
                for i in range(50):
                    row = await c2.get("kv", {"k": i})
                    assert row is not None and row["v"] == float(i)
                agg = await c2.scan("kv", ReadRequest(
                    "", aggregates=(AggSpec("count"),)))
                assert int(agg.agg_values[0]) == 50
                # writes keep working
                await c2.insert("kv", [{"k": 100, "v": 1.0}])
                assert (await c2.get("kv", {"k": 100}))["v"] == 1.0
            finally:
                await mc.shutdown()
        run(go())


class TestCdc:
    def test_stream_plain_and_txn_changes(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=2)
                await mc.wait_for_leaders("kv")
                stream = CdcStream(c, "kv")
                await c.insert("kv", [{"k": 1, "v": 1.0}, {"k": 2, "v": 2.0}])
                changes = await stream.poll()
                assert {ch["row"]["k"] for ch in changes} == {1, 2}
                # no duplicates on re-poll
                assert await stream.poll() == []
                # transactional changes arrive only on commit
                txn = await c.transaction().begin()
                await txn.insert("kv", [{"k": 3, "v": 3.0}])
                assert await stream.poll() == []
                await txn.commit()
                await asyncio.sleep(0.3)
                changes = await stream.poll()
                assert any(ch["row"]["k"] == 3 and ch.get("txn_id")
                           for ch in changes)
                # deletes stream too
                await c.delete("kv", [{"k": 1}])
                changes = await stream.poll()
                assert any(ch["op"] == "delete" for ch in changes)
            finally:
                await mc.shutdown()
        run(go())


class TestXCluster:
    def test_replicates_to_second_universe(self, tmp_path):
        async def go():
            src = await MiniCluster(str(tmp_path / "src"),
                                    num_tservers=1).start()
            dst = await MiniCluster(str(tmp_path / "dst"),
                                    num_tservers=1).start()
            try:
                cs, cd = src.client(), dst.client()
                await cs.create_table(kv_info(), num_tablets=2)
                await src.wait_for_leaders("kv")
                repl = XClusterReplicator(cs, cd, "kv", poll_interval=0.05)
                await repl.ensure_target_table()
                await dst.wait_for_leaders("kv")
                await cs.insert("kv", [{"k": i, "v": float(i)}
                                       for i in range(20)])
                n = 0
                for _ in range(20):
                    n += await repl.step()
                    if n >= 20:
                        break
                    await asyncio.sleep(0.05)
                assert n >= 20
                row = await cd.get("kv", {"k": 7})
                assert row is not None and row["v"] == 7.0
                # delete propagates
                await cs.delete("kv", [{"k": 7}])
                for _ in range(20):
                    await repl.step()
                    if await cd.get("kv", {"k": 7}) is None:
                        break
                    await asyncio.sleep(0.05)
                assert await cd.get("kv", {"k": 7}) is None
            finally:
                await src.shutdown()
                await dst.shutdown()
        run(go())


class TestXClusterSafeTime:
    def test_safe_time_advances_and_reads_consistently(self, tmp_path):
        async def go():
            src = await MiniCluster(str(tmp_path / "src"),
                                    num_tservers=1).start()
            dst = await MiniCluster(str(tmp_path / "dst"),
                                    num_tservers=1).start()
            try:
                cs, cd = src.client(), dst.client()
                await cs.create_table(kv_info(), num_tablets=2)
                await src.wait_for_leaders("kv")
                repl = XClusterReplicator(cs, cd, "kv", poll_interval=0.05)
                await repl.ensure_target_table()
                await dst.wait_for_leaders("kv")
                await cs.insert("kv", [{"k": i, "v": float(i)}
                                       for i in range(10)])
                n = 0
                for _ in range(20):
                    n += await repl.step()
                    if n >= 10:
                        break
                    await asyncio.sleep(0.05)
                r = await cd._master_call("get_xcluster_safe_time",
                                          {"table": "kv"})
                st1 = r["safe_ht"]
                assert st1 > 0
                # a read AT the safe time sees the full replicated cut
                resp = await cd.scan("kv", ReadRequest(
                    "", aggregates=(AggSpec("count"),), read_ht=st1))
                assert int(resp.agg_values[0]) == 10
                # more source writes -> safe time advances monotonically
                await cs.insert("kv", [{"k": 100, "v": 1.0}])
                for _ in range(20):
                    await repl.step()
                    r = await cd._master_call("get_xcluster_safe_time",
                                              {"table": "kv"})
                    if r["safe_ht"] > st1:
                        break
                    await asyncio.sleep(0.05)
                assert r["safe_ht"] > st1
                # cluster-wide min (no table arg) reports this table too
                r = await cd._master_call("get_xcluster_safe_time", {})
                assert r["safe_ht"] > 0 and "kv" in r["tables"]
            finally:
                await src.shutdown()
                await dst.shutdown()
        run(go())


class TestCdcStreamRegistry:
    def test_durable_checkpoints_resume(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=1)
                await mc.wait_for_leaders("kv")
                stream = await CdcStream.create(c, "kv")
                await c.insert("kv", [{"k": 1, "v": 1.0}])
                changes = await stream.poll()
                assert changes
                # at-least-once: checkpoints persist only on explicit ack
                await stream.commit_checkpoints()
                # resume from the registry: no replays
                resumed = await CdcStream.resume(mc.client(),
                                                 stream.stream_id)
                assert await resumed.poll() == []
                await c.insert("kv", [{"k": 2, "v": 2.0}])
                changes = await resumed.poll()
                assert [ch["row"]["k"] for ch in changes] == [2]
            finally:
                await mc.shutdown()
        run(go())


class TestAutoCompaction:
    def test_background_compaction_reduces_ssts(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=1)
                await mc.wait_for_leaders("kv")
                peer = next(p for ts in mc.tservers
                            for p in ts.peers.values())
                for round_ in range(5):
                    await c.insert("kv", [
                        {"k": round_ * 10 + i, "v": 1.0}
                        for i in range(10)])
                    peer.tablet.flush()
                assert peer.tablet.num_sst_files() >= 5
                # wait for the background pass (ticks every ~10s are too
                # slow for tests; trigger the same code path directly)
                await asyncio.get_running_loop().run_in_executor(
                    None, lambda: peer.tablet.compact(major=False))
                assert peer.tablet.num_sst_files() < 5
                agg = await c.scan("kv", ReadRequest(
                    "", aggregates=(AggSpec("count"),)))
                assert int(agg.agg_values[0]) == 50
            finally:
                await mc.shutdown()
        run(go())


class TestSnapshotSchedules:
    def test_schedule_retention_and_pitr_restore(self, tmp_path):
        async def go():
            import time as _t
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=1)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": 1, "v": 1.0}])
                r = await c._master_call(
                    "create_snapshot_schedule",
                    {"table": "kv", "interval_s": 0.0, "keep": 3})
                sched = r["schedule_id"]
                m = mc.master
                # deterministic ticking: stop the 1s maintenance loop so
                # only our manual ticks take snapshots
                m._lb_task.cancel()
                assert await m.tick_snapshot_schedules() == 1
                t_after_first = _t.time()
                await c.insert("kv", [{"k": 1, "v": 2.0}])
                await asyncio.sleep(0.05)
                assert await m.tick_snapshot_schedules() == 1
                assert await m.tick_snapshot_schedules() == 1
                tid = next(t for t, e in m.tables.items()
                           if e["info"]["name"] == "kv")
                sc = m.tables[tid]["snapshot_schedules"][sched]
                assert len(sc["snapshots"]) == 3
                # PITR: restore to just after the FIRST snapshot → v=1
                r = await c._master_call(
                    "restore_snapshot_schedule",
                    {"schedule_id": sched, "at": t_after_first,
                     "new_name": "kv_pitr"})
                await mc.wait_for_leaders("kv_pitr")
                row = await c.get("kv_pitr", {"k": 1})
                assert row["v"] == 1.0
                assert (await c.get("kv", {"k": 1}))["v"] == 2.0
                # retention: a 4th snapshot evicts the oldest (keep=3);
                # re-fetch: catalog commits replace the table entry
                first_snap = sc["snapshots"][0]["snapshot_id"]
                assert await m.tick_snapshot_schedules() == 1
                sc = m.tables[tid]["snapshot_schedules"][sched]
                assert len(sc["snapshots"]) == 3
                assert sc["snapshots"][0]["at"] > t_after_first
                # eviction deletes the snapshot for real (catalog + disk)
                assert first_snap not in m.tables[tid]["snapshots"]
            finally:
                await mc.shutdown()
        run(go())


class TestManagedXCluster:
    def test_master_driven_replication_lifecycle(self, tmp_path):
        """setup_xcluster_replication on the TARGET master spawns the
        poller in its maintenance loop: rows flow without any client-
        side replicator object, safe time publishes, drop stops it."""
        async def go():
            src = await MiniCluster(str(tmp_path / "src"),
                                    num_tservers=1).start()
            dst = await MiniCluster(str(tmp_path / "dst"),
                                    num_tservers=1).start()
            try:
                cs, cd = src.client(), dst.client()
                await cs.create_table(kv_info(), num_tablets=2)
                await src.wait_for_leaders("kv")
                await cs.insert("kv", [{"k": i, "v": float(i)}
                                       for i in range(15)])
                await cd._master_call(
                    "setup_xcluster_replication",
                    {"source_master": list(src.master.messenger.addr),
                     "table": "kv"})
                # rows appear on the target with no manual stepping
                for _ in range(100):
                    try:
                        row = await cd.get("kv", {"k": 14})
                        if row is not None:
                            break
                    except RpcError:
                        pass
                    await asyncio.sleep(0.1)
                assert (await cd.get("kv", {"k": 14}))["v"] == 14.0
                r = await cd._master_call("list_xcluster_replication", {})
                assert "kv" in r["replication"] and "kv" in r["running"]
                # safe time flows too
                for _ in range(50):
                    r2 = await cd._master_call("get_xcluster_safe_time",
                                               {"table": "kv"})
                    if r2["safe_ht"] > 0:
                        break
                    await asyncio.sleep(0.1)
                assert r2["safe_ht"] > 0
                # drop: poller stops; later source writes stay put
                await cd._master_call("drop_xcluster_replication",
                                      {"table": "kv"})
                await cs.insert("kv", [{"k": 500, "v": 1.0}])
                await asyncio.sleep(1.0)
                assert await cd.get("kv", {"k": 500}) is None
            finally:
                await src.shutdown()
                await dst.shutdown()
        run(go())


class TestXClusterResync:
    def test_stream_recovers_from_wal_gc_via_full_resync(self, tmp_path):
        """Rows written before setup whose WAL was GC'd still reach the
        target: the replicator detects CACHE_MISS_ERROR and bootstraps
        with a full copy, then streams new changes."""
        async def go():
            src = await MiniCluster(str(tmp_path / "s"),
                                    num_tservers=1).start()
            dst = await MiniCluster(str(tmp_path / "d"),
                                    num_tservers=1).start()
            try:
                cs, cd = src.client(), dst.client()
                await cs.create_table(kv_info(), num_tablets=1)
                await src.wait_for_leaders("kv")
                await cs.insert("kv", [{"k": i, "v": float(i)}
                                       for i in range(25)])
                # flush + GC the WAL so history is unstreamable
                # (tiny segments so the history spans several files)
                from yugabyte_db_tpu.utils import flags
                flags.REGISTRY.set("log_segment_size_bytes", 256)
                try:
                    await cs.insert("kv", [{"k": 1000 + i, "v": 1.0}
                                           for i in range(10)])
                finally:
                    flags.REGISTRY.reset("log_segment_size_bytes")
                peer = next(p for ts in src.tservers
                            for p in ts.peers.values())
                peer.tablet.flush()
                assert peer.maybe_gc_log() > 0
                repl = XClusterReplicator(cs, cd, "kv",
                                          poll_interval=0.05)
                await repl.ensure_target_table()
                await dst.wait_for_leaders("kv")
                # target has a row the source DELETED during the gap
                await cd.insert("kv", [{"k": 777, "v": 7.0}])
                n = await repl.step()      # CACHE_MISS -> resync
                assert n == 35
                assert (await cd.get("kv", {"k": 13}))["v"] == 13.0
                assert (await cd.get("kv", {"k": 1005}))["v"] == 1.0
                # delete reconciliation removed the stale target row
                assert await cd.get("kv", {"k": 777}) is None
                # post-resync writes stream normally
                await cs.insert("kv", [{"k": 99, "v": 9.0}])
                for _ in range(40):
                    await repl.step()
                    row = await cd.get("kv", {"k": 99})
                    if row is not None:
                        break
                    await asyncio.sleep(0.05)
                assert (await cd.get("kv", {"k": 99}))["v"] == 9.0
            finally:
                await src.shutdown()
                await dst.shutdown()
        run(go())


class TestXClusterTruncate:
    def test_truncate_replicates_to_target(self, tmp_path):
        """A source TRUNCATE streams through get_changes and applies on
        the target at the same stream position: earlier rows vanish,
        later writes survive (without this the universes silently
        diverge)."""
        async def go():
            src = await MiniCluster(str(tmp_path / "src"),
                                    num_tservers=1).start()
            dst = await MiniCluster(str(tmp_path / "dst"),
                                    num_tservers=1).start()
            try:
                cs, cd = src.client(), dst.client()
                await cs.create_table(kv_info(), num_tablets=2)
                await src.wait_for_leaders("kv")
                repl = XClusterReplicator(cs, cd, "kv",
                                          poll_interval=0.05)
                await repl.ensure_target_table()
                await dst.wait_for_leaders("kv")
                await cs.insert("kv", [{"k": i, "v": float(i)}
                                       for i in range(10)])
                for _ in range(20):
                    await repl.step()
                    if await cd.get("kv", {"k": 9}) is not None:
                        break
                    await asyncio.sleep(0.05)
                assert await cd.get("kv", {"k": 9}) is not None
                await cs.truncate_table("kv")
                await cs.insert("kv", [{"k": 100, "v": 1.0}])
                for _ in range(40):
                    await repl.step()
                    if (await cd.get("kv", {"k": 100}) is not None
                            and await cd.get("kv", {"k": 9}) is None):
                        break
                    await asyncio.sleep(0.05)
                from yugabyte_db_tpu.docdb import ReadRequest
                rows = (await cd.scan("kv", ReadRequest(""))).rows
                assert [(r["k"], r["v"]) for r in rows] == [(100, 1.0)]
            finally:
                await src.shutdown()
                await dst.shutdown()
        run(go())

    def test_virtual_wal_emits_truncate_record(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.cdc import VirtualWal
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=2)
                await mc.wait_for_leaders("kv")
                vw = await VirtualWal.create(c, ["kv"])
                await c.insert("kv", [{"k": 1, "v": 1.0}])
                await c.truncate_table("kv")
                recs = []
                for _ in range(60):
                    recs.extend(await vw.get_consistent_changes())
                    if any(r["op"] == "TRUNCATE" for r in recs):
                        break
                    await asyncio.sleep(0.05)
                ops = [r["op"] for r in recs
                       if r["op"] not in ("BEGIN", "COMMIT")]
                # ONE logical record for the whole statement, not one
                # per tablet (the per-tablet WAL entries share the
                # statement ht and merge)
                assert ops.count("TRUNCATE") == 1, ops
                # the insert streamed BEFORE the truncate
                i_ins = next(i for i, r in enumerate(recs)
                             if r["op"] == "upsert")
                i_tr = next(i for i, r in enumerate(recs)
                            if r["op"] == "TRUNCATE")
                assert i_ins < i_tr
            finally:
                await mc.shutdown()
        run(go())
