"""Regression tests for the round-1 advisor findings (ADVICE.md):

1. A follower behind the leader's WAL-GC horizon must never accept a
   gapped append (silent divergence); it recovers via leader-driven
   snapshot install instead (reference: remote bootstrap for followers
   behind log GC).
2. txn status RPCs answer only from the caught-up status-tablet leader
   (reference: TransactionStatusResolver leader-only status).
3. WAL conflict truncation is crash-atomic (old chain or old+new, never
   an empty window; reference: log truncation never deletes acked
   entries first).
4. Leader leases are measured from request SEND time, not ack-gather
   return.
5. Strong reads wait for the MVCC safe time to pass their read_ht
   (reference: mvcc.cc SafeTime wait).
"""
import asyncio
import os

import pytest

from yugabyte_db_tpu.consensus import Log, LogEntry
from yugabyte_db_tpu.docdb import ReadRequest
from yugabyte_db_tpu.ops import AggSpec
from yugabyte_db_tpu.rpc.messenger import RpcError
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
from yugabyte_db_tpu.utils import flags
from tests.test_load_balancer import kv_info


def run(coro):
    return asyncio.run(coro)


class TestGappedAppendRejection:
    def test_follower_rejects_gap(self, tmp_path):
        """Unit: an append that would leave an index gap is rejected
        with needs_bootstrap, not acked."""
        async def go():
            from yugabyte_db_tpu.consensus import (
                PeerSpec, RaftConfig, RaftConsensus,
            )
            from yugabyte_db_tpu.rpc import Messenger
            m = Messenger("gap-test")
            log = Log(str(tmp_path / "wal"), fsync=False)
            log.append([LogEntry(1, 1, "write", b"a"),
                        LogEntry(1, 2, "write", b"b")])

            async def apply(e):
                pass

            cfg = RaftConfig([PeerSpec("me", ("127.0.0.1", 0))])
            c = RaftConsensus("t-gap", "me", cfg, log, m,
                              str(tmp_path), apply)
            # leader GC'd to index 10 and sends [11, 12]: gap past our
            # last_index=2 — must reject with needs_bootstrap
            resp = await c.rpc_update_consensus({
                "term": 1, "leader": "ldr", "prev_index": 0,
                "prev_term": 0,
                "entries": [[1, 11, "write", b"x"],
                            [1, 12, "write", b"y"]],
                "commit_index": 12, "leader_ht": 0,
            })
            assert resp["success"] is False
            assert resp.get("needs_bootstrap") is True
            assert log.last_index == 2          # nothing appended
            # contiguous append still accepted
            resp = await c.rpc_update_consensus({
                "term": 1, "leader": "ldr", "prev_index": 2,
                "prev_term": 1,
                "entries": [[1, 3, "write", b"c"]],
                "commit_index": 3, "leader_ht": 0,
            })
            assert resp["success"] is True and log.last_index == 3
            # with a snapshot floor, entries just above it are fine
            c.snapshot_base_index = 50
            resp = await c.rpc_update_consensus({
                "term": 1, "leader": "ldr", "prev_index": 50,
                "prev_term": 1,
                "entries": [[1, 51, "write", b"z"]],
                "commit_index": 3, "leader_ht": 0,
            })
            assert resp["success"] is True
        run(go())

    def test_lagging_follower_snapshot_install(self, tmp_path):
        """End-to-end: follower down, leader writes + flushes + GCs its
        WAL past the follower (lag cap = 0 retention for peers), then
        the healed follower converges via leader-driven snapshot
        install, not a spliced log."""
        async def go():
            flags.set_flag("log_segment_size_bytes", 1024)
            flags.set_flag("log_gc_max_peer_lag_entries", 1)
            try:
                mc = await MiniCluster(str(tmp_path),
                                       num_tservers=3).start()
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=1,
                                     replication_factor=3)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": i, "v": 1.0}
                                      for i in range(20)])
                # find leader + one follower tserver
                leader_ts = follower_idx = None
                for i, ts in enumerate(mc.tservers):
                    for p in ts.peers.values():
                        if p.is_leader():
                            leader_ts = ts
                        elif follower_idx is None:
                            follower_idx = i
                if leader_ts is None:
                    for ts in mc.tservers:
                        for p in ts.peers.values():
                            if p.is_leader():
                                leader_ts = ts
                follower_uuid = mc.tservers[follower_idx].uuid
                await mc.stop_tserver(follower_idx)
                for batch in range(10):
                    await c.insert("kv", [
                        {"k": 100 + batch * 20 + i, "v": float(batch)}
                        for i in range(20)])
                peer = next(p for p in leader_ts.peers.values())
                peer.tablet.flush()
                assert peer.maybe_gc_log() > 0      # history is GONE
                assert peer.log.first_index > 1
                # heal the follower; leader must snapshot-install it
                new_ts = await mc.restart_tserver(follower_idx)
                fpeer = next(p for p in new_ts.peers.values())
                deadline = asyncio.get_event_loop().time() + 30.0
                while asyncio.get_event_loop().time() < deadline:
                    await asyncio.sleep(0.1)
                    fp = next(iter(new_ts.peers.values()), None)
                    if fp is None:
                        continue
                    base = fp.consensus.snapshot_base_index
                    if (base > 0 and fp.consensus.last_applied
                            >= peer.consensus.commit_index):
                        break
                fp = next(iter(new_ts.peers.values()))
                assert fp.consensus.snapshot_base_index > 0, \
                    "follower was never snapshot-installed"
                # follower data matches: count via follower read
                resp = fp.tablet.read(ReadRequest(
                    "", aggregates=(AggSpec("count"),)))
                assert int(resp.agg_values[0]) == 220
                # and the cluster still serves strongly
                agg = await mc.client().scan("kv", ReadRequest(
                    "", aggregates=(AggSpec("count"),)))
                assert int(agg.agg_values[0]) == 220
                await mc.shutdown()
            finally:
                flags.REGISTRY.reset("log_segment_size_bytes")
                flags.REGISTRY.reset("log_gc_max_peer_lag_entries")
        run(go())


class TestRewriteTruncatedAtomicity:
    def test_old_and_new_coexist_recovers(self, tmp_path):
        """Crash between the rename and the old-segment deletes leaves
        old+new segment files; recovery must produce the truncated
        (new) state, never a misaligned splice."""
        log = Log(str(tmp_path), fsync=False)
        log.append([LogEntry(1, i, "write", b"old%d" % i)
                    for i in range(1, 6)])
        # snapshot the old chain before the conflict truncation
        import shutil
        saved = {}
        for p in log._seg_paths():
            with open(os.path.join(str(tmp_path), p), "rb") as f:
                saved[p] = f.read()
        log.append([LogEntry(2, 3, "write", b"new3")])
        # resurrect the old segments next to the rewritten one
        for name, data in saved.items():
            path = os.path.join(str(tmp_path), name)
            if not os.path.exists(path):
                with open(path, "wb") as f:
                    f.write(data)
        log.close()
        log2 = Log(str(tmp_path), fsync=False)
        assert log2.last_index == 3
        assert log2.entry(3).payload == b"new3"
        assert log2.entry(4) is None

    def test_tmp_file_ignored_on_recovery(self, tmp_path):
        log = Log(str(tmp_path), fsync=False)
        log.append([LogEntry(1, 1, "write", b"a")])
        log.close()
        # a crash mid-rewrite leaves a .tmp — recovery must skip it
        with open(os.path.join(str(tmp_path), "wal-000099.tmp"),
                  "wb") as f:
            f.write(b"\x00" * 7)     # garbage
        log2 = Log(str(tmp_path), fsync=False)
        assert log2.last_index == 1


class TestTxnStatusGate:
    def test_follower_refuses_status(self, tmp_path):
        """A status-tablet NON-leader must refuse txn_status rather than
        answer unknown=ABORTED for a possibly committed txn."""
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=3).start()
            c = mc.client()
            await c.create_table(kv_info(), num_tablets=1,
                                 replication_factor=3)
            await mc.wait_for_leaders("kv")
            txn = await c.transaction().begin()
            await txn.insert("kv", [{"k": 1, "v": 1.0}])
            st_loc = await txn._status_tablet()
            st_tablet = st_loc.tablet_id
            await txn.commit()
            follower_ts = None
            for ts in mc.tservers:
                p = ts.peers.get(st_tablet)
                if p is not None and not p.is_leader():
                    follower_ts = ts
                    break
            assert follower_ts is not None
            with pytest.raises(RpcError) as ei:
                await c.messenger.call(
                    follower_ts.messenger.addr, "tserver", "txn_status",
                    {"tablet_id": st_tablet, "txn_id": txn.txn_id},
                    timeout=5.0)
            assert ei.value.code in ("LEADER_NOT_READY",)
            await mc.shutdown()
        run(go())


class TestInstallSwapRollForward:
    def _mk(self, tmp_path, names):
        for n in names:
            d = os.path.join(str(tmp_path), n)
            os.makedirs(d, exist_ok=True)
            with open(os.path.join(d, "marker.txt"), "w") as f:
                f.write(n)

    def _content(self, tmp_path, n):
        with open(os.path.join(str(tmp_path), n, "marker.txt")) as f:
            return f.read()

    def test_marker_present_rolls_forward(self, tmp_path):
        """Crash right after the commit marker: staged state wins, old
        stores and stale WAL retire."""
        from yugabyte_db_tpu.tserver.tablet_server import TabletServer
        self._mk(tmp_path, ["regular", "intents", "wals",
                            "regular.install", "intents.install"])
        with open(os.path.join(str(tmp_path), "install-commit"),
                  "w") as f:
            f.write("snap-1")
        TabletServer._complete_install_swap(str(tmp_path))
        assert self._content(tmp_path, "regular") == "regular.install"
        assert self._content(tmp_path, "intents") == "intents.install"
        left = set(os.listdir(str(tmp_path)))
        assert "wals" not in left and "install-commit" not in left
        assert not any(n.endswith((".old", ".install")) for n in left)

    def test_marker_present_partial_swap_completes(self, tmp_path):
        """Crash mid-swap (regular already swapped, intents not):
        roll-forward finishes only what remains."""
        from yugabyte_db_tpu.tserver.tablet_server import TabletServer
        self._mk(tmp_path, ["regular", "regular.old", "intents",
                            "intents.install", "wals"])
        with open(os.path.join(str(tmp_path), "install-commit"),
                  "w") as f:
            f.write("snap-1")
        TabletServer._complete_install_swap(str(tmp_path))
        assert self._content(tmp_path, "regular") == "regular"
        assert self._content(tmp_path, "intents") == "intents.install"
        assert "wals" not in os.listdir(str(tmp_path))

    def test_no_marker_discards_partial_fetch(self, tmp_path):
        """Crash mid-fetch (no marker): live dirs untouched, partial
        staging discarded."""
        from yugabyte_db_tpu.tserver.tablet_server import TabletServer
        self._mk(tmp_path, ["regular", "intents", "wals",
                            "regular.install"])
        TabletServer._complete_install_swap(str(tmp_path))
        assert self._content(tmp_path, "regular") == "regular"
        left = set(os.listdir(str(tmp_path)))
        assert "wals" in left
        assert "regular.install" not in left


class TestIntentRecoveryFromStore:
    def test_recover_after_wal_loss(self, tmp_path):
        """Intents that arrived as SST files (snapshot install) rebuild
        participant memory without any WAL entries to replay."""
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            c = mc.client()
            await c.create_table(kv_info(), num_tablets=1,
                                 replication_factor=1)
            await mc.wait_for_leaders("kv")
            txn = await c.transaction().begin()
            await txn.insert("kv", [{"k": 7, "v": 7.0}])
            ts = mc.tservers[0]
            peer = next(p for p in ts.peers.values()
                        if p.participant._intents)
            assert peer.participant._key_holder
            # simulate a replica built purely from snapshot files:
            # flush intents, blow away memory, recover from the store
            peer.tablet.intents.flush()
            keys_before = dict(peer.participant._key_holder)
            peer.participant._intents.clear()
            peer.participant._key_holder.clear()
            peer.participant._txn_meta.clear()
            n = peer.participant.recover_from_store()
            assert n >= 1
            assert peer.participant._key_holder == keys_before
            # the recovered txn can still commit and apply
            await txn.commit()
            row = await c.get("kv", {"k": 7})
            assert row is not None and row["v"] == 7.0
            await mc.shutdown()
        run(go())


class TestSafeTimeReadGate:
    def test_read_waits_for_inflight_write(self, tmp_path):
        """A strong read picking read_ht=now() must not run ahead of a
        queued write whose assigned HT is below it."""
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            c = mc.client()
            await c.create_table(kv_info(), num_tablets=1,
                                 replication_factor=1)
            await mc.wait_for_leaders("kv")
            await c.insert("kv", [{"k": 1, "v": 1.0}])
            ts = mc.tservers[0]
            peer = next(p for p in ts.peers.values())
            now = peer.clock.now().value
            # a write assigned an HT below now() sits in the queue,
            # unreplicated: safe_read_ht must clamp below it
            peer._write_queue.append(
                ({"req": None, "ht": now - 1000}, asyncio.Future()))
            assert peer.safe_read_ht(peer.clock.now().value) < now - 1000
            # the read at read_ht=now blocks until the queue drains
            read_task = asyncio.ensure_future(
                peer.read(ReadRequest("", pk_eq={"k": 1}, read_ht=now)))
            await asyncio.sleep(0.05)
            assert not read_task.done(), \
                "read ran ahead of an in-flight lower-HT write"
            peer._write_queue.clear()
            resp = await asyncio.wait_for(read_task, 5.0)
            assert resp.rows and resp.rows[0]["v"] == 1.0
            await mc.shutdown()
        run(go())
