"""Membership change + load balancer tests (reference analog:
integration-tests/load_balancer-test.cc, raft config change tests)."""
import asyncio

import pytest

from yugabyte_db_tpu.docdb import ReadRequest
from yugabyte_db_tpu.docdb.table_codec import TableInfo
from yugabyte_db_tpu.dockv.packed_row import (
    ColumnSchema, ColumnType, TableSchema,
)
from yugabyte_db_tpu.dockv.partition import PartitionSchema
from yugabyte_db_tpu.ops import AggSpec
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
from yugabyte_db_tpu.tserver import TabletServer


def kv_info(name="kv"):
    schema = TableSchema(columns=(
        ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
        ColumnSchema(1, "v", ColumnType.FLOAT64),
    ), version=1)
    return TableInfo("", name, schema, PartitionSchema("hash", 1))


def run(coro):
    return asyncio.run(coro)


class TestReplicaMove:
    def test_move_replica_to_new_tserver(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=2).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=1,
                                     replication_factor=1)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": i, "v": float(i)}
                                      for i in range(20)])
                ct = await c._table("kv")
                tablet_id = ct.locations[0].tablet_id
                src = ct.locations[0].replicas[0][0]
                dst = next(ts.uuid for ts in mc.tservers if ts.uuid != src)
                await c.messenger.call(
                    mc.master.messenger.addr, "master", "move_replica",
                    {"tablet_id": tablet_id, "from": src, "to": dst},
                    timeout=60.0)
                await mc.wait_for_leaders("kv")
                # data survives the move (log catch-up on the new replica)
                c2 = mc.client()
                for i in (0, 10, 19):
                    row = await c2.get("kv", {"k": i})
                    assert row is not None and row["v"] == float(i)
                # replica now lives on dst only
                src_ts = next(t for t in mc.tservers if t.uuid == src)
                dst_ts = next(t for t in mc.tservers if t.uuid == dst)
                assert tablet_id not in src_ts.peers
                assert tablet_id in dst_ts.peers
            finally:
                await mc.shutdown()
        run(go())

    def test_balancer_drains_blacklisted(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=2).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=2,
                                     replication_factor=1)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": i, "v": 1.0} for i in range(10)])
                victim = mc.tservers[0].uuid
                await c.messenger.call(mc.master.messenger.addr, "master",
                                       "blacklist", {"ts_uuid": victim})
                for _ in range(8):
                    r = await c.messenger.call(
                        mc.master.messenger.addr, "master", "balance_tick",
                        {}, timeout=60.0)
                    for ts in mc.tservers:
                        await ts._heartbeat_once()
                    if not mc.tservers[0].peers:
                        break
                assert not any(
                    not p.coordinator and True
                    for p in mc.tservers[0].peers.values()) or \
                    not mc.tservers[0].peers
                # all data still reachable
                c2 = mc.client()
                agg = await c2.scan("kv", ReadRequest(
                    "", aggregates=(AggSpec("count"),)))
                assert int(agg.agg_values[0]) == 10
            finally:
                await mc.shutdown()
        run(go())

    def test_rf3_add_then_remove_keeps_quorum(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=4).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=1,
                                     replication_factor=3)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": 1, "v": 1.0}])
                ct = await c._table("kv")
                tablet_id = ct.locations[0].tablet_id
                replicas = [u for u, _ in ct.locations[0].replicas]
                dst = next(ts.uuid for ts in mc.tservers
                           if ts.uuid not in replicas)
                await c.messenger.call(
                    mc.master.messenger.addr, "master", "move_replica",
                    {"tablet_id": tablet_id, "from": replicas[0],
                     "to": dst}, timeout=60.0)
                await mc.wait_for_leaders("kv")
                c2 = mc.client()
                await c2.insert("kv", [{"k": 2, "v": 2.0}])
                assert (await c2.get("kv", {"k": 2}))["v"] == 2.0
            finally:
                await mc.shutdown()
        run(go())


class TestZonePlacement:
    def test_rf3_spreads_across_zones(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.master import Master
            from yugabyte_db_tpu.tserver import TabletServer
            from yugabyte_db_tpu.client import YBClient
            m = Master(str(tmp_path / "m"))
            maddr = await m.start()
            tss = []
            # 2 tservers in zone-a, 2 in zone-b, 1 in zone-c
            for i, z in enumerate(["a", "a", "b", "b", "c"]):
                ts = TabletServer(f"ts-{i}", str(tmp_path / f"ts{i}"),
                                  master_addrs=[maddr], zone=f"zone-{z}")
                await ts.start()
                tss.append(ts)
            for _ in range(50):
                for ts in tss:
                    await ts._heartbeat_once()
                if len(m.live_tservers()) == 5:
                    break
                await asyncio.sleep(0.05)
            c = YBClient(maddr)
            await c.create_table(kv_info(), num_tablets=2,
                                 replication_factor=3)
            for ent in m.tablets.values():
                zones = {m.tservers[u]["zone"] for u in ent["replicas"]}
                assert len(zones) == 3      # one replica per zone
            for ts in tss:
                await ts.shutdown()
            await m.shutdown()
        run(go())
