"""Regression tests for the review findings: conflict-claim race,
coordinator failover re-drive, txn expiry, lease behavior."""
import asyncio
import time

import pytest

from yugabyte_db_tpu.rpc import RpcError
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
from tests.test_transactions import kv_info, make_cluster


def run(coro):
    return asyncio.run(coro)


class TestTxnRaces:
    def test_concurrent_same_key_intents_conflict(self, tmp_path):
        """Two txns writing the same key truly concurrently: exactly one
        claims; the other waits (and times out here) — the write-write
        race found in review."""
        async def go():
            mc, c = await make_cluster(str(tmp_path), tablets=1)
            try:
                for ts in mc.tservers:
                    for p in ts.peers.values():
                        p.participant.wait_timeout = 0.6
                t1 = await c.transaction().begin()
                t2 = await c.transaction().begin()
                r = await asyncio.gather(
                    t1.insert("acct", [{"k": 50, "bal": 1.0}]),
                    t2.insert("acct", [{"k": 50, "bal": 2.0}]),
                    return_exceptions=True)
                ok = [x for x in r if not isinstance(x, Exception)]
                errs = [x for x in r if isinstance(x, Exception)]
                assert len(ok) == 1 and len(errs) == 1
                winner = t1 if r[0] == 1 else t2
                await winner.commit()
            finally:
                await mc.shutdown()
        run(go())

    def test_expired_txn_auto_aborts_and_releases_locks(self, tmp_path):
        async def go():
            mc, c = await make_cluster(str(tmp_path), tablets=1)
            try:
                txn = await c.transaction().begin()
                await txn.insert("acct", [{"k": 60, "bal": 1.0}])
                # shrink the deadline and force a sweep
                ts = mc.tservers[0]
                coord = next(p.coordinator for p in ts.peers.values()
                             if p.coordinator is not None)
                coord.txns[txn.txn_id]["deadline"] = time.time() - 1
                await coord.sweep()
                await asyncio.sleep(0.5)
                # locks released: another txn can take the key
                t2 = await c.transaction().begin()
                await t2.insert("acct", [{"k": 60, "bal": 9.0}])
                await t2.commit()
                await asyncio.sleep(0.3)
                assert (await c.get("acct", {"k": 60}))["bal"] == 9.0
                # original commit must fail (already aborted)
                with pytest.raises(RpcError):
                    await txn.commit()
            finally:
                await mc.shutdown()
        run(go())

    def test_sweep_redrives_unresolved_commit(self, tmp_path):
        async def go():
            mc, c = await make_cluster(str(tmp_path), tablets=1)
            try:
                txn = await c.transaction().begin()
                await txn.insert("acct", [{"k": 70, "bal": 5.0}])
                await txn.commit()
                await asyncio.sleep(0.4)
                ts = mc.tservers[0]
                coord = next(p.coordinator for p in ts.peers.values()
                             if p.coordinator is not None)
                st = coord.txns[txn.txn_id]
                assert st["status"] == "COMMITTED"
                # simulate a failover that lost the notification; sweep
                # must be an idempotent re-drive
                st["resolved"] = False
                await coord.sweep()
                assert st.get("resolved") is True
                assert (await c.get("acct", {"k": 70}))["bal"] == 5.0
            finally:
                await mc.shutdown()
        run(go())
