"""Redis command breadth: lists/sets/zsets/INCR-family/string ops,
TYPE/KEYS/DEL across types (reference: redis command table
src/yb/yql/redis/redisserver/redis_commands.cc, storage ops
src/yb/docdb/redis_operation.cc)."""
import asyncio

from yugabyte_db_tpu.ql.redis_server import RedisServer
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
from tests.test_wire_servers import RedisClient


def run(coro):
    return asyncio.run(coro)


async def _client(tmp_path):
    mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
    srv = RedisServer(mc.client(), num_tablets=1)
    addr = await srv.start()
    reader, writer = await asyncio.open_connection(*addr)
    return mc, srv, RedisClient(reader, writer), writer


class TestStringsDepth:
    def test_incr_family_and_string_ops(self, tmp_path):
        async def go():
            mc, srv, r, w = await _client(tmp_path)
            try:
                assert await r.cmd("SET", "n", "10") == "OK"
                assert await r.cmd("INCRBY", "n", "5") == 15
                assert await r.cmd("DECR", "n") == 14
                assert await r.cmd("DECRBY", "n", "4") == 10
                assert await r.cmd("INCRBYFLOAT", "n", "0.5") == "10.5"
                # non-numeric INCR errors, value preserved
                await r.cmd("SET", "s", "abc")
                try:
                    await r.cmd("INCR", "s")
                    assert False, "INCR on non-int should error"
                except RuntimeError:
                    pass
                assert await r.cmd("GET", "s") == "abc"
                assert await r.cmd("APPEND", "s", "def") == 6
                assert await r.cmd("STRLEN", "s") == 6
                assert await r.cmd("GETRANGE", "s", "1", "3") == "bcd"
                assert await r.cmd("GETRANGE", "s", "-3", "-1") == "def"
                assert await r.cmd("SETRANGE", "s", "3", "DEF") == 6
                assert await r.cmd("GET", "s") == "abcDEF"
                assert await r.cmd("SETNX", "s", "zzz") == 0
                assert await r.cmd("SETNX", "fresh", "zzz") == 1
                assert await r.cmd("GETSET", "fresh", "yyy") == "zzz"
                assert await r.cmd("GET", "fresh") == "yyy"
            finally:
                w.close()
                await srv.shutdown()
                await mc.shutdown()
        run(go())


class TestHashesDepth:
    def test_hash_extended(self, tmp_path):
        async def go():
            mc, srv, r, w = await _client(tmp_path)
            try:
                await r.cmd("HSET", "h", "a", "1", "b", "2", "c", "3")
                assert await r.cmd("HLEN", "h") == 3
                assert await r.cmd("HEXISTS", "h", "a") == 1
                assert await r.cmd("HEXISTS", "h", "zz") == 0
                assert await r.cmd("HKEYS", "h") == ["a", "b", "c"]
                assert await r.cmd("HVALS", "h") == ["1", "2", "3"]
                assert await r.cmd("HMGET", "h", "a", "zz", "c") == \
                    ["1", None, "3"]
                assert await r.cmd("HINCRBY", "h", "a", "41") == 42
            finally:
                w.close()
                await srv.shutdown()
                await mc.shutdown()
        run(go())


class TestSets:
    def test_set_commands(self, tmp_path):
        async def go():
            mc, srv, r, w = await _client(tmp_path)
            try:
                assert await r.cmd("SADD", "s", "a", "b", "c") == 3
                assert await r.cmd("SADD", "s", "b", "d") == 1
                assert await r.cmd("SCARD", "s") == 4
                assert await r.cmd("SISMEMBER", "s", "a") == 1
                assert await r.cmd("SISMEMBER", "s", "zz") == 0
                assert await r.cmd("SMEMBERS", "s") == \
                    ["a", "b", "c", "d"]
                assert await r.cmd("SREM", "s", "a", "zz") == 1
                assert await r.cmd("SCARD", "s") == 3
            finally:
                w.close()
                await srv.shutdown()
                await mc.shutdown()
        run(go())


class TestZsets:
    def test_zset_commands(self, tmp_path):
        async def go():
            mc, srv, r, w = await _client(tmp_path)
            try:
                assert await r.cmd("ZADD", "z", "3", "c", "1", "a",
                                   "2", "b") == 3
                assert await r.cmd("ZCARD", "z") == 3
                assert await r.cmd("ZSCORE", "z", "b") == "2"
                assert await r.cmd("ZRANGE", "z", "0", "-1") == \
                    ["a", "b", "c"]
                assert await r.cmd("ZREVRANGE", "z", "0", "1") == \
                    ["c", "b"]
                assert await r.cmd("ZRANGE", "z", "0", "-1",
                                   "WITHSCORES") == \
                    ["a", "1", "b", "2", "c", "3"]
                assert await r.cmd("ZRANGEBYSCORE", "z", "2", "+inf") == \
                    ["b", "c"]
                assert await r.cmd("ZRANGEBYSCORE", "z", "(1", "3") == \
                    ["b", "c"]
                assert await r.cmd("ZINCRBY", "z", "10", "a") == "11"
                assert await r.cmd("ZRANGE", "z", "-1", "-1") == ["a"]
                assert await r.cmd("ZREM", "z", "a", "zz") == 1
                assert await r.cmd("ZCARD", "z") == 2
                # update score of existing member: not a new element
                assert await r.cmd("ZADD", "z", "9", "b") == 0
                assert await r.cmd("ZSCORE", "z", "b") == "9"
            finally:
                w.close()
                await srv.shutdown()
                await mc.shutdown()
        run(go())


class TestLists:
    def test_list_commands(self, tmp_path):
        async def go():
            mc, srv, r, w = await _client(tmp_path)
            try:
                assert await r.cmd("RPUSH", "l", "b", "c") == 2
                assert await r.cmd("LPUSH", "l", "a") == 3
                assert await r.cmd("LLEN", "l") == 3
                assert await r.cmd("LRANGE", "l", "0", "-1") == \
                    ["a", "b", "c"]
                assert await r.cmd("LRANGE", "l", "1", "2") == ["b", "c"]
                assert await r.cmd("LINDEX", "l", "0") == "a"
                assert await r.cmd("LINDEX", "l", "-1") == "c"
                assert await r.cmd("LSET", "l", "1", "B") == "OK"
                assert await r.cmd("LPOP", "l") == "a"
                assert await r.cmd("RPOP", "l") == "c"
                assert await r.cmd("LRANGE", "l", "0", "-1") == ["B"]
                assert await r.cmd("LPOP", "empty") is None
            finally:
                w.close()
                await srv.shutdown()
                await mc.shutdown()
        run(go())


class TestCrossType:
    def test_type_keys_del_exists(self, tmp_path):
        async def go():
            mc, srv, r, w = await _client(tmp_path)
            try:
                await r.cmd("SET", "str1", "v")
                await r.cmd("HSET", "h1", "f", "v")
                await r.cmd("SADD", "set1", "m")
                await r.cmd("ZADD", "z1", "1", "m")
                await r.cmd("RPUSH", "l1", "v")
                assert await r.cmd("TYPE", "str1") == "string"
                assert await r.cmd("TYPE", "h1") == "hash"
                assert await r.cmd("TYPE", "set1") == "set"
                assert await r.cmd("TYPE", "z1") == "zset"
                assert await r.cmd("TYPE", "l1") == "list"
                assert await r.cmd("TYPE", "nope") == "none"
                assert await r.cmd("EXISTS", "str1", "h1", "set1",
                                   "z1", "l1", "nope") == 5
                ks = await r.cmd("KEYS", "*1")
                assert sorted(ks) == ["h1", "l1", "set1", "str1", "z1"]
                # DEL works on every type
                assert await r.cmd("DEL", "h1", "l1", "nope") == 2
                assert await r.cmd("TYPE", "h1") == "none"
                assert await r.cmd("LLEN", "l1") == 0
            finally:
                w.close()
                await srv.shutdown()
                await mc.shutdown()
        run(go())
