"""Concurrency invariant test: concurrent transfer transactions with
conflict retries must conserve the total balance (the classic bank
workload; reference analog: snapshot-txn stress in
ql-transaction-test.cc)."""
import asyncio
import random

import pytest

from yugabyte_db_tpu.rpc import RpcError
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
from tests.test_transactions import kv_info, make_cluster


def run(coro):
    return asyncio.run(coro)


class TestBankTransfers:
    def test_total_conserved_under_concurrency(self, tmp_path):
        async def go():
            mc, c = await make_cluster(str(tmp_path), tablets=2)
            try:
                for ts in mc.tservers:
                    for p in ts.peers.values():
                        p.participant.wait_timeout = 2.0
                n_accounts = 8
                total0 = n_accounts * 100.0
                rng = random.Random(7)

                async def worker(wid: int, n_ops: int):
                    ok = 0
                    for _ in range(n_ops):
                        a, b = rng.sample(range(n_accounts), 2)
                        amount = float(rng.randint(1, 10))
                        txn = await c.transaction().begin()
                        try:
                            ra = await txn.get("acct", {"k": a})
                            rb = await txn.get("acct", {"k": b})
                            await txn.insert("acct", [
                                {"k": a, "bal": ra["bal"] - amount},
                                {"k": b, "bal": rb["bal"] + amount}])
                            await txn.commit()
                            ok += 1
                        except (RpcError, AssertionError):
                            await txn.abort()
                    return ok

                results = await asyncio.gather(
                    *[worker(i, 12) for i in range(4)])
                assert sum(results) > 0     # some transfers succeeded
                # let async applies settle, then check the invariant
                await asyncio.sleep(1.0)
                total = 0.0
                for k in range(n_accounts):
                    row = await c.get("acct", {"k": k})
                    total += row["bal"]
                assert abs(total - total0) < 1e-6, \
                    f"money leaked: {total} != {total0}"
            finally:
                await mc.shutdown()
        run(go())
