"""Vector index SQL integration (pgvector analog): CREATE INDEX ivfflat,
kNN ORDER BY <-> LIMIT, exact fallback (reference analog: vector index
paths in docdb/pgsql_operation.cc:2728 and vector_index/)."""
import asyncio

import numpy as np
import pytest

from yugabyte_db_tpu.ql import SqlSession
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster


def run(coro):
    return asyncio.run(coro)


class TestVectorSql:
    def test_knn_end_to_end(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute(
                    "CREATE TABLE docs (id bigint, body text, "
                    "embedding vector(8), PRIMARY KEY (id)) WITH tablets = 2")
                await mc.wait_for_leaders("docs")
                rng = np.random.default_rng(0)
                vecs = rng.normal(size=(40, 8)).astype(np.float32)
                for i in range(40):
                    vec = "[" + ",".join(f"{x:.5f}" for x in vecs[i]) + "]"
                    await s.execute(
                        f"INSERT INTO docs (id, body, embedding) VALUES "
                        f"({i}, 'doc{i}', '{vec}')")
                # exact (no index yet)
                q = vecs[17] + 0.001
                qlit = "[" + ",".join(f"{x:.5f}" for x in q) + "]"
                r = await s.execute(
                    f"SELECT id, body FROM docs ORDER BY embedding <-> "
                    f"'{qlit}' LIMIT 3")
                assert r.rows[0]["id"] == 17
                assert r.rows[0]["distance"] < r.rows[1]["distance"]
                # with an ivfflat index
                r2 = await s.execute(
                    "CREATE INDEX de ON docs USING ivfflat (embedding) "
                    "WITH lists = 4")
                assert "40 rows" in r2.status
                r3 = await s.execute(
                    f"SELECT id FROM docs ORDER BY embedding <-> "
                    f"'{qlit}' LIMIT 3")
                assert r3.rows[0]["id"] == 17
            finally:
                await mc.shutdown()
        run(go())
