"""Vector index SQL integration (pgvector analog): CREATE INDEX ivfflat,
kNN ORDER BY <-> LIMIT, exact fallback (reference analog: vector index
paths in docdb/pgsql_operation.cc:2728 and vector_index/)."""
import asyncio

import numpy as np
import pytest

from yugabyte_db_tpu.ql import SqlSession
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster


def run(coro):
    return asyncio.run(coro)


class TestVectorSql:
    def test_knn_end_to_end(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute(
                    "CREATE TABLE docs (id bigint, body text, "
                    "embedding vector(8), PRIMARY KEY (id)) WITH tablets = 2")
                await mc.wait_for_leaders("docs")
                rng = np.random.default_rng(0)
                vecs = rng.normal(size=(40, 8)).astype(np.float32)
                for i in range(40):
                    vec = "[" + ",".join(f"{x:.5f}" for x in vecs[i]) + "]"
                    await s.execute(
                        f"INSERT INTO docs (id, body, embedding) VALUES "
                        f"({i}, 'doc{i}', '{vec}')")
                # exact (no index yet)
                q = vecs[17] + 0.001
                qlit = "[" + ",".join(f"{x:.5f}" for x in q) + "]"
                r = await s.execute(
                    f"SELECT id, body FROM docs ORDER BY embedding <-> "
                    f"'{qlit}' LIMIT 3")
                assert r.rows[0]["id"] == 17
                assert r.rows[0]["distance"] < r.rows[1]["distance"]
                # with an ivfflat index
                r2 = await s.execute(
                    "CREATE INDEX de ON docs USING ivfflat (embedding) "
                    "WITH lists = 4")
                assert "40 rows" in r2.status
                r3 = await s.execute(
                    f"SELECT id FROM docs ORDER BY embedding <-> "
                    f"'{qlit}' LIMIT 3")
                assert r3.rows[0]["id"] == 17
            finally:
                await mc.shutdown()
        run(go())

    def test_incremental_maintenance_after_index_build(self, tmp_path):
        """Writes after CREATE INDEX are searchable without a rebuild
        (delta buffer), deletes disappear immediately, and an outgrown
        delta folds back into the frozen IVF chunk."""
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute(
                    "CREATE TABLE docs (id bigint, "
                    "embedding vector(4), PRIMARY KEY (id)) WITH tablets = 1")
                await mc.wait_for_leaders("docs")
                rng = np.random.default_rng(1)
                vecs = rng.normal(size=(30, 4)).astype(np.float32)
                for i in range(30):
                    vec = "[" + ",".join(f"{x:.5f}" for x in vecs[i]) + "]"
                    await s.execute(
                        f"INSERT INTO docs (id, embedding) VALUES "
                        f"({i}, '{vec}')")
                await s.execute(
                    "CREATE INDEX de ON docs USING ivfflat (embedding) "
                    "WITH lists = 4")
                # new row AFTER the build: must be findable (delta path)
                target = np.full(4, 9.0, np.float32)
                tlit = "[" + ",".join(f"{x:.1f}" for x in target) + "]"
                await s.execute(
                    f"INSERT INTO docs (id, embedding) VALUES (100, '{tlit}')")
                r = await s.execute(
                    f"SELECT id FROM docs ORDER BY embedding <-> "
                    f"'{tlit}' LIMIT 1")
                assert r.rows[0]["id"] == 100
                # overwrite an indexed row: new vector wins (PG-strict
                # INSERT needs the explicit upsert form)
                await s.execute(
                    f"INSERT INTO docs (id, embedding) VALUES (5, '{tlit}') "
                    f"ON CONFLICT (id) DO UPDATE "
                    f"SET embedding = excluded.embedding")
                r = await s.execute(
                    f"SELECT id FROM docs ORDER BY embedding <-> "
                    f"'{tlit}' LIMIT 2")
                assert {row["id"] for row in r.rows} == {100, 5}
                # delete hides the frozen copy immediately
                await s.execute("DELETE FROM docs WHERE id = 5")
                r = await s.execute(
                    f"SELECT id FROM docs ORDER BY embedding <-> "
                    f"'{tlit}' LIMIT 2")
                assert 5 not in {row["id"] for row in r.rows}
                # churn past the threshold, then fold the delta in
                peer = next(p for ts in mc.tservers
                            for p in ts.peers.values())
                for i in range(200, 280):
                    vec = "[" + ",".join(
                        f"{x:.5f}" for x in rng.normal(size=4)) + "]"
                    await s.execute(
                        f"INSERT INTO docs (id, embedding) VALUES "
                        f"({i}, '{vec}')")
                # (the ~10s background pass may have folded already;
                # the manual call below is then a no-op)
                peer.tablet.maybe_rebuild_vector_indexes()
                state = next(iter(peer.tablet.vector_indexes.values()))
                assert not state.delta and not state.dead
                assert len(state.pks) == 110   # 30 + id100 + 80 - id5
                r = await s.execute(
                    f"SELECT id FROM docs ORDER BY embedding <-> "
                    f"'{tlit}' LIMIT 1")
                assert r.rows[0]["id"] == 100   # still found post-fold
            finally:
                await mc.shutdown()
        run(go())
