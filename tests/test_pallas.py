"""Pallas fused-scan kernel vs numpy reference (interpret mode on the CPU
mesh; the same kernel compiles for TPU)."""
import numpy as np
import pytest

from yugabyte_db_tpu.ops.pallas_scan import BLOCK_ROWS, q6_scan


class TestPallasScan:
    def test_matches_numpy(self):
        rng = np.random.default_rng(1)
        n = 3 * BLOCK_ROWS + 777    # force padding
        qty = rng.uniform(1, 50, n)
        price = rng.uniform(900, 105000, n)
        disc = rng.integers(0, 11, n) / 100.0
        ship = rng.integers(8036, 10592, n).astype(float)
        s, c = q6_scan(qty, price, disc, ship, 8766, 9131, 0.05, 0.07,
                       24.0, interpret=True)
        m = ((ship >= 8766) & (ship < 9131) & (disc >= 0.05)
             & (disc <= 0.07) & (qty < 24))
        assert c == int(m.sum())
        expect = float((price * disc)[m].sum())
        assert abs(s - expect) <= max(1e-6, 2e-4 * abs(expect))

    def test_empty_match(self):
        n = BLOCK_ROWS
        z = np.zeros(n)
        s, c = q6_scan(z, z, z, z, 10.0, 20.0, 0.5, 0.6, -1.0,
                       interpret=True)
        assert (s, c) == (0.0, 0)


class TestPallasGrouped:
    def test_grouped_sums_match_numpy(self):
        rng = np.random.default_rng(2)
        n = 2 * BLOCK_ROWS + 123
        gids = rng.integers(0, 6, n).astype(np.float64)
        vals = rng.uniform(0, 10, n)
        mask = rng.random(n) < 0.7
        from yugabyte_db_tpu.ops.pallas_scan import grouped_sum
        out = grouped_sum(gids, vals, mask, num_groups=6, interpret=True)
        for g in range(6):
            m = (gids == g) & mask
            np.testing.assert_allclose(out[g], vals[m].sum(), rtol=2e-4)


class TestPallasRoutedPath:
    """The generic pallas path routed from ScanKernel.run behind the
    tpu_pallas_scan flag: results must match the XLA kernel on the
    same batch (Q6-style ungrouped and Q1-style grouped shapes)."""

    def _batch(self, n=3 * BLOCK_ROWS):
        from yugabyte_db_tpu.ops.device_batch import DeviceBatch
        import jax.numpy as jnp
        rng = np.random.default_rng(7)
        padded = ((n + BLOCK_ROWS - 1) // BLOCK_ROWS) * BLOCK_ROWS

        def pad(a, fill=0):
            out = np.full(padded, fill, a.dtype)
            out[:n] = a
            return jnp.asarray(out)
        cols = {
            0: pad(rng.uniform(1, 50, n).astype(np.float32)),
            1: pad(rng.uniform(900, 105000, n).astype(np.float64)),
            2: pad((rng.integers(0, 11, n) / 100.0)),
            3: pad(rng.integers(8036, 10592, n).astype(np.int32)),
            4: pad(rng.integers(0, 3, n).astype(np.int32)),
        }
        valid = np.zeros(padded, bool)
        valid[:n] = True
        nulls = {cid: jnp.zeros(padded, bool) for cid in cols}
        return DeviceBatch(cols=cols, nulls=nulls, valid=jnp.asarray(valid),
                           key_hash=None, ht=None, write_id=None,
                           tombstone=None, unique_keys=True, n_rows=n)

    def _q6(self, kernel, batch):
        from yugabyte_db_tpu.ops import Expr
        from yugabyte_db_tpu.ops.scan import AggSpec
        C = Expr.col
        where = ((C(3) >= 8766) & (C(3) < 9131) & (C(2) >= 0.05)
                 & (C(2) <= 0.07) & (C(0) < 24.0)).node
        aggs = (AggSpec("sum", (C(1) * C(2)).node), AggSpec("count"),
                AggSpec("min", C(1).node), AggSpec("max", C(1).node))
        return kernel.run(batch, where, aggs)

    def test_routed_matches_xla_ungrouped(self):
        from yugabyte_db_tpu.ops.scan import ScanKernel
        from yugabyte_db_tpu.utils import flags
        batch = self._batch()
        xla_out, xla_cnt, _ = self._q6(ScanKernel(), batch)
        flags.set_flag("tpu_pallas_scan", True)
        try:
            k = ScanKernel()
            pl_out, pl_cnt, mask = self._q6(k, batch)
            assert mask is None, "pallas path was not taken"
        finally:
            flags.set_flag("tpu_pallas_scan", False)
        assert int(pl_cnt) == int(xla_cnt)
        for a, b in zip(pl_out, xla_out):
            av, bv = float(np.asarray(a)), float(np.asarray(b))
            assert abs(av - bv) <= max(1e-6, 2e-4 * abs(bv)), (av, bv)

    def test_routed_matches_xla_grouped(self):
        from yugabyte_db_tpu.ops import Expr
        from yugabyte_db_tpu.ops.scan import AggSpec, GroupSpec, ScanKernel
        from yugabyte_db_tpu.utils import flags
        C = Expr.col
        batch = self._batch()
        group = GroupSpec(cols=((4, 3, 0),))
        where = (C(3) <= 10000).node
        aggs = (AggSpec("sum", C(1).node), AggSpec("count"))
        xla_out, xla_cnt, _ = ScanKernel().run(batch, where, aggs, group)
        flags.set_flag("tpu_pallas_scan", True)
        try:
            pl_out, pl_cnt, mask = ScanKernel().run(batch, where, aggs,
                                                    group)
            assert mask is None, "pallas path was not taken"
        finally:
            flags.set_flag("tpu_pallas_scan", False)
        assert np.asarray(pl_cnt).tolist() == np.asarray(xla_cnt).tolist()
        for a, b in zip(pl_out, xla_out):
            av, bv = np.asarray(a, np.float64), np.asarray(b, np.float64)
            assert np.allclose(av, bv, rtol=2e-4), (av, bv)

    def test_refusals_are_typed_and_tallied(self):
        # regression for the dead-PallasIneligible laundering: the
        # eligibility gate used to silently `return None`, so a
        # refused shape was indistinguishable from a bug.  Now every
        # decline raises PallasIneligible(reason) and the dispatcher
        # tallies it per reason.
        from yugabyte_db_tpu.ops import Expr
        from yugabyte_db_tpu.ops.pallas_scan import PallasIneligible
        from yugabyte_db_tpu.ops.scan import AggSpec, ScanKernel
        from yugabyte_db_tpu.utils import flags
        C = Expr.col
        batch = self._batch()
        k = ScanKernel()
        with pytest.raises(PallasIneligible, match="mvcc_or_no_aggs"):
            k._pallas_eligible(batch, None, (), None, "snapshot", ())
        with pytest.raises(PallasIneligible, match="agg_op"):
            k._pallas_eligible(batch, None, (AggSpec("avg", C(0).node),),
                               None, "none", ())
        import jax.numpy as jnp
        batch.cols[5] = jnp.asarray(
            np.arange(batch.padded_rows, dtype=np.int64))
        batch.nulls[5] = jnp.zeros(batch.padded_rows, bool)
        flags.set_flag("tpu_pallas_scan", True)
        try:
            out, cnt, mask = k.run(batch, (C(5) >= 10).node,
                                   (AggSpec("count"),))
            assert mask is not None          # served by XLA fallback
            assert k.pallas_refusals == {"column_dtype": 1}
            k.run(batch, (C(5) >= 10).node, (AggSpec("count"),))
            assert k.pallas_refusals == {"column_dtype": 2}
        finally:
            flags.set_flag("tpu_pallas_scan", False)

    def test_int64_columns_fall_back_to_xla(self):
        import jax.numpy as jnp
        from yugabyte_db_tpu.ops import Expr
        from yugabyte_db_tpu.ops.scan import AggSpec, ScanKernel
        from yugabyte_db_tpu.utils import flags
        batch = self._batch()
        batch.cols[5] = jnp.asarray(
            np.arange(batch.padded_rows, dtype=np.int64))
        batch.nulls[5] = jnp.zeros(batch.padded_rows, bool)
        C = Expr.col
        flags.set_flag("tpu_pallas_scan", True)
        try:
            out, cnt, mask = ScanKernel().run(
                batch, (C(5) >= 10).node,
                (AggSpec("count"),))
            assert mask is not None, "int64 predicate must stay on XLA"
        finally:
            flags.set_flag("tpu_pallas_scan", False)
        assert int(out[0]) == batch.padded_rows - 10 - int(
            (~np.asarray(batch.valid)).sum())
