"""Pallas fused-scan kernel vs numpy reference (interpret mode on the CPU
mesh; the same kernel compiles for TPU)."""
import numpy as np
import pytest

from yugabyte_db_tpu.ops.pallas_scan import BLOCK_ROWS, q6_scan


class TestPallasScan:
    def test_matches_numpy(self):
        rng = np.random.default_rng(1)
        n = 3 * BLOCK_ROWS + 777    # force padding
        qty = rng.uniform(1, 50, n)
        price = rng.uniform(900, 105000, n)
        disc = rng.integers(0, 11, n) / 100.0
        ship = rng.integers(8036, 10592, n).astype(float)
        s, c = q6_scan(qty, price, disc, ship, 8766, 9131, 0.05, 0.07,
                       24.0, interpret=True)
        m = ((ship >= 8766) & (ship < 9131) & (disc >= 0.05)
             & (disc <= 0.07) & (qty < 24))
        assert c == int(m.sum())
        expect = float((price * disc)[m].sum())
        assert abs(s - expect) <= max(1e-6, 2e-4 * abs(expect))

    def test_empty_match(self):
        n = BLOCK_ROWS
        z = np.zeros(n)
        s, c = q6_scan(z, z, z, z, 10.0, 20.0, 0.5, 0.6, -1.0,
                       interpret=True)
        assert (s, c) == (0.0, 0)


class TestPallasGrouped:
    def test_grouped_sums_match_numpy(self):
        rng = np.random.default_rng(2)
        n = 2 * BLOCK_ROWS + 123
        gids = rng.integers(0, 6, n).astype(np.float64)
        vals = rng.uniform(0, 10, n)
        mask = rng.random(n) < 0.7
        from yugabyte_db_tpu.ops.pallas_scan import grouped_sum
        out = grouped_sum(gids, vals, mask, num_groups=6, interpret=True)
        for g in range(6):
            m = (gids == g) & mask
            np.testing.assert_allclose(out[g], vals[m].sum(), rtol=2e-4)
