"""Storage layer tests: memtable, SST round-trips, merge, LSM store,
compaction, checkpoints, columnar blocks.

Modeled on the reference's rocksdb unit tests (reference:
src/yb/rocksdb/db/db_test.cc family) at much smaller scale.
"""
import os

import numpy as np
import pytest

from yugabyte_db_tpu.storage import (
    MemTable, SstWriter, SstReader, merging_iterator, LsmStore, WriteBatch,
    CompactionFeed, ColumnarBlock,
)
from yugabyte_db_tpu.storage.columnar import fnv64_bytes, fnv64_keys
from yugabyte_db_tpu.dockv.packed_row import (
    ColumnSchema, ColumnType, TableSchema, SchemaPacking, RowPacker,
)


def kv(i: int, suffix=b"") -> tuple:
    return (b"key%08d" % i + suffix, b"val%d" % i)


class TestMemTable:
    def test_put_iterate_sorted(self):
        m = MemTable()
        for i in (5, 1, 9, 3):
            m.put(*kv(i))
        keys = [k for k, _ in m.iterate()]
        assert keys == sorted(keys)

    def test_overwrite(self):
        m = MemTable()
        m.put(b"a", b"1")
        m.put(b"a", b"2")
        assert list(m.iterate()) == [(b"a", b"2")]

    def test_range(self):
        m = MemTable()
        for i in range(10):
            m.put(*kv(i))
        got = list(m.iterate(lower=kv(3)[0], upper=kv(7)[0]))
        assert [k for k, _ in got] == [kv(i)[0] for i in range(3, 7)]


class TestFnv:
    def test_vector_matches_scalar(self):
        keys = [b"", b"a", b"abc", b"abcdef" * 3, b"\x00\xff"]
        vec = fnv64_keys(keys)
        for k, h in zip(keys, vec):
            assert int(h) == fnv64_bytes(k)


class TestSst:
    def test_roundtrip_and_seek(self, tmp_path):
        p = str(tmp_path / "a.sst")
        w = SstWriter(p, block_rows=16)
        entries = [kv(i) for i in range(100)]
        for k, v in entries:
            w.add(k, v)
        info = w.finish()
        assert info["num_entries"] == 100
        r = SstReader(p)
        assert list(r.iterate()) == entries
        assert list(r.seek(kv(95)[0])) == entries[95:]
        assert list(r.iterate(lower=kv(10)[0], upper=kv(13)[0])) == entries[10:13]
        assert r.min_key == entries[0][0]
        assert r.max_key == entries[-1][0]

    def test_unsorted_raises(self, tmp_path):
        w = SstWriter(str(tmp_path / "b.sst"))
        w.add(b"b", b"")
        with pytest.raises(ValueError):
            w.add(b"a", b"")

    def test_bloom(self, tmp_path):
        p = str(tmp_path / "c.sst")
        w = SstWriter(p)
        for i in range(200):
            w.add(*kv(i))
        w.finish()
        r = SstReader(p)
        hits = sum(r.may_contain_hash(fnv64_bytes(kv(i)[0]))
                   for i in range(200))
        assert hits == 200
        false_pos = sum(r.may_contain_hash(fnv64_bytes(b"nope%d" % i))
                        for i in range(1000))
        assert false_pos < 100  # ~1% expected at 10 bits/key

    def test_frontier_persisted(self, tmp_path):
        p = str(tmp_path / "d.sst")
        w = SstWriter(p)
        w.add(b"k", b"v")
        w.set_frontier(op_id=[3, 42], max_ht=777)
        w.finish()
        r = SstReader(p)
        assert r.frontier["op_id"] == [3, 42]
        assert r.frontier["max_ht"] == 777


def make_columnar_block(n=50, start=0):
    keys = np.zeros((n, 12), np.uint8)
    ids = np.arange(start, start + n).astype(">u8")
    keys[:, 4:] = ids.view(np.uint8).reshape(n, 8)
    keys[:, 0] = 0x24
    return ColumnarBlock.from_arrays(
        schema_version=1,
        key_hash=fnv64_keys([keys[i].tobytes() for i in range(n)]),
        ht=np.full(n, 100, np.uint64),
        fixed={7: (np.arange(n, dtype=np.float64),
                   np.zeros(n, bool))},
        varlen={9: (np.cumsum(np.full(n, 3)).astype(np.uint32),
                    b"abc" * n, np.zeros(n, bool))},
        keys=keys)


class TestColumnar:
    def test_serialize_roundtrip(self):
        cb = make_columnar_block()
        cb2 = ColumnarBlock.deserialize(cb.serialize())
        assert cb2.n == cb.n
        np.testing.assert_array_equal(cb2.key_hash, cb.key_hash)
        np.testing.assert_array_equal(cb2.keys, cb.keys)
        np.testing.assert_array_equal(cb2.fixed[7][0], cb.fixed[7][0])
        ends, heap, null = cb2.varlen[9]
        assert heap == b"abc" * cb.n
        np.testing.assert_array_equal(ends, cb.varlen[9][0])

    def test_from_packed_entries(self):
        schema = TableSchema(columns=(
            ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
            ColumnSchema(1, "a", ColumnType.FLOAT64),
            ColumnSchema(2, "s", ColumnType.STRING),
        ), version=2)
        sp = SchemaPacking.from_schema(schema)
        packer = RowPacker(sp)
        n = 20
        keys = [b"k%04d" % i for i in range(n)]
        values = [packer.pack_value({1: float(i), 2: "s%d" % i})
                  for i in range(n)]
        blk = ColumnarBlock.from_packed_entries(
            sp, keys, np.arange(n, dtype=np.uint64),
            np.zeros(n, np.uint32), values)
        vals, nulls = blk.fixed[1]
        np.testing.assert_array_equal(vals, np.arange(n, dtype=np.float64))
        assert not nulls.any()
        ends, heap, vnull = blk.varlen[2]
        assert heap == b"".join(b"s%d" % i for i in range(n))
        # null handling
        values2 = [packer.pack_value({1: None, 2: None})]
        blk2 = ColumnarBlock.from_packed_entries(
            sp, [b"k"], np.array([1], np.uint64), np.zeros(1, np.uint32),
            values2)
        assert blk2.fixed[1][1][0]
        assert blk2.varlen[2][2][0]

    def test_columnar_only_sst(self, tmp_path):
        p = str(tmp_path / "col.sst")
        w = SstWriter(p)
        w.add_columnar_block(make_columnar_block(50, 0))
        w.add_columnar_block(make_columnar_block(50, 100))
        w.finish()

        def decoder(cb):
            return [(cb.keys[i].tobytes(), b"v") for i in range(cb.n)]

        r = SstReader(p, row_decoder=decoder)
        assert r.num_entries == 100
        blocks = list(r.columnar_blocks())
        assert len(blocks) == 2 and all(cb is not None for _, cb in blocks)
        entries = list(r.iterate())
        assert len(entries) == 100
        assert entries == sorted(entries)


class TestMerge:
    def test_kway(self):
        a = iter([(b"a", b"1"), (b"d", b"1")])
        b = iter([(b"b", b"2"), (b"d", b"2")])
        c = iter([(b"c", b"3")])
        out = list(merging_iterator([a, b, c]))
        assert out == [(b"a", b"1"), (b"b", b"2"), (b"c", b"3"), (b"d", b"1")]


class TestLsm:
    def test_write_read_flush(self, tmp_path):
        db = LsmStore(str(tmp_path))
        db.apply(WriteBatch([kv(i) for i in range(50)], op_id=(1, 10)))
        assert db.get(kv(25)[0]) == kv(25)[1]
        db.flush()
        assert db.memtable_empty()
        assert db.get(kv(25)[0]) == kv(25)[1]
        assert db.flushed_frontier()["op_id"] == [1, 10]

    def test_newest_wins_across_mem_and_sst(self, tmp_path):
        db = LsmStore(str(tmp_path))
        db.apply(WriteBatch([(b"k", b"old")]))
        db.flush()
        db.apply(WriteBatch([(b"k", b"new")]))
        assert db.get(b"k") == b"new"
        db.flush()
        assert db.get(b"k") == b"new"

    def test_reopen_recovers(self, tmp_path):
        db = LsmStore(str(tmp_path))
        db.apply(WriteBatch([kv(i) for i in range(20)], op_id=(2, 5)))
        db.flush()
        db2 = LsmStore(str(tmp_path))
        assert db2.get(kv(7)[0]) == kv(7)[1]
        assert db2.flushed_frontier()["op_id"] == [2, 5]

    def test_compaction_merges_and_deletes_inputs(self, tmp_path):
        db = LsmStore(str(tmp_path))
        for round_ in range(4):
            db.apply(WriteBatch([kv(i, b"_%d" % round_) for i in range(10)]))
            db.flush()
        assert len(db.ssts) == 4
        old_paths = [r.path for r in db.ssts]
        db.compact()
        assert len(db.ssts) == 1
        assert sum(1 for _ in db.iterate()) == 40
        for p in old_paths:
            assert not os.path.exists(p)

    def test_compaction_feed_filters(self, tmp_path):
        class DropOdd(CompactionFeed):
            def feed(self, k, v):
                i = int(k[3:11])
                return [] if i % 2 else [(k, v)]

        db = LsmStore(str(tmp_path))
        db.apply(WriteBatch([kv(i) for i in range(10)]))
        db.flush()
        db.compact(feed=DropOdd())
        keys = [k for k, _ in db.iterate()]
        assert keys == [kv(i)[0] for i in range(0, 10, 2)]

    def test_checkpoint_hardlinks(self, tmp_path):
        db = LsmStore(str(tmp_path / "db"))
        db.apply(WriteBatch([kv(i) for i in range(10)]))
        db.flush()
        db.checkpoint(str(tmp_path / "snap"))
        snap = LsmStore.open_checkpoint(str(tmp_path / "snap"))
        assert snap.get(kv(3)[0]) == kv(3)[1]
        # snapshot unaffected by later writes
        db.apply(WriteBatch([(kv(3)[0], b"changed")]))
        db.flush()
        assert snap.get(kv(3)[0]) == kv(3)[1]


class TestAsyncFlushSeams:
    """The PR-11 frozen-memtable handoff: freeze_active (pointer swap
    on the apply thread) + flush_frozen (SST write on the flush
    executor), with flush() as the synchronous drain-everything
    barrier every existing caller (pinner, DDL, shutdown) relies on."""

    def test_write_during_flush_byte_parity(self, tmp_path):
        """Writes landing in the fresh active memtable while a frozen
        one drains must read back byte-identical, before AND after the
        drain (the write-during-flush seam)."""
        db = LsmStore(str(tmp_path))
        db.apply(WriteBatch([kv(i) for i in range(40)], op_id=(1, 5)))
        assert db.freeze_active() is True
        # overwrite half the frozen rows + add new ones: newest-wins
        # must hold across frozen/active, then across sst/active
        db.apply(WriteBatch(
            [(kv(i)[0], b"new%d" % i) for i in range(20)]
            + [kv(i) for i in range(100, 110)], op_id=(1, 6)))
        expect = {kv(i)[0]: (b"new%d" % i if i < 20 else kv(i)[1])
                  for i in range(40)}
        expect.update({kv(i)[0]: kv(i)[1] for i in range(100, 110)})
        assert dict(db.iterate()) == expect          # pre-drain view
        assert db.flush_frozen() is not None
        assert dict(db.iterate()) == expect          # post-drain view
        assert db.frozen_count() == 0
        assert db.flushed_frontier()["op_id"] == [1, 5]
        db.flush()                                    # drain active too
        assert dict(db.iterate()) == expect
        assert db.flushed_frontier()["op_id"] == [1, 6]

    def test_frozen_backlog_drains_oldest_first_frontier_monotone(
            self, tmp_path):
        db = LsmStore(str(tmp_path))
        for n in range(3):
            db.apply(WriteBatch([kv(100 * n + i) for i in range(10)],
                                op_id=(1, n + 1)))
            assert db.freeze_active() is True
        assert db.frozen_count() == 3
        seen = []
        while db.frozen_count():
            assert db.flush_frozen() is not None
            seen.append(db.flushed_frontier()["op_id"])
        # oldest-first install: the frontier only ever advances
        assert seen == [[1, 1], [1, 2], [1, 3]]
        assert {k for k, _ in db.iterate()} == {
            kv(100 * n + i)[0] for n in range(3) for i in range(10)}
        # reopen: all three SSTs manifested, replay starts past (1,3)
        db2 = LsmStore(str(tmp_path))
        assert db2.flushed_frontier()["op_id"] == [1, 3]
        assert db2.get(kv(205)[0]) == kv(205)[1]

    def test_truncate_racing_background_flush_never_resurrects(
            self, tmp_path):
        """TRUNCATE while a frozen memtable is mid-write on the flush
        executor: the install must detect the drop and unlink its SST
        instead of resurrecting truncated rows."""
        import threading
        from yugabyte_db_tpu.utils import fault_injection as fi
        db = LsmStore(str(tmp_path))
        db.apply(WriteBatch([kv(i) for i in range(30)], op_id=(1, 1)))
        assert db.freeze_active() is True
        fi.stall_disk(0.4)      # hold the flush worker pre-write
        try:
            t = threading.Thread(target=db.flush_frozen)
            t.start()
            db.truncate(op_id=(1, 2))          # race the stalled write
            t.join(timeout=10.0)
            assert not t.is_alive()
        finally:
            fi.clear_disk_stall()
        assert list(db.iterate()) == []
        assert db.ssts == []
        # no orphan SST file either (unlinked at install-detect)
        leftovers = [f for f in os.listdir(str(tmp_path))
                     if f.endswith(".sst")]
        assert leftovers == []
        # and a reopen stays truncated with the truncate frontier
        db2 = LsmStore(str(tmp_path))
        assert list(db2.iterate()) == []
        assert db2.flushed_frontier()["op_id"] == [1, 2]

    def test_pin_refused_while_frozen_then_succeeds_after_drain(
            self, tmp_path):
        """The bypass pinner's require_empty_memtable contract covers
        FROZEN memtables too: a pin while the flush executor still
        owes a drain returns None (caller retries), and tablet.flush's
        drain-everything barrier makes the retry succeed."""
        db = LsmStore(str(tmp_path))
        db.apply(WriteBatch([kv(i) for i in range(10)], op_id=(1, 1)))
        db.flush()                       # one durable SST to lease
        db.apply(WriteBatch([kv(i, b"x") for i in range(10)],
                            op_id=(1, 2)))
        assert db.freeze_active() is True
        assert db.pin_ssts(require_empty_memtable=True) is None
        assert db.flush_frozen() is not None
        lease = db.pin_ssts(require_empty_memtable=True)
        assert lease is not None and len(lease.paths) == 2
        lease.release()


class TestPointEntriesVarlenPk:
    def test_point_reads_with_string_pk_sidecars(self, tmp_path):
        """Variable-length PKs produce sidecars WITHOUT a keys matrix;
        point_entries must fall back to row decode, not assert."""
        import asyncio
        from yugabyte_db_tpu.docdb import ReadRequest, RowOp, WriteRequest
        from yugabyte_db_tpu.docdb.table_codec import TableInfo
        from yugabyte_db_tpu.dockv.packed_row import (
            ColumnSchema, ColumnType, TableSchema,
        )
        from yugabyte_db_tpu.dockv.partition import PartitionSchema
        from yugabyte_db_tpu.tablet import Tablet
        info = TableInfo("", "sv", TableSchema(columns=(
            ColumnSchema(0, "name", ColumnType.STRING, is_hash_key=True),
            ColumnSchema(1, "v", ColumnType.FLOAT64)), version=1),
            PartitionSchema("hash", 1))
        t = Tablet("svt", info, str(tmp_path))
        t.apply_write(WriteRequest("", [
            RowOp("upsert", {"name": n, "v": float(i)})
            for i, n in enumerate(
                ["a", "bb", "ccc", "dddd", "x" * 40, "yy" * 7])]))
        t.flush()
        for i, n in enumerate(["a", "bb", "ccc", "dddd",
                               "x" * 40, "yy" * 7]):
            r = t.read(ReadRequest("", pk_eq={"name": n}))
            assert r.rows and r.rows[0]["v"] == float(i), n
        assert not t.read(ReadRequest("", pk_eq={"name": "zzz"})).rows
