"""Document shredding subsystem (yugabyte_db_tpu/docstore/).

The contract under test: shredded doc-path pushdown is BITWISE equal
to the interpreted JSON extractor at the same read point, every shape
it cannot serve falls back typed (and still answers correctly), the
v2 writer with ``doc_shred_enabled=False`` is byte-identical to a
build without the subsystem, and compaction re-shreds its output.
"""
import json
import tempfile

import numpy as np
import pytest

from yugabyte_db_tpu.docdb.operations import (ReadRequest, RowOp,
                                              WriteRequest)
from yugabyte_db_tpu.docdb.table_codec import TableInfo
from yugabyte_db_tpu.dockv.packed_row import (ColumnSchema, ColumnType,
                                              TableSchema)
from yugabyte_db_tpu.dockv.partition import PartitionSchema
from yugabyte_db_tpu.docstore import (DOC_STATS, LAST_DOC_STATS,
                                      shred_lanes, vcid_for)
from yugabyte_db_tpu.docstore.errors import (REASON_DOC_SHAPE,
                                             REASON_UNSHREDDED_BLOCK)
from yugabyte_db_tpu.ops.scan import AggSpec
from yugabyte_db_tpu.tablet import Tablet
from yugabyte_db_tpu.utils import flags


def J(key, inner=("col", 1)):
    return ("json", "text", inner, key)


def CASTI(n):
    return ("fn", "cast_bigint", n)


def CASTF(n):
    return ("fn", "cast_double", n)


def docs_info():
    schema = TableSchema(columns=(
        ColumnSchema(0, "id", ColumnType.INT64, is_range_key=True),
        ColumnSchema(1, "doc", ColumnType.JSON),
    ), version=1)
    return TableInfo("docs", "docs", schema, PartitionSchema("range", 0))


def make_doc(i):
    d = {"qty": int(i % 50), "price": float(i) * 1.5 + 0.25,
         "tag": ["alpha", "beta", "gamma"][i % 3],
         "meta": {"region": ["us", "eu"][i % 2]},
         "arr": [1, 2]}
    if i % 7 == 0:
        d.pop("qty")
    if i % 11 == 0:
        d["qty_null"] = None
    return d


def write_docs(t, lo, hi, mutate=None):
    rows = []
    for i in range(lo, hi):
        d = make_doc(i)
        if mutate:
            mutate(i, d)
        rows.append({"id": i, "doc": json.dumps(d)})
    t.apply_write(WriteRequest("docs", [RowOp("upsert", r)
                                       for r in rows]), t.clock.now())


@pytest.fixture()
def low_pushdown():
    flags.set_flag("tpu_min_rows_for_pushdown", 64)
    yield
    flags.REGISTRY.reset("tpu_min_rows_for_pushdown")


@pytest.fixture()
def docs_tablet(tmp_path, low_pushdown):
    t = Tablet("docs-t", docs_info(), str(tmp_path / "docs"))
    write_docs(t, 0, 4000)
    t.regular.flush()
    return t


def read_both(t, **kw):
    """(shredded response, interpreted response) for one request; the
    interpreted side runs with doc_shred_enabled off over the SAME
    SSTs — byte-for-byte the pre-subsystem read path."""
    r1 = t.read(ReadRequest("docs", **kw))
    flags.set_flag("doc_shred_enabled", False)
    try:
        r2 = t.read(ReadRequest("docs", **kw))
    finally:
        flags.REGISTRY.reset("doc_shred_enabled")
    assert r2.backend == "cpu"
    return r1, r2


def assert_parity(t, pushdown=True, **kw):
    r1, r2 = read_both(t, **kw)
    if pushdown:
        assert r1.backend == "tpu", f"fell back: {DOC_STATS['reasons']}"
    else:
        assert r1.backend == "cpu"
    if r1.agg_values is not None:
        a = [np.asarray(v).tolist() for v in r1.agg_values]
        b = [np.asarray(v).tolist() for v in r2.agg_values]
        assert a == b, f"{a} != {b}"
    else:
        assert r1.rows == r2.rows
    return r1


# ---------------------------------------------------------------------------
# Write-side inference units
# ---------------------------------------------------------------------------

class TestShredInference:
    def _lane(self, docs):
        texts = [json.dumps(d).encode() if d is not None else b""
                 for d in docs]
        ends = np.cumsum([len(x) for x in texts]).astype(np.uint32)
        heap = b"".join(texts)
        null = np.array([d is None for d in docs])
        return ends, heap, null

    def test_kinds_and_presence(self):
        docs = [{"i": 1, "f": 1.5, "s": "x", "b": True},
                {"i": 2, "f": 2.5, "s": "y", "b": False},
                {"f": 3.5, "s": "z", "b": True, "i": None}]
        out = shred_lanes(*self._lane(docs))
        assert out[("i",)][0] == "i"
        assert out[("f",)][0] == "f"
        assert out[("s",)][0] == "s"
        # bool shreds as its JSON text — what the interpreter returns
        assert out[("b",)][0] == "s"
        ulens, uheap, codes = out[("b",)][1]
        from yugabyte_db_tpu.storage.lane_codec import \
            decode_dict_strings
        assert set(decode_dict_strings(ulens, uheap)) == \
            {"true", "false"}
        # JSON null and absence are both just not-present
        assert out[("i",)][2].tolist() == [True, True, False]
        assert out[("i",)][3] == (1, 2)     # exact int bounds

    def test_heterogeneous_and_arrays_refused(self):
        docs = [{"m": 1, "a": [1], "fi": 1},
                {"m": "one", "a": [2], "fi": 2.0}]
        out = shred_lanes(*self._lane(docs))
        assert ("m",) not in out            # int+str mix
        assert ("a",) not in out            # arrays never shred
        assert ("fi",) not in out           # int+float mix

    def test_ancestor_purity(self):
        # rows where the parent is an embedded-JSON STRING: the
        # interpreter still descends (it parses the text), a shredded
        # child cannot — the whole subtree must stay raw
        docs = [{"p": {"x": 1}}, {"p": json.dumps({"x": 2})}]
        out = shred_lanes(*self._lane(docs))
        assert ("p", "x") not in out
        # pure-object parents are fine
        docs = [{"p": {"x": 1}}, {"p": {"x": 2}}, {"p": None}]
        out = shred_lanes(*self._lane(docs))
        assert out[("p", "x")][0] == "i"

    def test_coverage_and_max_paths(self):
        docs = [{"common": i} if i else
                {"common": i, "rare": 1} for i in range(100)]
        out = shred_lanes(*self._lane(docs))
        assert ("common",) in out
        assert ("rare",) not in out         # 1% coverage: not worth it
        docs = [{f"k{j}": j for j in range(8)} for _ in range(10)]
        out = shred_lanes(*self._lane(docs), max_paths=3)
        assert len(out) == 3

    def test_int64_overflow_refused(self):
        docs = [{"big": 2 ** 70}, {"big": 1}]
        out = shred_lanes(*self._lane(docs))
        assert ("big",) not in out

    def test_unparseable_docs_are_absent(self):
        texts = [b'{"k": 1}', b"not json", b'{"k": 2}']
        ends = np.cumsum([len(x) for x in texts]).astype(np.uint32)
        out = shred_lanes(ends, b"".join(texts), None)
        assert out[("k",)][2].tolist() == [True, False, True]

    def test_nonfinite_floats_refused(self):
        # json.loads accepts Infinity/NaN; their dumps spellings can
        # never repr-round-trip, and 'NaN' == 'NaN' is TRUE as text
        # while float NaN never compares equal — such paths stay raw
        texts = [b'{"x": Infinity, "y": 1.5}', b'{"x": NaN, "y": 2.5}']
        ends = np.cumsum([len(t) for t in texts]).astype(np.uint32)
        out = shred_lanes(ends, b"".join(texts), None)
        assert ("x",) not in out
        assert out[("y",)][0] == "f"


# ---------------------------------------------------------------------------
# Golden parity: shredded vs interpreted, bitwise, same read point
# ---------------------------------------------------------------------------

class TestGoldenParity:
    def test_string_predicates(self, docs_tablet):
        t = docs_tablet
        assert_parity(t, where=("cmp", "eq", J("tag"),
                                ("const", "beta")),
                      aggregates=(AggSpec("count"),))
        assert_parity(t, where=("cmp", "gt", J("tag"),
                                ("const", "alpha")),
                      aggregates=(AggSpec("count"),))
        assert_parity(t, where=("in", J("tag"), ["alpha", "gamma"]),
                      aggregates=(AggSpec("count"),))
        assert_parity(t, where=("between", J("tag"),
                                ("const", "alpha"), ("const", "beta")),
                      aggregates=(AggSpec("count"),))
        assert_parity(t, where=("like", J("tag"), "%amm%"),
                      aggregates=(AggSpec("count"),))

    def test_nested_path(self, docs_tablet):
        assert_parity(docs_tablet,
                      where=("cmp", "eq", J("region", J("meta")),
                             ("const", "eu")),
                      aggregates=(AggSpec("count"),))

    def test_numeric_casts(self, docs_tablet):
        t = docs_tablet
        r = assert_parity(
            t, where=("cmp", "lt", CASTI(J("qty")), ("const", 10)),
            aggregates=(AggSpec("sum", CASTI(J("qty"))),
                        AggSpec("count"),
                        AggSpec("min", CASTI(J("qty"))),
                        AggSpec("max", CASTI(J("qty")))))
        assert int(np.asarray(r.agg_values[0])) > 0
        assert_parity(
            t, where=("between", CASTF(J("price")), ("const", 100.0),
                      ("const", 900.0)),
            aggregates=(AggSpec("sum", CASTF(J("price"))),
                        AggSpec("count")))

    def test_text_eq_canonical(self, docs_tablet):
        t = docs_tablet
        assert_parity(t, where=("cmp", "eq", J("qty"), ("const", "7")),
                      aggregates=(AggSpec("count"),))
        # out-of-int64 canonical text: must compile to constant-false
        # (interpreted: no int64 value's text matches), never reach
        # jnp.asarray with an unrepresentable constant
        assert_parity(t, where=("cmp", "eq", J("qty"),
                                ("const", str(2 ** 64 + 1))),
                      aggregates=(AggSpec("count"),))
        # non-finite canonical-looking text over a float path
        assert_parity(t, where=("cmp", "eq", J("price"),
                                ("const", "inf")),
                      aggregates=(AggSpec("count"),))
        # non-canonical text can never equal an int's JSON text:
        # constant-false (but absent rows stay NULL) — still pushdown
        assert_parity(t, where=("cmp", "eq", J("qty"),
                                ("const", "07")),
                      aggregates=(AggSpec("count"),))
        assert_parity(t, where=("cmp", "ne", J("qty"),
                                ("const", "7.5")),
                      aggregates=(AggSpec("count"),))
        assert_parity(t, where=("in", J("qty"), ["7", "9", "x"]),
                      aggregates=(AggSpec("count"),))

    def test_presence_shapes(self, docs_tablet):
        t = docs_tablet
        assert_parity(t, where=("isnull", J("qty")),
                      aggregates=(AggSpec("count"),))
        assert_parity(t, where=("not", ("isnull", J("qty"))),
                      aggregates=(AggSpec("count"),))
        # COUNT(path) counts presence; json-null and missing both NULL
        assert_parity(t, aggregates=(AggSpec("count", J("qty")),
                                     AggSpec("count", J("tag")),
                                     AggSpec("count")))

    def test_string_minmax_decode(self, docs_tablet):
        r = assert_parity(docs_tablet,
                          aggregates=(AggSpec("min", J("tag")),
                                      AggSpec("max", J("tag"))))
        assert np.asarray(r.agg_values[0]).item() == "alpha"
        assert np.asarray(r.agg_values[1]).item() == "gamma"

    def test_row_filter_path(self, docs_tablet):
        r = assert_parity(docs_tablet,
                          where=("cmp", "eq", J("tag"),
                                 ("const", "beta")),
                          columns=("id",))
        assert len(r.rows) > 0

    def test_combined_doc_and_scalar_predicate(self, docs_tablet):
        assert_parity(docs_tablet,
                      where=("and",
                             ("cmp", "lt", ("col", 0), ("const", 2000)),
                             ("cmp", "eq", J("tag"),
                              ("const", "alpha"))),
                      aggregates=(AggSpec("count"),))

    def test_coverage_counter(self, docs_tablet):
        assert_parity(docs_tablet,
                      where=("cmp", "eq", J("tag"), ("const", "beta")),
                      aggregates=(AggSpec("count"),))
        assert LAST_DOC_STATS["coverage"] > 0
        assert LAST_DOC_STATS["paths"] == 1

    def test_vcid_stability(self, docs_tablet):
        v1 = vcid_for(1, ("tag",))
        assert_parity(docs_tablet,
                      where=("cmp", "eq", J("tag"), ("const", "beta")),
                      aggregates=(AggSpec("count"),))
        assert vcid_for(1, ("tag",)) == v1

    def test_attach_never_mutates_cached_blocks(self, docs_tablet):
        """Derived vcid lanes live on scan-lifetime CLONES: the cached
        SstReader blocks — also read by compaction, point reads and
        concurrent scans, and the source of any re-serialization —
        must stay untouched by a doc scan."""
        from yugabyte_db_tpu.docstore.pushdown import DOC_COL_BASE
        t = docs_tablet
        assert_parity(t, where=("cmp", "eq", J("tag"),
                                ("const", "beta")),
                      aggregates=(AggSpec("sum", CASTI(J("qty"))),
                                  AggSpec("count")))
        for r in t.regular.ssts:
            for i in range(r.num_blocks()):
                cb = r.columnar_block(i)
                assert all(c < DOC_COL_BASE for c in cb.fixed)
                assert all(c < DOC_COL_BASE for c in cb.varlen)
                assert all(c < DOC_COL_BASE
                           for c in (cb.zmap or {}))
        # and a compaction AFTER doc scans sees clean inputs
        write_docs(t, 4000, 4500)
        t.regular.flush()
        t.compact()
        assert_parity(t, where=("cmp", "eq", J("tag"),
                                ("const", "beta")),
                      aggregates=(AggSpec("count"),))


# ---------------------------------------------------------------------------
# Typed fallbacks — every unservable shape answers interpreted
# ---------------------------------------------------------------------------

class TestFallbacks:
    def test_text_ordering_over_numeric_path(self, docs_tablet):
        # '10' < '5' lexicographically: pushing a numeric compare
        # would CHANGE answers — must stay interpreted
        DOC_STATS["reasons"].clear()
        assert_parity(docs_tablet, pushdown=False,
                      where=("cmp", "gt", J("qty"), ("const", "10")),
                      aggregates=(AggSpec("count"),))
        assert DOC_STATS["reasons"].get(REASON_DOC_SHAPE, 0) >= 1

    def test_array_path(self, docs_tablet):
        assert_parity(docs_tablet, pushdown=False,
                      where=("cmp", "eq", J("arr"), ("const", "[1, 2]")),
                      aggregates=(AggSpec("count"),))

    def test_minmax_over_numeric_path_text(self, docs_tablet):
        # interpreted MIN over int-path TEXT is lexicographic
        assert_parity(docs_tablet, pushdown=False,
                      aggregates=(AggSpec("min", J("qty")),))

    def test_memtable_rows_fall_back(self, docs_tablet):
        t = docs_tablet
        write_docs(t, 4000, 4100)      # unflushed: no shredded lanes
        DOC_STATS["reasons"].clear()
        assert_parity(t, pushdown=False,
                      where=("cmp", "eq", J("tag"), ("const", "beta")),
                      aggregates=(AggSpec("count"),))
        assert DOC_STATS["reasons"].get(REASON_UNSHREDDED_BLOCK, 0) >= 1
        # flush: every block shredded again → pushdown resumes
        t.regular.flush()
        assert_parity(t, where=("cmp", "eq", J("tag"),
                                ("const", "beta")),
                      aggregates=(AggSpec("count"),))

    def test_heterogeneous_path_falls_back(self, tmp_path,
                                           low_pushdown):
        t = Tablet("docs-h", docs_info(), str(tmp_path / "h"))
        write_docs(t, 0, 1000,
                   mutate=lambda i, d: d.__setitem__(
                       "qty", "many" if i % 5 == 0 else d.get("qty", 0)))
        t.regular.flush()
        DOC_STATS["reasons"].clear()
        assert_parity(t, pushdown=False,
                      where=("cmp", "eq", J("qty"), ("const", "3")),
                      aggregates=(AggSpec("count"),))
        assert DOC_STATS["reasons"].get(REASON_UNSHREDDED_BLOCK, 0) >= 1

    def test_mixed_v1_v2_ssts(self, tmp_path, low_pushdown):
        t = Tablet("docs-m", docs_info(), str(tmp_path / "m"))
        flags.set_flag("sst_format_version", 1)
        try:
            write_docs(t, 0, 1000)
            t.regular.flush()              # v1 SST: no shredded lanes
        finally:
            flags.REGISTRY.reset("sst_format_version")
        write_docs(t, 1000, 2000)
        t.regular.flush()                  # v2 shredded SST
        DOC_STATS["reasons"].clear()
        assert_parity(t, pushdown=False,
                      where=("cmp", "eq", J("tag"), ("const", "beta")),
                      aggregates=(AggSpec("count"),))
        assert DOC_STATS["reasons"].get(REASON_UNSHREDDED_BLOCK, 0) >= 1

    def test_flag_off_no_pushdown(self, docs_tablet):
        flags.set_flag("doc_shred_enabled", False)
        try:
            r = docs_tablet.read(ReadRequest(
                "docs",
                where=("cmp", "eq", J("tag"), ("const", "beta")),
                aggregates=(AggSpec("count"),)))
            assert r.backend == "cpu"
        finally:
            flags.REGISTRY.reset("doc_shred_enabled")


# ---------------------------------------------------------------------------
# Format discipline
# ---------------------------------------------------------------------------

class TestFormatGate:
    def _entries(self, t):
        return [(k, v) for k, v in t.regular._mem.iterate()]

    def test_flag_off_byte_identity_oracle(self, tmp_path,
                                           low_pushdown):
        """doc_shred_enabled=False must reproduce the PRE-SHRED v2
        writer byte-for-byte.  The oracle is the pre-PR call shape:
        an SstWriter constructed WITHOUT any shred argument — exactly
        what every writer in the tree was before the subsystem."""
        from yugabyte_db_tpu.storage.sst import SstWriter
        t = Tablet("docs-o", docs_info(), str(tmp_path / "o"))
        write_docs(t, 0, 1000)
        entries = self._entries(t)
        codec = t.codec

        def write(path, **kw):
            w = SstWriter(str(path),
                          columnar_builder=codec.columnar_builder,
                          key_builder=codec.derive_keys, **kw)
            for k, v in entries:
                w.add(k, v)
            w.finish()
            return (tmp_path / path).read_bytes() \
                if not str(path).startswith("/") \
                else open(path, "rb").read()

        flags.set_flag("doc_shred_enabled", False)
        try:
            off_bytes = write(tmp_path / "off.sst",
                              shred_cols=codec.shred_cols)
        finally:
            flags.REGISTRY.reset("doc_shred_enabled")
        oracle_bytes = write(tmp_path / "oracle.sst")   # pre-PR shape
        assert off_bytes == oracle_bytes
        on_bytes = write(tmp_path / "on.sst",
                         shred_cols=codec.shred_cols)
        assert on_bytes != oracle_bytes
        assert b"shred" in on_bytes and b"shred" not in off_bytes

    def test_v1_never_shreds(self, tmp_path, low_pushdown):
        from yugabyte_db_tpu.storage.sst import SstWriter
        t = Tablet("docs-v1", docs_info(), str(tmp_path / "v1"))
        write_docs(t, 0, 500)
        w = SstWriter(str(tmp_path / "f1.sst"),
                      columnar_builder=t.codec.columnar_builder,
                      format_version=1,
                      shred_cols=t.codec.shred_cols)
        assert w.shred_cols == ()

    def test_old_reader_shape_unaffected(self, docs_tablet):
        """Shred lanes ride at the END of the payload stream and under
        a meta key old readers never touch: every standard lane of a
        shredded block must deserialize to the same bytes as its
        unshredded twin."""
        from yugabyte_db_tpu.storage.columnar import ColumnarBlock
        t = docs_tablet
        r = t.regular.ssts[0]
        cb = r.columnar_block(0)
        assert cb.shred            # shredded on disk
        plain = cb.serialize(2, t.codec.derive_keys)   # no shred arg
        twin = ColumnarBlock.deserialize(plain)
        assert not twin.shred
        for cid in cb.varlen:
            e1, h1, n1 = cb.varlen[cid]
            e2, h2, n2 = twin.varlen[cid]
            assert bytes(h1) == bytes(h2)
            assert np.array_equal(np.asarray(e1), np.asarray(e2))
            assert np.array_equal(np.asarray(n1), np.asarray(n2))
        assert np.array_equal(cb.ht, twin.ht)


# ---------------------------------------------------------------------------
# Compaction re-shreds
# ---------------------------------------------------------------------------

class TestCompactionReshred:
    def test_compaction_output_is_shredded(self, tmp_path,
                                           low_pushdown):
        t = Tablet("docs-c", docs_info(), str(tmp_path / "c"))
        write_docs(t, 0, 1500)
        t.regular.flush()
        write_docs(t, 1500, 3000)
        t.regular.flush()
        assert len(t.regular.ssts) == 2
        t.compact()
        assert len(t.regular.ssts) == 1
        r = t.regular.ssts[0]
        for i in range(r.num_blocks()):
            cb = r.columnar_block(i)
            assert cb.shred.get(1), f"block {i} lost its shred lanes"
        # and pushdown parity holds over the compacted tablet
        assert_parity(t, where=("cmp", "eq", J("tag"),
                                ("const", "gamma")),
                      aggregates=(AggSpec("sum", CASTI(J("qty"))),
                                  AggSpec("count")))


# ---------------------------------------------------------------------------
# Zone pruning over shredded lanes
# ---------------------------------------------------------------------------

class TestZonePrune:
    def test_shredded_lane_prunes_blocks(self, tmp_path, low_pushdown):
        # value-clustered int path: qty == id // 500, so each 4096-row
        # block covers ~8 distinct values and a selective equality
        # should prune most blocks
        t = Tablet("docs-z", docs_info(), str(tmp_path / "z"))
        write_docs(t, 0, 8192,
                   mutate=lambda i, d: d.__setitem__("qty", i // 500))
        t.regular.flush()
        flags.set_flag("streaming_chunk_rows", 4096)
        try:
            from yugabyte_db_tpu.ops.stream_scan import \
                LAST_STREAM_STATS
            r = assert_parity(
                t, where=("cmp", "eq", CASTI(J("qty")), ("const", 3)),
                aggregates=(AggSpec("count"),))
            assert r.backend == "tpu"
            assert LAST_STREAM_STATS.get("zone_blocks_pruned", 0) > 0
        finally:
            flags.REGISTRY.reset("streaming_chunk_rows")


# ---------------------------------------------------------------------------
# Bypass route
# ---------------------------------------------------------------------------

class TestBypassDoc:
    def _tablet(self, tmp_path):
        t = Tablet("docs-b", docs_info(), str(tmp_path / "b"))
        write_docs(t, 0, 6000)
        t.regular.flush()
        return t

    def test_keyless_doc_scan_parity(self, tmp_path, low_pushdown):
        from yugabyte_db_tpu.bypass.session import BypassSession
        t = self._tablet(tmp_path)
        where = ("cmp", "eq", J("tag"), ("const", "alpha"))
        aggs = (AggSpec("sum", CASTI(J("qty"))), AggSpec("count"),
                AggSpec("max", J("tag")))
        with BypassSession([t]) as s:
            outs, counts, stats = s.scan_aggregate(where, aggs)
            assert stats["key_rebuilds"] == 0
            rpc = t.read(ReadRequest("docs", where=where,
                                     aggregates=aggs,
                                     read_ht=s.read_ht))
        assert [np.asarray(v).tolist() for v in outs] == \
            [np.asarray(v).tolist() for v in rpc.agg_values]

    def test_typed_reason_flag_off(self, tmp_path, low_pushdown):
        from yugabyte_db_tpu.bypass.errors import (REASON_DOC_OFF,
                                                   BypassIneligible)
        from yugabyte_db_tpu.bypass.session import BypassSession
        t = self._tablet(tmp_path)
        flags.set_flag("doc_shred_enabled", False)
        try:
            with BypassSession([t]) as s:
                with pytest.raises(BypassIneligible) as ei:
                    s.scan_aggregate(
                        ("cmp", "eq", J("tag"), ("const", "alpha")),
                        (AggSpec("count"),))
                assert ei.value.reason == REASON_DOC_OFF
        finally:
            flags.REGISTRY.reset("doc_shred_enabled")

    def test_typed_reason_doc_shape(self, tmp_path, low_pushdown):
        from yugabyte_db_tpu.bypass.errors import (REASON_DOC_SHAPE,
                                                   BypassIneligible)
        from yugabyte_db_tpu.bypass.session import BypassSession
        t = self._tablet(tmp_path)
        with BypassSession([t]) as s:
            with pytest.raises(BypassIneligible) as ei:
                # text ordering over a numeric path
                s.scan_aggregate(
                    ("cmp", "gt", J("qty"), ("const", "10")),
                    (AggSpec("count"),))
            assert ei.value.reason == REASON_DOC_SHAPE


# ---------------------------------------------------------------------------
# Aggregate-over-string-payload satellite (plain string columns)
# ---------------------------------------------------------------------------

class TestDictMinMaxSatellite:
    @pytest.fixture()
    def str_tablet(self, tmp_path, low_pushdown):
        from yugabyte_db_tpu.models.tpch import (generate_lineitem,
                                                 lineitem_str_data,
                                                 lineitem_str_info)
        data = lineitem_str_data(
            {k: v[:40_000] for k, v in generate_lineitem(0.01).items()})
        t = Tablet("ls", lineitem_str_info(), str(tmp_path / "ls"))
        t.bulk_load(data, block_rows=8192)
        return t

    def _interp(self, t, req_kw):
        flags.set_flag("tpu_pushdown_enabled", False)
        try:
            return t.read(ReadRequest("lineitem_s", **req_kw))
        finally:
            flags.REGISTRY.reset("tpu_pushdown_enabled")

    def test_scalar_minmax_decodes(self, str_tablet):
        kw = dict(aggregates=(AggSpec("min", ("col", 6)),
                              AggSpec("max", ("col", 6)),
                              AggSpec("count", ("col", 6))))
        r = str_tablet.read(ReadRequest("lineitem_s", **kw))
        assert r.backend == "tpu"
        ref = self._interp(str_tablet, kw)
        assert [np.asarray(v).tolist() for v in r.agg_values] == \
            [np.asarray(v).tolist() for v in ref.agg_values]
        assert np.asarray(r.agg_values[0]).item() == "A"

    def test_minmax_with_predicate_streams(self, str_tablet):
        flags.set_flag("streaming_chunk_rows", 8192)
        try:
            from yugabyte_db_tpu.ops.stream_scan import \
                LAST_STREAM_STATS
            kw = dict(
                where=("cmp", "gt", ("col", 1), ("const", 25.0)),
                aggregates=(AggSpec("max", ("col", 6)),
                            AggSpec("min", ("col", 7)),
                            AggSpec("count")))
            r = str_tablet.read(ReadRequest("lineitem_s", **kw))
            assert r.backend == "tpu"
            assert LAST_STREAM_STATS.get("chunks", 0) >= 3
            ref = self._interp(str_tablet, kw)
            assert [np.asarray(v).tolist() for v in r.agg_values] == \
                [np.asarray(v).tolist() for v in ref.agg_values]
        finally:
            flags.REGISTRY.reset("streaming_chunk_rows")

    def test_grouped_minmax_payload(self, str_tablet):
        from yugabyte_db_tpu.ops.grouped_scan import DictGroupSpec
        kw = dict(aggregates=(AggSpec("max", ("col", 6)),
                              AggSpec("sum", ("col", 1))),
                  group_by=DictGroupSpec((7,)))
        r = str_tablet.read(ReadRequest("lineitem_s", **kw))
        assert r.backend == "tpu"
        flags.set_flag("grouped_pushdown_enabled", False)
        try:
            ref = str_tablet.read(ReadRequest("lineitem_s", **kw))
        finally:
            flags.REGISTRY.reset("grouped_pushdown_enabled")

        def by_key(resp):
            out = {}
            counts = np.asarray(resp.group_counts)
            for g in range(len(counts)):
                key = tuple(str(np.asarray(v)[g])
                            for v in resp.group_values)
                out[key] = (int(counts[g]),) + tuple(
                    np.asarray(v)[g] for v in resp.agg_values)
            return out

        assert by_key(r).keys() == by_key(ref).keys()
        for k, (c1, mx1, s1) in by_key(r).items():
            c2, mx2, s2 = by_key(ref)[k]
            assert (c1, str(mx1)) == (c2, str(mx2))
            assert float(s1) == pytest.approx(float(s2))

    def test_min_empty_input_is_null(self, str_tablet):
        kw = dict(where=("cmp", "lt", ("col", 1), ("const", -1.0)),
                  aggregates=(AggSpec("min", ("col", 6)),
                              AggSpec("count")))
        r = str_tablet.read(ReadRequest("lineitem_s", **kw))
        assert r.backend == "tpu"
        assert np.asarray(r.agg_values[0]).item() is None
        assert int(np.asarray(r.agg_values[1])) == 0

    def test_sum_over_string_still_refused(self, str_tablet):
        # only min/max/count ride the codes lane; SUM(string) keeps
        # the interpreted path (where it raises, as it always did)
        r = None
        try:
            r = str_tablet.read(ReadRequest(
                "lineitem_s",
                aggregates=(AggSpec("sum", ("col", 6)),)))
        except TypeError:
            return                      # interpreted path raised: fine
        assert r.backend == "cpu"


# ---------------------------------------------------------------------------
# SQL end-to-end: ->/->> predicates and aggregates through the executor
# ---------------------------------------------------------------------------

class TestSqlDocPushdown:
    def test_sql_doc_predicates(self, tmp_path, low_pushdown):
        import asyncio

        from yugabyte_db_tpu.ql.executor import SqlSession
        from yugabyte_db_tpu.tools.mini_cluster import MiniCluster

        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            s = SqlSession(mc.client())
            try:
                await s.execute(
                    "CREATE TABLE dt (k bigint, doc jsonb, "
                    "PRIMARY KEY (k))")
                await mc.wait_for_leaders("dt")
                vals = ", ".join(
                    "({}, '{}')".format(
                        i, json.dumps(make_doc(i)).replace("'", "''"))
                    for i in range(600))
                await s.execute(
                    f"INSERT INTO dt (k, doc) VALUES {vals}")
                for ts in mc.tservers:
                    for p in ts.peers.values():
                        p.tablet.flush()

                async def both(sql):
                    r1 = await s.execute(sql)
                    flags.set_flag("doc_shred_enabled", False)
                    try:
                        r2 = await s.execute(sql)
                    finally:
                        flags.REGISTRY.reset("doc_shred_enabled")
                    assert r1.rows == r2.rows, sql
                    return r1

                r = await both("SELECT count(*) FROM dt "
                               "WHERE doc->>'tag' = 'alpha'")
                assert r.rows[0]["count"] == 200
                r = await both(
                    "SELECT sum(cast(doc->>'qty' AS bigint)) AS q "
                    "FROM dt WHERE doc->'meta'->>'region' = 'eu'")
                assert r.rows[0]["q"] > 0
                r = await both("SELECT min(doc->>'tag') AS lo, "
                               "max(doc->>'tag') AS hi FROM dt")
                assert (r.rows[0]["lo"], r.rows[0]["hi"]) == \
                    ("alpha", "gamma")
                r = await both(
                    "SELECT k FROM dt WHERE doc->>'tag' = 'beta' "
                    "AND cast(doc->>'qty' AS bigint) < 5 ORDER BY k")
                assert r.rows and all(
                    row["k"] % 3 == 1 for row in r.rows)
            finally:
                await mc.shutdown()

        asyncio.run(go())
