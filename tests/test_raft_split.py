"""Raft-replicated SplitOperation: online split, replay idempotence,
and leader crash at arbitrary points (reference:
tablet/operations/split_operation.cc)."""
import asyncio

import pytest

from yugabyte_db_tpu.docdb import ReadRequest
from yugabyte_db_tpu.ops import AggSpec
from yugabyte_db_tpu.rpc import RpcError
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
from yugabyte_db_tpu.utils import flags
from tests.test_load_balancer import kv_info


def run(coro):
    return asyncio.run(coro)


async def _count(c, table="kv"):
    agg = await c.scan(table, ReadRequest(
        "", aggregates=(AggSpec("count"),)))
    return int(agg.agg_values[0])


class TestRaftSplit:
    def test_online_split_under_writes(self, tmp_path):
        """Writes racing the split either land in the parent (before
        the split entry) or re-route to children — none are lost."""
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            c = mc.client()
            await c.create_table(kv_info(), num_tablets=1,
                                 replication_factor=1)
            await mc.wait_for_leaders("kv")
            await c.insert("kv", [{"k": i, "v": 1.0} for i in range(100)])
            ct = await c._table("kv")
            parent = ct.locations[0].tablet_id

            stop = asyncio.Event()
            written = []

            async def writer():
                i = 100
                while not stop.is_set():
                    await c.insert("kv", [{"k": i, "v": 2.0}])
                    written.append(i)
                    i += 1
                    await asyncio.sleep(0.002)

            w = asyncio.create_task(writer())
            await asyncio.sleep(0.1)
            r = await c._master_call("split_tablet",
                                     {"tablet_id": parent}, timeout=60.0)
            await asyncio.sleep(0.3)
            stop.set()
            await w
            ct = await c._table("kv", refresh=True)
            assert {l.tablet_id for l in ct.locations} == \
                {r["left"], r["right"]}
            assert await _count(c) == 100 + len(written)
            await mc.shutdown()
        run(go())

    def test_split_replays_idempotently_after_restart(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            c = mc.client()
            await c.create_table(kv_info(), num_tablets=1,
                                 replication_factor=1)
            await mc.wait_for_leaders("kv")
            await c.insert("kv", [{"k": i, "v": float(i)}
                                  for i in range(60)])
            ct = await c._table("kv")
            await c._master_call(
                "split_tablet", {"tablet_id": ct.locations[0].tablet_id},
                timeout=60.0)
            assert await _count(c) == 60
            # restart: children reopen, the parent's split entry (if
            # still in any WAL) must not re-split or duplicate data
            await mc.restart_tserver(0)
            await mc.wait_for_leaders("kv")
            c2 = mc.client()
            assert await _count(c2) == 60
            row = await c2.get("kv", {"k": 42})
            assert row["v"] == 42.0
            await mc.shutdown()
        run(go())

    def test_leader_killed_mid_split_rf3(self, tmp_path):
        """RF=3: kill the parent leader right after the split entry
        replicates; the split must complete (entry committed -> applied
        by the new leader) or cleanly retry — never lose data."""
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=3).start()
            c = mc.client()
            await c.create_table(kv_info(), num_tablets=1,
                                 replication_factor=3)
            await mc.wait_for_leaders("kv")
            await c.insert("kv", [{"k": i, "v": float(i)}
                                  for i in range(80)])
            ct = await c._table("kv")
            parent = ct.locations[0].tablet_id
            leader_idx = None
            for i, ts in enumerate(mc.tservers):
                p = ts.peers.get(parent)
                if p is not None and p.is_leader():
                    leader_idx = i
            assert leader_idx is not None

            async def split_then_retry():
                for _ in range(6):
                    try:
                        return await c._master_call(
                            "split_tablet", {"tablet_id": parent},
                            timeout=60.0)
                    except RpcError:
                        await asyncio.sleep(0.5)
                raise AssertionError("split never completed")

            task = asyncio.create_task(split_then_retry())
            # kill the leader while the split is in flight
            await asyncio.sleep(0.05)
            await mc.stop_tserver(leader_idx)
            r = await asyncio.wait_for(task, 120.0)
            ct = await c._table("kv", refresh=True)
            assert {l.tablet_id for l in ct.locations} == \
                {r["left"], r["right"]}
            # every row survived, across the remaining replicas
            deadline = asyncio.get_event_loop().time() + 30.0
            while True:
                try:
                    n = await _count(c)
                    if n == 80:
                        break
                except RpcError:
                    pass
                assert asyncio.get_event_loop().time() < deadline, \
                    "children never became fully available"
                await asyncio.sleep(0.25)
            row = await c.get("kv", {"k": 77})
            assert row is not None and row["v"] == 77.0
            await mc.shutdown()
        run(go())
