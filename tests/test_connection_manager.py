"""Connection manager (odyssey analog): transaction-level pooling of
SqlSessions behind the PG wire protocol — many client sockets, a
bounded session pool, fair queuing, and mid-transaction disconnect
cleanup (reference: src/odyssey routing/pooling)."""
import asyncio

from yugabyte_db_tpu.ql.connection_manager import PooledPgServer
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
from tests.test_pg_wire import MiniPgClient


def run(coro):
    return asyncio.run(coro)


async def _connect(addr):
    reader, writer = await asyncio.open_connection(*addr)
    c = MiniPgClient(reader, writer)
    await c.startup()
    return c, writer


class TestPooling:
    def test_many_clients_share_small_pool(self, tmp_path):
        """20 concurrent clients over a 2-session pool: every statement
        completes (excess queues instead of failing)."""
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            srv = PooledPgServer(mc.client(), pool_size=2)
            addr = await srv.start()
            try:
                c0, w0 = await _connect(addr)
                await c0.query("CREATE TABLE p (k bigint, v double, "
                               "PRIMARY KEY (k)) WITH tablets = 1")
                await mc.wait_for_leaders("p")

                async def client(i):
                    c, w = await _connect(addr)
                    await c.query(f"INSERT INTO p (k, v) VALUES "
                                  f"({i}, {float(i)})")
                    msgs = await c.query(
                        f"SELECT v FROM p WHERE k = {i}")
                    w.close()
                    return MiniPgClient.rows(msgs)
                out = await asyncio.gather(*[client(i)
                                             for i in range(20)])
                assert all(r and float(r[0][0]) == float(i)
                           for i, r in enumerate(out))
                msgs = await c0.query("SELECT count(*) FROM p")
                assert int(MiniPgClient.rows(msgs)[0][0]) == 20
                assert srv.waits > 0, "pool never saturated: test is " \
                    "not exercising queuing"
                w0.close()
            finally:
                await srv.shutdown()
                await mc.shutdown()
        run(go())

    def test_transaction_holds_one_session(self, tmp_path):
        """A client inside BEGIN keeps ITS session across statements
        (sees its own uncommitted writes) while other clients proceed
        on the remaining pool."""
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            srv = PooledPgServer(mc.client(), pool_size=2)
            addr = await srv.start()
            try:
                c0, w0 = await _connect(addr)
                await c0.query("CREATE TABLE t (k bigint, v double, "
                               "PRIMARY KEY (k)) WITH tablets = 1")
                await mc.wait_for_leaders("t")
                ca, wa = await _connect(addr)
                cb, wb = await _connect(addr)
                await ca.query("BEGIN")
                await ca.query("INSERT INTO t (k, v) VALUES (1, 1.0)")
                # txn client reads its OWN write (same session held)
                msgs = await ca.query("SELECT v FROM t WHERE k = 1")
                assert MiniPgClient.rows(msgs), "txn lost its session"
                # other client: txn write invisible, own work fine
                msgs = await cb.query("SELECT count(*) FROM t")
                assert int(MiniPgClient.rows(msgs)[0][0]) == 0
                await cb.query("INSERT INTO t (k, v) VALUES (5, 5.0)")
                await ca.query("COMMIT")
                msgs = await cb.query("SELECT count(*) FROM t")
                assert int(MiniPgClient.rows(msgs)[0][0]) == 2
                wa.close()
                wb.close()
                w0.close()
            finally:
                await srv.shutdown()
                await mc.shutdown()
        run(go())

    def test_disconnect_mid_txn_rolls_back_and_returns_session(
            self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            srv = PooledPgServer(mc.client(), pool_size=1)
            addr = await srv.start()
            try:
                c0, w0 = await _connect(addr)
                await c0.query("CREATE TABLE d (k bigint, v double, "
                               "PRIMARY KEY (k)) WITH tablets = 1")
                await mc.wait_for_leaders("d")
                w0.close()
                ca, wa = await _connect(addr)
                await ca.query("BEGIN")
                await ca.query("INSERT INTO d (k, v) VALUES (1, 1.0)")
                wa.close()              # vanish mid-transaction
                await asyncio.sleep(0.2)
                # the single pooled session must come back, rolled back
                cb, wb = await _connect(addr)
                msgs = await cb.query("SELECT count(*) FROM d")
                assert int(MiniPgClient.rows(msgs)[0][0]) == 0
                # and the row is writable (no leaked intents/locks)
                await cb.query("INSERT INTO d (k, v) VALUES (1, 9.0)")
                msgs = await cb.query("SELECT v FROM d WHERE k = 1")
                assert float(MiniPgClient.rows(msgs)[0][0]) == 9.0
                wb.close()
            finally:
                await srv.shutdown()
                await mc.shutdown()
        run(go())


class TestReadYourOwnWrites:
    """RYOW overlay edge cases from review: projections without pk
    columns, partial upserts + DELETE re-evaluation, LIMIT interplay."""

    def test_projection_without_pk_still_overlays(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.ql.executor import SqlSession
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            s = SqlSession(mc.client())
            try:
                await s.execute("CREATE TABLE r1 (k bigint, v double, "
                                "PRIMARY KEY (k)) WITH tablets = 1")
                await s.execute(
                    "INSERT INTO r1 (k, v) VALUES (1, 10.0)")
                await s.execute("BEGIN")
                await s.execute("UPDATE r1 SET v = 20.0 WHERE k = 1")
                r = await s.execute("SELECT v FROM r1 WHERE k = 1")
                assert [x["v"] for x in r.rows] == [20.0], r.rows
                await s.execute("DELETE FROM r1 WHERE k = 1")
                r = await s.execute("SELECT v FROM r1 WHERE k = 1")
                assert r.rows == [], r.rows
                await s.execute("ROLLBACK")
                r = await s.execute("SELECT v FROM r1 WHERE k = 1")
                assert [x["v"] for x in r.rows] == [10.0]
            finally:
                await mc.shutdown()
        run(go())

    def test_delete_by_nonpk_col_with_partial_upsert(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.ql.executor import SqlSession
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            s = SqlSession(mc.client())
            try:
                await s.execute("CREATE TABLE r2 (k bigint, a double, "
                                "b double, PRIMARY KEY (k)) "
                                "WITH tablets = 1")
                await s.execute(
                    "INSERT INTO r2 (k, a, b) VALUES (1, 0.0, 5.0)")
                await s.execute("BEGIN")
                # partial upsert touches a only; b stays 5 committed
                # (PG-strict INSERT requires the explicit ON CONFLICT
                # form for upsert semantics)
                await s.execute("INSERT INTO r2 (k, a) VALUES (1, 9.0) "
                                "ON CONFLICT (k) DO UPDATE "
                                "SET a = excluded.a")
                await s.execute("DELETE FROM r2 WHERE b = 5.0")
                r = await s.execute("SELECT k FROM r2")
                assert r.rows == [], r.rows
                await s.execute("COMMIT")
                r = await s.execute("SELECT k FROM r2")
                assert r.rows == []
            finally:
                await mc.shutdown()
        run(go())

    def test_limit_not_undercut_by_overlay(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.ql.executor import SqlSession
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            s = SqlSession(mc.client())
            try:
                await s.execute("CREATE TABLE r3 (k bigint, v double, "
                                "PRIMARY KEY (k)) WITH tablets = 1")
                await s.execute(
                    "INSERT INTO r3 (k, v) VALUES "
                    + ", ".join(f"({i}, {float(i)})" for i in range(20)))
                await s.execute("BEGIN")
                await s.execute("DELETE FROM r3 WHERE k = 0")
                r = await s.execute("SELECT k FROM r3 LIMIT 10")
                assert len(r.rows) == 10, len(r.rows)
                await s.execute("ROLLBACK")
            finally:
                await mc.shutdown()
        run(go())


class TestAggregateReadYourOwnWrites:
    """Aggregates and GROUP BY inside a transaction see the txn's own
    uncommitted writes (scalar + grouped client-side folds over the
    overlaid scan; previously snapshot-only)."""

    def test_scalar_aggregates_see_pending_writes(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.ql.executor import SqlSession
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            s = SqlSession(mc.client())
            try:
                await s.execute("CREATE TABLE ag (k bigint, v double, "
                                "PRIMARY KEY (k)) WITH tablets = 1")
                await s.execute("INSERT INTO ag (k, v) VALUES "
                                "(1, 10.0), (2, 20.0), (3, 30.0)")
                await s.execute("BEGIN")
                await s.execute("INSERT INTO ag (k, v) VALUES (4, 40.0)")
                await s.execute("UPDATE ag SET v = 25.0 WHERE k = 2")
                await s.execute("DELETE FROM ag WHERE k = 1")
                r = await s.execute(
                    "SELECT count(*), sum(v), avg(v), min(v), max(v) "
                    "FROM ag")
                row = r.rows[0]
                vals = list(row.values())
                assert vals[0] == 3, row
                assert abs(vals[1] - 95.0) < 1e-9, row
                assert abs(vals[2] - 95.0 / 3) < 1e-9, row
                assert vals[3] == 25.0 and vals[4] == 40.0, row
                # WHERE + aggregate sees merged rows too
                r = await s.execute(
                    "SELECT count(*) FROM ag WHERE v >= 25.0")
                assert list(r.rows[0].values())[0] == 3
                await s.execute("ROLLBACK")
                r = await s.execute("SELECT count(*), sum(v) FROM ag")
                vals = list(r.rows[0].values())
                assert vals[0] == 3 and abs(vals[1] - 60.0) < 1e-9
            finally:
                await mc.shutdown()
        run(go())

    def test_grouped_aggregates_see_pending_writes(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.ql.executor import SqlSession
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            s = SqlSession(mc.client())
            try:
                await s.execute("CREATE TABLE gg (k bigint, grp text, "
                                "v double, PRIMARY KEY (k)) "
                                "WITH tablets = 1")
                await s.execute(
                    "INSERT INTO gg (k, grp, v) VALUES "
                    "(1, 'a', 1.0), (2, 'a', 2.0), (3, 'b', 3.0)")
                await s.execute("BEGIN")
                await s.execute(
                    "INSERT INTO gg (k, grp, v) VALUES (4, 'b', 7.0)")
                await s.execute("DELETE FROM gg WHERE k = 1")
                r = await s.execute(
                    "SELECT grp, sum(v) FROM gg GROUP BY grp")
                got = {row["grp"]: list(row.values())[1]
                       for row in r.rows}
                assert got == {"a": 2.0, "b": 10.0}, got
                # HAVING over the merged groups
                r = await s.execute(
                    "SELECT grp, sum(v) FROM gg GROUP BY grp "
                    "HAVING sum(v) > 5.0")
                assert [row["grp"] for row in r.rows] == ["b"], r.rows
                await s.execute("ROLLBACK")
                r = await s.execute(
                    "SELECT grp, sum(v) FROM gg GROUP BY grp")
                got = {row["grp"]: list(row.values())[1]
                       for row in r.rows}
                assert got == {"a": 3.0, "b": 3.0}, got
            finally:
                await mc.shutdown()
        run(go())
