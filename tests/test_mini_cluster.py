"""End-to-end MiniCluster integration tests: DDL through master, writes
through Raft to tablet leaders, fan-out scans with aggregate combine,
tserver restart recovery (reference analog:
src/yb/integration-tests/*-itest.cc over mini_cluster.h)."""
import asyncio

import numpy as np
import pytest

from yugabyte_db_tpu.docdb import ReadRequest
from yugabyte_db_tpu.docdb.table_codec import TableInfo
from yugabyte_db_tpu.dockv.packed_row import (
    ColumnSchema, ColumnType, TableSchema,
)
from yugabyte_db_tpu.dockv.partition import PartitionSchema
from yugabyte_db_tpu.ops import AggSpec, Expr
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster

C = Expr.col


def kv_info(name="kv"):
    schema = TableSchema(columns=(
        ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
        ColumnSchema(1, "v", ColumnType.FLOAT64),
        ColumnSchema(2, "s", ColumnType.STRING),
    ), version=1)
    return TableInfo("", name, schema, PartitionSchema("hash", 1))


def run(coro):
    return asyncio.run(coro)


class TestMiniCluster:
    def test_create_insert_read_rf1(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=2,
                                     replication_factor=1)
                await mc.wait_for_leaders("kv")
                n = await c.insert("kv", [
                    {"k": i, "v": float(i), "s": f"s{i}"} for i in range(40)])
                assert n == 40
                row = await c.get("kv", {"k": 17})
                assert row["v"] == 17.0 and row["s"] == "s17"
                assert await c.get("kv", {"k": 999}) is None
                resp = await c.scan("kv", ReadRequest(
                    "", where=(C(1) >= 20.0).node, columns=("k",)))
                assert len(resp.rows) == 20
                agg = await c.scan("kv", ReadRequest(
                    "", aggregates=(AggSpec("sum", C(1).node),
                                    AggSpec("count"))))
                assert float(agg.agg_values[0]) == sum(range(40))
                assert int(agg.agg_values[1]) == 40
            finally:
                await mc.shutdown()
        run(go())

    def test_rf3_write_survives_and_replicates(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=3).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=1,
                                     replication_factor=3)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": i, "v": 1.0, "s": "x"}
                                      for i in range(10)])
                # all three replicas applied the writes
                await asyncio.sleep(0.5)
                applied = []
                for ts in mc.tservers:
                    for tid, peer in ts.peers.items():
                        n = sum(1 for _ in peer.tablet.regular.iterate())
                        applied.append(n)
                assert applied.count(10) == 3
            finally:
                await mc.shutdown()
        run(go())

    def test_leader_failover_write_path(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=3).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=1,
                                     replication_factor=3)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": 1, "v": 1.0, "s": "a"}])
                # find and stop the leader tserver
                leader_idx = None
                for i, ts in enumerate(mc.tservers):
                    if any(p.is_leader() for p in ts.peers.values()):
                        leader_idx = i
                        break
                await mc.stop_tserver(leader_idx)
                # writes keep working after failover (client retries)
                await c.insert("kv", [{"k": 2, "v": 2.0, "s": "b"}])
                row = await c.get("kv", {"k": 2})
                assert row["v"] == 2.0
            finally:
                await mc.shutdown()
        run(go())

    def test_tserver_restart_recovers_data(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=1,
                                     replication_factor=1)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": i, "v": float(i), "s": "z"}
                                      for i in range(25)])
                await mc.restart_tserver(0)
                await mc.wait_for_leaders("kv")
                c2 = mc.client()
                row = await c2.get("kv", {"k": 13})
                assert row is not None and row["v"] == 13.0
                agg = await c2.scan("kv", ReadRequest(
                    "", aggregates=(AggSpec("count"),)))
                assert int(agg.agg_values[0]) == 25
            finally:
                await mc.shutdown()
        run(go())

    def test_drop_table(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=2)
                await mc.wait_for_leaders("kv")
                assert len(await c.list_tables()) == 1
                await c.drop_table("kv")
                assert len(await c.list_tables()) == 0
                assert all(not ts.peers for ts in mc.tservers)
            finally:
                await mc.shutdown()
        run(go())


class TestWriteBatching:
    def test_concurrent_writes_batch_into_fewer_raft_rounds(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=1,
                                     replication_factor=1)
                await mc.wait_for_leaders("kv")
                # fire 50 concurrent single-row inserts
                await asyncio.gather(*[
                    c.insert("kv", [{"k": i, "v": float(i), "s": "w"}])
                    for i in range(50)])
                peer = next(p for ts in mc.tservers
                            for p in ts.peers.values())
                write_entries = [e for e in peer.log.all_entries()
                                 if e.etype == "write"]
                # batching: far fewer Raft entries than writes
                assert len(write_entries) < 50
                # all rows present and correct
                for i in (0, 25, 49):
                    assert (await c.get("kv", {"k": i}))["v"] == float(i)
                agg = await c.scan("kv", ReadRequest(
                    "", aggregates=(AggSpec("count"),)))
                assert int(agg.agg_values[0]) == 50
            finally:
                await mc.shutdown()
        run(go())


class TestScanPages:
    def test_double_buffered_paging_streams_all_rows(self, tmp_path):
        """scan_pages yields every row exactly once across page
        boundaries and tablets, with the next page prefetched while the
        consumer holds the current one."""
        async def go():
            from yugabyte_db_tpu.docdb import ReadRequest
            from tests.test_load_balancer import kv_info
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=2)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": i, "v": float(i)}
                                      for i in range(57)])
                seen = []
                pages = 0
                async for page in c.scan_pages(
                        "kv", ReadRequest("", columns=("k",)),
                        page_size=10):
                    pages += 1
                    assert len(page) <= 10
                    seen.extend(r["k"] for r in page)
                assert sorted(seen) == list(range(57))
                assert pages >= 6     # 57 rows / 10 per page, 2 tablets
            finally:
                await mc.shutdown()
        run(go())
