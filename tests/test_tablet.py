"""Tablet-level tests: write/read/flush/compaction/snapshot lifecycle,
CPU-vs-TPU compaction equivalence (reference analog:
src/yb/tablet/tablet-test.cc family)."""
import numpy as np
import pytest

from yugabyte_db_tpu.docdb import ReadRequest, RowOp, WriteRequest
from yugabyte_db_tpu.dockv.packed_row import (
    ColumnSchema, ColumnType, TableSchema,
)
from yugabyte_db_tpu.dockv.partition import PartitionSchema
from yugabyte_db_tpu.docdb.table_codec import TableInfo
from yugabyte_db_tpu.ops import AggSpec, Expr
from yugabyte_db_tpu.tablet import Tablet
from yugabyte_db_tpu.utils import flags
from yugabyte_db_tpu.utils.hybrid_time import HybridClock, HybridTime, \
    MockPhysicalClock

C = Expr.col


def make_info():
    schema = TableSchema(columns=(
        ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
        ColumnSchema(1, "v", ColumnType.FLOAT64),
        ColumnSchema(2, "s", ColumnType.STRING),
    ), version=1)
    return TableInfo("t1", "kv", schema, PartitionSchema("hash", 1))


@pytest.fixture
def tablet(tmp_path):
    clock = HybridClock(MockPhysicalClock(1_000_000))
    return Tablet("tab-1", make_info(), str(tmp_path), clock=clock)


def upsert(tablet, rows, ht=None):
    return tablet.apply_write(
        WriteRequest("t1", [RowOp("upsert", r) for r in rows]),
        ht=ht)


class TestTabletLifecycle:
    def test_write_read_flush_compact(self, tablet):
        for round_ in range(3):
            upsert(tablet, [{"k": i, "v": float(round_), "s": f"r{round_}"}
                            for i in range(50)])
            tablet.flush()
        assert tablet.num_sst_files() == 3
        resp = tablet.read(ReadRequest("t1", pk_eq={"k": 10}))
        assert resp.rows[0]["v"] == 2.0
        tablet.compact()
        assert tablet.num_sst_files() == 1
        resp = tablet.read(ReadRequest("t1", pk_eq={"k": 10}))
        assert resp.rows[0]["v"] == 2.0

    def test_compaction_gc_drops_history(self, tablet):
        clk = tablet.clock
        upsert(tablet, [{"k": 1, "v": 1.0, "s": "old"}])
        tablet.flush()
        # advance far beyond retention (900s)
        clk._physical.advance_micros(2_000_000_000)
        upsert(tablet, [{"k": 1, "v": 2.0, "s": "new"}])
        tablet.flush()
        assert sum(1 for _ in tablet.regular.iterate()) == 2
        # with the new version still inside the retention window, BOTH
        # versions must survive (reads between cutoff and the new HT need
        # the old one)
        tablet.compact()
        assert sum(1 for _ in tablet.regular.iterate()) == 2
        # once the cutoff passes the new version too, the overwritten old
        # version is dropped
        clk._physical.advance_micros(2_000_000_000)
        tablet.compact()
        entries = list(tablet.regular.iterate())
        assert len(entries) == 1
        resp = tablet.read(ReadRequest("t1", pk_eq={"k": 1}))
        assert resp.rows[0]["v"] == 2.0

    def test_cpu_tpu_compaction_same_result(self, tmp_path):
        rows = [{"k": i, "v": float(i), "s": f"s{i}"} for i in range(200)]
        results = {}
        for mode in (True, False):
            clock = HybridClock(MockPhysicalClock(1_000_000))
            t = Tablet("tab-x", make_info(), str(tmp_path / str(mode)),
                       clock=clock)
            upsert(t, rows)
            t.flush()
            clock._physical.advance_micros(2_000_000_000)
            upsert(t, [{"k": i, "v": -1.0, "s": "upd"} for i in range(50)])
            upsert(t, [{"k": 199}])  # not a delete; an upsert with nulls
            t.flush()
            flags.set_flag("tpu_compaction_enabled", mode)
            try:
                t.compact()
            finally:
                flags.REGISTRY.reset("tpu_compaction_enabled")
            results[mode] = sorted(
                (k.hex(), v.hex()) for k, v in t.regular.iterate())
        assert results[True] == results[False]

    def test_delete_then_compact_removes_row(self, tablet):
        upsert(tablet, [{"k": 5, "v": 1.0, "s": "x"}])
        tablet.apply_write(WriteRequest("t1", [RowOp("delete", {"k": 5})]))
        tablet.flush()
        tablet.clock._physical.advance_micros(2_000_000_000)
        tablet.compact()
        assert sum(1 for _ in tablet.regular.iterate()) == 0

    def test_snapshot_restore(self, tablet, tmp_path):
        upsert(tablet, [{"k": i, "v": float(i), "s": "a"} for i in range(10)])
        snap = str(tmp_path / "snap")
        tablet.create_snapshot(snap)
        upsert(tablet, [{"k": 0, "v": 999.0, "s": "changed"}])
        restored = Tablet.restore_snapshot(
            "tab-r", make_info(), snap, str(tmp_path / "restored"))
        resp = restored.read(ReadRequest("t1", pk_eq={"k": 0}))
        assert resp.rows[0]["v"] == 0.0

    def test_bulk_load_and_aggregate(self, tablet):
        n = 5000
        cols = {"k": np.arange(n, dtype=np.int64),
                "v": np.linspace(0, 1, n),
                "s": np.array(["x"] * n, object)}
        loaded = tablet.bulk_load(cols)
        assert loaded == n
        flags.set_flag("tpu_min_rows_for_pushdown", 100)
        try:
            resp = tablet.read(ReadRequest(
                "t1", aggregates=(AggSpec("sum", C(1).node),
                                  AggSpec("count"))))
        finally:
            flags.REGISTRY.reset("tpu_min_rows_for_pushdown")
        assert resp.backend == "tpu"
        np.testing.assert_allclose(float(resp.agg_values[0]),
                                   cols["v"].sum(), rtol=1e-4)
        assert int(resp.agg_values[1]) == n

    def test_bulk_load_partition_split(self, tmp_path):
        info = make_info()
        parts = info.partition_schema.create_partitions(4)
        n = 1000
        cols = {"k": np.arange(n, dtype=np.int64),
                "v": np.ones(n), "s": np.array(["x"] * n, object)}
        tablets = [Tablet(f"tab-{i}", info, str(tmp_path / str(i)),
                          partition=p) for i, p in enumerate(parts)]
        total = sum(t.bulk_load(cols) for t in tablets)
        assert total == n
        # every row readable from exactly one tablet
        found = 0
        for t in tablets:
            resp = t.read(ReadRequest("t1", pk_eq={"k": 500}))
            found += len(resp.rows)
        assert found == 1
