"""Golden byte-format tests: the on-disk/wire encodings must stay stable
across rounds (a change here is a disk-format break and needs a version
gate — the AutoFlags pattern; reference: auto_flags.md)."""
import numpy as np

from yugabyte_db_tpu.dockv import (
    DocKey, KeyEntryValue, SubDocKey, PrimitiveValue,
)
from yugabyte_db_tpu.dockv.packed_row import (
    ColumnSchema, ColumnType, RowPacker, SchemaPacking, TableSchema,
)
from yugabyte_db_tpu.utils.hybrid_time import DocHybridTime, HybridTime

K = KeyEntryValue


class TestGoldenKeys:
    def test_doc_key_bytes(self):
        dk = DocKey.make(hash=0x1234, hashed=(K.int64(42),),
                         range=(K.string("ab"),))
        assert dk.encode().hex() == (
            "081234"                  # hash marker + 0x1234
            "26800000000000002a"      # kInt64 + biased 42
            "03"                      # group end
            "2a61620000"              # kString 'ab' + terminator
            "03")                     # group end

    def test_cotable_prefix_bytes(self):
        dk = DocKey.make(range=(K.int32(1),), cotable_id=7)
        assert dk.encode().hex() == (
            "0a00000007"              # cotable marker + id 7
            "2480000001"              # kInt32 + biased 1
            "03")

    def test_subdockey_ht_suffix(self):
        dk = DocKey.make(range=(K.int64(1),))
        sdk = SubDocKey(dk, (), DocHybridTime(HybridTime(0x1000), 2))
        enc = sdk.encode()
        assert enc[-13] == 0x05                   # kHybridTime marker
        assert DocHybridTime.decode_desc(enc[-12:]) == \
            DocHybridTime(HybridTime(0x1000), 2)

    def test_desc_complement(self):
        asc = K.int64(5)
        desc = K.int64(5, desc=True)
        from yugabyte_db_tpu.dockv.key_encoding import encode_key_entry
        a, d = encode_key_entry(asc), encode_key_entry(desc)
        assert bytes(x ^ 0xFF for x in a[1:]) == d[1:]


class TestGoldenValues:
    def test_primitive_values(self):
        assert PrimitiveValue.tombstone().encode() == b"\x10"
        assert PrimitiveValue.int64(1).encode().hex() == \
            "040100000000000000"
        assert PrimitiveValue.string("hi").encode() == b"\x07hi"

    def test_packed_row_bytes(self):
        schema = TableSchema(columns=(
            ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
            ColumnSchema(1, "a", ColumnType.INT32),
            ColumnSchema(2, "s", ColumnType.STRING),
        ), version=3)
        sp = SchemaPacking.from_schema(schema)
        packed = RowPacker(sp).pack_value({1: 7, 2: "x"})
        # marker, varint version 3, bitmap 00, int32 7 LE, end-offset 1, 'x'
        assert packed.hex() == "21" "03" "00" "07000000" "01000000" "78"

    def test_ttl_envelope(self):
        from yugabyte_db_tpu.dockv.value import unwrap_ttl, wrap_ttl
        v = wrap_ttl(b"\x21abc", 0x55)
        assert v[0] == 0x30 and unwrap_ttl(v) == (b"\x21abc", 0x55)
