"""Distributed scan / sharded vector search over the virtual 8-device CPU
mesh (the MiniCluster analog for the TPU data plane — reference tests run
real multi-node stacks in-process, src/yb/integration-tests/mini_cluster.h)."""
import jax
import numpy as np
import pytest

from yugabyte_db_tpu.ops import AggSpec, Expr
from yugabyte_db_tpu.ops.scan import GroupSpec
from yugabyte_db_tpu.parallel import tablet_mesh, sharded_exact_search
from yugabyte_db_tpu.parallel.distributed_scan import (
    build_sharded_batch, distributed_scan_aggregate, DistributedScanKernel,
)
from yugabyte_db_tpu.storage.columnar import ColumnarBlock

C = Expr.col

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")


def shard_block(n, seed, uniq=True):
    rng = np.random.default_rng(seed)
    qty = rng.uniform(0, 50, n)
    flag = rng.integers(0, 4, n).astype(np.int32)
    return ColumnarBlock.from_arrays(
        schema_version=1,
        key_hash=rng.integers(0, 2**63, n).astype(np.uint64),
        ht=np.full(n, 10, np.uint64),
        fixed={1: (qty, np.zeros(n, bool)),
               4: (flag, np.zeros(n, bool))},
        unique_keys=uniq), qty, flag


class TestDistributedScan:
    def test_psum_sum_count_8_tablets(self):
        tm = tablet_mesh(num_tablet_shards=8)
        blocks, all_qty = [], []
        for s in range(8):
            blk, qty, _ = shard_block(500 + 13 * s, seed=s)
            blocks.append([blk])
            all_qty.append(qty)
        batch = build_sharded_batch(tm, blocks, [1])
        (s_, c_), cnt = distributed_scan_aggregate(
            batch, (C(1) < 25.0).node,
            (AggSpec("sum", C(1).node), AggSpec("count")))
        cat = np.concatenate(all_qty)
        m = cat < 25.0
        np.testing.assert_allclose(float(s_), cat[m].sum(), rtol=1e-4)
        assert int(c_) == m.sum() == int(cnt)

    def test_min_max_combine(self):
        tm = tablet_mesh(num_tablet_shards=8)
        blocks, all_qty = [], []
        for s in range(8):
            blk, qty, _ = shard_block(100, seed=100 + s)
            blocks.append([blk])
            all_qty.append(qty)
        batch = build_sharded_batch(tm, blocks, [1])
        (mn, mx), _ = distributed_scan_aggregate(
            batch, None, (AggSpec("min", C(1).node), AggSpec("max", C(1).node)))
        cat = np.concatenate(all_qty)
        np.testing.assert_allclose(float(mn), cat.min(), rtol=1e-6)
        np.testing.assert_allclose(float(mx), cat.max(), rtol=1e-6)

    def test_grouped_2d_mesh(self):
        """4 tablet shards x 2 block shards (dp x sp) — Q1-style grouped
        aggregate combined across both axes."""
        tm = tablet_mesh(num_tablet_shards=4, num_block_shards=2)
        blocks, qs, fs = [], [], []
        for s in range(8):
            blk, qty, flag = shard_block(300, seed=200 + s)
            blocks.append([blk])
            qs.append(qty)
            fs.append(flag)
        batch = build_sharded_batch(tm, blocks, [1, 4])
        (sums, counts), _ = distributed_scan_aggregate(
            batch, None,
            (AggSpec("sum", C(1).node), AggSpec("count")),
            group=GroupSpec(cols=((4, 4, 0),)))
        qcat, fcat = np.concatenate(qs), np.concatenate(fs)
        for g in range(4):
            m = fcat == g
            np.testing.assert_allclose(np.asarray(sums)[g], qcat[m].sum(),
                                       rtol=1e-4)
            assert int(np.asarray(counts)[g]) == m.sum()

    def test_kernel_cached_across_runs(self):
        tm = tablet_mesh(num_tablet_shards=8)
        kern = DistributedScanKernel()
        for trial in range(3):
            blocks = [[shard_block(64, seed=300 + trial * 8 + s)[0]]
                      for s in range(8)]
            batch = build_sharded_batch(tm, blocks, [1])
            kern.run(batch, (C(1) < float(trial)).node, (AggSpec("count"),))
        assert kern.compiles == 1


class TestShardedVector:
    def test_global_topk_matches_local(self):
        tm = tablet_mesh(num_tablet_shards=4, num_block_shards=2)
        rng = np.random.default_rng(5)
        base = rng.normal(size=(8 * 64, 16)).astype(np.float32)
        q = base[[3, 200, 500]] + 0.001
        d, idx = sharded_exact_search(
            tm, q, np.asarray(base).reshape(8, 64, 16), k=4)
        assert idx[0, 0] == 3 and idx[1, 0] == 200 and idx[2, 0] == 500
        ref = ((q[:, None, :] - base[None, :, :]) ** 2).sum(-1)
        np.testing.assert_allclose(np.sort(d, axis=1)[:, 0],
                                   ref.min(axis=1), atol=1e-1)
