"""Tracing/ASH/webserver/encryption/CLI tests."""
import asyncio
import threading
import time
import urllib.request

import numpy as np
import pytest

from yugabyte_db_tpu.tserver.webserver import StatusWebServer
from yugabyte_db_tpu.utils import flags, metrics
from yugabyte_db_tpu.utils import trace as trace_mod
from yugabyte_db_tpu.utils.encryption import (
    CipherStream, KEY_MANAGER, UniverseKeyManager,
)
from yugabyte_db_tpu.utils.trace import (
    ASH, AshSampler, TRACE, TRACES, wait_status,
)

from yugabyte_db_tpu.utils.encryption import aes_available

requires_aes = pytest.mark.skipif(
    not aes_available(),
    reason="cryptography provider not installed in this image")


def run(coro):
    return asyncio.run(coro)


class TestTrace:
    def test_trace_records_and_rpcz(self):
        with TRACES.trace("read-query") as t:
            TRACE("picked read time")
            TRACE("scan done")
        assert len(t.events) == 2
        assert "read-query" in t.dump()

    def test_ash_sampling(self):
        state = {"s": "Idle"}
        ASH.register(lambda: ("worker", state["s"]))
        state["s"] = "WaitingOnRaft"
        ASH.sample_once()
        state["s"] = "Idle"
        ASH.sample_once()
        hist = ASH.histogram()
        assert hist.get("WaitingOnRaft", 0) >= 1


class TestSpanPropagation:
    """ISSUE 14: span context flows through task spawn, executor hops
    (explicit capture) and the RPC wire; sampled=0 propagates no-op."""

    def test_child_span_inherits_trace_and_parents(self):
        with TRACES.trace("root") as root:
            with TRACES.span("child") as child:
                assert child.trace_id == root.trace_id
                assert child.parent_id == root.span_id
                assert child.span_id != root.span_id

    def test_contextvar_survives_task_spawn(self):
        async def go():
            with TRACES.trace("root") as root:
                async def task_body():
                    return trace_mod.current_context()
                ctx = await asyncio.create_task(task_body())
                assert ctx.trace_id == root.trace_id
                assert ctx.span_id == root.span_id
        run(go())

    def test_executor_hop_needs_explicit_capture(self):
        async def go():
            loop = asyncio.get_running_loop()
            with TRACES.trace("root") as root:
                # WITHOUT capture: the thread sees no context
                naked = await loop.run_in_executor(
                    None, trace_mod.current_context)
                assert naked is None

                # WITH explicit capture + use_context: the thread-side
                # span lands in the same trace, parented correctly
                ctx = trace_mod.current_context()

                def thread_side():
                    with trace_mod.use_context(ctx):
                        with TRACES.span("thread-work",
                                         child_only=True) as sp:
                            return (sp.trace_id, sp.parent_id)
                tid, pid = await loop.run_in_executor(None, thread_side)
                assert tid == root.trace_id
                assert pid == root.span_id
        run(go())

    def test_rpc_wire_roundtrip_parents_server_span(self):
        from yugabyte_db_tpu.rpc.messenger import Messenger

        class Svc:
            async def rpc_ping(self, payload):
                ctx = trace_mod.current_context()
                return {"trace_id": ctx.trace_id if ctx else 0,
                        "sampled": bool(ctx and ctx.sampled)}

        async def go():
            m1, m2 = Messenger("c"), Messenger("s")
            m2.register_service("svc", Svc())
            addr = await m2.start()
            try:
                with TRACES.trace("client-op") as root:
                    r = await m1.call(addr, "svc", "ping", {})
                    assert r["sampled"]
                    assert r["trace_id"] == root.trace_id
                # chain: root <- rpc.c.svc.ping <- rpc.s.svc.ping
                recent = {t.name: t for t in TRACES.recent}
                cspan = recent["rpc.c.svc.ping"]
                sspan = recent["rpc.s.svc.ping"]
                assert cspan.parent_id == root.span_id
                assert sspan.parent_id == cspan.span_id
                assert sspan.trace_id == root.trace_id
            finally:
                await m1.shutdown()
                await m2.shutdown()
        run(go())

    def test_unsampled_propagates_as_noop(self):
        from yugabyte_db_tpu.rpc.messenger import Messenger

        class Svc:
            async def rpc_ping(self, payload):
                # downstream spans under an unsampled context must be
                # the shared no-op (nothing recorded)
                with TRACES.span("inner", child_only=True) as sp:
                    return {"sampled": sp.sampled}

        async def go():
            m1, m2 = Messenger("c"), Messenger("s")
            m2.register_service("svc", Svc())
            addr = await m2.start()
            flags.set_flag("trace_sampling_rate", 0.0)
            try:
                before = len(TRACES.recent)
                r = await m1.call(addr, "svc", "ping", {})
                assert r["sampled"] is False
                assert len(TRACES.recent) == before   # zero new spans
            finally:
                flags.REGISTRY.reset("trace_sampling_rate")
                await m1.shutdown()
                await m2.shutdown()
        run(go())

    def test_root_sampling_rate_zero_and_one(self):
        flags.set_flag("trace_sampling_rate", 0.0)
        try:
            with TRACES.span("maybe") as sp:
                assert not sp.sampled
            flags.set_flag("trace_sampling_rate", 1.0)
            with TRACES.span("always") as sp:
                assert sp.sampled
        finally:
            flags.REGISTRY.reset("trace_sampling_rate")

    def test_wire_inject_extract(self):
        assert trace_mod.extract(None) is None
        assert trace_mod.extract([1, 2, 0]).sampled is False
        ctx = trace_mod.extract([7, 9, 1])
        assert (ctx.trace_id, ctx.span_id, ctx.sampled) == (7, 9, True)
        assert trace_mod.extract("garbage") is None


class TestTraceRegistryRaces:
    def test_add_never_throws_after_finish(self):
        with TRACES.trace("t") as t:
            pass
        t.add("late event")          # after finish(): no raise
        t.set_tag("late", True)

    def test_rpcz_snapshot_race_with_appender(self):
        """A thread hammering Trace.add while rpcz() dumps must never
        raise (events snapshot under the registry lock)."""
        stop = threading.Event()
        errors = []

        def appender():
            try:
                with TRACES.trace("racy") as t:
                    while not stop.is_set():
                        t.add("x")
            except Exception as e:   # noqa: BLE001
                errors.append(e)

        th = threading.Thread(target=appender)
        th.start()
        try:
            for _ in range(200):
                TRACES.rpcz()
                TRACES.tracez()
        finally:
            stop.set()
            th.join(5.0)
        assert not errors

    def test_tracez_stamped_with_pid_and_ts(self):
        import os as _os
        with TRACES.trace("snap"):
            TRACE("e")
        d = TRACES.tracez()
        assert d["pid"] == _os.getpid()
        assert abs(d["ts"] - time.time()) < 5.0
        assert any(s["name"] == "snap" for s in d["spans"])
        assert "wait_states" in d["ash"]


class TestAsh:
    def test_provider_crash_swallowed(self):
        """Regression for sample_once's bare except: one crashing
        provider must not kill the sampler or starve later providers."""
        sampler = AshSampler()

        def bad():
            raise RuntimeError("provider exploded")
        hits = []

        def good():
            hits.append(1)
            return ("good", "WAL_Fsync")
        sampler.register(bad)
        sampler.register(good)
        sampler.sample_once()
        sampler.sample_once()
        assert sampler.samples_taken == 2
        assert len(hits) == 2
        assert sampler.histogram().get("WAL_Fsync", 0) >= 2
        assert sampler.summary()["cumulative"]["WAL_Fsync"] >= 2

    def test_wait_status_feeds_sampler_across_threads(self):
        """The active-wait table is process-global: a sampler running
        in THIS thread sees a wait_status scope held by another."""
        sampler = AshSampler()
        entered = threading.Event()
        release = threading.Event()

        def blocked_thread():
            with wait_status("Flush_SstWrite", component="flush"):
                entered.set()
                release.wait(5.0)

        th = threading.Thread(target=blocked_thread)
        th.start()
        try:
            assert entered.wait(5.0)
            sampler.sample_once()
        finally:
            release.set()
            th.join(5.0)
        assert sampler.histogram().get("Flush_SstWrite", 0) >= 1
        by_comp = sampler.summary()["by_component"]
        assert "flush" in by_comp

    def test_wait_status_rejects_free_text(self):
        with pytest.raises(ValueError):
            with wait_status("TotallyMadeUpState"):
                pass

    def test_sampler_thread_start_stop(self):
        sampler = AshSampler()
        sampler.start(interval_ms=5)
        time.sleep(0.1)
        sampler.stop()
        assert sampler.samples_taken >= 2

    def test_provider_deduped_against_wait_scope(self):
        """A component provider echoing a state already published by a
        wait_status scope that tick must not double-count it (the
        session-weighted scope signal wins)."""
        sampler = AshSampler()
        sampler.register(lambda: ("flush:x", "Flush_SstWrite"))
        with wait_status("Flush_SstWrite", component="flush"):
            sampler.sample_once()
        assert sampler.summary()["cumulative"]["Flush_SstWrite"] == 1
        # without the scope, the provider's coarse signal DOES count
        sampler.sample_once()
        assert sampler.summary()["cumulative"]["Flush_SstWrite"] == 2

    def test_unregister_stops_provider(self):
        sampler = AshSampler()
        calls = []

        def p():
            calls.append(1)
            return ("c", "Compaction_Run")
        sampler.register(p)
        sampler.sample_once()
        sampler.unregister(p)
        sampler.unregister(p)     # idempotent
        sampler.sample_once()
        assert len(calls) == 1


class TestHistogramSnapshot:
    def test_single_pass_matches_percentile(self):
        h = metrics.Histogram("h")
        for v in (1, 10, 100, 1000, 10000, 100000):
            for _ in range(7):
                h.increment(v)
        st = h.snapshot_stats()
        assert st["count"] == h.count()
        assert st["mean_us"] == pytest.approx(h.mean())
        for p in (50, 95, 99):
            assert st[f"p{p}_us"] == h.percentile(p)

    def test_empty_histogram(self):
        h = metrics.Histogram("e")
        st = h.snapshot_stats()
        assert st == {"count": 0, "mean_us": 0.0, "p50_us": 0.0,
                      "p95_us": 0.0, "p99_us": 0.0}

    def test_metrics_snapshot_stamped(self):
        import os as _os
        snap = metrics.snapshot()
        assert snap["pid"] == _os.getpid()
        assert abs(snap["ts"] - time.time()) < 5.0


class TestCollector:
    def _dump(self, pid, spans):
        return {"pid": pid, "ts": time.time(), "spans": spans,
                "active": [], "ash": {}}

    def _span(self, tid, sid, parent, name):
        return {"trace_id": tid, "span_id": sid, "parent_id": parent,
                "name": name, "start_unix": time.time(),
                "duration_ms": 1.0, "finished": True, "tags": {},
                "events": []}

    def test_stitch_across_processes(self):
        from yugabyte_db_tpu.cluster.collector import stitch, tree_names
        d1 = self._dump(100, [self._span(1, 10, 0, "client"),
                              self._span(1, 11, 10, "rpc.c.write")])
        d2 = self._dump(200, [self._span(1, 12, 11, "rpc.s.write"),
                              self._span(1, 13, 12, "tablet.apply")])
        trees = stitch([d1, d2])
        assert set(trees) == {1}
        t = trees[1]
        assert t["span_count"] == 4
        assert t["pids"] == [100, 200]
        assert len(t["roots"]) == 1
        names = tree_names(t["roots"][0])
        assert names == ["client", "rpc.c.write", "rpc.s.write",
                         "tablet.apply"]

    def test_orphan_span_becomes_root(self):
        from yugabyte_db_tpu.cluster.collector import stitch
        d = self._dump(1, [self._span(5, 50, 999, "orphan")])
        trees = stitch([d])
        assert trees[5]["roots"][0]["name"] == "orphan"

    def test_dominant_wait_and_attribution(self):
        from yugabyte_db_tpu.cluster.collector import (
            attribute_rounds, dominant_wait)
        # CPU buckets excluded while a blocking state exists
        assert dominant_wait({"OnCpu_Read": 100,
                              "Flush_SstWrite": 5}) == "Flush_SstWrite"
        # pure-CPU window: CPU is the honest fallback
        assert dominant_wait({"OnCpu_Read": 9}) == "OnCpu_Read"
        assert dominant_wait({}) is None
        rounds = [
            {"tag": "r0", "p99_ms": 10.0, "wait_delta": {}},
            {"tag": "r1", "p99_ms": 11.0,
             "wait_delta": {"WAL_Fsync": 2}},
            {"tag": "spike", "p99_ms": 200.0,
             "wait_delta": {"Flush_SstWrite": 40, "WAL_Fsync": 3}},
        ]
        attr = attribute_rounds(rounds, spread_gate=3.0)
        assert attr["over_spread_rounds"] == ["spike"]
        spike = [r for r in attr["rounds"] if r["tag"] == "spike"][0]
        assert spike["over_spread"]
        assert spike["dominant_wait"] == "Flush_SstWrite"
        assert spike["category"] == "flush"

    def test_every_wait_state_has_category(self):
        from yugabyte_db_tpu.cluster.collector import WAIT_CATEGORIES
        from yugabyte_db_tpu.utils.trace import WAIT_STATES
        uncovered = {s for s in WAIT_STATES if s != "Idle"} \
            - set(WAIT_CATEGORIES)
        assert not uncovered, (
            f"wait states missing an attribution category: {uncovered}")


class TestDeviceTelemetry:
    @staticmethod
    def _batch():
        from tests.test_ops_scan import make_block
        from yugabyte_db_tpu.ops.device_batch import build_batch
        blk, _ = make_block(n=512, seed=3)
        return build_batch([blk], [1, 2])

    def test_scan_launch_span_tagged(self):
        from yugabyte_db_tpu.ops import AggSpec, Expr, scan_aggregate
        batch = self._batch()
        where = (Expr.col(1) < 25.0).node
        aggs = (AggSpec("sum", Expr.col(2).node), AggSpec("count"))
        with TRACES.trace("scan-op") as t:
            scan_aggregate(batch, where, aggs)
            scan_aggregate(batch, where, aggs)
        spans = [s for s in TRACES.recent
                 if s.trace_id == t.trace_id
                 and s.name == "device.scan"]
        assert len(spans) == 2
        # first launch may or may not compile (shared kernel cache is
        # process-global), but the second MUST hit with the same sig
        assert spans[-1].tags["codepath"] == "cache_hit"
        assert spans[0].tags["signature"] == spans[1].tags["signature"]
        assert spans[0].tags["bucket"] == batch.padded_rows
        assert spans[0].tags["rows"] == batch.n_rows

    def test_no_spans_without_sampled_trace(self):
        from yugabyte_db_tpu.ops import AggSpec, scan_aggregate
        batch = self._batch()
        before = len([s for s in TRACES.recent
                      if s.name == "device.scan"])
        scan_aggregate(batch, None, (AggSpec("count"),))
        after = len([s for s in TRACES.recent
                     if s.name == "device.scan"])
        assert after == before


class TestClusterSpanTree:
    """ISSUE 14 acceptance: ONE acked cluster write produces ONE
    stitched cross-process span tree — client (this process) ->
    leader tserver (RPC server span, raft append+fsync, tablet apply,
    flush handoff) -> follower (consensus RPC server span, WAL
    append) — assembled from rpc_tracez dumps by cluster/collector."""

    def test_write_span_tree_stitches_across_processes(self, tmp_path):
        import os as _os

        from yugabyte_db_tpu.cluster import ClusterSupervisor
        from yugabyte_db_tpu.cluster.collector import (
            collect_cluster_tracez, stitch, tree_names)
        from yugabyte_db_tpu.docdb.table_codec import TableInfo
        from yugabyte_db_tpu.dockv.packed_row import (
            ColumnSchema, ColumnType, TableSchema)
        from yugabyte_db_tpu.dockv.partition import PartitionSchema

        info = TableInfo("", "kv", TableSchema(columns=(
            ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
            ColumnSchema(1, "v", ColumnType.FLOAT64)), version=1),
            PartitionSchema("hash", 1))

        async def main():
            sup = await ClusterSupervisor(str(tmp_path),
                                          num_tservers=2).start()
            c = None
            try:
                c = sup.client()
                await c.create_table(info, num_tablets=1,
                                     replication_factor=2)
                # a tiny flush threshold makes THIS write cross it, so
                # the apply triggers the flush-executor handoff and the
                # tree gets its flush.background leaf
                await sup.set_flag_all("memstore_flush_threshold_bytes",
                                       2000, roles=("tserver",))
                with TRACES.trace("user-write") as root:
                    n = await c.insert("kv", [
                        {"k": i, "v": float(i)} for i in range(200)])
                assert n == 200
                # follower append + leader apply + background flush all
                # finish within the replicate round; give stragglers a
                # moment before dumping
                await asyncio.sleep(1.0)
                dumps = await collect_cluster_tracez(sup)
                local = TRACES.tracez()
                local["process"] = "test-client"
                trees = stitch(dumps + [local])
                assert root.trace_id in trees, (
                    "the root trace vanished from every dump")
                t = trees[root.trace_id]
                names = []
                for r in t["roots"]:
                    names.extend(tree_names(r))
                # client -> leader -> follower: at least 3 distinct
                # pids contribute spans (test process + 2 tservers)
                assert len(t["pids"]) >= 3, (t["pids"], names)
                assert _os.getpid() in t["pids"]

                def has(prefix):
                    return any(nm.startswith(prefix) for nm in names)
                assert has("rpc.c.tserver.write"), names   # client stamp
                assert has("rpc.s.tserver.write"), names   # leader serve
                # leader append+fsync (fused or legacy path)
                assert has("raft.append_group") or \
                    has("raft.replicate"), names
                # follower WAL append via the consensus RPC
                assert has("rpc.s.consensus-"), names
                assert has("raft.follower_append"), names
                # state-machine apply + flush-executor handoff
                assert has("tablet.apply"), names
                assert has("flush.background"), names
            finally:
                if c is not None:
                    await c.messenger.shutdown()
                await sup.shutdown()
        run(main())


class TestEncryption:
    def test_cipher_roundtrip_random_access(self):
        cs = CipherStream(b"k" * 32, b"n" * 16)
        data = bytes(range(256)) * 10
        enc = cs.xor(data)
        assert enc != data
        assert cs.xor(enc) == data
        # random-access decrypt of a middle slice
        assert cs.xor(enc[100:200], offset=100) == data[100:200]

    def test_key_manager_envelope(self):
        km = UniverseKeyManager()
        km.generate_key("v1")
        raw = b"hello sst bytes" * 100
        enc = km.encrypt_file_bytes(raw)
        assert enc != raw and km.decrypt_file_bytes(enc) == raw
        # rotation keeps old files readable
        km.generate_key("v2")
        assert km.decrypt_file_bytes(enc) == raw

    def test_encrypted_sst_roundtrip(self, tmp_path):
        from yugabyte_db_tpu.storage import SstReader, SstWriter
        KEY_MANAGER.generate_key()
        flags.set_flag("encrypt_data_at_rest", True)
        try:
            p = str(tmp_path / "enc.sst")
            w = SstWriter(p)
            for i in range(50):
                w.add(b"k%04d" % i, b"v%d" % i)
            w.finish()
            with open(p, "rb") as f:
                raw = f.read()
            assert raw.startswith(b"YBTPUEN")  # v1 or v2 envelope
            assert b"k0001" not in raw          # actually encrypted
            r = SstReader(p)
            assert len(list(r.iterate())) == 50
        finally:
            flags.REGISTRY.reset("encrypt_data_at_rest")


class TestWebServer:
    def test_metrics_and_rpcz_endpoints(self):
        async def go():
            ent = metrics.REGISTRY.entity("server", "test-ws")
            ent.counter("test_requests").increment(3)
            ws = StatusWebServer("test")
            addr = await ws.start()
            loop = asyncio.get_running_loop()

            def fetch(path):
                with urllib.request.urlopen(
                        f"http://{addr[0]}:{addr[1]}{path}") as r:
                    return r.read().decode()

            body = await loop.run_in_executor(None, fetch, "/metrics")
            assert "test_requests" in body
            body = await loop.run_in_executor(None, fetch, "/rpcz")
            assert "active" in body
            body = await loop.run_in_executor(None, fetch, "/ash")
            assert "wait_states" in body
            await ws.shutdown()
        run(go())

    def test_master_path_handlers(self, tmp_path):
        """Master web UI (reference: master-path-handlers.cc): /tables,
        /tablet-servers, /tablets serve live catalog state as JSON."""
        async def go():
            import json as _json
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            from tests.test_load_balancer import kv_info
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            ws = StatusWebServer("m", extra_handlers=mc.master.web_handlers())
            addr = await ws.start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=2)
                await mc.wait_for_leaders("kv")
                loop = asyncio.get_running_loop()

                def fetch(path):
                    with urllib.request.urlopen(
                            f"http://{addr[0]}:{addr[1]}{path}") as r:
                        return r.read().decode()

                tables = _json.loads(
                    await loop.run_in_executor(None, fetch, "/tables"))
                assert any(t["name"] == "kv" and t["tablets"] == 2
                           for t in tables)
                tss = _json.loads(await loop.run_in_executor(
                    None, fetch, "/tablet-servers"))
                assert len(tss) == 1 and tss[0]["alive"]
                tablets = _json.loads(await loop.run_in_executor(
                    None, fetch, "/tablets"))
                assert sum(t["leader"] is not None for t in tablets) >= 2
            finally:
                await ws.shutdown()
                await mc.shutdown()
        run(go())


class TestAdminCli:
    def test_list_tables_and_compact(self, tmp_path, capsys):
        async def go():
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            from yugabyte_db_tpu.tools import ybtpu_admin
            from tests.test_load_balancer import kv_info
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=1)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": 1, "v": 1.0}])
                maddr = mc.master.messenger.addr
                ns = type("A", (), {
                    "master": f"{maddr[0]}:{maddr[1]}",
                    "command": "list_tables", "args": []})
                assert await ybtpu_admin.run_command(ns) == 0
                ns.command, ns.args = "flush_table", ["kv"]
                assert await ybtpu_admin.run_command(ns) == 0
            finally:
                await mc.shutdown()
        run(go())
        out = capsys.readouterr().out
        assert "kv" in out


class TestSstDump:
    def test_dump_sst_and_wal(self, tmp_path, capsys):
        from yugabyte_db_tpu.storage import SstWriter
        from yugabyte_db_tpu.consensus import Log, LogEntry
        from yugabyte_db_tpu.tools import sst_dump
        p = str(tmp_path / "x.sst")
        w = SstWriter(p)
        for i in range(10):
            w.add(b"key%03d" % i, b"v")
        w.set_frontier(op_id=[1, 5])
        w.finish()
        assert sst_dump.main([p, "--blocks", "--entries", "3"]) == 0
        out = capsys.readouterr().out
        assert "entries:   10" in out and "op_id" in out
        wal = Log(str(tmp_path / "wal"), fsync=False)
        wal.append([LogEntry(1, 1, "write", b"abc")])
        wal.close()
        assert sst_dump.main(["--wal", str(tmp_path / "wal")]) == 0
        out = capsys.readouterr().out
        assert "[1:1] write" in out


class TestAesCtr:
    """AES-CTR at rest (reference: encryption/cipher_stream.h over EVP
    AES-CTR) with the BLAKE2b keystream as documented fallback and a
    format-versioned envelope keeping every combination readable."""

    @requires_aes
    def test_aes_stream_roundtrip_random_access(self):
        from yugabyte_db_tpu.utils.encryption import (AesCtrStream,
                                                      aes_available)
        assert aes_available()   # cryptography is in this image
        cs = AesCtrStream(b"k" * 32, b"n" * 16)
        data = bytes(range(256)) * 10
        enc = cs.xor(data)
        assert enc != data and cs.xor(enc) == data
        # random access at non-block-aligned offsets
        for off in (0, 1, 15, 16, 17, 100, 2000):
            assert cs.xor(enc[off:off + 77], offset=off) == \
                data[off:off + 77]

    @requires_aes
    def test_envelope_selects_aes_and_rotates(self):
        from yugabyte_db_tpu.utils.encryption import (
            CIPHER_AES_CTR, MAGIC_V2, UniverseKeyManager)
        km = UniverseKeyManager()
        km.generate_key("v1")
        raw = b"sst bytes " * 200
        enc = km.encrypt_file_bytes(raw)
        assert enc.startswith(MAGIC_V2)
        assert enc[len(MAGIC_V2)] == CIPHER_AES_CTR
        assert km.decrypt_file_bytes(enc) == raw
        # rotation: new key writes new files; old files stay readable
        km.generate_key("v2")
        enc2 = km.encrypt_file_bytes(raw)
        assert km.decrypt_file_bytes(enc2) == raw
        assert km.decrypt_file_bytes(enc) == raw

    def test_rotation_on_fallback_cipher(self):
        from yugabyte_db_tpu.utils.encryption import (
            CIPHER_BLAKE2B, UniverseKeyManager)
        km = UniverseKeyManager()
        km.force_cipher = CIPHER_BLAKE2B
        km.generate_key("b1")
        raw = b"fallback " * 100
        enc = km.encrypt_file_bytes(raw)
        km.generate_key("b2")
        assert km.decrypt_file_bytes(enc) == raw
        assert km.decrypt_file_bytes(km.encrypt_file_bytes(raw)) == raw

    def test_legacy_v1_files_stay_readable(self):
        """Files written by the round-3/4 BLAKE2b-only envelope decrypt
        under the new manager."""
        from yugabyte_db_tpu.utils.encryption import (
            CipherStream, MAGIC, UniverseKeyManager)
        import secrets as _s
        km = UniverseKeyManager()
        km.add_key("old", b"K" * 32)
        raw = b"legacy payload " * 50
        nonce = _s.token_bytes(16)
        legacy = (MAGIC + bytes([3]) + b"old" + nonce
                  + CipherStream(b"K" * 32, nonce).xor(raw))
        assert km.decrypt_file_bytes(legacy) == raw

    @requires_aes
    def test_mixed_cipher_files_coexist(self):
        from yugabyte_db_tpu.utils.encryption import (
            CIPHER_AES_CTR, CIPHER_BLAKE2B, UniverseKeyManager)
        km = UniverseKeyManager()
        km.generate_key("m1")
        raw = b"mixed " * 300
        km.force_cipher = CIPHER_BLAKE2B
        e_b = km.encrypt_file_bytes(raw)
        km.force_cipher = CIPHER_AES_CTR
        e_a = km.encrypt_file_bytes(raw)
        km.force_cipher = None
        assert km.decrypt_file_bytes(e_b) == raw
        assert km.decrypt_file_bytes(e_a) == raw
