"""Tracing/ASH/webserver/encryption/CLI tests."""
import asyncio
import urllib.request

import numpy as np
import pytest

from yugabyte_db_tpu.tserver.webserver import StatusWebServer
from yugabyte_db_tpu.utils import flags, metrics
from yugabyte_db_tpu.utils.encryption import (
    CipherStream, KEY_MANAGER, UniverseKeyManager,
)
from yugabyte_db_tpu.utils.trace import ASH, TRACE, TRACES, wait_status

from yugabyte_db_tpu.utils.encryption import aes_available

requires_aes = pytest.mark.skipif(
    not aes_available(),
    reason="cryptography provider not installed in this image")


def run(coro):
    return asyncio.run(coro)


class TestTrace:
    def test_trace_records_and_rpcz(self):
        with TRACES.trace("read-query") as t:
            TRACE("picked read time")
            TRACE("scan done")
        assert len(t.events) == 2
        assert "read-query" in t.dump()

    def test_ash_sampling(self):
        state = {"s": "Idle"}
        ASH.register(lambda: ("worker", state["s"]))
        state["s"] = "WaitingOnRaft"
        ASH.sample_once()
        state["s"] = "Idle"
        ASH.sample_once()
        hist = ASH.histogram()
        assert hist.get("WaitingOnRaft", 0) >= 1


class TestEncryption:
    def test_cipher_roundtrip_random_access(self):
        cs = CipherStream(b"k" * 32, b"n" * 16)
        data = bytes(range(256)) * 10
        enc = cs.xor(data)
        assert enc != data
        assert cs.xor(enc) == data
        # random-access decrypt of a middle slice
        assert cs.xor(enc[100:200], offset=100) == data[100:200]

    def test_key_manager_envelope(self):
        km = UniverseKeyManager()
        km.generate_key("v1")
        raw = b"hello sst bytes" * 100
        enc = km.encrypt_file_bytes(raw)
        assert enc != raw and km.decrypt_file_bytes(enc) == raw
        # rotation keeps old files readable
        km.generate_key("v2")
        assert km.decrypt_file_bytes(enc) == raw

    def test_encrypted_sst_roundtrip(self, tmp_path):
        from yugabyte_db_tpu.storage import SstReader, SstWriter
        KEY_MANAGER.generate_key()
        flags.set_flag("encrypt_data_at_rest", True)
        try:
            p = str(tmp_path / "enc.sst")
            w = SstWriter(p)
            for i in range(50):
                w.add(b"k%04d" % i, b"v%d" % i)
            w.finish()
            with open(p, "rb") as f:
                raw = f.read()
            assert raw.startswith(b"YBTPUEN")  # v1 or v2 envelope
            assert b"k0001" not in raw          # actually encrypted
            r = SstReader(p)
            assert len(list(r.iterate())) == 50
        finally:
            flags.REGISTRY.reset("encrypt_data_at_rest")


class TestWebServer:
    def test_metrics_and_rpcz_endpoints(self):
        async def go():
            ent = metrics.REGISTRY.entity("server", "test-ws")
            ent.counter("test_requests").increment(3)
            ws = StatusWebServer("test")
            addr = await ws.start()
            loop = asyncio.get_running_loop()

            def fetch(path):
                with urllib.request.urlopen(
                        f"http://{addr[0]}:{addr[1]}{path}") as r:
                    return r.read().decode()

            body = await loop.run_in_executor(None, fetch, "/metrics")
            assert "test_requests" in body
            body = await loop.run_in_executor(None, fetch, "/rpcz")
            assert "active" in body
            body = await loop.run_in_executor(None, fetch, "/ash")
            assert "wait_states" in body
            await ws.shutdown()
        run(go())

    def test_master_path_handlers(self, tmp_path):
        """Master web UI (reference: master-path-handlers.cc): /tables,
        /tablet-servers, /tablets serve live catalog state as JSON."""
        async def go():
            import json as _json
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            from tests.test_load_balancer import kv_info
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            ws = StatusWebServer("m", extra_handlers=mc.master.web_handlers())
            addr = await ws.start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=2)
                await mc.wait_for_leaders("kv")
                loop = asyncio.get_running_loop()

                def fetch(path):
                    with urllib.request.urlopen(
                            f"http://{addr[0]}:{addr[1]}{path}") as r:
                        return r.read().decode()

                tables = _json.loads(
                    await loop.run_in_executor(None, fetch, "/tables"))
                assert any(t["name"] == "kv" and t["tablets"] == 2
                           for t in tables)
                tss = _json.loads(await loop.run_in_executor(
                    None, fetch, "/tablet-servers"))
                assert len(tss) == 1 and tss[0]["alive"]
                tablets = _json.loads(await loop.run_in_executor(
                    None, fetch, "/tablets"))
                assert sum(t["leader"] is not None for t in tablets) >= 2
            finally:
                await ws.shutdown()
                await mc.shutdown()
        run(go())


class TestAdminCli:
    def test_list_tables_and_compact(self, tmp_path, capsys):
        async def go():
            from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
            from yugabyte_db_tpu.tools import ybtpu_admin
            from tests.test_load_balancer import kv_info
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=1)
                await mc.wait_for_leaders("kv")
                await c.insert("kv", [{"k": 1, "v": 1.0}])
                maddr = mc.master.messenger.addr
                ns = type("A", (), {
                    "master": f"{maddr[0]}:{maddr[1]}",
                    "command": "list_tables", "args": []})
                assert await ybtpu_admin.run_command(ns) == 0
                ns.command, ns.args = "flush_table", ["kv"]
                assert await ybtpu_admin.run_command(ns) == 0
            finally:
                await mc.shutdown()
        run(go())
        out = capsys.readouterr().out
        assert "kv" in out


class TestSstDump:
    def test_dump_sst_and_wal(self, tmp_path, capsys):
        from yugabyte_db_tpu.storage import SstWriter
        from yugabyte_db_tpu.consensus import Log, LogEntry
        from yugabyte_db_tpu.tools import sst_dump
        p = str(tmp_path / "x.sst")
        w = SstWriter(p)
        for i in range(10):
            w.add(b"key%03d" % i, b"v")
        w.set_frontier(op_id=[1, 5])
        w.finish()
        assert sst_dump.main([p, "--blocks", "--entries", "3"]) == 0
        out = capsys.readouterr().out
        assert "entries:   10" in out and "op_id" in out
        wal = Log(str(tmp_path / "wal"), fsync=False)
        wal.append([LogEntry(1, 1, "write", b"abc")])
        wal.close()
        assert sst_dump.main(["--wal", str(tmp_path / "wal")]) == 0
        out = capsys.readouterr().out
        assert "[1:1] write" in out


class TestAesCtr:
    """AES-CTR at rest (reference: encryption/cipher_stream.h over EVP
    AES-CTR) with the BLAKE2b keystream as documented fallback and a
    format-versioned envelope keeping every combination readable."""

    @requires_aes
    def test_aes_stream_roundtrip_random_access(self):
        from yugabyte_db_tpu.utils.encryption import (AesCtrStream,
                                                      aes_available)
        assert aes_available()   # cryptography is in this image
        cs = AesCtrStream(b"k" * 32, b"n" * 16)
        data = bytes(range(256)) * 10
        enc = cs.xor(data)
        assert enc != data and cs.xor(enc) == data
        # random access at non-block-aligned offsets
        for off in (0, 1, 15, 16, 17, 100, 2000):
            assert cs.xor(enc[off:off + 77], offset=off) == \
                data[off:off + 77]

    @requires_aes
    def test_envelope_selects_aes_and_rotates(self):
        from yugabyte_db_tpu.utils.encryption import (
            CIPHER_AES_CTR, MAGIC_V2, UniverseKeyManager)
        km = UniverseKeyManager()
        km.generate_key("v1")
        raw = b"sst bytes " * 200
        enc = km.encrypt_file_bytes(raw)
        assert enc.startswith(MAGIC_V2)
        assert enc[len(MAGIC_V2)] == CIPHER_AES_CTR
        assert km.decrypt_file_bytes(enc) == raw
        # rotation: new key writes new files; old files stay readable
        km.generate_key("v2")
        enc2 = km.encrypt_file_bytes(raw)
        assert km.decrypt_file_bytes(enc2) == raw
        assert km.decrypt_file_bytes(enc) == raw

    def test_rotation_on_fallback_cipher(self):
        from yugabyte_db_tpu.utils.encryption import (
            CIPHER_BLAKE2B, UniverseKeyManager)
        km = UniverseKeyManager()
        km.force_cipher = CIPHER_BLAKE2B
        km.generate_key("b1")
        raw = b"fallback " * 100
        enc = km.encrypt_file_bytes(raw)
        km.generate_key("b2")
        assert km.decrypt_file_bytes(enc) == raw
        assert km.decrypt_file_bytes(km.encrypt_file_bytes(raw)) == raw

    def test_legacy_v1_files_stay_readable(self):
        """Files written by the round-3/4 BLAKE2b-only envelope decrypt
        under the new manager."""
        from yugabyte_db_tpu.utils.encryption import (
            CipherStream, MAGIC, UniverseKeyManager)
        import secrets as _s
        km = UniverseKeyManager()
        km.add_key("old", b"K" * 32)
        raw = b"legacy payload " * 50
        nonce = _s.token_bytes(16)
        legacy = (MAGIC + bytes([3]) + b"old" + nonce
                  + CipherStream(b"K" * 32, nonce).xor(raw))
        assert km.decrypt_file_bytes(legacy) == raw

    @requires_aes
    def test_mixed_cipher_files_coexist(self):
        from yugabyte_db_tpu.utils.encryption import (
            CIPHER_AES_CTR, CIPHER_BLAKE2B, UniverseKeyManager)
        km = UniverseKeyManager()
        km.generate_key("m1")
        raw = b"mixed " * 300
        km.force_cipher = CIPHER_BLAKE2B
        e_b = km.encrypt_file_bytes(raw)
        km.force_cipher = CIPHER_AES_CTR
        e_a = km.encrypt_file_bytes(raw)
        km.force_cipher = None
        assert km.decrypt_file_bytes(e_b) == raw
        assert km.decrypt_file_bytes(e_a) == raw
