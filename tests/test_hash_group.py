"""Device GROUP BY without declared domains (HashGroupSpec): sort +
segment aggregation, no ANALYZE prerequisite (reference: unconditional
aggregate pushdown, docdb/pgsql_operation.cc:3153-3163)."""
import asyncio
import tempfile

import numpy as np
import pytest

from yugabyte_db_tpu.docdb.operations import ReadRequest
from yugabyte_db_tpu.models.tpch import (
    TPCH_Q1, LineitemTable, generate_lineitem, numpy_reference,
)
from yugabyte_db_tpu.ops import AggSpec
from yugabyte_db_tpu.ops.scan import GroupSpec, HashGroupSpec
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster


def run(coro):
    return asyncio.run(coro)


def _q1_hash_spec():
    # same group columns as TPCH_Q1 but with NO domain declaration
    return HashGroupSpec(cols=tuple(c for c, _, _ in TPCH_Q1.group.cols))


class TestKernelHashGroup:
    def test_q1_matches_reference_without_stats(self):
        data = generate_lineitem(0.002)
        table = LineitemTable(tempfile.mkdtemp(prefix="hg-"),
                              num_tablets=1)
        table.load(data)
        t = table.tablets[0]
        resp = t.read(ReadRequest(
            "lineitem", where=TPCH_Q1.where, aggregates=TPCH_Q1.aggs,
            group_by=_q1_hash_spec()))
        assert resp.backend == "tpu"
        assert resp.group_values is not None
        ref = numpy_reference(TPCH_Q1, data)
        counts = np.asarray(resp.group_counts)
        live = np.nonzero(counts)[0]
        assert len(live) == 6
        for g in live:
            rf = int(resp.group_values[0][g])
            ls = int(resp.group_values[1][g])
            want_qty, want_price, want_cnt = ref[rf + 3 * ls]
            assert int(counts[g]) == want_cnt
            assert abs(float(resp.agg_values[0][g]) - want_qty) < 1e-3
            rel = abs(float(resp.agg_values[1][g]) - want_price) / \
                max(want_price, 1e-9)
            assert rel < 1e-5

    def test_overflow_falls_back_to_cpu(self):
        data = generate_lineitem(0.002)
        table = LineitemTable(tempfile.mkdtemp(prefix="hgo-"),
                              num_tablets=1)
        table.load(data)
        t = table.tablets[0]
        # group by rowid: every row its own group — far past max_groups
        spec = HashGroupSpec(cols=(0,), max_groups=64)
        resp = t.read(ReadRequest(
            "lineitem", aggregates=(AggSpec("count"),), group_by=spec,
            limit=None))
        assert resp.backend == "cpu"
        assert len(np.asarray(resp.group_counts)) == len(data["rowid"])
        assert np.asarray(resp.group_counts).sum() == len(data["rowid"])

    def test_min_max_and_nulls(self):
        """NULL group keys are excluded; min/max aggregate correctly."""
        from yugabyte_db_tpu.docdb.operations import RowOp, WriteRequest
        from yugabyte_db_tpu.docdb.table_codec import TableInfo
        from yugabyte_db_tpu.dockv.packed_row import (
            ColumnSchema, ColumnType, TableSchema,
        )
        from yugabyte_db_tpu.dockv.partition import PartitionSchema
        from yugabyte_db_tpu.tablet import Tablet
        schema = TableSchema((
            ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
            ColumnSchema(1, "g", ColumnType.INT64),
            ColumnSchema(2, "v", ColumnType.FLOAT64),
        ), 1)
        info = TableInfo("t", "t", schema, PartitionSchema("hash", 1))
        t = Tablet("t", info, tempfile.mkdtemp(prefix="hgn-"))
        rows = []
        for i in range(5000):
            rows.append({"k": i, "g": None if i % 11 == 0 else i % 37,
                         "v": float(i)})
        t.apply_write(WriteRequest("t", [RowOp("upsert", r)
                                         for r in rows]))
        t.flush()
        resp = t.read(ReadRequest(
            "t", aggregates=(AggSpec("min", ("col", 2)),
                             AggSpec("max", ("col", 2)),
                             AggSpec("count")),
            group_by=HashGroupSpec(cols=(1,))))
        counts = np.asarray(resp.group_counts)
        live = np.nonzero(counts)[0]
        assert len(live) == 37
        # python reference
        ref = {}
        for r in rows:
            if r["g"] is None:
                continue
            st = ref.setdefault(r["g"], [np.inf, -np.inf, 0])
            st[0] = min(st[0], r["v"])
            st[1] = max(st[1], r["v"])
            st[2] += 1
        for g in live:
            gv = int(resp.group_values[0][g])
            assert float(resp.agg_values[0][g]) == ref[gv][0]
            assert float(resp.agg_values[1][g]) == ref[gv][1]
            assert int(counts[g]) == ref[gv][2]


class TestMinMaxNullParity:
    def test_all_null_group_min_is_null_on_both_paths(self):
        """MIN/MAX over a group whose aggregated column is entirely NULL
        must be SQL NULL on the device path AND the CPU path — not a
        dtype sentinel, not 0."""
        from yugabyte_db_tpu.docdb.operations import RowOp, WriteRequest
        from yugabyte_db_tpu.docdb.table_codec import TableInfo
        from yugabyte_db_tpu.dockv.packed_row import (
            ColumnSchema, ColumnType, TableSchema,
        )
        from yugabyte_db_tpu.dockv.partition import PartitionSchema
        from yugabyte_db_tpu.tablet import Tablet
        from yugabyte_db_tpu.utils import flags
        schema = TableSchema((
            ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True),
            ColumnSchema(1, "g", ColumnType.INT64),
            ColumnSchema(2, "v", ColumnType.FLOAT64),
        ), 1)
        info = TableInfo("t", "t", schema, PartitionSchema("hash", 1))
        t = Tablet("t", info, tempfile.mkdtemp(prefix="mmn-"))
        rows = [{"k": i, "g": i % 2,
                 "v": None if i % 2 == 0 else float(i)}
                for i in range(6000)]
        t.apply_write(WriteRequest("t", [RowOp("upsert", r)
                                         for r in rows]))
        t.flush()
        req = lambda: ReadRequest(  # noqa: E731
            "t", aggregates=(AggSpec("min", ("col", 2)),
                             AggSpec("count")),
            group_by=HashGroupSpec(cols=(1,)))
        dev = t.read(req())
        assert dev.backend == "tpu"
        flags.set_flag("tpu_pushdown_enabled", False)
        try:
            cpu = t.read(req())
        finally:
            flags.set_flag("tpu_pushdown_enabled", True)
        assert cpu.backend == "cpu"
        for resp in (dev, cpu):
            counts = np.asarray(resp.group_counts)
            by_g = {}
            for g in np.nonzero(counts)[0]:
                by_g[int(np.asarray(resp.group_values[0])[g])] = \
                    np.asarray(resp.agg_values[0], object)[g]
            assert by_g[0] is None, resp.backend   # all-NULL group
            assert float(by_g[1]) == 1.0, resp.backend


class TestSqlHashGroup:
    def test_group_by_without_analyze_pushes_down(self, tmp_path):
        async def go():
            from yugabyte_db_tpu.ql import SqlSession
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            s = SqlSession(mc.client())
            await s.execute("CREATE TABLE m (k bigint, g bigint, "
                            "v double, PRIMARY KEY (k))")
            vals = ", ".join(f"({i}, {i % 53}, {i * 0.5})"
                             for i in range(6000))
            await s.execute(f"INSERT INTO m (k, g, v) VALUES {vals}")
            # NO ANALYZE ran: must still push down (hash group)
            ex = await s.execute(
                "EXPLAIN SELECT g, sum(v), count(*) FROM m GROUP BY g")
            plan = " ".join(str(r) for r in ex.rows)
            assert "DEVICE pushdown: sort + segment" in plan
            res = await s.execute(
                "SELECT g, sum(v), count(*) FROM m GROUP BY g")
            assert len(res.rows) == 53
            by_g = {r["g"]: r for r in res.rows}
            want = {}
            for i in range(6000):
                st = want.setdefault(i % 53, [0.0, 0])
                st[0] += i * 0.5
                st[1] += 1
            for g, (sv, cnt) in want.items():
                assert by_g[g]["count"] == cnt
                assert abs(by_g[g]["sum_v"] - sv) < 1e-6
            await mc.shutdown()
        run(go())

    def test_multi_tablet_hash_group_combine(self, tmp_path):
        """Hash-group slots differ per tablet; the client must merge
        partials by group KEY."""
        async def go():
            from yugabyte_db_tpu.ql import SqlSession
            mc = await MiniCluster(str(tmp_path), num_tservers=2).start()
            s = SqlSession(mc.client())
            await s.execute("CREATE TABLE m2 (k bigint, g bigint, "
                            "v double, PRIMARY KEY (k)) WITH tablets = 4")
            vals = ", ".join(f"({i}, {i % 19}, 1.0)" for i in range(4000))
            await s.execute(f"INSERT INTO m2 (k, g, v) VALUES {vals}")
            res = await s.execute(
                "SELECT g, count(*), sum(v) FROM m2 GROUP BY g")
            assert len(res.rows) == 19
            for r in res.rows:
                g = r["g"]
                want = len([i for i in range(4000) if i % 19 == g])
                assert r["count"] == want
                assert abs(r["sum_v"] - want) < 1e-9
            await mc.shutdown()
        run(go())
