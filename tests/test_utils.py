import threading

import pytest

from yugabyte_db_tpu.utils import (
    HybridTime, DocHybridTime, HybridClock, LogicalClock, Status, StatusError,
    flags, metrics,
)
from yugabyte_db_tpu.utils.hybrid_time import MockPhysicalClock
from yugabyte_db_tpu.utils import status as st


class TestHybridTime:
    def test_components(self):
        ht = HybridTime.from_micros(123456, 7)
        assert ht.physical_micros == 123456
        assert ht.logical == 7

    def test_ordering(self):
        assert HybridTime.from_micros(1) < HybridTime.from_micros(2)
        assert HybridTime.from_micros(1, 1) > HybridTime.from_micros(1, 0)
        assert HybridTime.min() < HybridTime.max()

    def test_clock_monotonic(self):
        clock = HybridClock(MockPhysicalClock())
        samples = [clock.now() for _ in range(100)]
        assert samples == sorted(samples)
        assert len(set(samples)) == 100  # strictly increasing (logical bumps)

    def test_clock_update_ratchets(self):
        clock = HybridClock(MockPhysicalClock(1000))
        remote = HybridTime.from_micros(10_000_000)
        clock.update(remote)
        assert clock.now() > remote

    def test_clock_threadsafe_strictly_increasing(self):
        clock = HybridClock(MockPhysicalClock())
        out = []
        def worker():
            for _ in range(200):
                out.append(clock.now().value)
        ts = [threading.Thread(target=worker) for _ in range(4)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert len(set(out)) == len(out)

    def test_doc_ht_desc_encoding_orders(self):
        a = DocHybridTime(HybridTime.from_micros(100), 0)
        b = DocHybridTime(HybridTime.from_micros(200), 0)
        assert b.encoded_desc() < a.encoded_desc()
        assert DocHybridTime.decode_desc(a.encoded_desc()) == a

    def test_logical_clock(self):
        c = LogicalClock()
        a, b = c.now(), c.now()
        assert a < b


class TestStatus:
    def test_ok(self):
        assert Status.OK().ok()
        assert bool(Status.OK())

    def test_error_raises(self):
        s = st.not_found("missing tablet", tablet_id="t1")
        assert not s.ok()
        with pytest.raises(StatusError) as ei:
            s.raise_if_error()
        assert ei.value.code == st.Code.NOT_FOUND
        assert ei.value.status.payload["tablet_id"] == "t1"


class TestFlags:
    def test_runtime_flag_set(self):
        flags.set_flag("tpu_pushdown_enabled", False)
        assert flags.get("tpu_pushdown_enabled") is False
        flags.REGISTRY.reset("tpu_pushdown_enabled")
        assert flags.get("tpu_pushdown_enabled") is True

    def test_callback(self):
        seen = []
        f = flags.DEFINE_RUNTIME("test_cb_flag", 1)
        flags.REGISTRY.on_change("test_cb_flag", seen.append)
        flags.set_flag("test_cb_flag", 5)
        assert seen == [5]

    def test_auto_flag_promotion(self):
        af = flags.DEFINE_AUTO("test_auto", initial=False, target=True)
        assert af.value is False
        flags.promote_auto_flags()
        assert af.value is True


class TestMetrics:
    def test_counter_histogram_prometheus(self):
        reg = metrics.MetricRegistry()
        e = reg.entity("tablet", "tab-1", table_name="t")
        e.counter("rows_scanned").increment(10)
        h = e.histogram("read_latency_us")
        for v in (10, 100, 1000):
            h.increment(v)
        assert h.count() == 3
        assert h.percentile(50) >= 10
        text = reg.to_prometheus()
        assert "rows_scanned" in text and 'metric_id="tab-1"' in text
