"""Colocated tables / tablegroups tests (reference analog:
architecture/design/ysql-colocated-tables.md, ysql_tablegroup_manager)."""
import asyncio

import pytest

from yugabyte_db_tpu.docdb import ReadRequest
from yugabyte_db_tpu.docdb.table_codec import TableInfo
from yugabyte_db_tpu.dockv.packed_row import (
    ColumnSchema, ColumnType, TableSchema,
)
from yugabyte_db_tpu.dockv.partition import PartitionSchema
from yugabyte_db_tpu.ops import AggSpec, Expr
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster

C = Expr.col


def small_table(name, cols=("v",)):
    schema_cols = [ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True)]
    for i, c in enumerate(cols):
        schema_cols.append(ColumnSchema(i + 1, c, ColumnType.FLOAT64))
    return TableInfo("", name, TableSchema(tuple(schema_cols), 1),
                     PartitionSchema("hash", 1))


def run(coro):
    return asyncio.run(coro)


class TestColocation:
    def test_two_tables_one_tablet(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_tablegroup("g1")
                await c.create_table(small_table("t_a"), tablegroup="g1")
                await c.create_table(small_table("t_b"), tablegroup="g1")
                # both tables share ONE tablet on the tserver
                ts = mc.tservers[0]
                assert len(ts.peers) == 1
                peer = next(iter(ts.peers.values()))
                assert len(peer.tablet.tables()) == 3  # parent + 2
                await mc.wait_for_leaders("t_a")
                await c.insert("t_a", [{"k": i, "v": float(i)}
                                       for i in range(10)])
                await c.insert("t_b", [{"k": i, "v": float(i) * 100}
                                       for i in range(5)])
                # reads keep the tables separate (cotable key prefixes)
                assert (await c.get("t_a", {"k": 3}))["v"] == 3.0
                assert (await c.get("t_b", {"k": 3}))["v"] == 300.0
                ra = await c.scan("t_a", ReadRequest(
                    "", aggregates=(AggSpec("count"),)))
                rb = await c.scan("t_b", ReadRequest(
                    "", aggregates=(AggSpec("count"),)))
                assert int(ra.agg_values[0]) == 10
                assert int(rb.agg_values[0]) == 5
                # filtered scan doesn't leak across cotables
                rows = await c.scan("t_b", ReadRequest(
                    "", columns=("k",), where=(C(1) > 0.0).node))
                assert {r["k"] for r in rows.rows} == {1, 2, 3, 4}
            finally:
                await mc.shutdown()
        run(go())

    def test_colocated_survive_restart(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_tablegroup("g2")
                await c.create_table(small_table("ca"), tablegroup="g2")
                await mc.wait_for_leaders("ca")
                await c.insert("ca", [{"k": 1, "v": 7.0}])
                await mc.restart_tserver(0)
                await mc.wait_for_leaders("ca")
                c2 = mc.client()
                assert (await c2.get("ca", {"k": 1}))["v"] == 7.0
            finally:
                await mc.shutdown()
        run(go())


class TestColocatedDrop:
    def test_drop_one_table_keeps_the_group_tablet(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_tablegroup("g3")
                await c.create_table(small_table("d1"), tablegroup="g3")
                await c.create_table(small_table("d2"), tablegroup="g3")
                await mc.wait_for_leaders("d1")
                await c.insert("d1", [{"k": 1, "v": 1.0}])
                await c.insert("d2", [{"k": 1, "v": 2.0}])
                await c.drop_table("d1")
                # the shared tablet (and d2's data) survives
                assert (await c.get("d2", {"k": 1}))["v"] == 2.0
                names = {t["name"] for t in await c.list_tables()}
                assert "d1" not in names and "d2" in names
            finally:
                await mc.shutdown()
        run(go())
