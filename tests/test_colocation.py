"""Colocated tables / tablegroups tests (reference analog:
architecture/design/ysql-colocated-tables.md, ysql_tablegroup_manager)."""
import asyncio

import pytest

from yugabyte_db_tpu.docdb import ReadRequest
from yugabyte_db_tpu.docdb.table_codec import TableInfo
from yugabyte_db_tpu.dockv.packed_row import (
    ColumnSchema, ColumnType, TableSchema,
)
from yugabyte_db_tpu.dockv.partition import PartitionSchema
from yugabyte_db_tpu.ops import AggSpec, Expr
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster

C = Expr.col


def small_table(name, cols=("v",)):
    schema_cols = [ColumnSchema(0, "k", ColumnType.INT64, is_hash_key=True)]
    for i, c in enumerate(cols):
        schema_cols.append(ColumnSchema(i + 1, c, ColumnType.FLOAT64))
    return TableInfo("", name, TableSchema(tuple(schema_cols), 1),
                     PartitionSchema("hash", 1))


def run(coro):
    return asyncio.run(coro)


class TestColocation:
    def test_two_tables_one_tablet(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_tablegroup("g1")
                await c.create_table(small_table("t_a"), tablegroup="g1")
                await c.create_table(small_table("t_b"), tablegroup="g1")
                # both tables share ONE tablet on the tserver
                ts = mc.tservers[0]
                assert len(ts.peers) == 1
                peer = next(iter(ts.peers.values()))
                assert len(peer.tablet.tables()) == 3  # parent + 2
                await mc.wait_for_leaders("t_a")
                await c.insert("t_a", [{"k": i, "v": float(i)}
                                       for i in range(10)])
                await c.insert("t_b", [{"k": i, "v": float(i) * 100}
                                       for i in range(5)])
                # reads keep the tables separate (cotable key prefixes)
                assert (await c.get("t_a", {"k": 3}))["v"] == 3.0
                assert (await c.get("t_b", {"k": 3}))["v"] == 300.0
                ra = await c.scan("t_a", ReadRequest(
                    "", aggregates=(AggSpec("count"),)))
                rb = await c.scan("t_b", ReadRequest(
                    "", aggregates=(AggSpec("count"),)))
                assert int(ra.agg_values[0]) == 10
                assert int(rb.agg_values[0]) == 5
                # filtered scan doesn't leak across cotables
                rows = await c.scan("t_b", ReadRequest(
                    "", columns=("k",), where=(C(1) > 0.0).node))
                assert {r["k"] for r in rows.rows} == {1, 2, 3, 4}
            finally:
                await mc.shutdown()
        run(go())

    def test_colocated_survive_restart(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_tablegroup("g2")
                await c.create_table(small_table("ca"), tablegroup="g2")
                await mc.wait_for_leaders("ca")
                await c.insert("ca", [{"k": 1, "v": 7.0}])
                await mc.restart_tserver(0)
                await mc.wait_for_leaders("ca")
                c2 = mc.client()
                assert (await c2.get("ca", {"k": 1}))["v"] == 7.0
            finally:
                await mc.shutdown()
        run(go())


class TestColocatedDrop:
    def test_drop_one_table_keeps_the_group_tablet(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_tablegroup("g3")
                await c.create_table(small_table("d1"), tablegroup="g3")
                await c.create_table(small_table("d2"), tablegroup="g3")
                await mc.wait_for_leaders("d1")
                await c.insert("d1", [{"k": 1, "v": 1.0}])
                await c.insert("d2", [{"k": 1, "v": 2.0}])
                await c.drop_table("d1")
                # the shared tablet (and d2's data) survives
                assert (await c.get("d2", {"k": 1}))["v"] == 2.0
                names = {t["name"] for t in await c.list_tables()}
                assert "d1" not in names and "d2" in names
            finally:
                await mc.shutdown()
        run(go())


class TestColocatedRepack:
    def test_compaction_repacks_per_cotable_after_alter(self, tmp_path):
        """ALTER one colocated table, write mixed-version rows, compact:
        surviving rows re-encode with each cotable's LATEST packing and
        remain readable (old packings still load from schema_history)."""
        async def go():
            from yugabyte_db_tpu.dockv.packed_row import ColumnType
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_tablegroup("gr")
                await c.create_table(small_table("ra"), tablegroup="gr")
                await c.create_table(small_table("rb"), tablegroup="gr")
                await mc.wait_for_leaders("ra")
                await c.insert("ra", [{"k": i, "v": float(i)}
                                      for i in range(8)])
                await c.insert("rb", [{"k": 1, "v": 5.0}])
                # ALTER ra only -> ra rows are now old-version packed
                await c.alter_table_add_columns(
                    "ra", [("extra", ColumnType.FLOAT64)])
                await c.insert("ra", [{"k": 100, "v": 1.0, "extra": 2.0}])
                peer = next(p for ts in mc.tservers
                            for p in ts.peers.values())
                peer.tablet.flush()
                peer.tablet.compact(major=True)
                # all rows readable post-repack; new column works
                for i in range(8):
                    row = await c.get("ra", {"k": i})
                    assert row["v"] == float(i) and row["extra"] is None
                assert (await c.get("ra", {"k": 100}))["extra"] == 2.0
                assert (await c.get("rb", {"k": 1}))["v"] == 5.0
                # rows actually repacked to the latest version
                codec = peer.tablet.codecs[
                    next(t for t, cd in peer.tablet.codecs.items()
                         if cd.info.name == "ra")]
                latest = codec.info.schema.version
                from yugabyte_db_tpu.dockv.value import ValueKind, unwrap_ttl
                seen = 0
                for k, v in peer.tablet.regular.iterate():
                    inner, _ = unwrap_ttl(v)
                    if inner and inner[0] == ValueKind.kPackedRowV2 and \
                            k.startswith(codec.scan_prefix()):
                        assert codec.info.packings.version_of(
                            inner, 1) == latest
                        seen += 1
                assert seen >= 9
            finally:
                await mc.shutdown()
        run(go())

    def test_truncate_one_colocated_table(self, tmp_path):
        """Colocated TRUNCATE tombstones only the target cotable's key
        range — the sibling table in the same tablet keeps its rows;
        replayed deterministically (the statement ht rides the WAL
        entry)."""
        async def go():
            from yugabyte_db_tpu.docdb import ReadRequest
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            try:
                c = mc.client()
                await c.create_tablegroup("gt")
                await c.create_table(small_table("ct_a"),
                                     tablegroup="gt")
                await c.create_table(small_table("ct_b"),
                                     tablegroup="gt")
                await mc.wait_for_leaders("ct_a")
                await c.insert("ct_a", [{"k": i, "v": 1.0}
                                        for i in range(20)])
                await c.insert("ct_b", [{"k": i, "v": 2.0}
                                        for i in range(10)])
                await c.truncate_table("ct_a")
                a = (await c.scan("ct_a", ReadRequest(""))).rows
                b = (await c.scan("ct_b", ReadRequest(""))).rows
                assert a == []
                assert len(b) == 10
                # post-truncate inserts land and survive restart replay
                await c.insert("ct_a", [{"k": 100, "v": 3.0}])
                await mc.restart_tserver(0)
                await mc.wait_for_leaders("ct_a")
                a = (await c.scan("ct_a", ReadRequest(""))).rows
                b = (await c.scan("ct_b", ReadRequest(""))).rows
                assert [(r["k"], r["v"]) for r in a] == [(100, 3.0)]
                assert len(b) == 10
            finally:
                await mc.shutdown()
        run(go())
