"""SUM accumulation soundness at scale (the round-2 bench failure class).

Round 2's bench crashed because grouped device SUM ran through an f32
one-hot matmul: past 2^24 a float32 accumulator cannot even represent
the exact total, and MXU accumulation order made the drift
device-dependent. The fix (ops/scan.py): exact int64 accumulation —
integer-valued columns sum exactly end-to-end; float values quantize to
int64 fixed point with a deterministic per-batch scale. These tests run
the Q1 shape at 2M+ rows with group sums far beyond 2^24 on the
TPU-representative f32 device dtype, so scale-dependent precision can
never again pass tests but fail the bench.

Reference semantics being matched: exact PG numeric aggregation in
EvalAggregate (src/yb/docdb/pgsql_operation.cc:3153).
"""
import numpy as np
import pytest

from yugabyte_db_tpu.ops import AggSpec, Expr, ScanKernel
from yugabyte_db_tpu.ops.device_batch import build_batch
from yugabyte_db_tpu.ops.scan import GroupSpec, HashGroupSpec
from yugabyte_db_tpu.storage.columnar import ColumnarBlock
from yugabyte_db_tpu.utils import flags

C = Expr.col
N = 2_000_000
QTY, PRICE, FLAG = 1, 2, 3


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    return {
        # integer-valued, per-group sums ~5.6e7 >> 2^24 (=1.7e7)
        "qty": rng.integers(1, 200, N).astype(np.float64),
        "price": rng.uniform(900.0, 105000.0, N),
        "flag": rng.integers(0, 3, N).astype(np.int32),
    }


def _block(d):
    n = len(d["qty"])
    return ColumnarBlock.from_arrays(
        schema_version=1,
        key_hash=np.arange(n, dtype=np.uint64),
        ht=np.full(n, 10, np.uint64),
        fixed={
            QTY: (d["qty"], np.zeros(n, bool)),
            PRICE: (d["price"], np.zeros(n, bool)),
            FLAG: (d["flag"], np.zeros(n, bool)),
        },
        tombstone=np.zeros(n, bool), unique_keys=True)


@pytest.fixture(scope="module")
def f32_batch(data):
    # force the TPU-representative device dtype on the CPU test backend
    flags.set_flag("device_float_dtype", "float32")
    try:
        yield build_batch([_block(data)], [QTY, PRICE, FLAG])
    finally:
        flags.set_flag("device_float_dtype", "auto")


AGGS = (AggSpec("sum", C(QTY).node), AggSpec("sum", C(PRICE).node),
        AggSpec("count"))


def test_integral_column_ships_as_exact_int(f32_batch):
    assert f32_batch.cols[QTY].dtype == np.int32      # integer-valued f64
    assert f32_batch.cols[PRICE].dtype == np.float32  # fractional f64


def test_grouped_sum_exact_past_2p24(data, f32_batch):
    outs, counts, _ = ScanKernel().run(
        f32_batch, None, AGGS, GroupSpec(cols=((FLAG, 3, 0),)))
    for g in range(3):
        m = data["flag"] == g
        want_qty = data["qty"][m].sum()       # exact in f64 (< 2^53)
        assert want_qty > 2 ** 24             # the round-2 failure regime
        # integer-valued column: EXACT, no tolerance at all
        assert float(outs[0][g]) == want_qty
        # fractional column: only per-row f32 representation error
        # (<= 2^-24 rel/row, all-positive => ~1.2e-7 on the sum) plus
        # <= 1e-12 quantization; 1e-5 keeps two orders of margin
        want_price = data["price"][m].sum()
        assert abs(float(outs[1][g]) - want_price) <= 1e-5 * want_price
        assert int(outs[2][g]) == int(counts[g]) == int(m.sum())


def test_ungrouped_sum_exact(data, f32_batch):
    outs, cnt, _ = ScanKernel().run(f32_batch, None, AGGS, None)
    assert float(outs[0]) == data["qty"].sum()
    want = data["price"].sum()
    assert abs(float(outs[1]) - want) <= 1e-5 * want
    assert int(cnt) == N


def test_hash_grouped_sum_exact(data, f32_batch):
    outs, counts, _, gvals, n_groups = ScanKernel().run(
        f32_batch, None, AGGS, HashGroupSpec(cols=(FLAG,)))
    assert int(n_groups) == 3
    order = np.argsort(np.asarray(gvals[0])[:3])
    for slot, g in zip(order, sorted(np.unique(data["flag"]))):
        m = data["flag"] == g
        assert float(outs[0][slot]) == data["qty"][m].sum()
        want = data["price"][m].sum()
        assert abs(float(outs[1][slot]) - want) <= 1e-5 * want
        assert int(counts[slot]) == int(m.sum())


def test_distributed_psum_matches_numpy(data):
    """8-shard psum combine: int64 partials with a pmax-agreed scale
    must land within the same bounds as the single-batch path — and the
    integer column must be EXACT across the mesh."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    from yugabyte_db_tpu.parallel.distributed_scan import (
        build_sharded_batch, distributed_scan_aggregate,
    )
    from yugabyte_db_tpu.parallel.mesh import tablet_mesh
    flags.set_flag("device_float_dtype", "float32")
    try:
        tm = tablet_mesh(num_tablet_shards=8)
        bounds = np.linspace(0, N, 9).astype(int)
        shards = []
        for i in range(8):
            sl = slice(bounds[i], bounds[i + 1])
            shards.append([_block({k: v[sl] for k, v in data.items()})])
        sbatch = build_sharded_batch(tm, shards, [QTY, PRICE, FLAG])
        outs, counts = distributed_scan_aggregate(
            sbatch, None, AGGS, GroupSpec(cols=((FLAG, 3, 0),)))
    finally:
        flags.set_flag("device_float_dtype", "auto")
    for g in range(3):
        m = data["flag"] == g
        assert float(outs[0][g]) == data["qty"][m].sum()
        want = data["price"][m].sum()
        assert abs(float(outs[1][g]) - want) <= 1e-5 * want
        assert int(counts[g]) == int(m.sum())


def test_degenerate_magnitudes_fall_back_to_float(data):
    """|v| past the quantizable range and Inf/NaN inputs use the plain
    float fallback lane instead of returning a garbage finite value."""
    from yugabyte_db_tpu.ops.scan import GroupSpec as GS
    d = {
        "qty": np.array([1e60, 2.5, 1.0, 3.0]),
        "price": np.array([1.0, 2.0, np.inf, 4.0]),
        "flag": np.array([0, 0, 1, 1], np.int32),
    }
    batch = build_batch([_block(d)], [QTY, PRICE, FLAG])
    outs, cnt, _ = ScanKernel().run(batch, None, AGGS, None)
    # 1e60 stays on the (widened) f64 quantized path: error bounded by
    # per-row quantization <= n_padded/2^63 ~ 4.4e-16 relative — NOT the
    # garbage finite value the clipped scale used to produce
    assert abs(float(outs[0]) - 1e60) <= 1e-12 * 1e60
    assert np.isinf(float(outs[1]))                    # Inf propagates
    assert int(cnt) == 4
    outs, counts, _ = ScanKernel().run(
        batch, None, AGGS, GS(cols=((FLAG, 2, 0),)))
    assert abs(float(outs[0][0]) - 1e60) <= 1e-12 * 1e60
    assert float(outs[0][1]) == 4.0
    assert np.isinf(float(outs[1][1]))
    assert float(outs[1][0]) == 3.0


def test_int_arithmetic_does_not_wrap():
    """Integer-valued f64 columns ship as int32; products past 2^31
    must widen to int64 instead of wrapping (expr compiler promotion)."""
    d = {
        "qty": np.full(8, 100000.0),       # integral -> int32 on device
        "price": np.full(8, 100000.0),
        "flag": np.zeros(8, np.int32),
    }
    batch = build_batch([_block(d)], [QTY, PRICE, FLAG])
    assert batch.cols[QTY].dtype == np.int32
    aggs = (AggSpec("sum", (C(QTY) * C(QTY)).node),)
    outs, cnt, _ = ScanKernel().run(batch, None, aggs, None)
    assert int(outs[0]) == 8 * 100000 * 100000     # 8e10 >> 2^31
