"""Multi-column hybrid skip-scan (reference: docdb/scan_choices.cc +
hybrid_scan_choices.cc): =/IN target sets on leading range-PK columns
enumerate into seek segments instead of a full scan, an interval on the
following column bounds each segment, and segment order preserves
encoded-pk order so ORDER BY + LIMIT stay pushdown-compatible."""
import asyncio

from yugabyte_db_tpu.docdb.operations import (
    ReadRequest, extract_scan_options,
)
from yugabyte_db_tpu.dockv.packed_row import (
    ColumnSchema, ColumnType, TableSchema,
)


def run(coro):
    return asyncio.run(coro)


def _schema():
    return TableSchema(columns=(
        ColumnSchema(0, "r1", ColumnType.INT64, is_range_key=True),
        ColumnSchema(1, "r2", ColumnType.INT64, is_range_key=True),
        ColumnSchema(2, "v", ColumnType.FLOAT64),
    ), version=1)


class TestExtractScanOptions:
    def test_in_plus_range(self):
        sch = _schema()
        where = ("and",
                 ("in", ("col", 0), [5, 1, 9]),
                 ("and",
                  ("cmp", "ge", ("col", 1), ("const", 10)),
                  ("cmp", "lt", ("col", 1), ("const", 20))))
        points, interval, residual = extract_scan_options(
            where, list(sch.key_columns))
        assert [(c.id, vals) for c, vals in points] == [(0, [1, 5, 9])]
        assert interval is not None
        c, lo, hi = interval
        assert (c.id, lo, hi) == (1, 10, 19)
        assert residual is None

    def test_eq_chain_consumed(self):
        sch = _schema()
        where = ("and",
                 ("cmp", "eq", ("col", 0), ("const", 7)),
                 ("cmp", "eq", ("col", 1), ("const", 3)))
        points, interval, residual = extract_scan_options(
            where, list(sch.key_columns))
        assert [(c.id, vals) for c, vals in points] == [(0, [7]),
                                                        (1, [3])]
        assert interval is None and residual is None

    def test_non_pk_conjunct_stays_residual(self):
        sch = _schema()
        where = ("and",
                 ("in", ("col", 0), [2, 4]),
                 ("cmp", "gt", ("col", 2), ("const", 0.5)))
        points, interval, residual = extract_scan_options(
            where, list(sch.key_columns))
        assert [(c.id, vals) for c, vals in points] == [(0, [2, 4])]
        assert residual == ("cmp", "gt", ("col", 2), ("const", 0.5))

    def test_contradictory_points_empty(self):
        sch = _schema()
        where = ("and",
                 ("cmp", "eq", ("col", 0), ("const", 1)),
                 ("cmp", "eq", ("col", 0), ("const", 2)))
        points, interval, residual = extract_scan_options(
            where, list(sch.key_columns))
        assert points[0][1] == []


class TestSkipScanSql:
    """End-to-end through SQL on a range-sharded two-column pk table."""

    def test_skip_scan_correctness_and_order(self, tmp_path):
        from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
        from yugabyte_db_tpu.ql.executor import SqlSession

        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute(
                    "CREATE TABLE sk (r1 bigint, r2 bigint, v double, "
                    "PRIMARY KEY (r1 ASC, r2 ASC)) WITH tablets = 1")
                await mc.wait_for_leaders("sk")
                rows = [(a, b, a * 100.0 + b)
                        for a in range(8) for b in range(20)]
                await s.execute(
                    "INSERT INTO sk (r1, r2, v) VALUES "
                    + ", ".join(f"({a}, {b}, {v})" for a, b, v in rows))
                r = await s.execute(
                    "SELECT r1, r2 FROM sk WHERE r1 IN (1, 5, 3) "
                    "AND r2 >= 15 AND r2 < 18 ORDER BY r1, r2")
                got = [(x["r1"], x["r2"]) for x in r.rows]
                want = [(a, b) for a in (1, 3, 5) for b in (15, 16, 17)]
                assert got == want, got
                # ORDER BY + LIMIT rides the ordered segments
                r = await s.execute(
                    "SELECT r1, r2 FROM sk WHERE r1 IN (5, 1) "
                    "AND r2 = 3 ORDER BY r1, r2 LIMIT 1")
                assert [(x["r1"], x["r2"]) for x in r.rows] == [(1, 3)]
                # residual predicates still filter
                r = await s.execute(
                    "SELECT r2 FROM sk WHERE r1 = 2 AND r2 > 16 "
                    "AND v > 203.0 ORDER BY r2")
                assert [x["r2"] for x in r.rows] == [17, 18, 19]
                # empty target set
                r = await s.execute(
                    "SELECT r1 FROM sk WHERE r1 = 1 AND r1 = 2")
                assert r.rows == []
            finally:
                await mc.shutdown()
        run(go())

    def test_segments_actually_bound_iteration(self, tmp_path):
        """The skip scan must touch only the targeted key ranges: count
        store iterations via a wrapped iterate()."""
        from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
        from yugabyte_db_tpu.ql.executor import SqlSession

        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute(
                    "CREATE TABLE sk2 (r1 bigint, r2 bigint, v double, "
                    "PRIMARY KEY (r1 ASC, r2 ASC)) WITH tablets = 1")
                await mc.wait_for_leaders("sk2")
                await s.execute(
                    "INSERT INTO sk2 (r1, r2, v) VALUES "
                    + ", ".join(f"({a}, {b}, 1.0)"
                                for a in range(50) for b in range(10)))
                ts = mc.tservers[0]
                peer = next(p for tid, p in ts.peers.items()
                            if p.coordinator is None)
                store = peer.tablet.regular
                seen = 0
                orig = store.iterate

                def counting(*a, **kw):
                    nonlocal seen
                    for kv in orig(*a, **kw):
                        seen += 1
                        yield kv
                store.iterate = counting
                try:
                    r = await s.execute(
                        "SELECT r1, r2 FROM sk2 WHERE r1 IN (7, 31) "
                        "ORDER BY r1, r2")
                    assert len(r.rows) == 20
                    # 500 rows total; two 10-row segments must not
                    # scan the whole table
                    assert seen <= 2 * 10 + 4, seen
                finally:
                    store.iterate = orig
            finally:
                await mc.shutdown()
        run(go())


class TestNonIntegralConstants:
    """Fractional constants against integer range-PK columns must not
    be truncated into wrong bounds (review finding): k = 4.5 matches
    nothing, k >= 4.5 means k >= 5, k < 5.5 means k <= 5."""

    def test_bounds_round_to_safe_side(self):
        sch = _schema()
        pts, interval, res = extract_scan_options(
            ("cmp", "eq", ("col", 0), ("const", 4.5)),
            list(sch.key_columns))
        assert pts and pts[0][1] == []      # provably false
        pts, interval, res = extract_scan_options(
            ("cmp", "ge", ("col", 0), ("const", 4.5)),
            list(sch.key_columns))
        assert interval == (sch.key_columns[0], 5, None)
        pts, interval, res = extract_scan_options(
            ("cmp", "lt", ("col", 0), ("const", 5.5)),
            list(sch.key_columns))
        assert interval == (sch.key_columns[0], None, 5)
        pts, interval, res = extract_scan_options(
            ("in", ("col", 0), [4, 4.5]),
            list(sch.key_columns))
        assert pts and pts[0][1] == [4]
        # a non-numeric constant cannot be consumed: stays residual
        node = ("cmp", "eq", ("col", 0), ("const", "x"))
        pts, interval, res = extract_scan_options(
            node, list(sch.key_columns))
        assert not pts and interval is None and res == node

    def test_sql_fractional_pk_predicates(self, tmp_path):
        from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
        from yugabyte_db_tpu.ql.executor import SqlSession

        async def go():
            mc = await MiniCluster(str(tmp_path),
                                   num_tservers=1).start()
            try:
                s = SqlSession(mc.client())
                await s.execute(
                    "CREATE TABLE fr (k bigint, v double, "
                    "PRIMARY KEY (k ASC)) WITH tablets = 1")
                await mc.wait_for_leaders("fr")
                await s.execute("INSERT INTO fr (k, v) VALUES "
                                "(4, 4.0), (5, 5.0), (6, 6.0)")
                r = await s.execute("SELECT k FROM fr WHERE k = 4.5")
                assert r.rows == [], r.rows
                r = await s.execute(
                    "SELECT k FROM fr WHERE k >= 4.5 ORDER BY k")
                assert [x["k"] for x in r.rows] == [5, 6]
                r = await s.execute(
                    "SELECT k FROM fr WHERE k < 5.5 ORDER BY k")
                assert [x["k"] for x in r.rows] == [4, 5]
                r = await s.execute(
                    "SELECT k FROM fr WHERE k IN (4, 4.5) ORDER BY k")
                assert [x["k"] for x in r.rows] == [4]
            finally:
                await mc.shutdown()
        run(go())


class TestHashEnumeratedScan:
    """Short ranges / IN lists over a single-int-hash-PK table rewrite
    to batched point gets (reference: point segments,
    docdb/hybrid_scan_choices.cc) — results must match the full-scan
    path exactly."""

    def _tablet(self, tmp_path):
        from yugabyte_db_tpu.tablet import Tablet
        from tests.test_tablet import make_info
        from yugabyte_db_tpu.docdb import RowOp, WriteRequest
        t = Tablet("hes", make_info(), str(tmp_path))
        t.apply_write(WriteRequest("t1", [
            RowOp("upsert", {"k": i, "v": float(i), "s": f"s{i}"})
            for i in range(200)]))
        t.apply_write(WriteRequest("t1", [RowOp("delete", {"k": 50})]))
        return t

    def _both(self, t, where, **kw):
        from yugabyte_db_tpu.docdb import ReadRequest
        from yugabyte_db_tpu.utils import flags
        fast = t.read(ReadRequest("t1", where=where, **kw)).rows
        flags.set_flag("hash_scan_enumerate_max", 0)   # force full scan
        try:
            slow = t.read(ReadRequest("t1", where=where, **kw)).rows
        finally:
            flags.REGISTRY.reset("hash_scan_enumerate_max")
        return fast, slow

    def test_between_matches_full_scan(self, tmp_path):
        t = self._tablet(tmp_path)
        w = ("between", ("col", 0), ("const", 45), ("const", 55))
        fast, slow = self._both(t, w)
        assert sorted(r["k"] for r in fast) == sorted(
            r["k"] for r in slow) == [45, 46, 47, 48, 49, 51, 52, 53,
                                      54, 55]   # 50 deleted

    def test_in_list_and_residual(self, tmp_path):
        t = self._tablet(tmp_path)
        w = ("and", ("in", ("col", 0), [3, 7, 9, 999]),
             ("cmp", "gt", ("col", 1), ("const", 5.0)))
        fast, slow = self._both(t, w)
        assert sorted(r["k"] for r in fast) == sorted(
            r["k"] for r in slow) == [7, 9]

    def test_limit_applies_after_filter(self, tmp_path):
        from yugabyte_db_tpu.docdb import ReadRequest
        t = self._tablet(tmp_path)
        w = ("and", ("between", ("col", 0), ("const", 0),
                     ("const", 30)),
             ("cmp", "ge", ("col", 1), ("const", 10.0)))
        rows = t.read(ReadRequest("t1", where=w, limit=5)).rows
        assert [r["k"] for r in rows] == [10, 11, 12, 13, 14]

    def test_open_ranges_stay_on_scan_path(self, tmp_path):
        t = self._tablet(tmp_path)
        w = ("cmp", "ge", ("col", 0), ("const", 190))
        fast, slow = self._both(t, w)
        assert sorted(r["k"] for r in fast) == sorted(
            r["k"] for r in slow) == list(range(190, 200))
