"""pg_regress-style harness: .sql scripts under tests/regress/ run
through the SQL session; the formatted output must match the committed
.out file exactly (reference: src/postgres/src/test/regress — schedule
of sql/ scripts diffed against expected/)."""
import asyncio
import os

import pytest

from yugabyte_db_tpu.ql.executor import SqlSession
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster

REGRESS_DIR = os.path.join(os.path.dirname(__file__), "regress")


def _fmt_value(v):
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "t" if v else "f"
    if isinstance(v, float):
        return format(v, ".10g")
    return str(v)


def _fmt_result(res) -> str:
    """Deterministic text form of one statement's result."""
    if not res.rows:
        return res.status
    cols = list(res.rows[0].keys())
    lines = [" | ".join(cols)]
    for r in res.rows:
        lines.append(" | ".join(_fmt_value(r.get(c)) for c in cols))
    return "\n".join(lines)


async def _run_script(path: str) -> str:
    import tempfile
    mc = await MiniCluster(tempfile.mkdtemp(prefix="regress-"),
                           num_tservers=1).start()
    try:
        sess = SqlSession(mc.client())
        out = []
        with open(path) as f:
            sql = f.read()
        # statements separated by lines of ';' terminated statements —
        # reuse the session's script splitter by executing the whole
        # file; errors print as ERROR: <first line> like pg_regress
        from yugabyte_db_tpu.ql.parser import parse_script
        from yugabyte_db_tpu.ql.pg_server import PgServer
        for stmt_sql in PgServer._split_statements(sql):
            stmt_sql = "\n".join(
                ln for ln in stmt_sql.splitlines()
                if not ln.strip().startswith("--"))
            if not stmt_sql.strip():
                continue
            out.append(f"-- {' '.join(stmt_sql.split())}")
            try:
                res = await sess.execute(stmt_sql)
                out.append(_fmt_result(res))
            except Exception as e:   # noqa: BLE001 — regress records errors
                msg = (str(e).splitlines() or [type(e).__name__])[0]
                out.append(f"ERROR: {msg}")
            out.append("")
        return "\n".join(out).rstrip() + "\n"
    finally:
        await mc.shutdown()


def _cases():
    if not os.path.isdir(REGRESS_DIR):
        return []
    return sorted(f[:-4] for f in os.listdir(REGRESS_DIR)
                  if f.endswith(".sql"))


@pytest.mark.parametrize("case", _cases())
def test_regress(case):
    sql_path = os.path.join(REGRESS_DIR, case + ".sql")
    out_path = os.path.join(REGRESS_DIR, case + ".out")
    got = asyncio.run(_run_script(sql_path))
    if os.environ.get("REGRESS_REGEN") == "1":
        with open(out_path, "w") as f:
            f.write(got)
        return
    with open(out_path) as f:
        want = f.read()
    assert got == want, (
        f"regress diff for {case}:\n"
        + "\n".join(_diff_lines(want, got)))


def _diff_lines(want: str, got: str):
    import difflib
    return list(difflib.unified_diff(
        want.splitlines(), got.splitlines(),
        fromfile="expected", tofile="actual", lineterm=""))[:40]
