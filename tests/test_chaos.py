"""Mixed-workload chaos: concurrent writes + txn transfers while the
cluster splits, moves replicas, snapshots, compacts, and restarts a
tserver (reference analog: tablet-split-itest.cc with workload +
ts-itest restarts). Invariants: no acked write lost, bank total
conserved."""
import asyncio
import random

import pytest

from yugabyte_db_tpu.docdb import ReadRequest
from yugabyte_db_tpu.docdb.table_codec import TableInfo
from yugabyte_db_tpu.dockv.packed_row import (
    ColumnSchema, ColumnType, TableSchema,
)
from yugabyte_db_tpu.dockv.partition import PartitionSchema
from yugabyte_db_tpu.ops import AggSpec
from yugabyte_db_tpu.rpc import RpcError
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
from tests.test_ops_features import kv_info, run

# transient faults the workload must RIDE THROUGH (client retry
# exhaustion surfaces TimeoutError/OSError, not just RpcError)
_TRANSIENT = (RpcError, asyncio.TimeoutError, OSError, RuntimeError)


class TestChaos:
    def test_workload_survives_ops_storm(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=2).start()
            try:
                c = mc.client()
                await c.create_table(kv_info("wl"), num_tablets=2)
                await c.create_table(kv_info("bank"), num_tablets=2)
                for t in ("wl", "bank"):
                    await mc.wait_for_leaders(t)
                await c.insert("bank", [{"k": i, "v": 100.0}
                                        for i in range(8)])
                await c._master_call("get_status_tablet", {})
                await mc.wait_for_leaders("system.transactions")

                acked = set()
                stop = asyncio.Event()

                async def writer(wid):
                    i = 0
                    while not stop.is_set():
                        k = wid * 100000 + i
                        try:
                            await c.insert("wl", [{"k": k,
                                                   "v": float(k)}])
                            acked.add(k)
                        except _TRANSIENT:
                            pass      # retried ops may fail mid-move
                        i += 1
                        await asyncio.sleep(0.002)

                async def transferer(seed):
                    rng = random.Random(seed)
                    while not stop.is_set():
                        a, b = rng.sample(range(8), 2)
                        t = None
                        try:
                            t = await c.transaction().begin()
                            ra = await t.get("bank", {"k": a})
                            rb = await t.get("bank", {"k": b})
                            amt = rng.uniform(0, 10)
                            await t.insert("bank", [
                                {"k": a, "v": ra["v"] - amt},
                                {"k": b, "v": rb["v"] + amt}])
                            await t.commit()
                        except _TRANSIENT:
                            if t is not None and t.state == "PENDING":
                                try:
                                    await t.abort()
                                except _TRANSIENT:
                                    pass
                        await asyncio.sleep(0.01)

                workers = [asyncio.create_task(writer(w))
                           for w in range(3)]
                workers += [asyncio.create_task(transferer(s))
                            for s in range(2)]

                async def ops_storm():
                    ct = await c._table("wl")
                    parent = ct.locations[0].tablet_id
                    await c._master_call("split_tablet",
                                         {"tablet_id": parent},
                                         timeout=60.0)
                    await asyncio.sleep(0.5)
                    for ts in mc.tservers:
                        for p in list(ts.peers.values()):
                            p.tablet.flush()
                    snap = await c._master_call(
                        "create_snapshot", {"table": "bank"},
                        timeout=60.0)
                    assert snap["snapshot_id"]
                    # move one wl replica to the other tserver
                    ct = await c._table("wl", refresh=True)
                    loc = ct.locations[0]
                    src = loc.replicas[0][0]
                    dst = next(t.uuid for t in mc.tservers
                               if t.uuid != src)
                    try:
                        await c._master_call(
                            "move_replica",
                            {"tablet_id": loc.tablet_id,
                             "from": src, "to": dst}, timeout=60.0)
                    except RpcError:
                        pass          # moves may legitimately collide
                    await asyncio.sleep(0.5)
                    for ts in mc.tservers:
                        for p in list(ts.peers.values()):
                            if p.is_leader():
                                await asyncio.get_running_loop() \
                                    .run_in_executor(
                                        None,
                                        lambda p=p: p.tablet.compact(
                                            major=False))

                await ops_storm()
                await asyncio.sleep(1.0)
                stop.set()
                results = await asyncio.gather(*workers,
                                               return_exceptions=True)
                unexpected = [r for r in results
                              if isinstance(r, BaseException)]
                assert not unexpected, unexpected   # no worker died
                # the workload must have actually run
                assert len(acked) > 50, len(acked)

                # restart a tserver mid-state, then verify
                await mc.restart_tserver(0)
                for t in ("wl", "bank"):
                    await mc.wait_for_leaders(t)
                c2 = mc.client()
                # every acked write is readable
                rng = random.Random(7)
                sample = (rng.sample(sorted(acked), 50)
                          if len(acked) > 50 else sorted(acked))
                for k in sample:
                    row = await c2.get("wl", {"k": k})
                    assert row is not None and row["v"] == float(k), k
                agg = await c2.scan("wl", ReadRequest(
                    "", aggregates=(AggSpec("count"),)))
                assert int(agg.agg_values[0]) >= len(acked)
                # bank conservation
                await asyncio.sleep(0.5)
                total = 0.0
                for i in range(8):
                    total += (await c2.get("bank", {"k": i}))["v"]
                assert abs(total - 800.0) < 1e-6, total
            finally:
                await mc.shutdown()
        run(go())
