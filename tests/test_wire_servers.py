"""CQL and Redis wire-protocol server tests using raw socket clients
(reference analog: cql/redis server tests under
src/yb/yql/cql/cqlserver and integration-tests)."""
import asyncio
import struct

import pytest

from yugabyte_db_tpu.ql.cql_server import CqlServer
from yugabyte_db_tpu.ql.redis_server import RedisServer
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster


def run(coro):
    return asyncio.run(coro)


async def cql_frame(writer, reader, opcode, body=b"", stream=1):
    writer.write(struct.pack(">BBhBI", 0x04, 0, stream, opcode, len(body))
                 + body)
    await writer.drain()
    hdr = await reader.readexactly(9)
    _, _, rstream, ropcode = struct.unpack(">BBhB", hdr[:5])
    (ln,) = struct.unpack(">I", hdr[5:9])
    rbody = await reader.readexactly(ln) if ln else b""
    return ropcode, rbody


def longstr(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">i", len(b)) + b + struct.pack(">BH", 0, 0)


class TestCqlServer:
    def test_startup_query_rows(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            srv = CqlServer(mc.client())
            addr = await srv.start()
            try:
                reader, writer = await asyncio.open_connection(*addr)
                op, _ = await cql_frame(writer, reader, 0x01,
                                        struct.pack(">H", 0))   # STARTUP
                assert op == 0x02   # READY
                op, _ = await cql_frame(
                    writer, reader, 0x07,
                    longstr("CREATE TABLE t (k bigint, v double, "
                            "PRIMARY KEY (k))"))
                assert op == 0x08
                await mc.wait_for_leaders("t")
                op, _ = await cql_frame(
                    writer, reader, 0x07,
                    longstr("INSERT INTO t (k, v) VALUES (1, 2.5), (2, 5.0)"))
                assert op == 0x08
                op, body = await cql_frame(
                    writer, reader, 0x07,
                    longstr("SELECT k, v FROM t WHERE k = 2"))
                assert op == 0x08
                (kind,) = struct.unpack(">i", body[:4])
                assert kind == 2    # Rows
                # decode: flags, colcount
                flags, ncols = struct.unpack(">ii", body[4:12])
                assert ncols == 2
                writer.close()
            finally:
                await srv.shutdown()
                await mc.shutdown()
        run(go())

    def test_error_frame_on_bad_sql(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            srv = CqlServer(mc.client())
            addr = await srv.start()
            try:
                reader, writer = await asyncio.open_connection(*addr)
                await cql_frame(writer, reader, 0x01, struct.pack(">H", 0))
                op, body = await cql_frame(writer, reader, 0x07,
                                           longstr("BOGUS STATEMENT"))
                assert op == 0x00   # ERROR
                writer.close()
            finally:
                await srv.shutdown()
                await mc.shutdown()
        run(go())


class TestCqlSystemSchema:
    def test_driver_metadata_discovery(self, tmp_path):
        """system_schema.keyspaces/tables/columns reflect the live
        catalog (reference: yql_*_vtable.cc virtual tables)."""
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            srv = CqlServer(mc.client())
            addr = await srv.start()
            try:
                reader, writer = await asyncio.open_connection(*addr)
                await cql_frame(writer, reader, 0x01, struct.pack(">H", 0))
                await cql_frame(
                    writer, reader, 0x07,
                    longstr("CREATE TABLE md (k bigint, v double, "
                            "PRIMARY KEY (k))"))
                await mc.wait_for_leaders("md")
                op, body = await cql_frame(
                    writer, reader, 0x07,
                    longstr("SELECT * FROM system_schema.tables"))
                assert op == 0x08 and b"md" in body
                op, body = await cql_frame(
                    writer, reader, 0x07,
                    longstr("SELECT * FROM system_schema.columns"))
                assert op == 0x08
                assert b"partition_key" in body and b"bigint" in body \
                    and b"double" in body
                op, body = await cql_frame(
                    writer, reader, 0x07,
                    longstr("SELECT * FROM system_schema.keyspaces"))
                assert op == 0x08 and b"ybtpu" in body
                writer.close()
            finally:
                await srv.shutdown()
                await mc.shutdown()
        run(go())


class RedisClient:
    def __init__(self, reader, writer):
        self.reader, self.writer = reader, writer

    async def cmd(self, *args):
        out = b"*" + str(len(args)).encode() + b"\r\n"
        for a in args:
            b = a.encode() if isinstance(a, str) else a
            out += b"$" + str(len(b)).encode() + b"\r\n" + b + b"\r\n"
        self.writer.write(out)
        await self.writer.drain()
        return await self._read_reply()

    async def _read_reply(self):
        line = (await self.reader.readline()).strip()
        t, rest = line[:1], line[1:]
        if t == b"+":
            return rest.decode()
        if t == b"-":
            raise RuntimeError(rest.decode())
        if t == b":":
            return int(rest)
        if t == b"$":
            n = int(rest)
            if n < 0:
                return None
            data = await self.reader.readexactly(n)
            await self.reader.readexactly(2)
            return data.decode()
        if t == b"*":
            return [await self._read_reply() for _ in range(int(rest))]
        raise RuntimeError(f"bad reply {line!r}")


class TestRedisServer:
    def test_string_and_hash_commands(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            srv = RedisServer(mc.client(), num_tablets=1)
            addr = await srv.start()
            try:
                reader, writer = await asyncio.open_connection(*addr)
                r = RedisClient(reader, writer)
                assert await r.cmd("PING") == "PONG"
                assert await r.cmd("SET", "a", "1") == "OK"
                # redis table creation is lazy; wait for leaders
                await mc.wait_for_leaders("system.redis_kv")
                assert await r.cmd("GET", "a") == "1"
                assert await r.cmd("GET", "missing") is None
                assert await r.cmd("INCR", "a") == 2
                assert await r.cmd("INCRBY", "a", "10") == 12
                assert await r.cmd("MSET", "x", "xv", "y", "yv") == "OK"
                assert await r.cmd("MGET", "x", "y", "zz") == \
                    ["xv", "yv", None]
                assert await r.cmd("DEL", "x") == 1
                assert await r.cmd("EXISTS", "x") == 0
                assert await r.cmd("HSET", "h", "f1", "v1", "f2", "v2") == 2
                await mc.wait_for_leaders("system.redis_hash")
                assert await r.cmd("HGET", "h", "f1") == "v1"
                assert await r.cmd("HGETALL", "h") == \
                    ["f1", "v1", "f2", "v2"]
                assert await r.cmd("HDEL", "h", "f1") == 1
                writer.close()
            finally:
                await srv.shutdown()
                await mc.shutdown()
        run(go())


class TestCqlPaging:
    def test_result_paging_frames(self, tmp_path):
        async def go():
            mc = await MiniCluster(str(tmp_path), num_tservers=1).start()
            srv = CqlServer(mc.client())
            addr = await srv.start()
            try:
                reader, writer = await asyncio.open_connection(*addr)
                await cql_frame(writer, reader, 0x01, struct.pack(">H", 0))
                await cql_frame(writer, reader, 0x07, longstr(
                    "CREATE TABLE pg (k bigint, PRIMARY KEY (k))"))
                await mc.wait_for_leaders("pg")
                await cql_frame(writer, reader, 0x07, longstr(
                    "INSERT INTO pg (k) VALUES "
                    + ", ".join(f"({i})" for i in range(25))))

                def q_with_paging(sql, page_size, state=None):
                    b = sql.encode()
                    flags = 0x04 | (0x08 if state else 0)
                    body = struct.pack(">i", len(b)) + b
                    body += struct.pack(">HB", 0, flags)
                    body += struct.pack(">i", page_size)
                    if state:
                        body += struct.pack(">i", len(state)) + state
                    return body

                total = 0
                state = None
                pages = 0
                while True:
                    op, body = await cql_frame(
                        writer, reader, 0x07,
                        q_with_paging("SELECT k FROM pg ORDER BY k",
                                      10, state))
                    assert op == 0x08
                    kind, flags_ = struct.unpack_from(">ii", body)
                    assert kind == 2
                    pos = 8
                    (ncols,) = struct.unpack_from(">i", body, pos)
                    pos += 4
                    state = None
                    if flags_ & 0x02:
                        (ln,) = struct.unpack_from(">i", body, pos)
                        pos += 4
                        state = body[pos:pos + ln]
                        pos += ln
                    # skip table spec + col specs
                    pages += 1
                    if state is None:
                        break
                assert pages == 3   # 25 rows @ page 10 -> 3 pages
                writer.close()
            finally:
                await srv.shutdown()
                await mc.shutdown()
        run(go())
