"""Streaming scan pipeline: parity vs the monolithic batch path, MVCC
chunk-safety refusals, flag revert, and the generic stage pipeline."""
import tempfile
import time

import numpy as np
import pytest

from yugabyte_db_tpu.models.tpch import (LineitemTable, TPCH_Q1, TPCH_Q6,
                                         generate_lineitem,
                                         numpy_reference)
from yugabyte_db_tpu.ops import stream_scan
from yugabyte_db_tpu.ops.device_batch import build_batch
from yugabyte_db_tpu.ops.scan import AggSpec, ScanKernel
from yugabyte_db_tpu.storage.pipeline import StreamPipeline, stream_map
from yugabyte_db_tpu.utils import flags


# --- the generic stage pipeline -------------------------------------------

class TestPipeline:
    def test_order_and_results(self):
        p = StreamPipeline([lambda x: x + 1, lambda x: x * 2])
        assert list(p.run(range(32))) == [(i + 1) * 2 for i in range(32)]
        assert p.items == 32

    def test_error_propagates_and_tears_down(self):
        def boom(x):
            if x == 5:
                raise ValueError("x5")
            return x
        with pytest.raises(ValueError, match="x5"):
            list(stream_map(range(64), [boom, lambda x: x]))

    def test_early_close_does_not_deadlock(self):
        p = StreamPipeline([lambda x: x, lambda x: x], depth=2)
        g = p.run(range(10_000))
        assert next(g) == 0
        g.close()       # must not hang on the bounded queues

    def test_stages_overlap(self):
        # two 30ms stages over 6 items: serial would be ~0.36s,
        # overlapped ~0.21s; assert meaningfully below serial
        def slow(x):
            time.sleep(0.03)
            return x
        t0 = time.perf_counter()
        assert list(stream_map(range(6), [slow, slow])) == list(range(6))
        assert time.perf_counter() - t0 < 0.31

    def test_empty_and_feeder_error(self):
        assert list(stream_map([], [lambda x: x])) == []

        def bad_iter():
            yield 1
            raise RuntimeError("feeder died")
        with pytest.raises(RuntimeError, match="feeder died"):
            list(stream_map(bad_iter(), [lambda x: x]))


# --- streaming scan parity ------------------------------------------------

@pytest.fixture(scope="module")
def lineitem():
    data = generate_lineitem(0.02)          # 120k rows
    table = LineitemTable(tempfile.mkdtemp(prefix="stream-scan-"),
                          num_tablets=1)
    table.load(data, block_rows=16384)
    t = table.tablets[0]
    blocks = []
    for r in t.regular.ssts:
        for i in range(r.num_blocks()):
            blocks.append(r.columnar_block(i))
    return data, table, blocks


class TestStreamScanParity:
    def _both(self, blocks, q, read_ht=None, chunk_rows=32768):
        kernel = ScanKernel()
        got = stream_scan.streaming_scan_aggregate(
            blocks, sorted(q.columns), q.where, q.aggs, q.group,
            read_ht, kernel=kernel, chunk_rows=chunk_rows)
        assert got is not None
        batch = build_batch(blocks, sorted(q.columns))
        mono = kernel.run(batch, q.where, q.aggs, q.group, read_ht)
        return got, mono

    def test_q6_matches_monolithic_and_numpy(self, lineitem):
        data, _t, blocks = lineitem
        (souts, scnt), (mouts, mcnt, _) = self._both(blocks, TPCH_Q6)
        ref = numpy_reference(TPCH_Q6, data)
        assert abs(float(souts[0]) - ref) <= 1e-6 * abs(ref)
        assert abs(float(souts[0]) - float(mouts[0])) \
            <= 1e-6 * abs(float(mouts[0]))
        assert int(scnt) == int(mcnt)

    def test_q1_grouped_matches(self, lineitem):
        data, _t, blocks = lineitem
        (souts, scnt), (mouts, mcnt, _) = self._both(blocks, TPCH_Q1)
        ref = numpy_reference(TPCH_Q1, data)
        for g in range(6):
            wq, wp, wc = ref[g]
            assert int(np.asarray(scnt)[g]) == wc
            assert abs(float(souts[0][g]) - wq) <= 1e-9 * max(abs(wq), 1)
            assert abs(float(souts[1][g]) - wp) \
                <= 1e-5 * max(abs(wp), 1e-9)

    def test_with_read_point_visible_rows(self, lineitem):
        data, table, blocks = lineitem
        read_ht = table.tablets[0].clock.now().value
        (souts, scnt), (mouts, mcnt, _) = self._both(
            blocks, TPCH_Q6, read_ht=read_ht)
        assert abs(float(souts[0]) - float(mouts[0])) \
            <= 1e-6 * max(abs(float(mouts[0])), 1e-9)

    def test_single_chunk_declines(self, lineitem):
        _data, _t, blocks = lineitem
        got = stream_scan.streaming_scan_aggregate(
            blocks, sorted(TPCH_Q6.columns), TPCH_Q6.where,
            TPCH_Q6.aggs, None, None, chunk_rows=10_000_000)
        assert got is None      # < min_chunks: monolithic is better

    def test_minmax_empty_match_sentinels_combine(self, lineitem):
        # a WHERE no row satisfies: min/max sentinels must survive the
        # cross-chunk combine so the executor's NULL rule still fires
        from yugabyte_db_tpu.ops import Expr
        _data, _t, blocks = lineitem
        C = Expr.col
        where = (C(5) < -10).node        # shipdate < -10: empty
        kernel = ScanKernel()
        got = stream_scan.streaming_scan_aggregate(
            blocks, [1, 5], where,
            (AggSpec("min", C(1).node), AggSpec("count")), None, None,
            kernel=kernel, chunk_rows=32768)
        assert got is not None
        outs, _counts = got
        assert int(outs[1]) == 0
        v = np.asarray(outs[0])
        if np.issubdtype(v.dtype, np.integer):
            assert int(v) == np.iinfo(v.dtype).max   # MIN sentinel
        else:
            assert not np.isfinite(float(v)) and float(v) > 0


class TestChunkSafety:
    def _blocks(self, t):
        out = []
        for r in t.regular.ssts:
            for i in range(r.num_blocks()):
                out.append(r.columnar_block(i))
        return out

    def test_single_sorted_sst_is_safe(self, lineitem):
        _d, _t, blocks = lineitem
        assert stream_scan.chunk_safe_mvcc(blocks)

    def test_overlapping_ssts_refused(self):
        # two bulk loads of the SAME keys: block sequence restarts ->
        # boundary monotonicity breaks -> not chunk-safe
        data = generate_lineitem(0.005)
        table = LineitemTable(tempfile.mkdtemp(prefix="overlap-"),
                              num_tablets=1)
        t = table.tablets[0]
        t.bulk_load(data, block_rows=8192)
        t.bulk_load(data, block_rows=8192)
        blocks = self._blocks(t)
        assert len(t.regular.ssts) == 2
        assert not stream_scan.chunk_safe_mvcc(blocks)

    def test_non_unique_block_refused(self, lineitem):
        _d, _t, blocks = lineitem
        blocks = [b for b in blocks]
        blocks[0].unique_keys = False
        try:
            assert not stream_scan.chunk_safe_mvcc(blocks)
        finally:
            blocks[0].unique_keys = True

    def test_missing_keys_matrix_refused(self, lineitem):
        """A block with neither a keys matrix nor v2 boundary keys
        can't prove chunk safety (boundary keys ALONE are sufficient —
        that's the v2 keyless contract chunk_safe_mvcc now honors)."""
        _d, _t, blocks = lineitem
        b = blocks[0]
        saved = (b.keys, b._first_key, b._last_key)
        b.keys = None
        b._first_key = b._last_key = None
        try:
            assert not stream_scan.chunk_safe_mvcc(blocks)
        finally:
            b.keys, b._first_key, b._last_key = saved

    def test_boundary_keys_alone_suffice(self, lineitem):
        """v2 keyless blocks prove chunk safety from stored boundary
        keys without materializing the derived matrix."""
        _d, _t, blocks = lineitem
        saved = [(b.keys, b._first_key, b._last_key) for b in blocks]
        try:
            for b in blocks:
                fk, lk = b.first_full_key(), b.last_full_key()
                b.keys = None
                b._first_key, b._last_key = fk, lk
            assert stream_scan.chunk_safe_mvcc(blocks)
            assert all(b._keys is None for b in blocks)  # no rebuilds
        finally:
            for b, (k, f, l) in zip(blocks, saved):
                b.keys, b._first_key, b._last_key = k, f, l


class TestExecutorWiring:
    def test_flag_off_reproduces_monolithic(self, lineitem):
        data, table_tablet, _blocks = lineitem
        data_table = LineitemTable(tempfile.mkdtemp(prefix="flagoff-"),
                                   num_tablets=1)
        data_table.load(data, block_rows=16384)
        flags.set_flag("streaming_chunk_rows", 32768)
        try:
            stream_scan.LAST_STREAM_STATS.clear()
            on, on_cnt = data_table.run(TPCH_Q6)
            assert stream_scan.LAST_STREAM_STATS.get("chunks", 0) >= 2
            flags.set_flag("streaming_scan_enabled", False)
            stream_scan.LAST_STREAM_STATS.clear()
            off, off_cnt = data_table.run(TPCH_Q6)
            assert not stream_scan.LAST_STREAM_STATS
            assert abs(float(on[0]) - float(off[0])) \
                <= 1e-6 * max(abs(float(off[0])), 1e-9)
        finally:
            flags.REGISTRY.reset("streaming_scan_enabled")
            flags.REGISTRY.reset("streaming_chunk_rows")
