"""Multi-process cluster harness (cluster/): supervisor, driver,
chaos, and crash-recovery across REAL process kills.

Tier-1 keeps the short shapes (a 2-tserver smoke, the two SIGKILL
crash-recovery tests — each cluster spins up in a couple of seconds);
the 2x-saturation / auto-split / rebalance / chaos rounds run under
``-m slow`` (CLUSTER.md documents the split).

Every test creates its own supervisor inside its own asyncio.run: the
supervisor owns a client-side Messenger bound to the running loop, so
nothing here can be shared across event loops.
"""
import asyncio
import os
import time

import pytest

from yugabyte_db_tpu.cluster import ChaosController, ClusterSupervisor
from yugabyte_db_tpu.cluster.supervisor import ManagedProcess
from yugabyte_db_tpu.docdb.operations import ReadRequest
from yugabyte_db_tpu.docdb.wire import read_request_to_wire
from yugabyte_db_tpu.ops.scan import AggSpec
from yugabyte_db_tpu.rpc.messenger import RpcError
from yugabyte_db_tpu.utils.fault_injection import HARD_CRASH_EXIT_CODE


def run(coro):
    return asyncio.run(coro)


async def _driver_setup(sup, rows=200, num_tablets=2, rf=2, **kw):
    await sup.spawn_driver("drv-0")
    return await sup.call("drv-0", "driver", "setup",
                          {"rows": rows, "num_tablets": num_tablets,
                           "replication_factor": rf, **kw},
                          timeout=90.0)


async def _verify_zero_loss(sup, timeout=120.0):
    v = await sup.call("drv-0", "driver", "verify", {}, timeout=timeout)
    assert v["missing"] == 0 and v["mismatched"] == 0 \
        and v["unreachable"] == 0, v
    return v


# --------------------------------------------------------------------------
# process-free units
# --------------------------------------------------------------------------
class TestSupervisorUnits:
    def test_backoff_schedule_monotone_capped(self):
        delays = [ClusterSupervisor.backoff_delay(i) for i in range(10)]
        assert delays[0] == 0.0
        assert delays == sorted(delays)
        assert delays[9] == ClusterSupervisor.BACKOFF_S[-1]

    def test_chaos_plan_seeded_deterministic(self):
        """Same seed + same cluster shape = identical plan; spare is
        never a victim; kills get a paired restart."""
        def fake_sup():
            sup = ClusterSupervisor.__new__(ClusterSupervisor)
            sup.procs = {
                f"ts-{i}": ManagedProcess(
                    name=f"ts-{i}", role="tserver", module="m",
                    args=[], env={}, log_path="/", data_dir="/")
                for i in range(4)}
            return sup
        plans = [ChaosController(fake_sup(), seed=7).plan_round(
            kills=2, stalls=1, round_s=3.0, spare=("ts-0",))
            for _ in range(2)]
        assert [e.as_tuple() for e in plans[0]] == \
            [e.as_tuple() for e in plans[1]]
        assert all(e.victim != "ts-0" for e in plans[0])
        kills = [e for e in plans[0] if e.kind == "kill"]
        restarts = {e.victim: e for e in plans[0] if e.kind == "restart"}
        assert len(kills) == 2
        for k in kills:
            assert restarts[k.victim].at_s > k.at_s
        # a different seed reshuffles (victims or times)
        other = ChaosController(fake_sup(), seed=8).plan_round(
            kills=2, stalls=1, round_s=3.0, spare=("ts-0",))
        assert [e.as_tuple() for e in other] != \
            [e.as_tuple() for e in plans[0]]


# --------------------------------------------------------------------------
# tier-1 multi-process shapes (seconds each, real OS processes)
# --------------------------------------------------------------------------
class TestClusterSmoke:
    def test_smoke_load_verify_drain(self, tmp_path):
        """2 tservers + master + driver as real processes: load, open
        loop, zero-loss verify, cross-process metrics/fault RPCs,
        graceful SIGTERM drain (exit 0), restart on the SAME port."""
        async def main():
            sup = await ClusterSupervisor(str(tmp_path),
                                          num_tservers=2).start()
            try:
                r = await _driver_setup(sup, rows=120, rf=2)
                assert r["rows"] == 120 and r["table_id"]
                ph = await sup.call(
                    "drv-0", "driver", "run_phase",
                    {"rate": 150, "seconds": 1.0, "sla_ms": 4000},
                    timeout=30.0)
                assert ph["ok"] > 0
                await _verify_zero_loss(sup)

                # cross-process metrics snapshot (the satellite's
                # assertion surface): pid proves it is the CHILD's
                snap = await sup.call("ts-0", "tserver",
                                      "metrics_snapshot", {}, timeout=10.0)
                assert snap["pid"] != os.getpid()
                assert snap["tablets"] and all(
                    "wal_index" in t for t in snap["tablets"].values())

                # fault arming round-trips cross-process
                st = await sup.call("ts-0", "tserver", "arm_fault",
                                    {"crash_points": ["p:x"],
                                     "disk_stall_s": 0.0},
                                    timeout=10.0)
                assert st["status"]["crash_points"] == ["p:x"]
                st = await sup.call("ts-0", "tserver", "arm_fault",
                                    {"clear_all": True}, timeout=10.0)
                assert st["status"]["crash_points"] == []

                # graceful drain: exit 0 + DRAINED marker, memtables
                # flushed so the restart replays (almost) nothing
                code = await sup.stop("ts-1", drain=True)
                assert code == 0
                with open(sup.procs["ts-1"].log_path) as f:
                    assert "DRAINED" in f.read()
                old_port = sup.procs["ts-1"].port
                await sup.restart("ts-1")
                assert sup.procs["ts-1"].port == old_port
                await sup.wait_tservers_live()
                await _verify_zero_loss(sup)
            finally:
                await sup.shutdown()
        run(main())

    def test_monitor_restarts_unexpected_death(self, tmp_path):
        """The auto-restart monitor: a child dying OUTSIDE the
        supervisor (SIGKILL straight at the pid — stopped stays False)
        is respawned with backoff on its own port, and the data
        survives via WAL replay."""
        async def main():
            sup = await ClusterSupervisor(str(tmp_path),
                                          num_tservers=1).start()
            try:
                await _driver_setup(sup, rows=80, num_tablets=1, rf=1,
                                    flush=False)
                await sup.start_monitor()
                mp = sup.procs["ts-0"]
                old_port = mp.port
                os.kill(mp.proc.pid, 9)     # not via sup.stop/kill
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    if mp.restarts >= 1 and mp.alive() \
                            and mp.addr is not None:
                        break
                    await asyncio.sleep(0.1)
                assert mp.restarts >= 1 and mp.alive()
                assert mp.port == old_port
                await sup.wait_tservers_live()
                await _verify_zero_loss(sup)
            finally:
                await sup.shutdown()
        run(main())

    def test_bypass_scan_from_replica_process(self, tmp_path):
        """An aggregate served through the bypass engine by a SEPARATE
        replica process (rpc_bypass_scan): correct result, zero key
        rebuilds, and flag-off refusal."""
        async def main():
            sup = await ClusterSupervisor(str(tmp_path),
                                          num_tservers=2).start()
            try:
                r = await _driver_setup(sup, rows=300, rf=2, flush=True)
                table_id = r["table_id"]
                req = {"table_id": table_id,
                       "req": read_request_to_wire(ReadRequest(
                           table_id,
                           aggregates=(AggSpec("count"),
                                       AggSpec("sum", ("col", 0)))))}
                with pytest.raises(RpcError):   # flag off on the child
                    await sup.call("ts-1", "tserver", "bypass_scan",
                                   req, timeout=30.0)
                await sup.call("ts-1", "tserver", "set_flag",
                               {"name": "bypass_reader_enabled",
                                "value": True}, timeout=10.0)
                resp = await sup.call("ts-1", "tserver", "bypass_scan",
                                      req, timeout=60.0)
                assert resp["agg_values"][0] == 300.0
                assert resp["agg_values"][1] == 300 * 299 / 2
                assert resp["stats"]["key_rebuilds"] == 0
            finally:
                await sup.shutdown()
        run(main())


class TestCrashRecoveryRealKill:
    """SIGKILL-fidelity crash recovery: the armed crash point os._exits
    the CHILD process (no atexit, no finally), and the restart must
    reclaim everything via the PR-4 tombstone / PR-7 unmanifested-SST
    sweeps."""

    def test_kill_mid_flush_sweeps_unmanifested_sst(self, tmp_path):
        """Env-handshake-armed `flush:before_manifest` kills the
        tserver with the SST fully written but NOT in the manifest; the
        restart must sweep the orphan file and recover every acked row
        from the WAL."""
        async def main():
            sup = await ClusterSupervisor(str(tmp_path),
                                          num_tservers=1).start()
            try:
                # arm via the ENV handshake on a fresh process: the
                # point is live before the first request (the RPC route
                # is exercised by the smoke test above)
                await sup.stop("ts-0", drain=False)
                sup.procs["ts-0"].env.update({
                    "YBTPU_CRASH_POINTS": "flush:before_manifest",
                    "YBTPU_CRASH_HARD": "1"})
                await sup.restart("ts-0")
                await sup.wait_tservers_live()
                st = await sup.call("ts-0", "tserver", "fault_status",
                                    {}, timeout=10.0)
                assert st["status"]["crash_points"] == \
                    ["flush:before_manifest"]
                assert st["status"]["hard_crash"] is True

                r = await _driver_setup(sup, rows=100, num_tablets=1,
                                        rf=1, flush=False)
                snap = await sup.call("ts-0", "tserver",
                                      "metrics_snapshot", {}, timeout=10.0)
                tablet_id = next(iter(snap["tablets"]))
                with pytest.raises((RpcError, asyncio.TimeoutError,
                                    OSError)):
                    await sup.call("ts-0", "tserver", "flush",
                                   {"tablet_id": tablet_id}, timeout=15.0)
                await sup._wait_exit(sup.procs["ts-0"], 10.0)
                assert sup.procs["ts-0"].exit_code() == \
                    HARD_CRASH_EXIT_CODE

                # the orphan: a full .sst on disk, absent from the
                # manifest the crash never wrote
                reg = os.path.join(str(tmp_path), "ts-0", "tablets",
                                   tablet_id, "regular")
                orphans = [f for f in os.listdir(reg)
                           if f.endswith(".sst")]
                assert orphans, "crash point fired before the SST wrote"

                sup.procs["ts-0"].env.pop("YBTPU_CRASH_POINTS")
                sup.procs["ts-0"].env.pop("YBTPU_CRASH_HARD")
                sup.procs["ts-0"].stopped = True
                await sup.restart("ts-0")
                await sup.wait_tservers_live()
                await _verify_zero_loss(sup)
                # the sweep reclaimed the unmanifested file at open
                left = set(os.listdir(reg))
                assert not (set(orphans) & left), (orphans, left)
            finally:
                await sup.shutdown()
        run(main())

    def test_kill_mid_flush_with_frozen_backlog_replays_all(
            self, tmp_path):
        """PR-11 async-flush crash seam: SIGKILL at
        ``flush:before_manifest`` while the BACKGROUND flush executor
        owns the write and more frozen memtables are queued behind it
        (a disk stall holds the first flush while a tiny
        memstore threshold keeps freezing new ones).  Restart must
        sweep the unmanifested SST and replay every acked write —
        frozen-memtable state is memory-only, so the WAL (whose GC
        gates on the flushed frontier) still covers all of it."""
        async def main():
            sup = await ClusterSupervisor(str(tmp_path),
                                          num_tservers=1).start()
            try:
                await sup.stop("ts-0", drain=False)
                sup.procs["ts-0"].env.update({
                    "YBTPU_CRASH_POINTS": "flush:before_manifest",
                    "YBTPU_CRASH_HARD": "1"})
                await sup.restart("ts-0")
                await sup.wait_tservers_live()

                r = await _driver_setup(sup, rows=60, num_tablets=1,
                                        rf=1, flush=False)
                snap = await sup.call("ts-0", "tserver",
                                      "metrics_snapshot", {},
                                      timeout=10.0)
                tablet_id = next(iter(snap["tablets"]))
                # tiny flush threshold + roomy frozen bound + a disk
                # stall on the first background flush: applies keep
                # freezing while the flush worker is held, so the
                # crash fires with a REAL frozen backlog behind it
                for name, val in (
                        ("memstore_flush_threshold_bytes", 15_000),
                        ("max_frozen_memtables", 8)):
                    await sup.call("ts-0", "tserver", "set_flag",
                                   {"name": name, "value": val},
                                   timeout=10.0)
                await sup.call("ts-0", "tserver", "arm_fault",
                               {"disk_stall_s": 1.0}, timeout=10.0)
                await sup.call(
                    "drv-0", "driver", "run_phase",
                    {"rate": 600.0, "seconds": 3.0,
                     "write_fraction": 1.0, "sla_ms": 2000,
                     "tag": "backlog"}, timeout=60.0)
                await sup._wait_exit(sup.procs["ts-0"], 20.0)
                assert sup.procs["ts-0"].exit_code() == \
                    HARD_CRASH_EXIT_CODE

                reg = os.path.join(str(tmp_path), "ts-0", "tablets",
                                   tablet_id, "regular")
                orphans = [f for f in os.listdir(reg)
                           if f.endswith(".sst")]
                assert orphans, "crash fired before any SST wrote"

                sup.procs["ts-0"].env.pop("YBTPU_CRASH_POINTS")
                sup.procs["ts-0"].env.pop("YBTPU_CRASH_HARD")
                sup.procs["ts-0"].stopped = True
                await sup.restart("ts-0")
                await sup.wait_tservers_live()
                await _verify_zero_loss(sup)
                left = set(os.listdir(reg))
                assert not (set(orphans) & left), (orphans, left)
            finally:
                await sup.shutdown()
        run(main())

    def test_kill_mid_split_rebuilds_child(self, tmp_path):
        """`split:before_marker` kills the tserver with the first split
        child's data flushed but its split-complete marker absent; the
        restarted process must rebuild the children from the replayed
        split entry and lose nothing."""
        async def main():
            sup = await ClusterSupervisor(str(tmp_path),
                                          num_tservers=1).start()
            try:
                await _driver_setup(sup, rows=200, num_tablets=1, rf=1,
                                    flush=False)
                snap = await sup.call("ts-0", "tserver",
                                      "metrics_snapshot", {}, timeout=10.0)
                tablet_id = next(iter(snap["tablets"]))
                await sup.call("ts-0", "tserver", "arm_fault",
                               {"crash_points": ["split:before_marker"],
                                "hard": True}, timeout=10.0)
                with pytest.raises((RpcError, asyncio.TimeoutError,
                                    OSError)):
                    await sup.call("master-0", "master", "split_tablet",
                                   {"tablet_id": tablet_id}, timeout=20.0)
                await sup._wait_exit(sup.procs["ts-0"], 10.0)
                assert sup.procs["ts-0"].exit_code() == \
                    HARD_CRASH_EXIT_CODE

                sup.procs["ts-0"].stopped = True
                await sup.restart("ts-0")
                await sup.wait_tservers_live()
                # the replayed split entry rebuilt BOTH children (the
                # parent stops serving; each child carries a marker)
                deadline = time.monotonic() + 30
                children = []
                while time.monotonic() < deadline:
                    snap = await sup.call("ts-0", "tserver",
                                          "metrics_snapshot", {},
                                          timeout=10.0)
                    children = [t for t in snap["tablets"]
                                if t != tablet_id]
                    if len(children) == 2:
                        break
                    await asyncio.sleep(0.25)
                assert len(children) == 2, snap["tablets"]
                await _verify_zero_loss(sup)
            finally:
                await sup.shutdown()
        run(main())


# --------------------------------------------------------------------------
# full live-fire shapes (slow: 2x saturation, control plane, chaos)
# --------------------------------------------------------------------------
@pytest.mark.slow
class TestClusterLiveFire:
    def test_overload_sheds_not_collapse(self, tmp_path):
        """Open loop at 2x the measured saturation: the cluster sheds /
        slows but completes the phase, and every acked write survives."""
        async def main():
            sup = await ClusterSupervisor(str(tmp_path),
                                          num_tservers=2).start()
            try:
                await _driver_setup(sup, rows=300, rf=2)
                sat = await sup.call("drv-0", "driver", "saturation",
                                     {"seconds": 1.5, "workers": 32},
                                     timeout=60.0)
                rate = max(200.0, 2.0 * sat["ops_per_s"])
                ph = await sup.call(
                    "drv-0", "driver", "run_phase",
                    {"rate": rate, "seconds": 3.0, "sla_ms": 2000},
                    timeout=120.0)
                assert ph["ok"] > 0
                await _verify_zero_loss(sup)
            finally:
                await sup.shutdown()
        run(main())

    def test_autosplit_under_live_load(self, tmp_path):
        """enable_automatic_tablet_splitting + a lowered size threshold
        while the driver fires: the master splits a tablet THROUGH the
        online Raft split path, under load, and nothing is lost."""
        async def main():
            sup = await ClusterSupervisor(str(tmp_path),
                                          num_tservers=2).start()
            try:
                await _driver_setup(sup, rows=200, num_tablets=2, rf=2)
                await sup.call("master-0", "master", "set_flag",
                               {"name":
                                "tablet_split_size_threshold_bytes",
                                "value": 40_000}, timeout=10.0)
                await sup.call("master-0", "master", "set_flag",
                               {"name":
                                "enable_automatic_tablet_splitting",
                                "value": True}, timeout=10.0)
                ntab, deadline = 2, time.monotonic() + 45
                while time.monotonic() < deadline:
                    await sup.call("drv-0", "driver", "run_phase",
                                   {"rate": 300, "seconds": 1.0,
                                    "sla_ms": 4000}, timeout=30.0)
                    snap = await sup.call("master-0", "master",
                                          "metrics_snapshot", {},
                                          timeout=10.0)
                    ntab = len(snap["tablet_reports"])
                    if ntab > 2:
                        break
                assert ntab > 2, "auto-split did not fire under load"
                await _verify_zero_loss(sup)
            finally:
                await sup.shutdown()
        run(main())

    def test_rebalance_drains_blacklisted_tserver(self, tmp_path):
        """Blacklist-driven rebalance under load: a third tserver joins,
        the blacklisted one drains via balancer replica moves (the
        remote-bootstrap catch-up path), writes keep landing."""
        async def main():
            sup = await ClusterSupervisor(str(tmp_path), num_tservers=2,
                                          auto_balance=True).start()
            try:
                await _driver_setup(sup, rows=200, num_tablets=2, rf=2)
                await sup.spawn_tserver(2)
                await sup.wait_tservers_live()
                await sup.call("master-0", "master", "blacklist",
                               {"ts_uuid": "ts-0"}, timeout=10.0)
                load = asyncio.ensure_future(sup.call(
                    "drv-0", "driver", "run_phase",
                    {"rate": 200, "seconds": 4.0, "sla_ms": 4000},
                    timeout=60.0))
                drained, deadline = False, time.monotonic() + 45
                while time.monotonic() < deadline:
                    snap = await sup.call("ts-0", "tserver",
                                          "metrics_snapshot", {},
                                          timeout=10.0)
                    if not snap["tablets"]:
                        drained = True
                        break
                    await asyncio.sleep(0.5)
                ph = await load
                assert ph["ok"] > 0
                assert drained, "blacklisted tserver still owns replicas"
                await _verify_zero_loss(sup)
            finally:
                await sup.shutdown()
        run(main())

    def test_seeded_chaos_round_zero_loss(self, tmp_path):
        """A seeded kill + disk-stall + restart round under load: the
        plan's PAIRED restart brings the victim back, the stall
        clears, and the quiesced byte-verify finds every acked write
        intact."""
        async def main():
            sup = await ClusterSupervisor(str(tmp_path),
                                          num_tservers=3).start()
            try:
                await _driver_setup(sup, rows=200, num_tablets=2, rf=3)
                chaos = ChaosController(sup, seed=42)
                plan = chaos.plan_round(kills=1, stalls=1, stall_s=1.0,
                                        round_s=2.0)
                load = asyncio.ensure_future(sup.call(
                    "drv-0", "driver", "run_phase",
                    {"rate": 250, "seconds": 4.0, "sla_ms": 4000},
                    timeout=90.0))
                log = await chaos.run_round(plan)
                assert any(o.startswith("exit=") for *_, o in log)
                ph = await load
                assert ph["ok"] > 0
                await chaos.clear_all()
                await _verify_zero_loss(sup)
            finally:
                await sup.shutdown()
        run(main())
