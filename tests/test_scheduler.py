"""Request-scheduler subsystem tests (yugabyte_db_tpu/sched/).

Covers the PR-3 acceptance surface:
- admission control + typed sheds (retry_after_ms) under fault-injected
  lane stalls and forced sheds,
- group-commit write batching is durability-equivalent to serial
  writes (WAL replay parity after a tserver restart),
- batched point-read and coalesced-scan responses byte-identical to
  their unbatched (scheduler-off) equivalents,
- client backoff honors retry_after_ms,
- the maintenance lane cannot starve foreground reads,
- per-connection messenger inflight cap,
- scheduler off = direct dispatch (flag revert path).
"""
import asyncio
import time

import pytest

from yugabyte_db_tpu.docdb.operations import ReadRequest, RowOp
from yugabyte_db_tpu.docdb.wire import read_request_to_wire
from yugabyte_db_tpu.models.ycsb import usertable_info
from yugabyte_db_tpu.ops.scan import AggSpec
from yugabyte_db_tpu.rpc.messenger import Messenger, RpcError
from yugabyte_db_tpu.sched import Lane, OverloadError, RequestScheduler
from yugabyte_db_tpu.sched.batching import ScanItem
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
from yugabyte_db_tpu.utils import fault_injection as fi
from yugabyte_db_tpu.utils import flags


@pytest.fixture(autouse=True)
def _clean():
    yield
    fi.clear_lane_stalls()
    fi.clear_forced_sheds()
    for f in ("scheduler_enabled", "sched_point_read_depth",
              "sched_scan_depth", "sched_maintenance_depth",
              "rpc_max_inflight_per_connection",
              "sched_cut_through_min_interval_us",
              "fused_replicate_enabled", "async_flush_enabled",
              "sched_cross_tablet_fusion"):
        flags.REGISTRY.reset(f)


async def _cluster(tmp, n_rows=400):
    mc = await MiniCluster(str(tmp), num_tservers=1).start()
    c = mc.client()
    await c.create_table(usertable_info(), num_tablets=1,
                         replication_factor=1)
    await mc.wait_for_leaders("usertable")
    rows = [{"ycsb_key": i,
             **{f"field{j}": f"v{i}-{j}" for j in range(10)}}
            for i in range(n_rows)]
    await c.insert("usertable", rows)
    return mc, c, rows


class TestAdmission:
    def test_stalled_lane_sheds_with_retry_after(self):
        """Stall the scan lane; fill it past depth; admission must
        shed with typed SERVICE_UNAVAILABLE + retry_after_ms while the
        queue stays bounded."""
        async def run():
            flags.set_flag("sched_scan_depth", 8)
            s = RequestScheduler("t-stall")
            fi.stall_lane("scan")

            async def work():
                return {"ok": 1}

            loop = asyncio.get_running_loop()
            tasks = [loop.create_task(
                s.submit_grouped(Lane.SCAN, ("sig", i), ScanItem(work)))
                for i in range(30)]
            await asyncio.sleep(0.1)    # sheds resolve; admitted park
            sheds = [t.exception() for t in tasks
                     if t.done() and t.exception() is not None]
            assert sheds, "no sheds despite stalled lane over depth"
            assert all(isinstance(e, OverloadError) for e in sheds)
            assert all(e.code == "SERVICE_UNAVAILABLE" for e in sheds)
            assert all(e.retry_after_ms >= 1 for e in sheds)
            st = s.lanes[Lane.SCAN]
            assert st.depth <= st.cfg.max_depth
            # release: every admitted request completes
            fi.release_lane("scan")
            results = await asyncio.gather(*tasks,
                                           return_exceptions=True)
            done = [r for r in results if isinstance(r, dict)]
            assert len(done) + len(sheds) == 30
            await s.shutdown()
        asyncio.run(run())

    def test_forced_shed_rejects_everything(self):
        async def run():
            s = RequestScheduler("t-force")
            fi.force_shed_lane("point_read")

            async def work():
                return 1
            with pytest.raises(OverloadError) as ei:
                await s.submit(Lane.POINT_READ, work)
            assert ei.value.retry_after_ms >= 1
            fi.clear_forced_sheds()
            assert await s.submit(Lane.POINT_READ, work) == 1
            await s.shutdown()
        asyncio.run(run())

    def test_retry_after_crosses_the_wire(self, tmp_path):
        """A shed on the server arrives at a remote caller as an
        RpcError with code + retry_after_ms intact."""
        async def run():
            mc, c, rows = await _cluster(tmp_path)
            try:
                fi.force_shed_lane("point_read")
                ts = mc.tservers[0]
                m = Messenger("probe")
                ct = await c._table("usertable")
                loc = ct.locations[0]
                with pytest.raises(RpcError) as ei:
                    await m.call(ts.messenger.addr, "tserver", "read",
                                 {"tablet_id": loc.tablet_id,
                                  "req": read_request_to_wire(ReadRequest(
                                      ct.info.table_id,
                                      pk_eq={"ycsb_key": 1}))},
                                 timeout=5.0)
                assert ei.value.code == "SERVICE_UNAVAILABLE"
                assert ei.value.retry_after_ms >= 1
                await m.shutdown()
            finally:
                fi.clear_forced_sheds()
                await mc.shutdown()
        asyncio.run(run())


class TestGroupCommitDurability:
    def test_replay_parity_with_serial_writes(self, tmp_path):
        """Rows written through group commit must survive a tserver
        restart (WAL replay) identical to rows written serially with
        the scheduler off — same visible data either way."""
        async def run():
            mc, c, rows = await _cluster(tmp_path, n_rows=1)
            try:
                # concurrent single-row writes -> group commit merges
                batch_rows = [
                    {"ycsb_key": 1000 + i,
                     **{f"field{j}": f"b{i}-{j}" for j in range(10)}}
                    for i in range(60)]
                await asyncio.gather(
                    *[c.insert("usertable", [r]) for r in batch_rows])
                # serial writes with the scheduler OFF (the baseline)
                flags.set_flag("scheduler_enabled", False)
                serial_rows = [
                    {"ycsb_key": 2000 + i,
                     **{f"field{j}": f"s{i}-{j}" for j in range(10)}}
                    for i in range(20)]
                for r in serial_rows:
                    await c.insert("usertable", [r])
                flags.set_flag("scheduler_enabled", True)
                # fanin proves merging actually happened
                ts = mc.tservers[0]
                st = ts.scheduler.lanes[Lane.POINT_WRITE]
                assert st.m_fanin._max and st.m_fanin._max > 1, \
                    "group commit never merged anything"
                # restart: WAL replay rebuilds state from the log
                ts2 = await mc.restart_tserver(0)
                await mc.wait_for_leaders("usertable")
                for r in batch_rows + serial_rows:
                    got = await c.get("usertable",
                                      {"ycsb_key": r["ycsb_key"]})
                    assert got == r, f"replay lost/changed {r['ycsb_key']}"
            finally:
                flags.set_flag("scheduler_enabled", True)
                await mc.shutdown()
        asyncio.run(run())

    def test_same_key_last_write_wins_in_one_group(self, tmp_path):
        """Two writes of the same key merged into one group: the later
        member's value wins (write_id order preserves arrival order)."""
        async def run():
            mc, c, rows = await _cluster(tmp_path, n_rows=1)
            try:
                ts = mc.tservers[0]
                tablet_id = (await c._table("usertable")) \
                    .locations[0].tablet_id
                peer = ts.peers[tablet_id]
                from yugabyte_db_tpu.docdb.operations import WriteRequest
                from yugabyte_db_tpu.sched.batching import (
                    WriteItem, dispatch_write_group)
                loop = asyncio.get_running_loop()
                mk = lambda v: WriteRequest("usertable", [RowOp(
                    "upsert", {"ycsb_key": 7,
                               **{f"field{j}": v for j in range(10)}})])
                items = [(WriteItem(peer, mk("first")),
                          loop.create_future(), 0, 0.0),
                         (WriteItem(peer, mk("second")),
                          loop.create_future(), 0, 0.0)]
                st = ts.scheduler.lanes[Lane.POINT_WRITE]
                await dispatch_write_group(items, st.m_fanin)
                got = await c.get("usertable", {"ycsb_key": 7})
                assert got["field0"] == "second"
            finally:
                await mc.shutdown()
        asyncio.run(run())


class TestBatchedReadParity:
    def test_batched_point_reads_byte_identical(self, tmp_path):
        """Wire responses from the batched multi_get path must equal
        the unbatched (scheduler-off) responses byte for byte."""
        async def run():
            mc, c, rows = await _cluster(tmp_path)
            try:
                ts = mc.tservers[0]
                ct = await c._table("usertable")
                loc = ct.locations[0]

                def req(i, cols=()):
                    return {"tablet_id": loc.tablet_id,
                            "req": read_request_to_wire(ReadRequest(
                                ct.info.table_id,
                                columns=tuple(cols),
                                pk_eq={"ycsb_key": i}))}
                keys = list(range(0, 40)) + [9999]   # incl. a miss
                # batched: concurrent -> grouped through the scheduler
                batched = await asyncio.gather(
                    *[ts.rpc_read(req(i)) for i in keys])
                proj = await asyncio.gather(
                    *[ts.rpc_read(req(i, ("ycsb_key", "field3")))
                      for i in keys])
                flags.set_flag("scheduler_enabled", False)
                direct = [await ts.rpc_read(req(i)) for i in keys]
                dproj = [await ts.rpc_read(req(i, ("ycsb_key",
                                                   "field3")))
                         for i in keys]
                flags.set_flag("scheduler_enabled", True)
                assert batched == direct
                assert proj == dproj
                import msgpack
                assert msgpack.packb(batched) == msgpack.packb(direct)
            finally:
                await mc.shutdown()
        asyncio.run(run())

    def test_coalesced_scans_byte_identical(self, tmp_path):
        """N identical aggregate scans coalesced into one execution
        return exactly what N unbatched executions return."""
        async def run():
            mc, c, rows = await _cluster(tmp_path)
            try:
                ts = mc.tservers[0]
                ct = await c._table("usertable")
                loc = ct.locations[0]

                def req():
                    return {"tablet_id": loc.tablet_id,
                            "req": read_request_to_wire(ReadRequest(
                                ct.info.table_id,
                                aggregates=(AggSpec("count"),
                                            AggSpec("min", ("col", 0)),
                                            AggSpec("max", ("col", 0)))))}
                # force the coalescing path regardless of EWMA state
                fi.stall_lane("scan")
                loop = asyncio.get_running_loop()
                tasks = [loop.create_task(ts.rpc_read(req()))
                         for _ in range(10)]
                await asyncio.sleep(0.05)   # all queued into one group
                fi.release_lane("scan")
                coalesced = await asyncio.gather(*tasks)
                st = ts.scheduler.lanes[Lane.SCAN]
                assert st.m_batch._max and st.m_batch._max >= 10
                flags.set_flag("scheduler_enabled", False)
                direct = await ts.rpc_read(req())
                flags.set_flag("scheduler_enabled", True)
                import msgpack
                for r in coalesced:
                    assert msgpack.packb(r) == msgpack.packb(direct)
            finally:
                await mc.shutdown()
        asyncio.run(run())


class TestClientBackoff:
    def test_client_honors_retry_after(self, tmp_path):
        """Two typed sheds carrying retry_after_ms=100 must make the
        client sleep jittered-exponentially (>= 50ms then >= 100ms —
        the jitter floor) before the third attempt succeeds."""
        async def run():
            mc, c, rows = await _cluster(tmp_path, n_rows=10)
            try:
                real_call = c.messenger.call
                calls = {"shed": 0}

                async def flaky(addr, service, method, payload,
                                timeout=10.0):
                    if method == "read" and calls["shed"] < 2:
                        calls["shed"] += 1
                        raise RpcError("overloaded",
                                       "SERVICE_UNAVAILABLE",
                                       retry_after_ms=100)
                    return await real_call(addr, service, method,
                                           payload, timeout=timeout)
                c.messenger.call = flaky
                t0 = time.monotonic()
                got = await c.get("usertable", {"ycsb_key": 3})
                dt = time.monotonic() - t0
                assert got is not None and got["field0"] == "v3-0"
                assert calls["shed"] == 2
                # jitter floor: 0.5 * 100ms + 0.5 * 200ms = 150ms
                assert dt >= 0.14, f"client did not back off: {dt:.3f}s"
            finally:
                await mc.shutdown()
        asyncio.run(run())

    def test_shed_window_heals_transparently(self, tmp_path):
        """A forced-shed window that clears while the client is backing
        off ends in success, not an error surfaced to the caller."""
        async def run():
            mc, c, rows = await _cluster(tmp_path, n_rows=10)
            try:
                fi.force_shed_lane("point_read")
                asyncio.get_running_loop().call_later(
                    0.05, fi.clear_forced_sheds)
                got = await c.get("usertable", {"ycsb_key": 3})
                assert got is not None and got["field0"] == "v3-0"
            finally:
                fi.clear_forced_sheds()
                await mc.shutdown()
        asyncio.run(run())


class TestLaneIsolation:
    def test_maintenance_cannot_starve_foreground_reads(self, tmp_path):
        """Saturate + stall the maintenance lane; foreground point
        reads must still be served promptly (separate lanes, separate
        dispatch slots)."""
        async def run():
            mc, c, rows = await _cluster(tmp_path)
            try:
                ts = mc.tservers[0]
                ct = await c._table("usertable")
                loc = ct.locations[0]
                fi.stall_lane("maintenance")
                maint = [asyncio.get_running_loop().create_task(
                    ts.rpc_flush({"tablet_id": loc.tablet_id}))
                    for _ in range(8)]
                await asyncio.sleep(0.02)
                t0 = time.monotonic()
                for i in range(20):
                    got = await c.get("usertable", {"ycsb_key": i})
                    assert got is not None
                dt = time.monotonic() - t0
                assert dt < 2.0, f"reads starved: {dt:.2f}s"
                fi.release_lane("maintenance")
                await asyncio.gather(*maint)
            finally:
                fi.clear_lane_stalls()
                await mc.shutdown()
        asyncio.run(run())


class TestMessengerInflightCap:
    def test_over_cap_frames_rejected_typed(self, tmp_path):
        """One connection pipelining past the per-connection cap gets
        typed SERVICE_UNAVAILABLE rejects; the server stays healthy and
        serves the conn again afterwards."""
        async def run():
            mc, c, rows = await _cluster(tmp_path)
            try:
                flags.set_flag("rpc_max_inflight_per_connection", 4)
                # stall the lane so inflight dispatch tasks pile up
                fi.stall_lane("point_read")
                ts = mc.tservers[0]
                ct = await c._table("usertable")
                loc = ct.locations[0]
                m = Messenger("flood")

                def req(i):
                    return {"tablet_id": loc.tablet_id,
                            "req": read_request_to_wire(ReadRequest(
                                ct.info.table_id,
                                pk_eq={"ycsb_key": i % 100}))}
                tasks = [asyncio.get_running_loop().create_task(
                    m.call(ts.messenger.addr, "tserver", "read",
                           req(i), timeout=10.0)) for i in range(40)]
                await asyncio.sleep(0.1)
                fi.release_lane("point_read")
                results = await asyncio.gather(*tasks,
                                               return_exceptions=True)
                sheds = [r for r in results if isinstance(r, RpcError)
                         and r.code == "SERVICE_UNAVAILABLE"]
                ok = [r for r in results if isinstance(r, dict)]
                assert sheds, "cap never rejected"
                assert all(r.retry_after_ms for r in sheds)
                assert ok, "cap rejected everything"
                # connection still usable
                r = await m.call(ts.messenger.addr, "tserver", "read",
                                 req(1), timeout=5.0)
                assert r["rows"]
                await m.shutdown()
            finally:
                fi.clear_lane_stalls()
                await mc.shutdown()
        asyncio.run(run())


class TestFlagRevert:
    def test_scheduler_off_is_direct_dispatch(self, tmp_path):
        """scheduler_enabled=False reverts to the pre-scheduler path:
        reads/writes work, no lane accounting moves."""
        async def run():
            flags.set_flag("scheduler_enabled", False)
            mc, c, rows = await _cluster(tmp_path, n_rows=50)
            try:
                ts = mc.tservers[0]
                before = {ln.value: st.m_admitted.value()
                          for ln, st in ts.scheduler.lanes.items()}
                await asyncio.gather(
                    *[c.get("usertable", {"ycsb_key": i})
                      for i in range(20)])
                await c.insert("usertable", [{
                    "ycsb_key": 999,
                    **{f"field{j}": "x" for j in range(10)}}])
                resp = await c.scan("usertable", ReadRequest(
                    "", aggregates=(AggSpec("count"),)))
                assert int(resp.agg_values[0]) == 51
                after = {ln.value: st.m_admitted.value()
                         for ln, st in ts.scheduler.lanes.items()}
                assert before == after, "scheduler saw traffic while off"
            finally:
                flags.set_flag("scheduler_enabled", True)
                await mc.shutdown()
        asyncio.run(run())


class TestFusedWritePath:
    """PR-11 write-path fusion: fused consensus appends (one WAL
    append + one replicate round per accumulated group — the
    ReplicateBatch shape), one LogEntry batch per coalesced scheduler
    write group, cross-tablet dispatch fusion, and flag reverts."""

    def test_concurrent_replicates_fuse_into_one_append(self, tmp_path):
        """Replicate calls queued while an append is pending ride ONE
        fused append: the counter sees one append, the fanin histogram
        sees the whole group, and every caller gets its own index."""
        async def run():
            mc, c, rows = await _cluster(tmp_path, n_rows=1)
            try:
                ts = mc.tservers[0]
                tablet_id = (await c._table("usertable")) \
                    .locations[0].tablet_id
                cons = ts.peers[tablet_id].consensus
                a0 = cons._m_fused_appends.value()
                idxs = await asyncio.gather(
                    *[cons.replicate("noop", b"") for _ in range(8)])
                assert sorted(idxs) == idxs and len(set(idxs)) == 8
                # all 8 queued in one loop sweep -> one fused append
                assert cons._m_fused_appends.value() == a0 + 1
                assert cons._m_fused_fanin._max >= 8
                assert cons.log.last_index == idxs[-1]
            finally:
                await mc.shutdown()
        asyncio.run(run())

    def test_coalesced_group_is_one_log_entry_batch(self, tmp_path):
        """Concurrent client writes that the scheduler coalesces land
        as FEWER WAL entries than requests — each coalesced group one
        LogEntry batch — and write_id order inside the merged batch is
        arrival order (the replay-parity invariant)."""
        async def run():
            mc, c, rows = await _cluster(tmp_path, n_rows=1)
            try:
                ts = mc.tservers[0]
                tablet_id = (await c._table("usertable")) \
                    .locations[0].tablet_id
                peer = ts.peers[tablet_id]
                n0 = sum(1 for e in peer.log.all_entries()
                         if e.etype == "write")
                n_req = 48
                await asyncio.gather(*[
                    c.insert("usertable", [{
                        "ycsb_key": 5000 + i,
                        **{f"field{j}": f"f{i}-{j}" for j in range(10)}}])
                    for i in range(n_req)])
                entries = [e for e in peer.log.all_entries()
                           if e.etype == "write"]
                n_entries = len(entries) - n0
                st = ts.scheduler.lanes[Lane.POINT_WRITE]
                assert st.m_fanin._max > 1, "no group ever coalesced"
                assert n_entries < n_req, (
                    f"{n_req} writes produced {n_entries} WAL entries "
                    "— coalesced groups did not share entries")
                # every write readable (write_id order preserved the
                # per-member effects through the merged batches)
                for i in range(n_req):
                    got = await c.get("usertable",
                                      {"ycsb_key": 5000 + i})
                    assert got["field0"] == f"f{i}-0"
            finally:
                await mc.shutdown()
        asyncio.run(run())

    def test_fused_replicate_off_reverts(self, tmp_path):
        """fused_replicate_enabled=0: the per-call append path serves
        identical results (the byte-identical revert leg)."""
        async def run():
            flags.set_flag("fused_replicate_enabled", False)
            mc, c, rows = await _cluster(tmp_path, n_rows=1)
            try:
                ts = mc.tservers[0]
                tablet_id = (await c._table("usertable")) \
                    .locations[0].tablet_id
                cons = ts.peers[tablet_id].consensus
                a0 = cons._m_fused_appends.value()
                await asyncio.gather(*[
                    c.insert("usertable", [{
                        "ycsb_key": 7000 + i,
                        **{f"field{j}": f"o{i}" for j in range(10)}}])
                    for i in range(12)])
                assert cons._m_fused_appends.value() == a0, \
                    "flag off must bypass the fused drainer"
                for i in range(12):
                    got = await c.get("usertable", {"ycsb_key": 7000 + i})
                    assert got["field3"] == f"o{i}"
            finally:
                await mc.shutdown()
        asyncio.run(run())

    def test_cross_tablet_fusion_one_wakeup_drains_ready_groups(self):
        """With the lane stalled, queued groups pile up; the released
        worker's ONE wakeup drains and dispatches them all (bounded by
        sched_fusion_max_groups), observable in the fused-wakeup
        histogram.  Flag off: one group per wakeup."""
        from yugabyte_db_tpu.sched.lanes import LaneConfig

        async def run(fusion_on):
            flags.set_flag("sched_cross_tablet_fusion", fusion_on)
            # distinct owner per leg: the metrics registry keys lane
            # entities by owner, and a shared histogram would leak the
            # first leg's max into the second
            sched = RequestScheduler(f"t-fuse-{fusion_on}", configs={
                Lane.SCAN: LaneConfig(max_depth=64, soft_bytes=1 << 20,
                                      workers=1, max_batch=4)})
            done = []

            def mk(i):
                async def payload():
                    done.append(i)
                    return i
                return payload

            fi.stall_lane("scan")
            tasks = [asyncio.create_task(
                sched.submit_grouped(Lane.SCAN, key=("k", i), payload=mk(i)))
                for i in range(5)]
            await asyncio.sleep(0.05)
            fi.release_lane("scan")
            res = await asyncio.gather(*tasks)
            assert sorted(res) == list(range(5))
            st = sched.lanes[Lane.SCAN]
            await sched.shutdown()
            return st.m_fused_wakeup._max

        assert asyncio.run(run(True)) == 5
        flags.REGISTRY.reset("sched_cross_tablet_fusion")
        assert asyncio.run(run(False)) == 1

    def test_replay_parity_with_fusion_flags_flipped(self, tmp_path):
        """Rows written with the fusion levers ON and OFF in the same
        log replay identically across a restart (WAL-replay parity —
        fusion changes batching at the durability boundary, never log
        content)."""
        async def run():
            mc, c, rows = await _cluster(tmp_path, n_rows=1)
            try:
                mk = lambda base, tag: [
                    {"ycsb_key": base + i,
                     **{f"field{j}": f"{tag}{i}-{j}" for j in range(10)}}
                    for i in range(16)]
                await asyncio.gather(
                    *[c.insert("usertable", [r]) for r in mk(8000, "a")])
                flags.set_flag("fused_replicate_enabled", False)
                flags.set_flag("async_flush_enabled", False)
                await asyncio.gather(
                    *[c.insert("usertable", [r]) for r in mk(8100, "b")])
                flags.set_flag("fused_replicate_enabled", True)
                flags.set_flag("async_flush_enabled", True)
                await mc.restart_tserver(0)
                await mc.wait_for_leaders("usertable")
                for base, tag in ((8000, "a"), (8100, "b")):
                    for i in range(16):
                        got = await c.get("usertable",
                                          {"ycsb_key": base + i})
                        assert got == {
                            "ycsb_key": base + i,
                            **{f"field{j}": f"{tag}{i}-{j}"
                               for j in range(10)}}, (base, i)
            finally:
                await mc.shutdown()
        asyncio.run(run())
