"""v2 columnar SST block format: lane codec, keyless derivation, zone
maps, format gates.

Four contracts under test:

1. LANE CODEC — every encoding round-trips bit-exactly through its
   numpy decode oracle, and the strict "encode only if smaller" rule
   keeps incompressible lanes raw.
2. V1 BYTE IDENTITY — ``sst_format_version=1`` serializes blocks
   byte-identically to the pre-v2 writer (pinned by an inline oracle
   reimplementation of the old serializer).
3. KEYLESS V2 — the keys matrix is dropped only when the codec rebuild
   byte-matches, readers re-derive lazily, and the whole read surface
   (entries, point reads, aggregates) is equal across formats —
   including mixed v1+v2 SSTs in one tablet.
4. ZONE MAPS — pruning never changes results (boundary-straddling
   predicates included) and provably skips blocks on selective scans
   over key-clustered data.
"""
import struct

import msgpack
import numpy as np
import pytest

from yugabyte_db_tpu.docdb import ReadRequest, RowOp, WriteRequest
from yugabyte_db_tpu.ops.scan import (AggSpec, zone_maybe_match,
                                      zone_prune_blocks)
from yugabyte_db_tpu.storage import lane_codec
from yugabyte_db_tpu.storage.columnar import (SUPPORTED_FORMAT_VERSION,
                                              ColumnarBlock)
from yugabyte_db_tpu.storage.sst import SstReader, resolve_format_version
from yugabyte_db_tpu.tablet import Tablet
from yugabyte_db_tpu.utils import flags
from yugabyte_db_tpu.utils.hybrid_time import (HybridClock, HybridTime,
                                               MockPhysicalClock)
from tests.test_tablet import make_info


@pytest.fixture
def v2_flag():
    flags.set_flag("sst_format_version", 2)
    yield
    flags.REGISTRY.reset("sst_format_version")


@pytest.fixture
def v1_flag():
    flags.set_flag("sst_format_version", 1)
    yield
    flags.REGISTRY.reset("sst_format_version")


def _roundtrip(arr):
    meta, bufs, enc = lane_codec.encode_lane(arr)
    stream = b"".join(memoryview(np.ascontiguousarray(b)).cast("B")
                      for b in bufs)
    pos = [0]

    def fetch(nb):
        raw = stream[pos[0]:pos[0] + nb]
        pos[0] += nb
        return raw

    out = lane_codec.decode_lane(meta, fetch)
    assert pos[0] == len(stream)
    assert out.dtype == arr.dtype and out.shape == arr.shape
    assert np.array_equal(out.view(np.uint8),
                          np.ascontiguousarray(arr).view(np.uint8))
    return enc, sum(np.ascontiguousarray(b).nbytes for b in bufs)


class TestLaneCodec:
    def test_const_lane(self):
        enc, size = _roundtrip(np.full(4096, 0x1234, np.uint64))
        assert enc == "const" and size == 8

    def test_dconst_arange(self):
        enc, size = _roundtrip(np.arange(4096, dtype=np.uint32))
        assert enc == "dconst" and size == 8

    def test_dconst_descending_wraparound(self):
        enc, _ = _roundtrip(np.arange(4096, 0, -1, dtype=np.uint64))
        assert enc == "dconst"

    def test_delta_slowly_varying(self):
        rng = np.random.default_rng(0)
        arr = np.cumsum(rng.integers(0, 100, 4096)).astype(np.uint64)
        enc, size = _roundtrip(arr)
        assert enc == "delta" and size < arr.nbytes / 4

    def test_rle_sparse_bool(self):
        rng = np.random.default_rng(1)
        enc, size = _roundtrip(rng.random(4096) < 0.005)
        assert enc == "rle" and size < 4096

    def test_dict_low_cardinality_floats(self):
        rng = np.random.default_rng(2)
        arr = rng.integers(0, 11, 8192).astype(np.float64) / 100.0
        enc, size = _roundtrip(arr)
        assert enc == "dict"
        assert size < arr.nbytes / 4

    def test_nan_payloads_bit_exact(self):
        # two distinct NaN bit patterns must survive (dict/const work on
        # the unsigned VIEW, never on float comparisons)
        a = np.array([np.nan] * 8, np.float64)
        b = a.view(np.uint64).copy()
        b[::2] |= np.uint64(1)
        _roundtrip(b.view(np.float64))

    def test_encode_only_if_smaller_incompressible(self):
        rng = np.random.default_rng(3)
        for arr in (rng.random(4096),
                    rng.integers(0, 2**63, 4096).astype(np.uint64)):
            enc, size = _roundtrip(arr)
            assert enc == "raw" and size == arr.nbytes

    def test_tiny_and_empty_lanes(self):
        _roundtrip(np.array([], np.float64))
        _roundtrip(np.array([7], np.uint64))
        _roundtrip(np.array([1, 2], np.int32))

    def test_fuzz_all_dtypes(self):
        rng = np.random.default_rng(4)
        for dt in (np.uint8, np.int16, np.uint32, np.int64, np.float64,
                   np.float32, bool):
            for shape in (1, 2, 3, 100, 4097):
                if dt is bool:
                    arr = rng.random(shape) < rng.random()
                else:
                    arr = rng.integers(-50, 50, shape).astype(dt)
                _roundtrip(arr)


def _oracle_v1_serialize(cb: ColumnarBlock) -> bytes:
    """The PRE-v2 serializer, verbatim — pins v1 byte identity."""
    bufs = []

    def ref(arr):
        a = np.ascontiguousarray(arr)
        bufs.append(a)
        return {"dtype": str(arr.dtype), "shape": list(arr.shape),
                "len": a.nbytes}

    meta = {
        "n": cb.n, "sv": cb.schema_version, "uniq": cb.unique_keys,
        "keys": ref(cb.keys) if cb.keys is not None else None,
        "key_hash": ref(cb.key_hash), "ht": ref(cb.ht),
        "wid": ref(cb.write_id), "tomb": ref(cb.tombstone),
        "pk": {str(k): ref(v) for k, v in cb.pk.items()},
        "fixed": {str(k): [ref(v), ref(m)]
                  for k, (v, m) in cb.fixed.items()},
        "varlen": {},
    }
    for k, (ends, heap, null) in cb.varlen.items():
        bufs.append(heap)
        meta["varlen"][str(k)] = [ref(ends), {"len": len(heap)},
                                  ref(null)]
    head = msgpack.packb(meta)
    return struct.pack("<I", len(head)) + head + b"".join(
        b if isinstance(b, bytes) else memoryview(b).cast("B")
        for b in bufs)


def _make_tablet(tmp_path, tag, rows=600, versions=2):
    clock = HybridClock(MockPhysicalClock(1_000_000))
    t = Tablet(f"v2-{tag}", make_info(), str(tmp_path / tag), clock=clock)
    for ver in range(versions):
        t.apply_write(WriteRequest("t1", [
            RowOp("upsert", {"k": i, "v": float(ver * 1000 + i),
                             "s": f"s{i % 7}"})
            for i in range(rows)]))
        t.flush()
    return t


class TestV1ByteIdentity:
    def test_flush_block_serializes_identically(self, tmp_path, v1_flag):
        t = _make_tablet(tmp_path, "oracle")
        for r in t.regular.ssts:
            for i in range(r.num_blocks()):
                cb = r.columnar_block(i)
                assert cb.serialize(version=1) == _oracle_v1_serialize(cb)

    def test_v1_sst_has_no_version_markers(self, tmp_path, v1_flag):
        t = _make_tablet(tmp_path, "gate")
        for r in t.regular.ssts:
            assert r.format_version == 1
            for i in range(r.num_blocks()):
                raw = r._data[r.index[i].col_offset:]
                hlen = struct.unpack_from("<I", raw)[0]
                meta = msgpack.unpackb(bytes(raw[4:4 + hlen]),
                                       strict_map_key=False)
                assert "v" not in meta

    def test_resolver_clamps(self):
        flags.set_flag("sst_format_version", 1)
        assert resolve_format_version() == 1
        flags.set_flag("sst_format_version", 3)   # unknown -> compatible
        assert resolve_format_version() == 1
        flags.set_flag("sst_format_version", 2)
        assert resolve_format_version() == 2
        flags.REGISTRY.reset("sst_format_version")


class TestKeylessV2:
    def test_bulk_load_drops_keys_and_rereads_identically(
            self, tmp_path, v2_flag):
        rng = np.random.default_rng(0)
        n = 5000
        data = {"k": rng.permutation(n).astype(np.int64),
                "v": rng.random(n),
                "s": np.array([f"x{i % 13}" for i in range(n)],
                              dtype=object)}
        t2 = Tablet("kb2", make_info(), str(tmp_path / "b2"))
        t2.bulk_load(data, ht=HybridTime.from_micros(1 << 40),
                     block_rows=1024)
        flags.set_flag("sst_format_version", 1)
        t1 = Tablet("kb1", make_info(), str(tmp_path / "b1"))
        t1.bulk_load(data, ht=HybridTime.from_micros(1 << 40),
                     block_rows=1024)
        flags.set_flag("sst_format_version", 2)
        r2 = t2.regular.ssts[0]
        assert r2.format_version == 2
        assert r2.file_size < t1.regular.ssts[0].file_size * 0.8
        # keys genuinely absent on disk, derived lazily on access
        cb = r2.columnar_block(0)
        assert cb._keys is None and cb.keys_derivable
        assert list(t1.regular.iterate()) == list(t2.regular.iterate())

    def test_point_reads_over_keyless_blocks(self, tmp_path, v2_flag):
        rng = np.random.default_rng(1)
        n = 3000
        data = {"k": np.arange(n, dtype=np.int64), "v": rng.random(n),
                "s": np.array(["p"] * n, dtype=object)}
        t = Tablet("kp", make_info(), str(tmp_path))
        t.bulk_load(data, ht=HybridTime.from_micros(1 << 40),
                    block_rows=512)
        for k in (0, 17, 1234, n - 1):
            rows = t.read(ReadRequest("t1", pk_eq={"k": k})).rows
            assert len(rows) == 1 and rows[0]["k"] == k
            assert rows[0]["v"] == data["v"][k]

    def test_underivable_pk_keeps_inline_keys(self, tmp_path, v2_flag):
        """String hash PK can't rebuild from cb.pk (varlen component)
        — the writer must keep the keys matrix inline and everything
        still reads."""
        from yugabyte_db_tpu.dockv.packed_row import (ColumnSchema,
                                                      ColumnType,
                                                      TableSchema)
        from yugabyte_db_tpu.dockv.partition import PartitionSchema
        from yugabyte_db_tpu.docdb.table_codec import TableInfo
        info = TableInfo("ts", "ts", TableSchema(columns=(
            ColumnSchema(0, "k", ColumnType.STRING, is_hash_key=True),
            ColumnSchema(1, "v", ColumnType.FLOAT64),
        ), version=1), PartitionSchema("hash", 1))
        t = Tablet("str", info, str(tmp_path))
        t.apply_write(WriteRequest("ts", [
            RowOp("upsert", {"k": f"key-{i:04d}", "v": float(i)})
            for i in range(300)]))
        t.flush()
        r = t.regular.ssts[0]
        cb = r.columnar_block(0)
        assert cb is not None and cb._keys is not None   # inline keys
        rows = t.read(ReadRequest("ts", pk_eq={"k": "key-0042"})).rows
        assert rows and rows[0]["v"] == 42.0

    def test_mixed_v1_v2_ssts_in_one_tablet(self, tmp_path):
        rng = np.random.default_rng(2)
        n = 2000

        def halves(t):
            flags.set_flag("sst_format_version", 1)
            t.bulk_load({"k": np.arange(n, dtype=np.int64) * 2,
                         "v": rng.random(n),
                         "s": np.array(["a"] * n, dtype=object)},
                        ht=HybridTime.from_micros(1 << 40),
                        block_rows=512)
            flags.set_flag("sst_format_version", 2)
            t.bulk_load({"k": np.arange(n, dtype=np.int64) * 2 + 1,
                         "v": rng.random(n),
                         "s": np.array(["b"] * n, dtype=object)},
                        ht=HybridTime.from_micros((1 << 40) + 100),
                        block_rows=512)

        try:
            t = Tablet("mix", make_info(), str(tmp_path / "m"))
            halves(t)
            got = {1, 2} <= {r.format_version for r in t.regular.ssts}
            assert got
            total = t.read(ReadRequest(
                "t1", aggregates=(AggSpec("count"),)))
            assert int(np.asarray(total.agg_values[0])) == 2 * n
            for k in (0, 1, 777, 2 * n - 1):
                rows = t.read(ReadRequest("t1", pk_eq={"k": k})).rows
                assert len(rows) == 1
        finally:
            flags.REGISTRY.reset("sst_format_version")


class TestVersionRejection:
    def test_block_newer_version_rejected(self):
        cb = ColumnarBlock.from_arrays(
            schema_version=1,
            key_hash=np.arange(4, dtype=np.uint64),
            ht=np.full(4, 9, np.uint64),
            keys=np.zeros((4, 20), np.uint8))
        raw = cb.serialize(version=2)
        with pytest.raises(ValueError, match="v2 is newer"):
            ColumnarBlock.deserialize(raw, max_version=1)
        # and the supported version round-trips
        back = ColumnarBlock.deserialize(raw)
        assert back.n == 4

    def test_v2_file_rejected_by_v1_reader(self, tmp_path, v2_flag,
                                           monkeypatch):
        t = _make_tablet(tmp_path, "rej", rows=100, versions=1)
        path = t.regular.ssts[0].path
        import yugabyte_db_tpu.storage.sst as sst_mod
        monkeypatch.setattr(sst_mod, "SUPPORTED_FORMAT_VERSION", 1)
        with pytest.raises(ValueError, match="format v2 is newer"):
            SstReader(path)
        assert SUPPORTED_FORMAT_VERSION == 2   # module constant intact


class TestZoneMaps:
    def _range_tablet(self, tmp_path, n=20000, block_rows=1024):
        from yugabyte_db_tpu.models.tpch import lineitem_range_info
        rng = np.random.default_rng(5)
        data = {
            "rowid": np.arange(n, dtype=np.int64),
            "l_quantity": rng.integers(1, 51, n).astype(np.float64),
            "l_extendedprice": rng.uniform(900, 105000, n),
            "l_discount": rng.integers(0, 11, n).astype(np.float64) / 100,
            "l_tax": rng.integers(0, 9, n).astype(np.float64) / 100,
            "l_shipdate": rng.integers(8036, 10592, n).astype(np.int32),
            "l_returnflag": rng.integers(0, 3, n).astype(np.int32),
            "l_linestatus": rng.integers(0, 2, n).astype(np.int32),
        }
        t = Tablet("zr", lineitem_range_info(), str(tmp_path))
        t.bulk_load(data, ht=HybridTime.from_micros(1 << 40),
                    block_rows=block_rows)
        return t, data

    def test_zone_maps_stored_and_exact(self, tmp_path, v2_flag):
        t, data = self._range_tablet(tmp_path, n=4000)
        r = t.regular.ssts[0]
        lo = 0
        for i in range(r.num_blocks()):
            cb = r.columnar_block(i)
            assert cb.zmap is not None
            zlo, zhi = cb.zmap[0]            # rowid: range-clustered
            assert zlo == lo and zhi == lo + cb.n - 1
            lo += cb.n
            qlo, qhi = cb.zmap[1]            # l_quantity
            sl = data["l_quantity"][zlo:zhi + 1]
            assert qlo == sl.min() and qhi == sl.max()

    def test_boundary_straddling_predicates(self, tmp_path, v2_flag):
        """Predicate edges exactly ON block boundary min/max values:
        pruning must keep every boundary row (le/ge/lt/gt asymmetry is
        where an off-by-one would hide)."""
        t, data = self._range_tablet(tmp_path, n=8000, block_rows=1000)
        n = len(data["rowid"])
        from yugabyte_db_tpu.ops import Expr
        C = Expr.col
        cases = [
            (C(0) < 1000).node,             # exactly one block
            (C(0) <= 1000).node,            # first row of block 2
            (C(0) >= 6999).node,            # last row of block 7
            (C(0) > 6999).node,
            ((C(0) >= 999) & (C(0) <= 1000)).node,   # straddles a cut
            ((C(0) >= 2000) & (C(0) < 3000)).node,   # aligned window
            (C(0) < 0).node,                         # empty
        ]
        for where in cases:
            req = ReadRequest("lineitem_r", where=where,
                              aggregates=(AggSpec("count"),
                                          AggSpec("sum", C(0).node)))
            on = t.read(req)
            flags.set_flag("zone_map_pruning", False)
            off = t.read(req)
            flags.REGISTRY.reset("zone_map_pruning")
            for a, b in zip(on.agg_values, off.agg_values):
                assert float(np.asarray(a)) == float(np.asarray(b)), \
                    where
            got = int(np.asarray(on.agg_values[0]))
            # CPU oracle over raw data
            from yugabyte_db_tpu.docdb.operations import eval_expr_py
            want = sum(
                1 for i in range(n)
                if eval_expr_py(where, {0: int(data["rowid"][i])})
                is True)
            assert got == want, where

    def test_selective_scan_skips_blocks(self, tmp_path, v2_flag):
        t, data = self._range_tablet(tmp_path, n=20000, block_rows=1000)
        from yugabyte_db_tpu.ops import Expr
        from yugabyte_db_tpu.ops.stream_scan import LAST_STREAM_STATS
        from yugabyte_db_tpu.docdb.operations import LAST_SCAN_PRUNE_STATS
        C = Expr.col
        req = ReadRequest("lineitem_r",
                          where=(C(0) < 2000).node,
                          aggregates=(AggSpec("count"),))
        resp = t.read(req)
        assert int(np.asarray(resp.agg_values[0])) == 2000
        skipped = (LAST_STREAM_STATS.get("zone_blocks_pruned")
                   or LAST_SCAN_PRUNE_STATS.get("blocks_pruned", 0))
        assert skipped >= 15   # ~18 of 20 blocks provably out of range

    def test_f32_boundary_rounding_never_prunes_matches(
            self, tmp_path, v2_flag):
        """Zone maps are exact f64 but the kernel may evaluate in the
        device float dtype (f32): a value just below a predicate
        boundary can f32-round ONTO it and match. The prune intervals
        widen through the f32 envelope, so pruning must agree with the
        unpruned scan bit-for-bit."""
        from yugabyte_db_tpu.models.tpch import lineitem_range_info
        from yugabyte_db_tpu.ops import Expr
        n = 8192
        data = {
            "rowid": np.arange(n, dtype=np.int64),
            "l_quantity": np.full(n, 1.0),
            "l_extendedprice": np.full(n, 1.0),
            "l_discount": np.full(n, 0.0499999999),  # f32-rounds to .05
            "l_tax": np.zeros(n),
            "l_shipdate": np.full(n, 9000, np.int32),
            "l_returnflag": np.zeros(n, np.int32),
            "l_linestatus": np.zeros(n, np.int32),
        }
        flags.set_flag("device_float_dtype", "float32")
        try:
            t = Tablet("f32z", lineitem_range_info(), str(tmp_path))
            t.bulk_load(data, ht=HybridTime.from_micros(1 << 40),
                        block_rows=512)
            req = ReadRequest("lineitem_r",
                              where=(Expr.col(3) >= 0.05).node,
                              aggregates=(AggSpec("count"),))
            on = t.read(req)
            flags.set_flag("zone_map_pruning", False)
            off = t.read(req)
            assert int(np.asarray(on.agg_values[0])) == \
                int(np.asarray(off.agg_values[0]))
        finally:
            flags.REGISTRY.reset("zone_map_pruning")
            flags.REGISTRY.reset("device_float_dtype")

    def test_prune_helper_conservative_shapes(self):
        zmap = {0: (10, 20), 1: (0.5, 0.7)}
        # provable misses
        assert not zone_maybe_match(("cmp", "lt", ("col", 0),
                                     ("const", 10)), zmap)
        assert not zone_maybe_match(("cmp", "eq", ("col", 0),
                                     ("const", 21)), zmap)
        assert not zone_maybe_match(("in", ("col", 0), [1, 2, 30]), zmap)
        # boundary hits stay
        assert zone_maybe_match(("cmp", "le", ("col", 0),
                                 ("const", 10)), zmap)
        assert zone_maybe_match(("cmp", "ge", ("col", 0),
                                 ("const", 20)), zmap)
        # unknown shapes / columns never prune
        assert zone_maybe_match(("cmp", "lt", ("col", 9),
                                 ("const", 0)), zmap)
        assert zone_maybe_match(("not", ("cmp", "lt", ("col", 0),
                                         ("const", 10))), zmap)
        assert zone_maybe_match(("like", ("col", 2), "x%"), zmap)
        # OR needs every branch to miss
        assert not zone_maybe_match(
            ("or", ("cmp", "lt", ("col", 0), ("const", 5)),
             ("cmp", "gt", ("col", 0), ("const", 25))), zmap)
        assert zone_maybe_match(
            ("or", ("cmp", "lt", ("col", 0), ("const", 5)),
             ("cmp", "gt", ("col", 0), ("const", 15))), zmap)

    def test_prune_never_empties_block_list(self):
        blocks = []
        for i in range(3):
            cb = ColumnarBlock.from_arrays(
                schema_version=1,
                key_hash=np.arange(4, dtype=np.uint64),
                ht=np.full(4, 9, np.uint64))
            cb.zmap = {0: (i * 10, i * 10 + 9)}
            blocks.append(cb)
        kept, idx = zone_prune_blocks(
            blocks, ("cmp", "gt", ("col", 0), ("const", 100)))
        assert len(kept) == 1 and len(idx) == 1


class TestLaneStatsPlumbing:
    def test_incompressible_lane_reports_raw(self, tmp_path, v2_flag):
        rng = np.random.default_rng(6)
        n = 4000
        t = Tablet("st", make_info(), str(tmp_path))
        t.bulk_load({"k": np.arange(n, dtype=np.int64),
                     "v": rng.random(n),
                     "s": np.array(["q"] * n, dtype=object)},
                    ht=HybridTime.from_micros(1 << 40), block_rows=1024)
        from yugabyte_db_tpu.docdb.compaction import (
            LAST_COMPACTION_STATS, tpu_compact)
        t.bulk_load({"k": np.arange(n, dtype=np.int64) + n,
                     "v": rng.random(n),
                     "s": np.array(["q"] * n, dtype=object)},
                    ht=HybridTime.from_micros((1 << 40) + 5),
                    block_rows=1024)
        tpu_compact(t.regular, t.codec, t.history_cutoff(),
                    backend="native")
        lanes = LAST_COMPACTION_STATS["lanes"]
        # random f64 value column: encode-only-if-smaller keeps it raw
        fv = lanes["fixed_vals"]
        assert fv["encodings"].get("raw", 0) >= 1
        # keys derived away entirely
        assert lanes["keys"]["post_bytes"] == 0
        assert lanes["keys"]["encodings"] == {
            "derived": lanes["keys"]["encodings"]["derived"]}
        assert LAST_COMPACTION_STATS["format_version"] == 2
        assert LAST_COMPACTION_STATS["output_bytes"] > 0
