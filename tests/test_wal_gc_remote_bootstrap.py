"""WAL GC + remote bootstrap tests (reference analogs: log GC in
consensus/log.cc, tserver/remote_bootstrap_service.cc)."""
import asyncio
import os

import pytest

from yugabyte_db_tpu.consensus import Log, LogEntry
from yugabyte_db_tpu.docdb import ReadRequest
from yugabyte_db_tpu.ops import AggSpec
from yugabyte_db_tpu.tools.mini_cluster import MiniCluster
from yugabyte_db_tpu.utils import flags
from tests.test_load_balancer import kv_info


def run(coro):
    return asyncio.run(coro)


class TestLogGc:
    def test_gc_drops_flushed_segments(self, tmp_path):
        flags.set_flag("log_segment_size_bytes", 512)
        try:
            log = Log(str(tmp_path), fsync=False)
            for i in range(1, 101):
                log.append([LogEntry(1, i, "write", b"x" * 64)])
            nseg_before = len(log._seg_paths())
            assert nseg_before > 3
            dropped = log.gc(upto_index=80)
            assert dropped > 0
            assert log.last_index == 100
            assert log._first_index > 1
            # retained entries still readable; reopen works
            log.close()
            log2 = Log(str(tmp_path), fsync=False)
            assert log2.last_index == 100
            assert log2.entry(100).payload == b"x" * 64
            assert log2._first_index == log._first_index
        finally:
            flags.REGISTRY.reset("log_segment_size_bytes")

    def test_restart_after_gc_serves_reads(self, tmp_path):
        async def go():
            flags.set_flag("log_segment_size_bytes", 2048)
            try:
                mc = await MiniCluster(str(tmp_path),
                                       num_tservers=1).start()
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=1)
                await mc.wait_for_leaders("kv")
                for batch in range(5):
                    await c.insert("kv", [
                        {"k": batch * 20 + i, "v": 1.0} for i in range(20)])
                ts = mc.tservers[0]
                peer = next(p for p in ts.peers.values())
                peer.tablet.flush()
                dropped = peer.maybe_gc_log()
                assert dropped > 0
                # restart: bootstrap must work from SSTs + retained log
                await mc.restart_tserver(0)
                await mc.wait_for_leaders("kv")
                c2 = mc.client()
                agg = await c2.scan("kv", ReadRequest(
                    "", aggregates=(AggSpec("count"),)))
                assert int(agg.agg_values[0]) == 100
                await mc.shutdown()
            finally:
                flags.REGISTRY.reset("log_segment_size_bytes")
        run(go())


class TestRemoteBootstrap:
    def test_move_replica_after_wal_gc(self, tmp_path):
        """The real remote-bootstrap scenario: the leader's WAL no longer
        has history, so the new replica must come up from snapshot files."""
        async def go():
            flags.set_flag("log_segment_size_bytes", 2048)
            try:
                mc = await MiniCluster(str(tmp_path),
                                       num_tservers=2).start()
                c = mc.client()
                await c.create_table(kv_info(), num_tablets=1,
                                     replication_factor=1)
                await mc.wait_for_leaders("kv")
                for batch in range(5):
                    await c.insert("kv", [
                        {"k": batch * 20 + i, "v": float(batch)}
                        for i in range(20)])
                ts0 = mc.tservers[0]
                src = next((ts.uuid for ts in mc.tservers
                            if ts.peers), None)
                src_ts = next(ts for ts in mc.tservers if ts.uuid == src)
                peer = next(p for p in src_ts.peers.values())
                tablet_id = peer.tablet.tablet_id
                peer.tablet.flush()
                assert peer.maybe_gc_log() > 0   # history is GONE
                dst = next(ts.uuid for ts in mc.tservers if ts.uuid != src)
                await c.messenger.call(
                    mc.master.messenger.addr, "master", "move_replica",
                    {"tablet_id": tablet_id, "from": src, "to": dst},
                    timeout=60.0)
                await mc.wait_for_leaders("kv")
                c2 = mc.client()
                agg = await c2.scan("kv", ReadRequest(
                    "", aggregates=(AggSpec("count"),)))
                assert int(agg.agg_values[0]) == 100
                row = await c2.get("kv", {"k": 85})
                assert row is not None and row["v"] == 4.0
                await mc.shutdown()
            finally:
                flags.REGISTRY.reset("log_segment_size_bytes")
        run(go())
