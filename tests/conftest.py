"""Test configuration: force JAX onto a virtual 8-device CPU mesh so
multi-chip sharding paths are exercised without TPU hardware (the driver
separately dry-runs them; see __graft_entry__.dryrun_multichip).

Note: the environment presets JAX_PLATFORMS to the TPU tunnel platform,
so we must override via jax.config (env setdefault is not enough), and
it must happen before any backend initialization.
"""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# the package skips the persistent XLA compile cache on CPU backends
# (XLA:CPU AOT entries can fail the loader's machine check); make the
# CPU choice visible to yugabyte_db_tpu/__init__.py before its import
os.environ.setdefault("YBTPU_PLATFORM", "cpu")

# state-invariant sanitizer (utils/sanitizer.py — the TSAN/DCHECK-build
# analog): every MiniCluster shutdown sweeps claims-vs-intents,
# read-lock symmetry, memtable probe guards, and manifest consistency,
# so every test drive doubles as an invariant check
os.environ.setdefault("YBTPU_SANITIZE", "1")
