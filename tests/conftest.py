"""Test configuration: force JAX onto a virtual 8-device CPU mesh so
multi-chip sharding paths are exercised without TPU hardware (the driver
separately dry-runs them; see __graft_entry__.dryrun_multichip)."""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")
